"""Distributed K-FAC execution on a TPU mesh (SPMD, shard_map).

This is the TPU-native replacement for the reference's three communication
strategies (reference kfac/preconditioner.py:19-36, kfac/utils.py:59-147)
and its NCCL/Horovod broadcast groups (kfac/comm.py). The world is a 2-D
``jax.sharding.Mesh`` of shape ``(n_inv_groups, grad_workers)``:

  - axis ``kfac_ig`` indexes the *inverse groups* (KAISA's contiguous
    inverse-broadcast groups, reference kfac/utils.py:156-159);
  - axis ``kfac_gw`` indexes position *within* a group (the strided
    gradient-broadcast groups, reference kfac/utils.py:150-153, are the
    columns of this view).

Data parallelism shards the batch over *both* axes flattened; gradient
averaging is one ``pmean`` over ``(kfac_ig, kfac_gw)``.

The reference's rank-selective work and broadcasts become SPMD-friendly
masked collectives (the "zero the non-assigned buffer and sum" trick the
reference itself uses for tensor gathers, kfac/layers/base.py:202-206):

  - **factor allreduce** (reference preconditioner.py:525-533) — ``pmean``
    of per-device covariance contributions over both axes;
  - **inverse compute + broadcast** (reference preconditioner.py:555-564,
    base.py:129-171) — same-size factors are stacked per *bucket*, every
    device eigendecomposes its slice of its row's stack (one batched
    ``eigh`` on the MXU instead of ~100 sequential kernels), and one
    ``all_gather`` over ``kfac_gw`` leaves each inverse group holding
    exactly its own layers' inverses — COMM_OPT (1 group) replicates all
    inverses everywhere, MEM_OPT (group size 1) keeps each inverse on a
    single device, HYBRID in between;
  - **gradient broadcast** (reference preconditioner.py:545-553,
    base.py:173-196) — each row preconditions its own layers (the value is
    masked to zero on other rows), and a single ``psum`` over ``kfac_ig``
    delivers every layer's preconditioned gradient to all devices.

All placement is decided host-side at trace time (``WorkAssignment``),
exactly like the reference's one-time deferred assignment
(preconditioner.py:616-659): greedy LPT of layers onto inverse groups,
then of factors onto group members.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from distributed_kfac_pytorch_tpu import fp16 as fp16_ops
from distributed_kfac_pytorch_tpu import layers as L
from distributed_kfac_pytorch_tpu.capture import (CONV2D_GROUPED,
                                                  EMBEDDING,
                                                  subsample_captures)
from distributed_kfac_pytorch_tpu.observability import (
    metrics as obs_metrics,
)
from distributed_kfac_pytorch_tpu.observability import profiling
from distributed_kfac_pytorch_tpu.ops import factors as F
from distributed_kfac_pytorch_tpu.ops import linalg
from distributed_kfac_pytorch_tpu.ops import pallas_kernels
from distributed_kfac_pytorch_tpu.parallel.placement import load_balance
from distributed_kfac_pytorch_tpu.parallel.sequence import SEQ_AXIS
from distributed_kfac_pytorch_tpu.preconditioner import (
    KFAC,
    CommMethod,
    _fused_bucket_ok,
    cadence_gate,
    eigen_family,
    grouped_block_inverses,
    guard_nonfinite_factors,
    q_stack_degenerate,
    resolve_eigh_method,
)

# Mesh axis names. Batch/data parallelism shards over both axes jointly;
# an optional third SEQ_AXIS ('kfac_sp') shards the sequence dimension for
# ring-attention context parallelism (parallel.sequence). Multi-slice
# pods (r20) prepend an OUTER slice axis: devices within a slice share
# fast ICI, slices are joined by slow DCN, and the collective topology
# is two-level — inverse groups never span slices
# (multislice.make_multislice_mesh builds the nested mesh).
SLICE_AXIS = 'kfac_slice'
INV_GROUP_AXIS = 'kfac_ig'
GRAD_WORKER_AXIS = 'kfac_gw'
KFAC_AXES = (INV_GROUP_AXIS, GRAD_WORKER_AXIS)


def resolve_grad_workers(size: int, comm_method: CommMethod,
                         grad_worker_fraction: float) -> int:
    """Number of devices per inverse group for a strategy.

    Reference parity: preconditioner.py:235-259 (COMM_OPT -> world,
    MEM_OPT -> 1, HYBRID_OPT -> validated ``grad_worker_fraction``).
    """
    if comm_method is CommMethod.COMM_OPT:
        return size
    if comm_method is CommMethod.MEM_OPT:
        return 1
    gw = max(1, round(size * grad_worker_fraction))
    if size % gw != 0:
        raise ValueError(
            f'grad_worker_fraction {grad_worker_fraction} gives '
            f'{gw} grad workers, which does not divide world size {size}')
    return gw


def make_kfac_mesh(devices: Sequence[jax.Device] | None = None, *,
                   comm_method: CommMethod = CommMethod.COMM_OPT,
                   grad_worker_fraction: float = 0.25,
                   seq_parallel: int = 1) -> Mesh:
    """Build the ``(n_inv_groups, grad_workers[, seq])`` mesh.

    Contiguous device runs form inverse groups (rows), matching the
    reference's contiguous ``partition_inv_ranks`` (kfac/utils.py:156-159)
    — on a TPU slice, contiguous devices are ICI neighbors, so the
    latency-critical inverse all_gather rides the fastest links.

    ``seq_parallel > 1`` appends a third ``SEQ_AXIS`` of that size as the
    *innermost* (fastest-varying) axis, so the ring-attention ppermute
    hops between physically adjacent chips.

    The device grid is *derived from* the golden KAISA topology spec
    (``placement.WorkerAllocator``, reference kfac/utils.py:59-159,
    pinned by tests/test_placement.py): mesh rows are the allocator's
    contiguous inverse-broadcast groups, and the columns across rows are
    exactly its strided gradient-broadcast groups — one source of truth
    for the topology, consumed here rather than re-derived by reshape.
    """
    from distributed_kfac_pytorch_tpu.parallel.placement import (
        WorkerAllocator,
    )
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if devices.size % seq_parallel:
        raise ValueError(f'{seq_parallel=} does not divide '
                         f'{devices.size} devices')
    dp = devices.size // seq_parallel
    gw = resolve_grad_workers(dp, comm_method, grad_worker_fraction)
    alloc = WorkerAllocator(dp, gw / dp)
    assert alloc.grad_workers == gw
    # (n_inv_groups, grad_workers) grid of K-FAC ranks per the spec.
    grid = alloc.grid
    if seq_parallel > 1:
        # Rank r owns the contiguous run of seq_parallel devices.
        devs = devices.reshape(dp, seq_parallel)[grid]
        return Mesh(devs, KFAC_AXES + (SEQ_AXIS,))
    return Mesh(devices[grid], KFAC_AXES)


def normalize_batch_specs(batch_spec, batch):
    """Per-leaf PartitionSpec tree for a batch pytree.

    A single ``PartitionSpec`` (or None) is broadcast over every leaf; a
    pytree of specs matching ``batch`` passes through unchanged. Single
    point of truth for every train-step builder that accepts
    ``batch_spec``.
    """
    if batch_spec is None or isinstance(batch_spec, P):
        return jax.tree.map(lambda _: batch_spec, batch)
    return batch_spec


# ---------------------------------------------------------------------------
# Host-side static work assignment
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Layout of all same-size factors as one stacked eigh workload.

    The global stack has shape ``(n_rows * slots_per_row, dim, dim)``,
    sharded over ``kfac_ig`` (each row of the mesh owns the contiguous
    slice of ``slots_per_row`` slots holding its layers' factors). Device
    ``(i, j)`` eigendecomposes local slots
    ``[j * slots_per_col, (j+1) * slots_per_col)``; unassigned slots hold
    identity padding.
    """
    dim: int
    slots_per_col: int          # eigh workload per device for this bucket
    n_cols: int
    # (layer_name, 'A'|'G') -> slot index within the owning row's slice.
    slot: dict[tuple[str, str], int]

    @property
    def slots_per_row(self) -> int:
        return self.slots_per_col * self.n_cols


@dataclasses.dataclass(frozen=True)
class WorkAssignment:
    """Static placement of K-FAC second-order work onto the mesh.

    ``layer_row[name]`` is the inverse group that computes, stores, and
    preconditions with layer ``name``'s inverses — the analogue of the
    reference's per-layer inverse worker + its broadcast group
    (preconditioner.py:616-659). ``buckets`` lay out the eigh work;
    ``diag_layers`` (embedding A factors) are diagonal and handled
    replicated (their inverse is an elementwise reciprocal).
    """
    n_rows: int
    n_cols: int
    layer_row: dict[str, int]
    buckets: dict[int, BucketPlan]
    diag_layers: tuple[str, ...]
    # Grouped/depthwise convs: per-group block stacks, computed
    # replicated (tiny blocks) and preconditioned by their owning row.
    grouped_layers: tuple[str, ...] = ()


def assign_work(kfac: KFAC, params, n_rows: int, n_cols: int, *,
                distribute_layer_factors: bool | None = None
                ) -> WorkAssignment:
    """LPT-place layers onto inverse groups and factors onto members.

    Two-level greedy longest-processing-time balance, mirroring the
    reference cost model (n^3 'compute' / n^2 'memory',
    preconditioner.py:625-628): layers across rows (each layer's A and G
    stay in one inverse group, as required by the KAISA topology), then
    factors across the row's columns. ``distribute_layer_factors`` places A
    and G on different columns when possible (reference
    preconditioner.py:638-645); it defaults to True when each group has
    more than one member.
    """
    if distribute_layer_factors is None:
        distribute_layer_factors = n_cols > 1
    exp = 3 if kfac.assignment_strategy == 'compute' else 2
    names = list(kfac.specs)
    shapes = {}
    diag_layers = []
    grouped_layers = []
    for name in names:
        spec = kfac.specs[name]
        a_dim, g_dim = L.factor_shapes(spec, _get(params, spec.path))
        shapes[name] = (a_dim, g_dim)
        if spec.kind == EMBEDDING:
            diag_layers.append(name)
        elif spec.kind == CONV2D_GROUPED:
            grouped_layers.append(name)

    def factor_entries(name):
        """[(key, dim, cost)] for the dense (eigh-requiring) factors.

        Grouped convs contribute none: their per-group block stacks run
        replicated (outside the bucket layout) — they still get a row
        for precondition ownership via ``layer_cost`` below.
        """
        if name in grouped_layers:
            return []
        a_dim, g_dim = shapes[name]
        out = []
        if name not in diag_layers:
            out.append(((name, 'A'), a_dim, a_dim ** exp))
        out.append(((name, 'G'), g_dim, g_dim ** exp))
        return out

    layer_cost = {n: sum(c for _, _, c in factor_entries(n)) for n in names}
    for n in grouped_layers:
        ng = kfac.specs[n].feature_group_count
        a_dim, g_dim = shapes[n]
        layer_cost[n] = ng * (a_dim ** exp + g_dim ** exp)
    row_of = dict(zip(names, load_balance(
        n_rows, [layer_cost[n] for n in names])))

    # Per row: LPT factors -> columns (or whole layers -> columns when not
    # distributing A/G, reference preconditioner.py:638-645).
    cell: dict[tuple[int, int, int], list] = collections.defaultdict(list)
    for r in range(n_rows):
        row_names = [n for n in names if row_of[n] == r]
        if not row_names:
            continue
        if distribute_layer_factors:
            items = [e for n in row_names for e in factor_entries(n)]
        else:
            items = [((n, '*'), 0, layer_cost[n])
                     for n in row_names if factor_entries(n)]
        if not items:
            continue  # row holds only grouped/diag layers (no buckets)
        cols = load_balance(n_cols, [c for _, _, c in items])
        for (key, dim, _), col in zip(items, cols):
            if key[1] == '*':
                for sub_key, sub_dim, _ in factor_entries(key[0]):
                    cell[(r, col, sub_dim)].append(sub_key)
            else:
                cell[(r, col, dim)].append(key)

    dims = sorted({d for (_, _, d) in cell})
    buckets = {}
    for dim in dims:
        s = max(len(cell[(r, c, dim)])
                for r in range(n_rows) for c in range(n_cols))
        slot = {}
        for r in range(n_rows):
            for c in range(n_cols):
                for k, key in enumerate(cell[(r, c, dim)]):
                    slot[key] = c * s + k
        buckets[dim] = BucketPlan(dim=dim, slots_per_col=s, n_cols=n_cols,
                                  slot=slot)
    return WorkAssignment(n_rows=n_rows, n_cols=n_cols, layer_row=row_of,
                          buckets=buckets, diag_layers=tuple(diag_layers),
                          grouped_layers=tuple(grouped_layers))


# ---------------------------------------------------------------------------
# The distributed preconditioner
# ---------------------------------------------------------------------------

class DistributedKFAC:
    """K-FAC with second-order work sharded over a ``make_kfac_mesh`` mesh.

    Wraps a :class:`KFAC` (which must have been ``init()``-ed so layer
    specs exist) and re-implements its inverse and preconditioning stages
    as SPMD collectives; factor statistics and hyperparameter semantics are
    inherited. ``spmd_step`` is the in-``shard_map`` analogue of
    ``KFAC.step``; ``build_train_step`` assembles the full jitted
    data-parallel training step around it.
    """

    def __init__(self, kfac: KFAC, mesh: Mesh, params, *,
                 distribute_layer_factors: bool | None = None,
                 shard_precond_compute: bool = True):
        if set(KFAC_AXES) - set(mesh.axis_names):
            raise ValueError(
                f'mesh must have axes {KFAC_AXES}, got {mesh.axis_names}')
        self.kfac = kfac
        self.mesh = mesh
        # KAISA grad-worker compute saving (reference
        # preconditioner.py:577-585: only compute_grad_ranks compute the
        # preconditioned gradients). True (default) stacks same-shape
        # dense layers per inverse group and dynamic-slices per device,
        # so MEM/HYBRID rows compute only their OWN layers' precondition
        # matmuls (1/n_rows of the FLOPs) instead of computing every
        # layer and masking; at n_rows == 1 (COMM_OPT) the same plan is
        # a pure same-shape batching — one vmapped matmul per shape
        # group on the replicated path too (r6). False keeps the
        # per-layer replicate-and-mask form (the round-1..3 path; also
        # the parity oracle in tests).
        self.shard_precond_compute = shard_precond_compute
        self.n_rows = mesh.shape[INV_GROUP_AXIS]
        self.n_cols = mesh.shape[GRAD_WORKER_AXIS]
        # Multi-slice (r20): an outer SLICE_AXIS makes the inverse-row
        # space two-level — each slice holds ``n_rows`` contiguous
        # global rows, so inverse state and decompositions stay
        # slice-confined (the in-group all_gather rides ICI only);
        # only preconditioned gradients cross the DCN (the delivery
        # psum widens to both row axes).
        self.sliced = SLICE_AXIS in mesh.axis_names
        self.n_slices = (mesh.shape[SLICE_AXIS] if self.sliced else 1)
        self.total_rows = self.n_slices * self.n_rows
        # Axis spec of the global inverse-row dimension: stacks are
        # sharded (and row-space collectives reduce) over the slice
        # axis jointly with the inverse-group axis when sliced.
        self._row_axes = ((SLICE_AXIS, INV_GROUP_AXIS) if self.sliced
                          else INV_GROUP_AXIS)
        if kfac.hierarchical_reduce and not self.sliced:
            raise ValueError(
                'hierarchical_reduce=True requires a multi-slice mesh '
                f'(an outer {SLICE_AXIS!r} axis — '
                'multislice.make_multislice_mesh with num_slices > 1); '
                'on a flat mesh there is no DCN boundary to defer over')
        # The EFFECTIVE A/G-across-columns flag (assign_work resolves
        # None to n_cols > 1). Recorded in every checkpoint's topology
        # scalars (elastic.topology) so the elastic resume path can
        # reconstruct this exact placement on a different mesh.
        self.distribute_layer_factors = (
            self.n_cols > 1 if distribute_layer_factors is None
            else bool(distribute_layer_factors))
        # Gradient/factor averaging spans every data-bearing axis: the two
        # K-FAC axes plus the sequence axis when context parallelism is on
        # (each device then holds a (batch shard, sequence block) tile),
        # plus the outer slice axis on a multi-slice mesh.
        self.data_axes = (
            ((SLICE_AXIS,) if self.sliced else ())
            + KFAC_AXES
            + ((SEQ_AXIS,) if SEQ_AXIS in mesh.axis_names else ()))
        # Batch-dim sharding axes (data_axes minus SEQ_AXIS, which
        # shards the sequence dim, not the batch dim).
        self.batch_axes = (((SLICE_AXIS,) if self.sliced else ())
                           + KFAC_AXES)
        self.data_size = int(np.prod([mesh.shape[a]
                                      for a in self.data_axes]))
        # Work placement spans the GLOBAL row space (slices x in-slice
        # inverse groups): assign_work is a pure function of
        # (specs/shapes, total rows, cols, flag), so a flat
        # ``total_rows``-row mesh and a sliced one produce the same
        # layer/bucket layout — the property the elastic reshard path's
        # slice-count changes rely on (elastic.topology.layout_key).
        self.assignment = assign_work(
            kfac, params, self.total_rows, self.n_cols,
            distribute_layer_factors=self.distribute_layer_factors)
        self._factor_dims = {
            name: L.factor_shapes(spec, _get(params, spec.path))
            for name, spec in kfac.specs.items()}
        self._precond_groups = self._plan_precond_groups()
        # Eigen-family dim buckets (exact eigen AND r19 low-rank) that
        # hold at least one *mixed* layer's side additionally carry a
        # firing-time-baked dense inverse stack (see
        # _spmd_update_inverses / KFAC.update_inverses for the
        # timing-semantics rationale).
        self._bucket_mixed = {
            dim: any(self._layer_is_mixed(name)
                     for (name, _w) in plan.slot)
            for dim, plan in self.assignment.buckets.items()
            if eigen_family(kfac.method_for_dim(dim))}
        # Pipelined inverse firing (inv_pipeline_chunks > 1): static
        # chunk plan over within-slice slot offsets; None at k == 1.
        self._chunk_plan = self._plan_firing_chunks()

    def _plan_firing_chunks(self) -> dict | None:
        """Static SPMD chunk plan for pipelined inverse firing.

        The SPMD work unit is one *within-slice slot offset* ``m`` of a
        dim bucket: every device decomposes the slot at its own
        ``col * slots_per_col + m`` position, so firing offset ``m``
        costs each device exactly one dim^3 decomposition and the
        in-group all_gather moves exactly the fired slots — per-device
        load (the spike the pipelining smears) splits in these units.
        Greedy LPT (``preconditioner.plan_inverse_chunks``, the same
        balancer as the single-chip per-matrix plan) packs the offsets
        plus the grouped/diagonal items into ``k`` chunks. Returns
        ``{'offsets': {dim: {chunk: (m, ...)}}, 'diag': {name: chunk},
        'grouped': {name: chunk}}``; ``None`` when the chunk-firing
        machinery is off (``k == 1`` without ``inv_staleness`` — at
        staleness=1 even ``k == 1`` builds a one-chunk plan so the
        whole firing can run mid-window from the frozen snapshot).
        """
        kfac = self.kfac
        k = kfac.inv_pipeline_chunks
        if not kfac.pipelined_firing:
            return None
        from distributed_kfac_pytorch_tpu.ops.linalg import (
            decomposition_cost,
        )
        from distributed_kfac_pytorch_tpu.preconditioner import (
            measured_unit_scale,
            plan_inverse_chunks,
        )
        measured = kfac.inv_pipeline_costs or {}
        # Same unit discipline as KFAC.inverse_chunk_items (shared
        # helper): a measurement dict must cover every bucket dim, and
        # the tiny grouped/diagonal proxy costs rescale into the
        # measured unit. The SPMD work unit is a slot offset, so the
        # per-dim unit count is slots_per_col.
        proxy_scale = measured_unit_scale(
            measured,
            {dim: plan.slots_per_col
             for dim, plan in self.assignment.buckets.items()},
            'inverse bucket dim of this mesh layout')
        items: list[tuple[tuple, float]] = []
        for dim in sorted(self.assignment.buckets):
            plan = self.assignment.buckets[dim]
            # r19: low-rank buckets fire at r·dim^2, not dim^3 (same
            # rank-aware model as the single-chip planner).
            unit = (float(measured[dim]) / plan.slots_per_col
                    if dim in measured
                    else decomposition_cost(
                        dim, rank=kfac.lowrank_rank_for(dim)))
            for m in range(plan.slots_per_col):
                items.append((('slot', dim, m), unit))
        for name in self.assignment.diag_layers:
            items.append((('diag', name),
                          proxy_scale
                          * float(self._factor_dims[name][0])))
        for name in self.assignment.grouped_layers:
            ng = kfac.specs[name].feature_group_count
            a_dim, g_dim = self._factor_dims[name]
            items.append((('grouped', name),
                          proxy_scale
                          * (ng * decomposition_cost(a_dim)
                             + ng * decomposition_cost(g_dim))))
        if k > len(items):
            raise ValueError(
                f'inv_pipeline_chunks={k} exceeds the {len(items)} '
                'inverse work items of this mesh layout (bucket slot '
                'offsets + grouped/diagonal layers); lower it to at '
                f'most {len(items)}')
        assignment = plan_inverse_chunks(items, k)
        offsets: dict[int, dict[int, tuple[int, ...]]] = {
            dim: {} for dim in self.assignment.buckets}
        diag: dict[str, int] = {}
        grouped: dict[str, int] = {}
        for key, j in assignment.items():
            if key[0] == 'slot':
                offsets[key[1]].setdefault(j, [])
                offsets[key[1]][j].append(key[2])
            elif key[0] == 'diag':
                diag[key[1]] = j
            else:
                grouped[key[1]] = j
        offsets = {dim: {j: tuple(sorted(ms))
                         for j, ms in per.items()}
                   for dim, per in offsets.items()}
        return {'offsets': offsets, 'diag': diag, 'grouped': grouped}

    def _layer_is_mixed(self, name: str) -> bool:
        """Dense layer with exactly one eigen-family side (an 'auto'
        straddle, or a low-rank side paired with a baked one)."""
        spec = self.kfac.specs[name]
        if spec.kind in (EMBEDDING, CONV2D_GROUPED):
            return False
        a_dim, g_dim = self._factor_dims[name]
        return (eigen_family(self.kfac.method_for_dim(a_dim))
                != eigen_family(self.kfac.method_for_dim(g_dim)))

    def _plan_precond_groups(self):
        """Static plan for the row-sharded precondition compute.

        Dense layers are grouped by gradient-matrix shape ``(g_dim,
        a_dim)`` (a vmap-able unit, like the factor buckets); within a
        group each inverse group's layers occupy contiguous slots
        ``row * S + k``, and a ``lax.switch`` over the static rows
        stacks exactly this device's own row's ``S`` grad matrices —
        the SPMD form of "only the grad workers compute" (reference
        preconditioner.py:577-585). ``a_idx`` / ``g_idx`` map each
        global slot to the layer's in-row slot inside the factor-dim
        bucket stacks, so inverse operands are one traced-index gather
        from this row's (local) inverse shard. Padding slots point at
        slot 0 (computed then never read back).
        """
        by_shape: dict[tuple[int, int], dict[int, list[str]]] = {}
        for name, spec in self.kfac.specs.items():
            if spec.kind in (EMBEDDING, CONV2D_GROUPED):
                continue  # diagonal A / block stacks: per-layer path
            a_dim, g_dim = self._factor_dims[name]
            rows = by_shape.setdefault((g_dim, a_dim), {})
            rows.setdefault(self.assignment.layer_row[name],
                            []).append(name)
        groups = []
        for (g_dim, a_dim), rows in by_shape.items():
            s = max(len(v) for v in rows.values())
            slot_of = {}
            a_idx = np.zeros(self.total_rows * s, np.int32)
            g_idx = np.zeros(self.total_rows * s, np.int32)
            for r, names in rows.items():
                for k, name in enumerate(names):
                    gslot = r * s + k
                    slot_of[name] = gslot
                    a_idx[gslot] = self.assignment.buckets[
                        a_dim].slot[(name, 'A')]
                    g_idx[gslot] = self.assignment.buckets[
                        g_dim].slot[(name, 'G')]
            groups.append({'shape': (g_dim, a_dim), 'S': s,
                           'slot_of': slot_of,
                           'a_idx': a_idx, 'g_idx': g_idx})
        return groups

    # -- state ---------------------------------------------------------

    def init_state(self, params) -> dict:
        """Fresh distributed K-FAC state pytree (global shapes).

        ``factors`` are replicated like the reference's post-allreduce
        factors; ``inv_stacks`` hold per-bucket eigendecompositions (or
        Cholesky inverses) sharded over inverse groups; ``diag_inv`` holds
        replicated diagonal inverses for embedding A factors.
        """
        base = self.kfac.init_state(params)
        idt = self.kfac.inv_dtype
        stacks = {}
        for dim, plan in self.assignment.buckets.items():
            n_slots = self.total_rows * plan.slots_per_row
            # Buckets are dim-homogeneous, so the per-dim dispatch
            # ('auto': eigen below the cutoff, damped inverse above,
            # r19 low-rank at/above the engaged threshold —
            # KFAC.method_for_dim) picks each bucket's representation
            # wholesale; global modes make every bucket the same.
            method = self.kfac.method_for_dim(dim)
            if eigen_family(method):
                # Identity bases / unit eigenvalues: the exact
                # eigendecomposition of the identity-seeded factors, and
                # a valid warm start for the eigh_method='auto' polish
                # from step 0 (see KFAC.init_state). Low-rank buckets
                # carry a RECTANGULAR (dim, r) identity-column basis —
                # orthonormal columns, valid for the subspace-refresh
                # + polish from step 0.
                r = (self.kfac.inv_lowrank_rank if method == 'lowrank'
                     else dim)
                stacks[str(dim)] = {
                    'Q': jnp.broadcast_to(jnp.eye(dim, r, dtype=idt),
                                          (n_slots, dim, r)),
                    'd': jnp.ones((n_slots, r), idt)}
                if self._bucket_mixed.get(dim):
                    # Baked per-side damped inverses for mixed layers'
                    # eigen-family sides (zero-seeded; step 0 fires
                    # first).
                    stacks[str(dim)]['inv'] = jnp.zeros(
                        (n_slots, dim, dim), idt)
            else:
                stacks[str(dim)] = {
                    'inv': jnp.zeros((n_slots, dim, dim), idt)}
        diag_inv = {}
        for name in self.assignment.diag_layers:
            a_dim = base['factors'][name]['A'].shape[0]
            diag_inv[name] = jnp.zeros((a_dim,), idt)
        # Grouped convs: replicated per-group block-inverse stacks (the
        # single-chip init already builds the right zero shapes).
        grouped_inv = {name: base['inverses'][name]
                       for name in self.assignment.grouped_layers}
        state = {'step': base['step'], 'factors': base['factors'],
                 'inv_stacks': stacks, 'diag_inv': diag_inv,
                 'grouped_inv': grouped_inv,
                 # Pipelined-firing position (next chunk due; constant 0
                 # under inv_pipeline_chunks=1) — see KFAC.init_state.
                 'inv_chunk_phase': base['inv_chunk_phase']}
        if self.kfac.deferred_factor_reduction \
                or self.kfac.hierarchical_reduce:
            # Per-DEVICE local accumulators (deferred reduce, r14):
            # each device folds its own un-reduced contributions, so
            # the leaves carry a leading device dim sharded over the
            # data axes (state_pspecs) — a replicated spec would
            # silently collapse device-varying values. The decay
            # product is identical on every device (replicated).
            # Hierarchical reduce (r20) accumulates SLICE-mean
            # contributions (post intra-slice pmean), identical within
            # a slice: the leading dim is the slice count, sharded
            # over the slice axis only.
            lead = (self.n_slices if self.kfac.hierarchical_reduce
                    else self.data_size)
            state['factor_accum'] = jax.tree.map(
                lambda x: jnp.zeros((lead,) + x.shape, x.dtype),
                base['factors'])
            state['accum_decay'] = jnp.ones((), jnp.float32)
        if self.kfac.inv_staleness:
            # Replicated window-head factor snapshot (post-reduce
            # factors are replicated like the factors themselves).
            state['frozen_factors'] = jax.tree.map(lambda x: x,
                                                   base['factors'])
        if self.kfac.collect_metrics:
            # Replicated on-device metrics scalars (the single-chip
            # slot; state_pspecs' default P() covers them).
            state['metrics'] = obs_metrics.init_metrics(
                self.kfac.metric_bucket_keys(params))
        return state

    def state_pspecs(self, state: dict) -> dict:
        """PartitionSpecs for a state pytree: stacks row-sharded, rest
        replicated."""
        specs = jax.tree.map(lambda _: P(), state)
        specs['inv_stacks'] = jax.tree.map(
            lambda _: P(self._row_axes), state['inv_stacks'])
        if 'factor_accum' in state:
            # Leading device dim sharded over every data-bearing axis:
            # each device owns exactly its own accumulator slice.
            # Hierarchical reduce: slice-mean accumulators, sharded
            # over the slice axis only (replicated within a slice).
            acc_axes = ((SLICE_AXIS,)
                        if self.kfac.hierarchical_reduce
                        else self.data_axes)
            specs['factor_accum'] = jax.tree.map(
                lambda _: P(acc_axes), state['factor_accum'])
        return specs

    def shard_state(self, state: dict) -> dict:
        """Device-put a host state pytree with its proper shardings."""
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            state, self.state_pspecs(state))

    # -- SPMD pipeline stages (call inside shard_map over self.mesh) ----

    def local_factor_contribs(self, captures) -> dict:
        """Per-layer local covariance contributions {name: {'A', 'G'}}.

        The device-local half of the factor update (reference
        compute_factors, preconditioner.py:566-575), split out so gradient
        accumulation can average contributions over micro-batches before
        the mesh ``pmean``.
        """
        cdt = self.kfac.factor_compute_dtype
        captures = subsample_captures(captures,
                                      self.kfac.factor_batch_fraction)
        fused_on = self.kfac.fused_contraction_active()
        interp = jax.default_backend() != 'tpu'
        out = {}
        for name, spec in self.kfac.specs.items():
            # r21 fused contraction: eligible sides run the packed
            # Pallas x.T@x kernel in contraction-only form (old=None,
            # decay=0 — the mesh pmean sits between contraction and
            # EMA here, so only the covariance itself fuses).
            fused = (self.kfac.fused_factor_inputs(spec, captures[name])
                     if fused_on else {})
            contrib = {}
            for side, stock in (
                    ('A', lambda: L.compute_a_factor(
                        spec, captures[name]['a'], compute_dtype=cdt)),
                    ('G', lambda: L.compute_g_factor(
                        spec, captures[name]['g'], compute_dtype=cdt))):
                if side in fused:
                    x, scale, has_bias = fused[side]
                    contrib[side] = pallas_kernels.fused_factor_ema(
                        x, None, 0.0, scale=scale, has_bias=has_bias,
                        compute_dtype=cdt, interpret=interp)
                else:
                    contrib[side] = stock()
            extras = L.compute_tied_factor_extras(spec, captures[name],
                                                  compute_dtype=cdt)
            if extras is not None:
                # Tied embedding (attend site): kept as SEPARATE parts
                # through accumulation/pmean because their world/accum
                # rescale differs — 'A_g2' is quadratic in the (local-
                # mean-loss) output grads like 'G'; 'G_a' is
                # activation-derived like 'A' (L.GRAD_QUADRATIC_KEYS).
                # _spmd_update_factors folds them in post-scale.
                contrib.update(extras)
            out[name] = contrib
        return out

    @profiling.scope('kfac/factors')
    def _spmd_update_factors(self, state, contribs, factor_decay):
        """Local covariance contributions, ``pmean``ed over the mesh.

        The analogue of compute_factors + allreduce_factors (reference
        preconditioner.py:566-575,525-533): each device contracts its batch
        shard, one pmean over both axes averages — equal local batch sizes
        make the mean exact.

        G normalization: local captures ``g`` come from the *local*-mean
        loss, so they are ``world_size`` times larger than global-mean-loss
        gradients; G is quadratic in g, hence the ``1 / world_size**2``.
        The reference skips this, making its G scale (and effective
        damping) depend on per-rank batch size — here factors are
        world-size-invariant, so single-device and distributed runs agree
        and hyperparameters transfer across world sizes.
        """
        kfac = self.kfac
        alpha = kfac.factor_decay if factor_decay is None else factor_decay
        g_scale = 1.0 / self.data_size ** 2

        def factor_pmean(m):
            """pmean of a symmetric factor; triangular-packed if enabled.

            Reference symmetry_aware_comm (kfac/layers/base.py:120-125):
            halves the bytes on the wire at the cost of the gather-free
            mask/concat pack+unpack (ops.factors.pack_symmetric).
            Embedding A factors are 1-D (already minimal).
            """
            with profiling.annotate('kfac/comm/factor_allreduce'):
                if kfac.symmetry_aware_comm and m.ndim == 2:
                    packed = jax.lax.pmean(F.pack_symmetric(m),
                                           self.data_axes)
                    return F.unpack_symmetric(packed, m.shape[-1])
                return jax.lax.pmean(m, self.data_axes)

        new_factors = {}
        for name in kfac.specs:
            a_new = factor_pmean(contribs[name]['A'])
            g_new = g_scale * factor_pmean(contribs[name]['G'])
            if 'A_g2' in contribs[name]:
                # Tied-embedding attend parts: the vocab-side diagonal
                # is grad-quadratic (g_scale corrects the local-mean-
                # loss blowup exactly like 'G'); the d-side input
                # covariance is activation-derived (no rescale, like
                # 'A'). See L.GRAD_QUADRATIC_KEYS.
                a_new = a_new + g_scale * factor_pmean(
                    contribs[name]['A_g2'])
                g_new = g_new + factor_pmean(contribs[name]['G_a'])
            old = state['factors'][name]
            new_factors[name] = {
                'A': F.update_running_avg(a_new.astype(old['A'].dtype),
                                          old['A'], alpha),
                'G': F.update_running_avg(g_new.astype(old['G'].dtype),
                                          old['G'], alpha)}
        return new_factors

    def _local_combined_contribs(self, contribs) -> dict:
        """World-scale one batch's local contributions into combined
        per-layer ``{'A', 'G'}`` parts.

        The scaling half of :meth:`_spmd_update_factors`, applied
        LOCALLY (every scale is a constant, so scaling before or after
        the mean is the same linear map): grad-quadratic parts ('G',
        tied 'A_g2' — ``L.GRAD_QUADRATIC_KEYS``) get the
        ``1/world**2`` local-mean-loss correction, activation parts
        ('A', 'G_a') none, and the tied extras fold into their sides.
        Feeds the deferred-reduction accumulator, whose boundary pmean
        then needs no per-key bookkeeping.
        """
        g_scale = 1.0 / self.data_size ** 2
        out = {}
        for name in self.kfac.specs:
            c = contribs[name]
            a_new = c['A']
            g_new = g_scale * c['G']
            if 'A_g2' in c:
                a_new = a_new + g_scale * c['A_g2']
                g_new = g_new + c['G_a']
            out[name] = {'A': a_new, 'G': g_new}
        return out

    @profiling.scope('kfac/factors')
    def _spmd_accumulate_factors(self, state, contribs, factor_decay,
                                 captures=None
                                 ) -> tuple[dict, jax.Array]:
        """Deferred-reduction factor step: fold this device's batch
        contribution into ITS slice of the accumulator — NO collective.

        The per-step factor ``pmean`` of the eager path
        (:meth:`_spmd_update_factors`) is exactly what this defers:
        ``acc ← α·acc + (1-α)·c_local`` and ``decay ← α·decay``
        per device; :meth:`_spmd_reduce_factors` pmeans the
        accumulators once per window. By linearity
        ``pmean(Σ w_i c_i) = Σ w_i pmean(c_i)``, so the boundary value
        matches the per-step recursion up to fp associativity
        (test-pinned). Returns ``(new_accum, new_decay)``; inside
        shard_map the accumulator leaves are this device's ``(1, ...)``
        slice of the sharded stack.

        ``captures``: this batch's raw local captures, when available
        (no micro-batch pre-accumulation). With the r21
        ``fused_factor_contraction`` knob engaged (and no r20
        intra-slice pmean in the way), eligible layer sides then fuse
        the contraction WITH this fold — ``acc ← α·acc + (1-α)·cov``
        runs in one VMEM-resident kernel, the r14 analogue of the
        single-chip fused EMA. The SPMD g-side ``1/world**2`` rescale
        folds into the kernel's covariance scale (it is a constant
        multiple of the contraction). Ineligible sides (and
        micro-batched ``contribs``-only calls) keep the stock fold.
        """
        kfac = self.kfac
        alpha = kfac.factor_decay if factor_decay is None else factor_decay
        fused_fold = (captures is not None
                      and not kfac.hierarchical_reduce
                      and kfac.fused_contraction_active())
        if fused_fold:
            captures_s = subsample_captures(captures,
                                            kfac.factor_batch_fraction)
        combined = self._local_combined_contribs(contribs)
        if kfac.hierarchical_reduce:
            # Hierarchical reduce (r20): the intra-slice half of the
            # factor reduction runs EVERY factor step on ICI — after
            # this pmean every device in a slice holds the slice-mean
            # contribution; the inter-slice half (slow DCN) is the
            # deferred window-boundary pmean over SLICE_AXIS only
            # (_spmd_reduce_factors). pmean_slices(pmean_intra(c)) ==
            # pmean_all(c) for uniform shard counts, so the boundary
            # value matches the flat reduce by the same EMA linearity.
            intra = tuple(a for a in self.data_axes if a != SLICE_AXIS)
            with profiling.annotate(
                    'kfac/comm/factor_allreduce_intra'):
                if kfac.symmetry_aware_comm:
                    combined = {
                        name: {k: (F.unpack_symmetric(
                                       jax.lax.pmean(
                                           F.pack_symmetric(v), intra),
                                       v.shape[-1])
                                   if v.ndim == 2
                                   else jax.lax.pmean(v, intra))
                               for k, v in entry.items()}
                        for name, entry in combined.items()}
                else:
                    combined = jax.lax.pmean(combined, intra)
        acc = state['factor_accum']
        cdt = kfac.factor_compute_dtype
        interp = jax.default_backend() != 'tpu'
        g_rescale = float(self.data_size) ** 2
        new_acc = {}
        for name in kfac.specs:
            spec = kfac.specs[name]
            old = acc[name]
            fused = (kfac.fused_factor_inputs(spec, captures_s[name])
                     if fused_fold else {})
            entry = {}
            for which in ('A', 'G'):
                if which in fused:
                    x, scale, has_bias = fused[which]
                    if which == 'G':
                        # combined G = (1/world**2) * cov(x, scale) —
                        # a constant multiple, so it folds into the
                        # kernel's covariance scale exactly.
                        scale = (scale if scale is not None
                                 else float(x.shape[0])) * g_rescale
                    entry[which] = pallas_kernels.fused_factor_ema(
                        x, old[which][0].astype(jnp.float32), alpha,
                        scale=scale, has_bias=has_bias,
                        compute_dtype=cdt, interpret=interp
                    ).astype(old[which].dtype)[None]
                else:
                    entry[which] = F.update_running_avg(
                        combined[name][which].astype(
                            old[which].dtype)[None],
                        old[which], alpha)
            new_acc[name] = entry
        return new_acc, alpha * state['accum_decay']

    @profiling.scope('kfac/factors')
    def _spmd_reduce_factors(self, state, acc, decay) -> dict:
        """Window-boundary deferred reduction: ONE bucketed pmean of
        the whole accumulator tree, then the EMA boundary update.

        This is the single collective that replaces the eager path's
        per-factor-step ``pmean`` (``kfac/comm/factor_reduce`` — the
        r14 overlap win's comm attribution scope). The tree is reduced
        in one ``lax.pmean`` call so XLA buckets the transfers;
        ``symmetry_aware_comm`` packs 2-D matrices before the wire
        exactly like the eager path's ``factor_pmean``.
        """
        kfac = self.kfac

        def pack(m):
            m = m[0]  # this device's slice of the sharded stack
            if kfac.symmetry_aware_comm and m.ndim == 2:
                return F.pack_symmetric(m)
            return m

        packed = {name: {k: pack(v) for k, v in entry.items()}
                  for name, entry in acc.items()}
        if kfac.hierarchical_reduce:
            # r20: the accumulators already hold slice means (the
            # intra-slice ICI pmean ran per factor step), so the
            # boundary collective crosses ONLY the slice axis — this
            # is the one DCN transfer of the whole factor pipeline,
            # attributed separately for the straggler wait buckets.
            with profiling.annotate('kfac/comm/factor_reduce_dcn'):
                reduced = jax.lax.pmean(packed, (SLICE_AXIS,))
        else:
            with profiling.annotate('kfac/comm/factor_reduce'):
                reduced = jax.lax.pmean(packed, self.data_axes)
        new_factors = {}
        for name in kfac.specs:
            old = state['factors'][name]
            entry = {}
            for which in ('A', 'G'):
                r = reduced[name][which]
                if kfac.symmetry_aware_comm and old[which].ndim == 2:
                    r = F.unpack_symmetric(r, old[which].shape[-1])
                entry[which] = (decay * old[which]
                                + r).astype(old[which].dtype)
            new_factors[name] = entry
        return new_factors

    def _build_bucket_stack(self, factors, plan: BucketPlan) -> jax.Array:
        """Replicated ``(n_rows * slots_per_row, dim, dim)`` factor stack.

        Unassigned (padding) slots hold the identity so the batched
        decomposition stays well-conditioned.
        """
        S = plan.slots_per_row
        mats: list[Any] = [None] * (self.total_rows * S)
        for (name, which), slot_idx in plan.slot.items():
            g = self.assignment.layer_row[name] * S + slot_idx
            mats[g] = factors[name][which].astype(jnp.float32)
        eye = jnp.eye(plan.dim, dtype=jnp.float32)
        return jnp.stack([eye if m is None else m for m in mats])

    def _build_bucket_substack(self, factors, plan: BucketPlan,
                               offs) -> jax.Array:
        """Fired-offsets-only factor stack for a partial chunk firing.

        A chunk that fires ``offs`` ⊂ [0, slots_per_col) of a bucket
        needs only those slots' matrices; stacking the whole bucket
        (``_build_bucket_stack``) would pay the full O(n_slots · dim²)
        assembly on every chunk phase — k× the monolithic build cost
        per window (measured as the dominant share of the pipelined
        legs' per-firing overhead on the CPU bench). Layout is
        ``[(row, col, m ∈ offs)]`` so a device's fired slots are the
        contiguous ``(row · n_cols + col) · len(offs)`` slice — the
        same dynamic_slice program shape as the whole-slice path.
        Across one window every slot is built exactly once, matching
        the monolithic firing's total assembly work.
        """
        S = plan.slots_per_row
        s = plan.slots_per_col
        by_global = {}
        for (name, which), slot_idx in plan.slot.items():
            g = self.assignment.layer_row[name] * S + slot_idx
            by_global[g] = factors[name][which]
        eye = jnp.eye(plan.dim, dtype=jnp.float32)
        mats = []
        for r in range(self.total_rows):
            for c in range(self.n_cols):
                for m in offs:
                    mat = by_global.get(r * S + c * s + int(m))
                    mats.append(eye if mat is None
                                else mat.astype(jnp.float32))
        return jnp.stack(mats)

    @profiling.scope('kfac/inverses')
    def _spmd_update_inverses(self, factors, damping, prev_stacks=None,
                              chunk: int | None = None,
                              prev_diag=None, prev_grouped=None):
        """Sharded batched inverse computation + in-group all_gather.

        Each device decomposes its ``slots_per_col`` slice of its row's
        stack (``lax.dynamic_slice`` at a device-dependent offset — the
        SPMD form of "only the assigned rank computes",
        reference kfac/layers/base.py:249,294), then an ``all_gather``
        over ``kfac_gw`` reassembles the row's full inverse stack.

        ``prev_stacks``: the state's previous inverse stacks. On the
        eigen path they hold each slot's previous eigenbasis — this
        device slices *its own slots'* bases (the stacks are
        ``kfac_ig``-sharded and slot layout is static, so the slice
        aligns with the factors being decomposed) and runs the
        warm-start polish instead of a cold eigh (eigh_method 'auto').

        ``chunk``: pipelined firing — decompose only the slot offsets /
        diag / grouped items ``_plan_firing_chunks`` assigns to this
        chunk, passing everything else through from ``prev_stacks`` /
        ``prev_diag`` / ``prev_grouped`` unchanged (local row shards
        in, local row shards out). A bucket whose offsets are split
        across chunks fires partially: each device decomposes only its
        fired slots (a static-offset gather), the in-group all_gather
        moves only those slots, and the results scatter into the
        stored stack at static indices — no collective ever touches a
        non-fired slot, so the amortized COMM_OPT gather shrinks by
        exactly the chunk fraction.

        Scope of the per-chunk-group program shape: it applies to the
        in-run firing path (``prev_stacks`` present), where it makes a
        frozen-factor pipelined window bit-identical to a monolithic
        firing WITHIN this SPMD path. The eager rebuild
        (``prev_stacks=None`` — ``recompute_inverses`` after a
        factor-only/layout-mismatch restore) keeps the historical
        whole-slice program even at ``inv_pipeline_chunks > 1``: there
        are no stored shards to merge into, and no bitwise contract
        spans a rebuild — a rebuilt basis differs from the in-run one
        by the same slice-instability ulps regardless (single-chip vs
        SPMD were never bitwise-comparable either; their stacks batch
        different layer sets by construction). Each slot is simply
        overwritten next time its chunk fires.
        """
        kfac = self.kfac
        chunk_plan = self._chunk_plan
        row = self._global_row()
        col = jax.lax.axis_index(GRAD_WORKER_AXIS)
        eigh_method = resolve_eigh_method(kfac.eigh_method)
        stacks = {}
        for dim, plan in self.assignment.buckets.items():
            s = plan.slots_per_col
            # Offset groups to fire this call. Pipelined mode (k > 1)
            # ALWAYS decomposes per chunk group — a monolithic firing
            # runs every group, a chunk firing exactly one — so the
            # per-slot computation is the same trace fragment either
            # way and the frozen-window bit-identity contract is
            # structural (the backend's batched kernels are NOT
            # slice-stable across batch sizes: a different vmap width
            # rotates Q by O(1) within near-degenerate clusters,
            # observed on CPU). The eager rebuild path (no prev stacks
            # to merge into, ``recompute_inverses``) and k == 1 keep
            # the historical whole-slice program.
            if chunk_plan is None or prev_stacks is None:
                groups = [None] if chunk is None else None
            else:
                per = chunk_plan['offsets'][dim]
                if chunk is not None:
                    fired = per.get(chunk, ())
                    groups = [fired] if fired else []
                else:
                    groups = [per[j] for j in sorted(per)]
            if groups is None:
                raise ValueError(
                    'inv_chunk requires inv_pipeline_chunks > 1 and '
                    'stored inverse stacks')
            if not groups:
                # Not this chunk's work: the stored (row-local) stack
                # passes through untouched — no decomposition, no
                # in-group all_gather.
                stacks[str(dim)] = prev_stacks[str(dim)]
                continue
            # The whole-bucket stack is built ONLY for whole-slice
            # groups (the historical program shape); partial groups
            # assemble just their fired slots (_build_bucket_substack),
            # so a window's k chunk firings pay the monolithic firing's
            # total assembly cost, not k times it.
            full = (self._build_bucket_stack(factors, plan)
                    if any(g is None or len(g) == s for g in groups)
                    else None)
            bucket_method = kfac.method_for_dim(dim)
            prev_entry = (prev_stacks[str(dim)]
                          if prev_stacks is not None else None)
            # A group of all s offsets is the whole contiguous slice —
            # encode as offs=None (dynamic_slice + full replace, the
            # historical program shape).
            cur = dict(prev_entry) if prev_entry is not None else {}
            for group in groups:
                offs = (None if group is None or len(group) == s
                        else np.asarray(group, np.int32))

                def fired_factors(offs=offs):
                    """This device's fired factor matrices (contiguous
                    dynamic_slice of the whole-bucket stack for a
                    whole-slice group, or of the fired-only substack
                    when partial)."""
                    if offs is None:
                        return jax.lax.dynamic_slice(
                            full,
                            (row * plan.slots_per_row + col * s, 0, 0),
                            (s, plan.dim, plan.dim))
                    sub = self._build_bucket_substack(
                        factors, plan, offs)
                    u = len(offs)
                    return jax.lax.dynamic_slice(
                        sub, ((row * self.n_cols + col) * u, 0, 0),
                        (u, plan.dim, plan.dim))

                def local_slots(src, offs=offs):
                    """This device's fired slots of a ROW-LOCAL stored
                    stack (contiguous slice for a whole-slice group;
                    static-offset gather when partial)."""
                    base = col * s
                    if offs is None:
                        start = (base,) + (0,) * (src.ndim - 1)
                        return jax.lax.dynamic_slice(
                            src, start, (s,) + src.shape[1:])
                    return jnp.take(src, base + jnp.asarray(offs),
                                    axis=0)

                def merge(computed, key, offs=offs):
                    """all_gather this group's slots over the grad
                    workers and merge into the stored row stack (full
                    replace for a whole-slice group; static-index
                    scatter when partial)."""
                    with profiling.annotate(
                            'kfac/comm/inverse_allgather'):
                        g = jax.lax.all_gather(
                            computed, GRAD_WORKER_AXIS, tiled=True)
                    g = g.astype(kfac.inv_dtype)
                    if offs is None:
                        cur[key] = g
                        return
                    # Gathered layout: col c's fired slots sit at
                    # g[c*u:(c+1)*u] — their in-row slot indices
                    # c*s + offs are static, so the merge is one
                    # static scatter into the stored shard.
                    idx = np.concatenate(
                        [c * s + offs for c in range(self.n_cols)])
                    cur[key] = cur[key].at[idx].set(g)

                local = fired_factors()
                if eigen_family(bucket_method):
                    q_prev = None
                    if prev_entry is not None and (
                            bucket_method == 'lowrank'
                            or eigh_method == 'auto'):
                        # Inside shard_map the stored stack is the
                        # *local* row shard (slots_per_row, dim, dim):
                        # index by the in-row column offset only
                        # (local_slots does). Low-rank warm starts are
                        # NOT gated on eigh_method — the carried
                        # truncated basis IS the low-rank state.
                        q_prev = local_slots(
                            prev_entry['Q'].astype(jnp.float32))
                    if bucket_method == 'lowrank':
                        q, d = linalg.batched_lowrank_eigh(
                            local, kfac.inv_lowrank_rank,
                            q_prev=q_prev,
                            polish_iters=kfac.eigh_polish_iters)
                    else:
                        q, d = linalg.batched_eigh(
                            local, eigh_method, clip=0.0,
                            q_prev=q_prev,
                            polish_iters=kfac.eigh_polish_iters)
                    if self._bucket_mixed.get(dim):
                        # Bake this firing's damping into the mixed
                        # layers' eigen sides (whole group for vmap
                        # uniformity — the extra d^3 per pure-eigen
                        # slot is noise next to the polish). Same λ as
                        # the baked big-side inverses: the split
                        # operator stays symmetric under damping
                        # schedules.
                        inv = jax.vmap(
                            lambda qi, di: linalg.eigen_side_inverse(
                                qi, di, damping))(q, d)
                        merge(inv, 'inv')
                    merge(q, 'Q')
                    merge(d, 'd')
                else:
                    inv = pallas_kernels.damped_inverse_stack(
                        local, damping, bucket_method,
                        iters=kfac.newton_iters)
                    merge(inv, 'inv')
            stacks[str(dim)] = cur
        diag_inv = {}
        for name in self.assignment.diag_layers:
            if chunk is not None and \
                    chunk_plan['diag'][name] != chunk:
                diag_inv[name] = prev_diag[name]
                continue
            diag_inv[name] = linalg.get_elementwise_inverse(
                factors[name]['A'].astype(jnp.float32),
                damping=damping).astype(kfac.inv_dtype)
        # Replicated per-group block inverses (tiny blocks — replicating
        # beats any sharding bookkeeping); shared helper with the
        # single-chip path so the two cannot drift.
        grouped_inv = {
            name: (prev_grouped[name]
                   if chunk is not None
                   and chunk_plan['grouped'][name] != chunk
                   else grouped_block_inverses(factors[name], damping,
                                               kfac.inv_dtype))
            for name in self.assignment.grouped_layers}
        return stacks, diag_inv, grouped_inv

    def _global_row(self):
        """This device's GLOBAL inverse-row index (traced scalar).

        Flat mesh: the inverse-group axis index. Multi-slice: slices
        hold contiguous runs of ``n_rows`` rows, matching the
        ``P((SLICE_AXIS, INV_GROUP_AXIS))`` sharding of the stacks —
        no inverse-bearing collective ever crosses the slice axis, so
        the index arithmetic is the only place slices appear in the
        inverse pipeline.
        """
        row = jax.lax.axis_index(INV_GROUP_AXIS)
        if self.sliced:
            row = jax.lax.axis_index(SLICE_AXIS) * self.n_rows + row
        return row

    def _layer_inverses(self, inv_stacks, name: str) -> dict:
        """This device's (row-local) inverse views for one layer.

        Static slot indices are identical across devices (SPMD); rows that
        do not own the layer read a different layer's slot — their result
        is masked to zero before the ``psum`` in ``_spmd_precondition``.
        """
        kfac = self.kfac
        spec = kfac.specs[name]
        a_dim, g_dim = self._shape_of(name)
        # Mixed layers read their eigen side's firing-time-baked dense
        # inverse (same λ as the baked big side); pure-eigen layers
        # read Q/d for the joint-damping formula.
        mixed = self._layer_is_mixed(name)
        out = {}
        if spec.kind != EMBEDDING:
            plan = self.assignment.buckets[a_dim]
            sl = plan.slot[(name, 'A')]
            if eigen_family(kfac.method_for_dim(a_dim)) and not mixed:
                out['QA'] = inv_stacks[str(a_dim)]['Q'][sl]
                out['dA'] = inv_stacks[str(a_dim)]['d'][sl]
            else:
                out['A_inv'] = inv_stacks[str(a_dim)]['inv'][sl]
        plan = self.assignment.buckets[g_dim]
        sl = plan.slot[(name, 'G')]
        if eigen_family(kfac.method_for_dim(g_dim)) and not mixed:
            out['QG'] = inv_stacks[str(g_dim)]['Q'][sl]
            out['dG'] = inv_stacks[str(g_dim)]['d'][sl]
        else:
            out['G_inv'] = inv_stacks[str(g_dim)]['inv'][sl]
        return out

    def _shape_of(self, name):
        return self._factor_dims[name]

    def _rowsharded_precond_mats(self, inv_stacks, grad_mats, damping,
                                 row) -> tuple[dict, dict]:
        """Row-masked preconditioned mats, computing only this row's
        layers (KAISA grad-worker compute semantics, reference
        preconditioner.py:577-585).

        Per shape group (see :meth:`_plan_precond_groups`): a
        ``lax.switch`` over the static rows stacks exactly this row's
        ``S`` grad matrices, gathers the matching inverse operands from
        the row-local factor stacks by traced slot index, and runs ONE
        vmapped :func:`linalg.precondition_dispatch` over the slice —
        1/n_rows of the replicate-and-mask path's matmul FLOPs. The
        output assembly reuses the same aliased-read + ownership-mask
        trick as :meth:`_layer_inverses`: position ``k`` of the local
        result holds a *different* layer on every row, and the mask
        keeps exactly the owner's value for the delivery ``psum``.

        Returns ``(mats, vg)``: ``vg`` holds the r21 fused kernel's
        row-masked KL-clip partials ``sum(v * g)`` (fp32,
        pre-``lr**2``) for the layers whose group ran
        :func:`pallas_kernels.fused_bucket_precondition` — empty on the
        stock path. The partials carry the same ownership mask as the
        mats, so the caller's existing ``psum`` assembles the global
        clip scale unchanged.
        """
        kfac = self.kfac
        fused_on = kfac.fused_precond_active()
        interp = jax.default_backend() != 'tpu'
        out = {}
        vg_out = {}
        for grp in self._precond_groups:
            g_dim, a_dim = grp['shape']
            s = grp['S']
            slot_name = {gslot: name
                         for name, gslot in grp['slot_of'].items()}

            # lax.switch over the (static) rows: each branch stacks only
            # ITS row's S grad matrices (+ zero padding) and carries the
            # row's inverse slot indices as constants — the full
            # (n_rows*S, g, a) stack is never written, so the stack
            # traffic is 1/n_rows of the dynamic-slice-of-everything
            # form (round-4 review finding). XLA compiles all branches,
            # executes one.
            def make_branch(r):
                def branch():
                    mats = [
                        (grad_mats[slot_name[r * s + k]]
                         .astype(jnp.float32)
                         if (r * s + k) in slot_name
                         else jnp.zeros((g_dim, a_dim), jnp.float32))
                        for k in range(s)]
                    return (jnp.stack(mats),
                            jnp.asarray(grp['a_idx'][r * s:(r + 1) * s]),
                            jnp.asarray(grp['g_idx'][r * s:(r + 1) * s]))
                return branch

            local, my_a, my_g = jax.lax.switch(
                row, [make_branch(r) for r in range(self.total_rows)])
            # Mixed-ness is uniform per group (a function of the dim
            # pair): split groups gather baked inverses for both sides.
            # Eigen-family covers the r19 low-rank buckets too — their
            # rectangular Q/d gather exactly the same way (the group's
            # rank is uniform because its dims are).
            a_eig = eigen_family(kfac.method_for_dim(a_dim))
            g_eig = eigen_family(kfac.method_for_dim(g_dim))
            entry = {}
            if a_eig and g_eig:
                entry['QA'] = inv_stacks[str(a_dim)]['Q'][my_a]
                entry['dA'] = inv_stacks[str(a_dim)]['d'][my_a]
                entry['QG'] = inv_stacks[str(g_dim)]['Q'][my_g]
                entry['dG'] = inv_stacks[str(g_dim)]['d'][my_g]
            else:
                entry['A_inv'] = inv_stacks[str(a_dim)]['inv'][my_a]
                entry['G_inv'] = inv_stacks[str(g_dim)]['inv'][my_g]
            if fused_on and _fused_bucket_ok(entry):
                vs, vgs = pallas_kernels.fused_bucket_precondition(
                    local, entry, damping,
                    compute_dtype=kfac.precond_compute_dtype,
                    interpret=interp)
                for name, gslot in grp['slot_of'].items():
                    mask = (row == self.assignment.layer_row[name]
                            ).astype(vs.dtype)
                    out[name] = vs[gslot % s] * mask
                    vg_out[name] = vgs[gslot % s] * mask
                continue
            vs = jax.vmap(
                lambda gm, e: linalg.precondition_dispatch(
                    gm, e, damping,
                    compute_dtype=kfac.precond_compute_dtype))(
                local, entry)
            for name, gslot in grp['slot_of'].items():
                mask = (row == self.assignment.layer_row[name]).astype(
                    vs.dtype)
                out[name] = vs[gslot % s] * mask
        return out, vg_out

    @profiling.scope('kfac/precond')
    def _spmd_precondition(self, inv_stacks, diag_inv, grouped_inv,
                           grads, damping, lr, with_stats: bool = False,
                           gates: dict | None = None):
        """Row-masked preconditioning + one ``psum`` gradient broadcast.

        Every member of a layer's inverse group computes its preconditioned
        gradient redundantly (KAISA's compute/comm tradeoff — the
        reference's grad workers, preconditioner.py:577-585); other rows
        produce zeros, and ``psum`` over ``kfac_ig`` is exactly the
        strided-group gradient broadcast (reference base.py:173-196).
        The KL-clip factor is assembled the same way: row-partial ``v·g``
        sums, ``psum``ed, so the scale matches the single-device path
        bit-for-bit in structure (reference preconditioner.py:661-682).

        ``gates`` (r16 self-healing quarantine): per-shape-bucket 0/1
        traced scalars — a gated-off bucket's layers serve the RAW
        gradient (plain SGD direction). The blend happens on the
        row-masked per-layer mats BEFORE the KL-clip and delivery
        ``psum`` (the SGD fallback carries the same owner-row mask, so
        the psum still sums exactly one contribution and the clip sees
        the blended ``v·g``); replicated scalar gates keep the select
        identical on every device. ``None`` = the bit-identical
        historical path (see ``KFAC.precondition``).
        """
        kfac = self.kfac
        row = self._global_row()
        grad_mats = {
            name: L.grads_to_matrix(spec, _get(grads, spec.path))
            for name, spec in kfac.specs.items()}
        # Bucketed batched precondition matmuls on every mesh shape:
        # with n_rows > 1 each row computes only its own layers (KAISA
        # compute sharding); at n_rows == 1 (COMM_OPT) the same path
        # degenerates to a pure same-shape batching — one vmapped
        # matmul per shape group instead of a per-layer dispatch, the
        # replicated-path analogue of the single-chip
        # KFAC._bucketed_precond_mats.
        sharded = self.shard_precond_compute
        if sharded:
            precond_mats, fused_vg = self._rowsharded_precond_mats(
                inv_stacks, grad_mats, damping, row)
        else:
            precond_mats, fused_vg = {}, {}
        for name, spec in kfac.specs.items():
            if name in precond_mats:
                continue  # computed by the row-sharded path
            if spec.kind == CONV2D_GROUPED:
                # Replicated block-stack inverses; batched
                # G_inv @ grad @ A_inv broadcasts over the group dim.
                # Masked to the owning row like every per-layer path so
                # the delivery psum stays a sum of one contribution.
                inv = grouped_inv[name]
            else:
                inv = self._layer_inverses(inv_stacks, name)
            # Same four-way per-side dispatch as the single-chip path
            # (linalg.precondition_dispatch) so 'auto' mixed-method
            # layers cannot drift between the two.
            v = linalg.precondition_dispatch(
                grad_mats[name], inv, damping,
                diag_a=(diag_inv[name] if spec.kind == EMBEDDING
                        else None),
                compute_dtype=kfac.precond_compute_dtype)
            mask = (row == self.assignment.layer_row[name]).astype(v.dtype)
            precond_mats[name] = v * mask

        if gates is not None:
            # Quarantine blend (r16): row-masked SGD fallback so the
            # delivery psum still sums one owner contribution; where is
            # a select, so a poisoned (NaN) preconditioned branch does
            # not propagate into the blended output.
            for name in precond_mats:
                g = gates.get(obs_metrics.shape_key(
                    grad_mats[name].shape))
                if g is None:
                    continue
                pm = precond_mats[name]
                own = (row == self.assignment.layer_row[name]).astype(
                    pm.dtype)
                precond_mats[name] = jnp.where(
                    jnp.asarray(g, jnp.float32) >= 0.5, pm,
                    grad_mats[name].astype(pm.dtype) * own)

        if kfac.kl_clip is not None:
            # r21 fused buckets already reduced their row-masked v·g
            # partial in the kernel epilogue; the per-layer scalars
            # join the sum in the same registration order and ride the
            # same psum. The r16 gate blend rewrites the mats after the
            # buckets ran, so gated runs keep the full-tensor
            # reduction (the fused partial would be stale).
            vg_sum = jnp.zeros((), jnp.float32)
            for name in precond_mats:
                if gates is None and name in fused_vg:
                    vg_sum += fused_vg[name] * lr ** 2
                else:
                    vg_sum += jnp.sum(precond_mats[name] *
                                      grad_mats[name].astype(jnp.float32)
                                      * lr ** 2)
            with profiling.annotate('kfac/comm/klclip_psum'):
                vg_sum = jax.lax.psum(vg_sum, self._row_axes)
            nu = jnp.minimum(
                1.0, jnp.sqrt(kfac.kl_clip / (jnp.abs(vg_sum) + 1e-30)))
        else:
            nu = jnp.ones((), jnp.float32)

        with profiling.annotate('kfac/comm/grad_psum'):
            # The delivery broadcast spans the whole row space — on a
            # multi-slice mesh this is the ONE collective of the
            # inverse/precondition pipeline that crosses the DCN
            # (gradients, not factors or inverses, ride the slow
            # interconnect — arXiv:2206.15143's placement rule).
            precond_mats = jax.lax.psum(precond_mats, self._row_axes)

        # Stats AFTER the delivery psum: every device sees the full
        # preconditioned matrices, so the norms are replicated scalars.
        stats = (obs_metrics.precond_stats(grad_mats, precond_mats, nu)
                 if with_stats else None)
        out = jax.tree.map(lambda x: x, grads)
        for name, spec in kfac.specs.items():
            sub = _get(grads, spec.path)
            new_sub = L.matrix_to_grads(
                spec, (nu * precond_mats[name]).astype(jnp.float32), sub)
            out = _set(out, spec.path, jax.tree.map(
                lambda n, o: n.astype(o.dtype), new_sub, sub))
        return (out, stats) if with_stats else out

    # -- the step -------------------------------------------------------

    def spmd_step(self, state: dict, grads: dict, captures: dict = None, *,
                  contribs: dict = None,
                  damping=None, lr=None, factor_decay=None,
                  factor_update_freq=None, inv_update_freq=None,
                  factor_update: bool | None = None,
                  inv_update: bool | None = None,
                  inv_chunk: int | None = None,
                  factor_reduce: bool = False,
                  factor_snapshot: bool = False,
                  gates: dict | None = None) -> tuple[dict, dict]:
        """One distributed K-FAC update; call inside ``shard_map``.

        Same contract and cadence semantics as :meth:`KFAC.step`
        (reference preconditioner.py:472-523): ``grads`` must be the
        already-averaged global gradients (reference's DDP contract,
        preconditioner.py:479-482); ``captures`` are this device's *local*
        batch shard captures — factor statistics are averaged globally
        inside (the subtle pre-psum/post-psum contract from SURVEY §7).

        ``contribs`` may be passed instead of ``captures``: precomputed
        local factor contributions (from :meth:`local_factor_contribs`),
        e.g. averaged over gradient-accumulation micro-batches (the
        analogue of the reference's ``accumulate_data`` path,
        kfac/layers/base.py:364-379).

        ``factor_update`` / ``inv_update``: static cadence gating — see
        :meth:`KFAC.step`. ``None`` keeps the dynamic ``lax.cond`` form;
        Python bools bake the schedule into the trace (the fast path on
        TPU — a cond whose branch holds the decompositions costs 10-18x
        in XLA layout/copy pathologies around it, measured on v5e).

        ``inv_chunk``: pipelined inverse firing (static, mutually
        exclusive with ``inv_update=True``): recompute only chunk
        ``j``'s buckets this step, pass the rest of the (row-sharded)
        stacks through untouched — see :meth:`KFAC.step` and
        :meth:`_spmd_update_inverses`.

        ``factor_reduce`` / ``factor_snapshot``: the r14 overlap flags
        (deferred window-boundary factor reduction / frozen-snapshot
        refresh) — static-cadence only, same contract as
        :meth:`KFAC.step`.

        ``gates``: per-shape-bucket quarantine mask (r16 self-healing,
        traced scalar values) — see :meth:`_spmd_precondition`;
        ``None`` (default) keeps the historical program bit-identical.
        """
        kfac = self.kfac
        damping = kfac.damping if damping is None else damping
        lr = kfac.lr if lr is None else lr
        f_freq = (kfac.factor_update_freq if factor_update_freq is None
                  else factor_update_freq)
        i_freq = (kfac.inv_update_freq if inv_update_freq is None
                  else inv_update_freq)
        step = state['step']
        if contribs is None and captures is None:
            raise ValueError('pass captures or contribs')

        def do_factors():
            # Contraction stays inside the gated path: covariance work
            # only runs on factor-update steps.
            return self._spmd_update_factors(
                state,
                (contribs if contribs is not None
                 else self.local_factor_contribs(captures)),
                factor_decay)

        track = kfac.collect_metrics or kfac.nonfinite_guard
        overlap_state = {}
        if kfac.deferred_factor_reduction or kfac.hierarchical_reduce:
            # Deferred reduce (r14): factor steps fold into this
            # device's local accumulator slice — no collective; the
            # window-boundary reduce step pays ONE bucketed pmean.
            # Hierarchical reduce (r20) shares the window machinery:
            # factor steps additionally pmean intra-slice on ICI, and
            # the boundary pmean crosses only the slice axis (DCN).
            # Static cadence only (the reduce is program structure).
            if factor_update is None:
                raise ValueError(
                    'deferred_factor_reduction / hierarchical_reduce '
                    'require static cadence '
                    'flags (Python-bool factor_update/factor_reduce) — '
                    'the window-boundary reduce is static program '
                    'structure, like inv_chunk')
            acc, decay = state['factor_accum'], state['accum_decay']
            if factor_update:
                acc, decay = self._spmd_accumulate_factors(
                    state,
                    (contribs if contribs is not None
                     else self.local_factor_contribs(captures)),
                    factor_decay,
                    captures=(captures if contribs is None else None))
            if factor_reduce:
                candidate = self._spmd_reduce_factors(state, acc, decay)
                # Post-pmean candidate check: collective-safe (every
                # device sees the same averaged values), exactly like
                # the eager path's guard — moved to the reduce point.
                factors, finite_f = guard_nonfinite_factors(
                    candidate, state['factors'], kfac.nonfinite_guard)
                acc = jax.tree.map(jnp.zeros_like, acc)
                decay = jnp.ones((), jnp.float32)
            else:
                factors = state['factors']
                finite_f = jnp.ones((), jnp.int32)
            overlap_state['factor_accum'] = acc
            overlap_state['accum_decay'] = decay
        else:
            if factor_reduce:
                raise ValueError(
                    'factor_reduce requires '
                    'deferred_factor_reduction=True or '
                    'hierarchical_reduce=True')
            if track:
                # Tracked form: finiteness of the candidate factors
                # rides out of the gate (guard skip + metrics count);
                # semantics shared with the single-chip step via
                # preconditioner.guard_nonfinite_factors.
                def do_factors_tracked():
                    return guard_nonfinite_factors(
                        do_factors(), state['factors'],
                        kfac.nonfinite_guard)

                factors, finite_f = cadence_gate(
                    factor_update, step, f_freq, do_factors_tracked,
                    lambda: (state['factors'], jnp.ones((), jnp.int32)))
            else:
                # Metrics/guard off: the historical program, untouched.
                factors = cadence_gate(factor_update, step, f_freq,
                                       do_factors,
                                       lambda: state['factors'])
        if kfac.inv_staleness:
            if inv_update is None:
                raise ValueError(
                    'inv_staleness=1 requires static cadence flags '
                    '(the frozen-snapshot firing schedule is static '
                    'program structure, like inv_chunk)')
            # Window heads (and monolithic firings — the step-0
            # warmup) refresh the snapshot from this step's
            # post-update factors; in-window chunk firings decompose
            # the carried one, breaking the data dependency on this
            # step's forward/backward/factor work.
            frozen = (factors if factor_snapshot or inv_update
                      else state['frozen_factors'])
            overlap_state['frozen_factors'] = frozen
            fire_factors = frozen
        else:
            if factor_snapshot:
                raise ValueError(
                    'factor_snapshot requires inv_staleness=1')
            fire_factors = factors
        if inv_chunk is not None:
            k = kfac.inv_pipeline_chunks
            if inv_update:
                raise ValueError(
                    'inv_chunk is mutually exclusive with '
                    'inv_update=True (a monolithic firing already '
                    'covers every chunk)')
            if not 0 <= inv_chunk < k:
                raise ValueError(
                    f'{inv_chunk=} out of range for '
                    f'inv_pipeline_chunks={k}')
            with profiling.annotate(f'kfac/inverse/chunk{inv_chunk}'):
                inv_stacks, diag_inv, grouped_inv = (
                    self._spmd_update_inverses(
                        fire_factors, damping,
                        prev_stacks=state['inv_stacks'],
                        chunk=inv_chunk,
                        prev_diag=state['diag_inv'],
                        prev_grouped=state.get('grouped_inv', {})))
            chunk_phase = jnp.asarray((inv_chunk + 1) % k, jnp.int32)
        else:
            inv_stacks, diag_inv, grouped_inv = cadence_gate(
                inv_update, step, i_freq,
                lambda: self._spmd_update_inverses(
                    fire_factors, damping,
                    prev_stacks=state['inv_stacks']),
                lambda: (state['inv_stacks'], state['diag_inv'],
                         state.get('grouped_inv', {})))
            chunk_phase = (jnp.zeros((), jnp.int32) if inv_update
                           else state['inv_chunk_phase'])

        if not kfac.collect_metrics:
            precond = self._spmd_precondition(
                inv_stacks, diag_inv, grouped_inv, grads, damping, lr,
                gates=gates)
            new_state = {'step': step + 1, 'factors': factors,
                         'inv_stacks': inv_stacks, 'diag_inv': diag_inv,
                         'grouped_inv': grouped_inv,
                         'inv_chunk_phase': chunk_phase,
                         **overlap_state}
            return precond, new_state

        precond, stats = self._spmd_precondition(
            inv_stacks, diag_inv, grouped_inv, grads, damping, lr,
            with_stats=True, gates=gates)
        one = lambda: jnp.ones((), jnp.int32)
        zero = lambda: jnp.zeros((), jnp.int32)
        did_f = cadence_gate(factor_update, step, f_freq, one, zero)
        did_i = (zero() if inv_chunk is not None
                 else cadence_gate(inv_update, step, i_freq, one, zero))
        did_c = one() if inv_chunk is not None else zero()
        # Row-local clip counts summed over inverse groups: each row's
        # stacks hold only its own layers' spectra (columns agree after
        # the in-group all_gather), so one psum yields the global count.
        eig_clipped = jax.lax.psum(
            obs_metrics.count_clipped_eigvals_stacks(inv_stacks),
            self._row_axes)
        new_state = {'step': step + 1, 'factors': factors,
                     'inv_stacks': inv_stacks, 'diag_inv': diag_inv,
                     'grouped_inv': grouped_inv,
                     'inv_chunk_phase': chunk_phase,
                     **overlap_state,
                     'metrics': obs_metrics.update_metrics(
                         state['metrics'], damping=damping, stats=stats,
                         did_factor=did_f, did_inv=did_i,
                         did_chunk=did_c,
                         factor_finite=finite_f,
                         eig_clipped=eig_clipped)}
        return precond, new_state

    # -- checkpointing --------------------------------------------------

    def state_dict(self, state: dict, include_inverses: bool = True
                   ) -> dict:
        """Checkpointable state: step + factors (+ inverse stacks).

        Unlike the reference (which recomputes inverses on load and
        refuses to save them under MEM_OPT, preconditioner.py:294-353),
        inverse stacks default to *included*: orbax writes each device's
        shard, so no rank pays for the whole stack and resume needs no
        recompute. Pass ``include_inverses=False`` for reference-style
        factor-only checkpoints, then call :meth:`recompute_inverses`
        after restoring.
        """
        out = {'step': state['step'], 'factors': state['factors'],
               'inv_chunk_phase': state.get(
                   'inv_chunk_phase', jnp.zeros((), jnp.int32))}
        # r14 overlap state (deferred accumulators are device-sharded;
        # orbax writes each device's slice): present only when the
        # knobs are on — default checkpoints keep the historical
        # layout (MIGRATION.md).
        for key in ('factor_accum', 'accum_decay', 'frozen_factors'):
            if key in state:
                out[key] = state[key]
        if include_inverses:
            out['inv_stacks'] = state['inv_stacks']
            out['diag_inv'] = state['diag_inv']
            out['grouped_inv'] = state.get('grouped_inv', {})
        return out

    def load_state_dict(self, sd: dict, params, *,
                        damping: float | None = None) -> dict:
        """Rebuild full distributed state from a checkpoint tree.

        Validates layer congruence (reference preconditioner.py:334-336)
        and recomputes inverses from factors when they were not saved.
        """
        state = self.init_state(params)
        if set(sd['factors']) != set(state['factors']):
            raise ValueError(
                'checkpoint layers do not match registered layers: '
                f'{sorted(sd["factors"])} vs {sorted(state["factors"])}')
        state = {**state, 'step': jnp.asarray(sd['step'], jnp.int32),
                 'factors': sd['factors'],
                 # Pre-r9 checkpoints: default the pipeline position to
                 # 0 — always safe, the engine re-derives the chunk
                 # schedule from the step counter (MIGRATION.md).
                 'inv_chunk_phase': jnp.asarray(
                     sd.get('inv_chunk_phase', 0), jnp.int32)}
        from distributed_kfac_pytorch_tpu.preconditioner import (
            _overlay_overlap_state,
        )
        state = _overlay_overlap_state(state, sd)
        # Layout compatibility: a checkpoint written under a different
        # inverse dispatch (e.g. 'eigen' stacks loaded into an 'auto'
        # config whose large buckets are 'inv'-typed) — or under a
        # DIFFERENT mesh topology, whose slot stacks have other shapes
        # (the elastic resume path reshards them BEFORE calling here;
        # anything that reaches this check mismatched is rebuilt) — is
        # recomputed from the replicated factors rather than spliced in
        # structurally mismatched. Shapes matter as much as key sets: a
        # 4-device stack spliced into an 8-device program would feed
        # out-of-range (silently clamped) dynamic-slice offsets.
        compatible = 'inv_stacks' in sd and all(
            set(sd['inv_stacks'].get(k, ())) == set(state['inv_stacks'][k])
            and all(tuple(np.shape(sd['inv_stacks'][k][n]))
                    == tuple(state['inv_stacks'][k][n].shape)
                    for n in state['inv_stacks'][k])
            for k in state['inv_stacks'])
        if compatible and not self._degenerate_stacks(sd['inv_stacks']):
            state = {**state, 'inv_stacks': sd['inv_stacks'],
                     'diag_inv': sd['diag_inv'],
                     'grouped_inv': sd.get('grouped_inv',
                                           state['grouped_inv'])}
        else:
            state = self.recompute_inverses(state, damping=damping)
        return self._commit_host_leaves(state)

    def _commit_host_leaves(self, state: dict) -> dict:
        """Device-put host or mis-placed leaves to their proper mesh
        shardings (row-sharded stacks included).

        A checkpoint restored WITHOUT ``like=`` (or against a template
        whose leaves were uncommitted init arrays) hands back host or
        single-device arrays with the proper shardings lost (see
        ``CheckpointManager.restore``); spliced into the state
        uncommitted they would be re-sharded lazily on first jitted
        use — and row-sharded inverse stacks would transit as full
        replicated arrays first, which on multi-host is an outright
        placement error. Leaves already carrying their target sharding
        pass through untouched, so a fully-placed like= restore costs
        nothing. Single-process: a plain ``device_put`` per mis-placed
        leaf. Multi-host: a mis-placed-but-addressable leaf is a full
        per-process copy (the restore template carried global shapes),
        so the global array is rebuilt from it per device shard via
        ``make_array_from_callback`` — ``device_put`` cannot target
        non-addressable shardings; a NON-addressable leaf with a
        merely different layout is left for the step to reshard.
        """
        specs = self.state_pspecs(state)
        multiprocess = jax.process_count() > 1

        def place(x, spec):
            target = NamedSharding(self.mesh, spec)
            if isinstance(x, jax.Array) and \
                    x.sharding.is_equivalent_to(target, x.ndim):
                return x
            if multiprocess:
                if not getattr(x, 'is_fully_addressable', True):
                    return x
                arr = np.asarray(x)
                return jax.make_array_from_callback(
                    arr.shape, target, lambda idx: arr[idx])
            return jax.device_put(jnp.asarray(x), target)

        return jax.tree.map(place, state, specs)

    def _degenerate_stacks(self, inv_stacks: dict) -> bool:
        """True if any stored eigenbasis stack is unusable (all-zero).

        Pre-warm-eigh checkpoints stored zero-initialized Q stacks;
        Q=0 is a fixed point of the warm polish, so such checkpoints
        must be rebuilt from factors instead of warm-started. Shares
        :func:`preconditioner.q_stack_degenerate` (multi-host safe:
        inspects addressable shards only). Under 'auto' dispatch only
        the eigen buckets carry Q stacks — only those are checked.
        """
        return any(q_stack_degenerate(entry['Q'])
                   for entry in inv_stacks.values() if 'Q' in entry)

    def recompute_inverses(self, state: dict,
                           damping: float | None = None) -> dict:
        """Eagerly recompute all inverse stacks from current factors.

        The distributed analogue of the reference's post-load
        ``compute_inverses`` + broadcast (preconditioner.py:347-353).
        """
        damping = self.kfac.damping if damping is None else damping
        kspecs = self.state_pspecs(state)

        def compute(factors):
            return self._spmd_update_inverses(factors, damping)

        stacks, diag, grouped = jax.jit(jax.shard_map(
            compute, mesh=self.mesh,
            in_specs=(jax.tree.map(lambda _: P(), state['factors']),),
            out_specs=(kspecs['inv_stacks'],
                       jax.tree.map(lambda _: P(), state['diag_inv']),
                       jax.tree.map(lambda _: P(),
                                    state.get('grouped_inv', {}))),
            check_vma=False))(state['factors'])
        return {**state, 'inv_stacks': stacks, 'diag_inv': diag,
                'grouped_inv': grouped}

    # -- straggler probe (r10 observability) ---------------------------

    def build_barrier_probe(self):
        """Host-side pre-collective barrier-wait probe for this mesh.

        Returns ``probe() -> wait_ms``: a minimal psum over the same
        data axes every K-FAC collective in this module reduces over
        (the factor ``pmean``, the in-group inverse ``all_gather``,
        the gradient/KL ``psum`` — COMM_OPT and KAISA alike), blocked
        on from the host. Since the device stream is in-order, the
        measured wall time is own-queue drain plus the wait for the
        slowest participant — the wait this host's next collective
        would pay. Compiled+warmed here; see
        ``observability.stragglers`` for semantics and cost (the probe
        serializes host dispatch with device completion — opt-in via
        ``--straggler-shards``).
        """
        from distributed_kfac_pytorch_tpu.observability import (
            stragglers,
        )
        return stragglers.build_barrier_probe(self.mesh,
                                              self.data_axes)

    # -- full train step builder ---------------------------------------

    def build_train_step(self, loss_fn, tx, *, model_args_fn=None,
                         model_kwargs_fn=None,
                         metrics_fn=None,
                         mutable_cols: Sequence[str] = (),
                         batch_spec: P | None = None,
                         donate: bool = True,
                         grad_accum_steps: int = 1,
                         loss_scale=None):
        """Jitted data-parallel train step with distributed K-FAC.

        The functional analogue of the reference training engine step
        (examples/cnn_utils/engine.py:29-83): forward/backward with
        capture, gradient pmean, K-FAC preconditioning, then the wrapped
        optax transformation (the reference applies SGD after KFAC.step,
        engine.py:74-82).

        Args:
          loss_fn: ``loss_fn(model_out, batch) -> scalar`` mean loss over
            the (local) batch.
          tx: optax GradientTransformation applied to the preconditioned
            gradients.
          model_args_fn: maps a batch pytree to the model's positional
            args; default ``batch[0],`` (i.e. ``(x, y)`` batches).
          model_kwargs_fn: optional ``batch -> kwargs dict`` evaluated
            *inside* the shard_map, so it may use ``jax.lax.axis_index``
            — e.g. a sequence-parallel LM's ``pos_offset`` (the global
            start of this device's sequence block).
          metrics_fn: optional ``metrics_fn(model_out, batch) -> dict`` of
            scalars, globally averaged and merged into the returned
            metrics (e.g. train accuracy, reference engine.py:81-83).
          mutable_cols: flax variable collections updated in the forward
            pass (e.g. ``('batch_stats',)``); their updates are
            ``pmean``ed (synchronized batch statistics).
          batch_spec: PartitionSpec of every batch leaf (or a pytree of
            specs matching the batch, e.g. to keep a per-step dropout key
            replicated while data is sharded); defaults to batch-dim
            sharding over both K-FAC mesh axes.
          grad_accum_steps: micro-batch count per step. The per-device
            batch shard is split into this many micro-batches processed
            sequentially under ``lax.scan``, averaging gradients and
            factor contributions — the analogue of the reference's
            ``batches_per_allreduce`` sub-batch loop with ``no_sync`` and
            hook-data accumulation (engine.py:33-65, base.py:364-379).
            Peak activation memory drops by ~the accumulation factor;
            numerics match the single-pass step up to fp associativity
            (G contributions carry the exact ``1/accum**2`` loss-scale
            correction).
          loss_scale: fp16 loss scaling. A float is a FIXED scale
            forwarded to ``KFACCapture.loss_and_grads`` (grads and
            output-grad captures are unscaled before any factor
            statistics). The string ``'dynamic'`` enables the full
            GradScaler-parity schedule (reference engine.py:38-41,
            75-80): the live scale is read from
            ``extra_vars['loss_scale']`` (seed with
            ``fp16.init_loss_scale()``), non-finite captures are zeroed
            before factor statistics, the parameter/optimizer update is
            skipped collectively on any non-finite gradient, and the
            scale state backs off / grows per ``fp16.update_loss_scale``.
            Metrics gain ``loss_scale`` and ``overflow``.

        Returns a function
        ``step(params, opt_state, kfac_state, extra_vars, batch, hyper)
        -> (params, opt_state, kfac_state, extra_vars, metrics)`` where
        ``hyper`` is a dict with 'lr', 'damping', 'factor_update_freq',
        'inv_update_freq', 'factor_decay' scalars (all dynamic).
        """
        if model_args_fn is None:
            model_args_fn = lambda batch: (batch[0],)
        if batch_spec is None:
            batch_spec = P(self.batch_axes)
        if grad_accum_steps < 1:
            raise ValueError(f'{grad_accum_steps=} must be >= 1')
        capture = self.kfac.capture
        mutable_cols = tuple(mutable_cols)

        dynamic_ls = loss_scale == 'dynamic'
        static_ls = None if dynamic_ls else loss_scale

        def fwd_bwd(params, extra_vars, batch, scale=None,
                    do_capture=True):
            """One micro/full-batch pass -> (loss, metrics, grads,
            contribs, updated_vars).

            ``do_capture=False`` is the static-cadence non-factor-step
            fast path: plain autodiff, no interception (the reference
            gates its hooks off on those steps the same way —
            _periodic_hook, kfac/preconditioner.py:684-699)."""
            def wrapped_loss(out):
                extra = metrics_fn(out, batch) if metrics_fn else {}
                return loss_fn(out, batch), extra

            kwargs = model_kwargs_fn(batch) if model_kwargs_fn else {}
            loss, extra_metrics, grads, captures, updated = (
                capture.loss_and_grads(
                    wrapped_loss, params, *model_args_fn(batch),
                    extra_vars=extra_vars, mutable_cols=mutable_cols,
                    has_aux=True,
                    loss_scale=static_ls if scale is None else scale,
                    intercept=do_capture,
                    **kwargs))
            if dynamic_ls and captures:
                # Reference hook behavior under GradScaler: non-finite
                # grad-output tensors are dropped before factor
                # statistics (kfac/layers/base.py:397-407); the SPMD
                # form zeroes them (fp16.sanitize_captures). Steps whose
                # *gradients* overflow are skipped wholesale in
                # local_step — this sanitize covers the residual case of
                # a non-finite per-call capture inside an otherwise
                # finite step (e.g. one timestep of a multi-call layer),
                # keeping the factor math NaN-free without poisoning the
                # EWMA.
                captures, _ = fp16_ops.sanitize_captures(captures)
            return loss, extra_metrics, grads, captures, updated

        def accum_fwd_bwd(params, extra_vars, batch, do_factors,
                          scale=None):
            """Scan over micro-batches, averaging grads/contribs/metrics.

            Captures are reduced to factor contributions inside the scan
            so memory stays flat in the accumulation count (unlike the
            reference, whose hook buffers grow linearly, README.md:144-148);
            the contraction itself is gated on ``do_factors`` so
            non-factor-update steps skip the covariance work, like the
            single-pass path's in-cond contraction.
            """
            specs = normalize_batch_specs(batch_spec, batch)

            def split(x, spec):
                if spec == P():
                    # Fully-replicated per-step leaf (e.g. a dropout PRNG
                    # key): identical for every micro-batch, not sliced.
                    return jnp.broadcast_to(x[None],
                                            (grad_accum_steps,) + x.shape)
                if x.shape[0] % grad_accum_steps:
                    raise ValueError(
                        f'per-device batch shard of {x.shape[0]} is not '
                        f'divisible by {grad_accum_steps=}')
                return x.reshape((grad_accum_steps,
                                  x.shape[0] // grad_accum_steps)
                                 + x.shape[1:])

            micro = jax.tree.map(split, batch, specs)
            first = jax.tree.map(lambda x: x[0], micro)
            loss_sh, extras_sh, grads_sh, captures_sh, _ = jax.eval_shape(
                fwd_bwd, params, extra_vars, first, scale)
            contribs_sh = jax.eval_shape(self.local_factor_contribs,
                                         captures_sh)
            zeros = lambda sh: jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), sh)

            # Running sums live in the carry so peak memory stays at one
            # micro-batch (a stacked scan output would materialize
            # accum x every grad/contrib leaf before the reduction).
            def body(carry, mb):
                extra_c, sums = carry
                loss, extra_metrics, grads, captures, updated = fwd_bwd(
                    params, extra_c, mb, scale,
                    do_capture=do_factors is not False)
                if isinstance(do_factors, bool):
                    # Static cadence: the contraction is simply present or
                    # absent from this program variant.
                    contribs = (self.local_factor_contribs(captures)
                                if do_factors else zeros(contribs_sh))
                else:
                    contribs = jax.lax.cond(
                        do_factors,
                        lambda: self.local_factor_contribs(captures),
                        lambda: zeros(contribs_sh))
                new_sums = jax.tree.map(
                    jnp.add, sums, (loss, extra_metrics, grads, contribs))
                new_extra = ({**extra_c, **updated} if updated
                             else extra_c)
                return (new_extra, new_sums), None

            init = (extra_vars, (zeros(loss_sh), zeros(extras_sh),
                                 zeros(grads_sh), zeros(contribs_sh)))
            (extra_out, sums), _ = jax.lax.scan(body, init, micro)
            loss_sum, extras_sum, grads_sum, contribs_sum = sums
            inv_n = 1.0 / grad_accum_steps
            mean = lambda t: jax.tree.map(lambda x: x * inv_n, t)
            # g captures come from the micro-mean loss: accum x larger
            # than the local-batch-mean-loss g; grad-QUADRATIC contrib
            # parts ('G', and a tied embedding's 'A_g2' — see
            # L.GRAD_QUADRATIC_KEYS) get the 1/accum**2 correction;
            # activation-derived parts ('A', 'G_a') only the mean.
            g_fix = 1.0 / grad_accum_steps ** 2
            contribs = {
                name: {k: (g_fix if k in L.GRAD_QUADRATIC_KEYS
                           else 1.0) * v * inv_n
                       for k, v in c.items()}
                for name, c in contribs_sum.items()}
            updated = ({c: extra_out[c] for c in mutable_cols
                        if c in extra_out} if mutable_cols else {})
            return (mean(loss_sum), mean(extras_sum), mean(grads_sum),
                    contribs, updated)

        def make_local_step(factor_update, inv_update, inv_chunk,
                            factor_reduce=False, factor_snapshot=False):
            def local_step(params, opt_state, kstate, extra_vars, batch,
                           hyper):
                if dynamic_ls:
                    if 'loss_scale' not in extra_vars:
                        raise ValueError(
                            "loss_scale='dynamic' requires a loss-scale "
                            "state in extra_vars['loss_scale'] — seed it "
                            'with fp16.init_loss_scale()')
                    ls_state = extra_vars['loss_scale']
                    scale = ls_state['scale']
                else:
                    scale = None
                if grad_accum_steps == 1:
                    # Static factor_update=False: skip the capture
                    # machinery entirely — its cost is NOT dead-code-
                    # eliminated by XLA when captures go unused
                    # (measured +2.7 ms/iter, ResNet-50 @224 b64).
                    loss, extra_metrics, grads, captures, updated = fwd_bwd(
                        params, extra_vars, batch, scale,
                        do_capture=factor_update is not False)
                    contribs = None
                else:
                    if factor_update is not None:
                        do_factors = factor_update
                    else:
                        f_freq = hyper.get('factor_update_freq')
                        if f_freq is None:
                            f_freq = self.kfac.factor_update_freq
                        do_factors = kstate['step'] % f_freq == 0
                    loss, extra_metrics, grads, contribs, updated = (
                        accum_fwd_bwd(params, extra_vars, batch, do_factors,
                                      scale))
                    captures = None
                grads = jax.lax.pmean(grads, self.data_axes)
                loss = jax.lax.pmean(loss, self.data_axes)
                metrics = {'loss': loss,
                           **jax.lax.pmean(extra_metrics, self.data_axes)}
                precond, new_kstate = self.spmd_step(
                    kstate, grads, captures, contribs=contribs,
                    damping=hyper['damping'], lr=hyper['lr'],
                    factor_decay=hyper.get('factor_decay'),
                    factor_update_freq=hyper.get('factor_update_freq'),
                    inv_update_freq=hyper.get('inv_update_freq'),
                    factor_update=factor_update, inv_update=inv_update,
                    inv_chunk=inv_chunk, factor_reduce=factor_reduce,
                    factor_snapshot=factor_snapshot,
                    # r16 self-healing quarantine gates ride in hyper
                    # (replicated traced scalars) — present exactly
                    # when the ladder is armed; the dict-structure
                    # check is static, so the unarmed program is
                    # byte-for-byte the historical one.
                    gates=hyper.get('bucket_gate'))
                updates, new_opt_state = tx.update(precond, opt_state,
                                                   params)
                new_params = jax.tree.map(
                    lambda p, u: (p + u).astype(p.dtype), params, updates)
                if dynamic_ls:
                    # GradScaler semantics (reference engine.py:75-80):
                    # on non-finite gradients skip the entire state
                    # advance — params, optimizer, K-FAC factor/inverse
                    # content (a zeroed-capture EWMA update would shrink
                    # factors toward zero at full weight), and the
                    # mutable collections (BN running stats computed
                    # from a non-finite forward would be poisoned
                    # forever: momentum*NaN stays NaN). Only the K-FAC
                    # step counter and the loss-scale state advance, so
                    # the static-cadence phase stays aligned with the
                    # host counter. The pmean above propagates any
                    # device's non-finite values to all devices, so the
                    # skip is collective.
                    finite = fp16_ops.tree_all_finite(grads)
                    new_params, new_opt_state = fp16_ops.apply_if_finite(
                        finite, (new_params, new_opt_state),
                        (params, opt_state))
                    new_kstate = {
                        **fp16_ops.apply_if_finite(finite, new_kstate,
                                                   kstate),
                        'step': new_kstate['step']}
                    if updated:
                        # A collection first *created* during apply has
                        # no incoming value to fall back to on an
                        # overflow-skipped step, and jit's static output
                        # structure forbids dropping it conditionally —
                        # keeping the new value would let a non-finite
                        # first step poison e.g. BN running stats
                        # forever. Demand the seed loudly (ADVICE r3
                        # flagged the former bare KeyError here).
                        missing = [c for c in updated
                                   if c not in extra_vars]
                        if missing:
                            raise ValueError(
                                f'mutable collections {missing} are '
                                'created inside the step but absent '
                                "from extra_vars; with loss_scale="
                                "'dynamic' the overflow-skip needs "
                                'their incoming values — seed them '
                                'from model.init() (e.g. '
                                "extra_vars['batch_stats'] = "
                                "variables['batch_stats'])")
                        updated = fp16_ops.apply_if_finite(
                            finite, updated,
                            {c: extra_vars[c] for c in updated})
                    extra_vars = {
                        **extra_vars,
                        'loss_scale': fp16_ops.update_loss_scale(
                            ls_state, finite)}
                    metrics = {**metrics, 'loss_scale': scale,
                               'overflow': 1.0
                               - finite.astype(jnp.float32)}
                if updated:
                    extra_vars = {**extra_vars,
                                  **jax.lax.pmean(updated, self.data_axes)}
                if self.kfac.collect_metrics:
                    # Expose the on-device K-FAC metrics in the step's
                    # metrics dict (replicated scalars — flows through
                    # the P() out-spec): the engine's sink drains these
                    # asynchronously, and the epoch meters average them
                    # like any other metric.
                    metrics = {**metrics, **obs_metrics.flatten_metrics(
                        new_kstate['metrics'])}
                return (new_params, new_opt_state, new_kstate, extra_vars,
                        metrics)
            return local_step

        def make_step_impl(factor_update, inv_update, inv_chunk,
                           factor_reduce=False, factor_snapshot=False):
            key = _variant_key(factor_update, inv_update, inv_chunk,
                               factor_reduce, factor_snapshot)

            def step_impl(params, opt_state, kstate, extra_vars, batch,
                          hyper):
                # Host-side trace tally: this body re-executes exactly
                # when jax retraces the variant, so the count pins
                # PERF.md pitfall 3 (one compile per flag combination,
                # ever) — asserted by the retrace-guard test. A count
                # above 1 additionally queues a 'retrace' telemetry
                # event (drained into the metrics stream by the
                # engine): the offline echo of the same contract, so a
                # recorded run can be audited for mid-run recompiles
                # (observability.gate regresses the count against 0).
                n = trace_counts.get(key, 0) + 1
                trace_counts[key] = n
                if n > 1:
                    compile_events.append(
                        {'event': 'retrace',
                         'variant': _variant_label(key),
                         'trace_count': n})
                kspecs = self.state_pspecs(kstate)
                rep = P()
                batch_specs = normalize_batch_specs(batch_spec, batch)
                in_specs = (
                    jax.tree.map(lambda _: rep, params),
                    jax.tree.map(lambda _: rep, opt_state,
                                 is_leaf=lambda x: x is None),
                    kspecs,
                    jax.tree.map(lambda _: rep, extra_vars),
                    batch_specs,
                    jax.tree.map(lambda _: rep, hyper),
                )
                out_specs = (
                    jax.tree.map(lambda _: rep, params),
                    jax.tree.map(lambda _: rep, opt_state,
                                 is_leaf=lambda x: x is None),
                    kspecs,
                    jax.tree.map(lambda _: rep, extra_vars),
                    rep,  # metrics dict: P() prefix covers any keys
                )
                fn = jax.shard_map(
                    make_local_step(factor_update, inv_update,
                                    inv_chunk, factor_reduce,
                                    factor_snapshot),
                    mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False)
                return fn(params, opt_state, kstate, extra_vars, batch,
                          hyper)
            return step_impl

        # One separately-jitted callable per cadence-flag combination
        # (factor_update, inv_update, inv_chunk), built lazily and kept
        # for the builder's lifetime. Passing the flags through one jit
        # via static_argnums retraced + recompiled on EVERY flag flip
        # (observed on jax 0.8: the tracing cache kept only the most
        # recent static-arg variant — ~15-45 s per flip on TPU);
        # distinct jit callables have independent caches, so each
        # variant compiles exactly once. With pipelined firing each
        # chunk phase is one more variant (k-1 extra compiles per run,
        # zero retraces — pinned by the trace_counts guard test).
        donate_argnums = (0, 1, 2, 3) if donate else ()
        variants: dict[tuple, Any] = {}
        trace_counts: dict[tuple, int] = {}
        compile_events: list[dict] = []

        # hierarchical_reduce (r20) reuses the r14 window machinery:
        # the engine schedules its boundary reduce off the same
        # `deferred_factor_reduction` step attribute, and the variant
        # key gains the reduce flag identically.
        deferred = (self.kfac.deferred_factor_reduction
                    or self.kfac.hierarchical_reduce)
        staleness = self.kfac.inv_staleness

        def _variant_key(f, i, c, r=False, s=False):
            """Variant-cache key. Both knobs off keeps the historical
            3-tuple (the trace_counts guard tests pin that shape); each
            engaged knob appends its flag — per-builder the key length
            is constant, so lookups stay unambiguous."""
            key = (f, i, c)
            if deferred:
                key += (bool(r),)
            if staleness:
                key += (bool(s),)
            return key

        def _variant_label(key) -> str:
            f, i, c = key[:3]
            label = f'factor={f},inv={i},chunk={c}'
            extra = key[3:]
            if deferred:
                label += f',reduce={extra[0]}'
                extra = extra[1:]
            if staleness:
                label += f',snapshot={extra[0]}'
            return label

        def step(params, opt_state, kstate, extra_vars, batch, hyper,
                 factor_update: bool | None = None,
                 inv_update: bool | None = None,
                 inv_chunk: int | None = None,
                 factor_reduce: bool = False,
                 factor_snapshot: bool = False):
            """``factor_update`` / ``inv_update``: static cadence flags
            (see :meth:`KFAC.step`). ``None`` = dynamic on-device conds;
            host-driven bools select one of the statically-compiled
            program variants (the TPU fast path). ``inv_chunk``: fire
            only pipelined chunk ``j`` of the inverse work (static int;
            requires ``inv_update`` falsy — see ``KFAC.step``).
            ``factor_reduce`` / ``factor_snapshot``: the r14 overlap
            flags (see :meth:`spmd_step`) — each engaged knob's flag is
            part of the variant key."""
            key = _variant_key(factor_update, inv_update, inv_chunk,
                               factor_reduce, factor_snapshot)
            first = key not in variants
            if first:
                variants[key] = jax.jit(
                    make_step_impl(factor_update, inv_update, inv_chunk,
                                   factor_reduce, factor_snapshot),
                    donate_argnums=donate_argnums)
                t0 = time.perf_counter()
            out = variants[key](params, opt_state, kstate, extra_vars,
                                batch, hyper)
            if first:
                # First-call wall = trace + XLA compile + dispatch (the
                # execution itself is async, so this is dominated by
                # compile — the 15-45 s/variant cost PERF.md pitfall 2
                # is about). Queued, not written: the engine drains
                # compile_events into the metrics sink off the step
                # path; a sink-less caller just accumulates a short
                # list (one entry per variant, ever).
                compile_events.append(
                    {'event': 'compile',
                     'variant': _variant_label(key),
                     'first_call_ms': (time.perf_counter() - t0)
                     * 1000.0})
                # r21: a first call is where the fused-kernel probes
                # run (trace time); surface any recorded fallbacks
                # through the same engine-drained queue so a fleet run
                # can tell "fused" from "fell back to XLA".
                compile_events.extend(
                    pallas_kernels.drain_pallas_events())
            return out

        # Introspection for the engine's chunk scheduler and the
        # retrace-guard test (host-side, no runtime cost);
        # compile_events additionally feeds the r10 compile/retrace
        # telemetry (drained by engine.train_epoch).
        step.inv_pipeline_chunks = self.kfac.inv_pipeline_chunks
        step.deferred_factor_reduction = deferred
        step.hierarchical_reduce = self.kfac.hierarchical_reduce
        step.inv_staleness = staleness
        step.trace_counts = trace_counts
        step.compile_events = compile_events
        return step


def _get(tree, path):
    for part in path:
        tree = tree[part]
    return tree


def _set(tree, path, value):
    if not path:
        return value
    out = dict(tree)
    out[path[0]] = _set(tree[path[0]], path[1:], value)
    return out
