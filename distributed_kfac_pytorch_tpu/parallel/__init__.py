"""Mesh topology, collectives, and static work placement."""

from distributed_kfac_pytorch_tpu.parallel.distributed import (
    GRAD_WORKER_AXIS,
    INV_GROUP_AXIS,
    KFAC_AXES,
    DistributedKFAC,
    WorkAssignment,
    assign_work,
    make_kfac_mesh,
    resolve_grad_workers,
)
from distributed_kfac_pytorch_tpu.parallel.sequence import (
    SEQ_AXIS,
    chunked_causal_attention,
    local_causal_attention,
    ring_self_attention,
)
from distributed_kfac_pytorch_tpu.parallel.placement import (
    WorkerAllocator,
    get_block_boundary,
    load_balance,
    partition_grad_ranks,
    partition_inv_ranks,
)
