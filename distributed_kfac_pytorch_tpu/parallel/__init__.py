"""Mesh topology, collectives, and static work placement."""

from distributed_kfac_pytorch_tpu.parallel.placement import (
    WorkerAllocator,
    get_block_boundary,
    load_balance,
    partition_grad_ranks,
    partition_inv_ranks,
)
