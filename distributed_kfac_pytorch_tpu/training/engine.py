"""Train/eval epoch loops over the distributed K-FAC step.

Reference parity: examples/cnn_utils/engine.py (train/test loops with
allreduce-averaged metrics, progress display, TensorBoard scalars). The
per-step work (forward/backward, K-FAC, SGD, metric averaging) is entirely
inside the jitted step from ``DistributedKFAC.build_train_step``; the host
loop only feeds batches and accumulates metrics.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp

from distributed_kfac_pytorch_tpu.analysis import sanitize as _sanitize
from distributed_kfac_pytorch_tpu.observability import tracing
from distributed_kfac_pytorch_tpu.parallel.distributed import KFAC_AXES
from distributed_kfac_pytorch_tpu.training.utils import Metric, accuracy


def cadence_flags(step: int, factor_update_freq, inv_update_freq,
                  inv_pipeline_chunks: int = 1, *,
                  deferred_reduce: bool = False,
                  inv_staleness: int = 0) -> dict:
    """Static cadence flags for one host step (single point of truth).

    The classic schedule fires the whole inverse update at
    ``step % inv_update_freq == 0``. With ``inv_pipeline_chunks=k > 1``
    the firing is pipelined: chunk ``j`` fires on phase step
    ``j * inv_update_freq / k`` of each window (``inv_chunk=j`` in the
    returned flags), smearing the decomposition spike across the
    window — except at step 0, which fires monolithically
    (``inv_update=True``): every inverse slot is zero-seeded and must
    exist before its first preconditioning use, so the pipeline takes
    over from the first window's later phases onward. Each distinct
    flag combination is its own statically-compiled program variant
    (PERF.md pitfalls 2-3).

    r14 overlap knobs (read off the step builder's attributes by
    ``train_epoch``): ``deferred_reduce`` adds ``factor_reduce=True``
    on window-head steps — the one bucketed factor collective per
    window. ``inv_staleness=1`` re-times the firing schedule: window
    heads (past step 0) take a factor SNAPSHOT instead of firing, and
    chunk ``j`` fires at phase ``j * stride + 1`` from that snapshot —
    one step after the head, so the decomposition never shares a step
    with the window's factor reduction and carries no data dependency
    on its own step's factor work (with ``k == 1`` the whole firing
    runs as chunk 0 at phase 1). Step 0 stays a monolithic warmup
    either way.
    """
    f_freq, i_freq = int(factor_update_freq), int(inv_update_freq)
    k = int(inv_pipeline_chunks)
    phase = step % i_freq
    flags = {'factor_update': step % f_freq == 0}
    if int(inv_staleness) == 1 and i_freq % k == 0 and i_freq // k >= 2:
        stride = i_freq // k
        flags['inv_update'] = step == 0
        if step != 0:
            if phase == 0:
                flags['factor_snapshot'] = True
            elif (phase - 1) % stride == 0 and (phase - 1) // stride < k:
                flags['inv_chunk'] = (phase - 1) // stride
    elif k > 1 and i_freq % k == 0:
        stride = i_freq // k
        flags['inv_update'] = step == 0
        if step != 0 and phase % stride == 0:
            flags['inv_chunk'] = phase // stride
    else:
        flags['inv_update'] = step % i_freq == 0
    if deferred_reduce:
        flags['factor_reduce'] = phase == 0
    return flags


def _drain_selfheal(selfheal, metrics_sink) -> None:
    """Move the ladder's queued decision events into the metrics sink
    (duck-typed sinks without ``event_record`` keep their queue, like
    the compile-event drain)."""
    if not selfheal.pending_events or metrics_sink is None:
        return
    emit = getattr(metrics_sink, 'event_record', None)
    if emit is None:
        return
    for ev in selfheal.drain_events():
        emit(ev['event'], **{k: v for k, v in ev.items()
                             if k != 'event'})


def fired_stage(flags: dict) -> str | None:
    """Most expensive stage a step's static flags fire (for step-time
    attribution in the metrics stream): 'inverse' > 'chunk<j>' >
    'reduce' (the deferred window-boundary factor collective, r14) >
    'factor' > None. A firing step that ALSO pays the deferred reduce
    (the non-staleness combos put both on the window head) gets a
    compound label ('inverse+reduce' / 'chunk<j>+reduce') so the
    straggler merger's comm-wait split can still see the factor
    collective — classing those steps as collective-free 'firing'
    would hide the one real factor reduction per window from exactly
    the attribution the r14 decision rule reads. The report's outlier
    attribution and the merger's split consume this."""
    reduce_tag = '+reduce' if flags.get('factor_reduce') else ''
    if flags.get('inv_update'):
        return 'inverse' + reduce_tag
    if flags.get('inv_chunk') is not None:
        return f"chunk{flags['inv_chunk']}" + reduce_tag
    if flags.get('factor_reduce'):
        return 'reduce'
    if flags.get('factor_update'):
        return 'factor'
    return None


@dataclasses.dataclass
class TrainState:
    """Everything a training step threads through (one pytree-of-pytrees).

    The analogue of the reference's (model, optimizer, preconditioner,
    schedulers) object group (torch_cifar10_resnet.py:153-176).
    """
    params: Any
    opt_state: Any
    kfac_state: Any
    extra_vars: dict
    step: int = 0
    epoch: int = 0


def train_epoch(step_fn, state: TrainState, batches: Iterable,
                hyper: dict, *, log_writer=None, verbose: bool = False,
                epoch_len: int | None = None,
                static_cadence: tuple[int, int] | str | None = 'auto',
                metrics_sink=None, checkpointer=None,
                start_step_in_epoch: int = 0,
                rank_sink=None, barrier_probe=None,
                straggler_sample_every: int = 1,
                memory_interval: int = 0,
                cadence_policy=None, selfheal=None,
                heartbeat=None) -> dict[str, float]:
    """One training epoch; returns averaged metrics.

    ``hyper`` holds this epoch's dynamic hyperparameters ('lr', 'damping',
    optionally cadence overrides) — the reference adjusts these per epoch
    via LambdaLR/KFACParamScheduler (engine.py:84-93).

    ``static_cadence=(factor_update_freq, inv_update_freq)`` drives the
    K-FAC cadence from the host step counter (``state.step``) instead of
    on-device ``lax.cond``s: the step runs as one of a few
    statically-compiled program variants, which on TPU avoids the
    measured 10-18x cond-around-decompositions slowdown (see
    ``KFAC.step``). The freqs may change between epochs (the
    KFACParamScheduler path) — each distinct flag combination reuses its
    compiled variant. Requires a ``step_fn`` from
    ``DistributedKFAC.build_train_step``; pass None for on-device conds.
    The default ``'auto'`` uses the freqs in ``hyper`` when ``step_fn``
    accepts the flags (i.e. is a K-FAC step) and falls back to dynamic
    otherwise (e.g. the SGD baseline step).

    ``metrics_sink``: an ``observability.sink.JsonlMetricsSink`` (or
    None). Per-step metrics (including the on-device K-FAC telemetry
    when ``collect_metrics`` is on) are *enqueued* each step — device
    scalars, no sync — plus the host dispatch time; an epoch record with
    the averaged metrics and a host trace-table snapshot is appended and
    the sink flushed at epoch end (the only point the host blocks on
    metric values, where it already blocks for the epoch summary).

    ``checkpointer``: a ``resilience.policy.StepCheckpointer`` (or
    None). Its ``after_step(state, step_in_epoch)`` is called once per
    completed step — the single poll point for step-interval /
    wall-clock checkpoints, preemption drains, and fault injection. It
    may raise ``resilience.preemption.Preempted`` AFTER a blocking
    save; the exception propagates to the CLI, which exits with the
    relaunch code. ``start_step_in_epoch`` is the mid-epoch resume
    offset (how many batches of this epoch were already trained before
    ``batches``, which the caller built with a matching
    ``skip_batches=``) so checkpoint bundles record the true position.
    A resumed run whose offset already covers the whole epoch (the
    preemption landed on the final step) yields zero batches — that is
    treated as a completed epoch, not an error.

    ``rank_sink``: THIS process's straggler shard sink
    (``observability.stragglers.make_rank_shard_sink`` — every rank
    writes its own ``<path>.rank<r>``, unlike the rank-0-gated
    ``metrics_sink``). Each step's host dispatch time (and, with
    ``barrier_probe``, the pre-collective barrier wait) is recorded so
    ``observability.report`` can attribute mesh-wide skew to hosts.

    ``barrier_probe``: ``DistributedKFAC.build_barrier_probe()`` (or
    None). Called once per step BEFORE the step dispatch; the returned
    wait-ms lands in the rank shard. NOTE: the probe blocks the host
    on device completion each step (that is what it measures), so it
    costs async-dispatch pipelining — only wired when straggler
    attribution is requested.

    ``straggler_sample_every``: probe only on steps where
    ``step % N == 0`` (r14) — the probe's host-sync cost then
    amortizes to 1/N of the run, cheap enough to leave on in long
    runs. Every rank samples the SAME steps (the schedule is a pure
    function of the global step), so the merger's common-step skew
    analysis still lines up; non-sampled steps simply carry no wait
    field (report/merge handle the sparse shards). 1 (default) = the
    r10 every-step probe.

    ``memory_interval``: every Nth step, emit a ``kind='memory'``
    record into ``metrics_sink`` — device allocator watermarks plus the
    resident K-FAC state footprint (``observability.memory``). Pure
    host-side reads (0 = off). The footprint is computed once per
    epoch: the state's shapes/dtypes are static across steps.

    ``cadence_policy``: an ``autotune.StragglerCadencePolicy`` (or
    None, the default — that path is byte-for-byte the pre-policy
    engine). Per step, the policy sees the static cadence flags plus
    the barrier-probe wait and may suppress a scheduled factor update
    (straggler-aware cadence backoff, r12). The first suppression per
    flag combination may compile a new program variant once (a normal
    lazy-cache compile, recorded and labeled like any other — see
    ``autotune.policy``); the zero-RETRACE contract still holds with
    the policy active. Its decision events drain into
    ``metrics_sink`` like the compile telemetry. Requires
    ``barrier_probe`` to act on skew (without one the policy is
    inert).

    ``selfheal``: a ``resilience.selfheal.SelfHealController`` (or
    None, the default — that path is byte-for-byte the pre-r16
    engine). Per step the controller adjusts the traced
    hyperparameters (escalated damping, per-bucket quarantine gates —
    VALUE changes only, zero retraces) and observes the step's
    metrics; at window boundaries (its ``check_every``) it reads a
    handful of device scalars — the armed ladder's one deliberate
    host sync, amortized like the sampled straggler probe — and may
    reset quarantined layers' factor EWMAs in ``state.kfac_state`` or
    raise ``resilience.selfheal.Rollback`` (sinks are flushed first;
    the CLI catches it and restores in-process — README
    "Self-healing"). Ladder decision events drain into
    ``metrics_sink`` like the compile/backoff telemetry.

    ``heartbeat``: a ``resilience.heartbeat.HeartbeatEmitter`` (or
    None, the default — that path is byte-for-byte the pre-r17
    engine). Once per completed step the emitter publishes this
    rank's liveness lease (atomic write-then-rename; stride inside
    the emitter) BEFORE the checkpointer hook runs, so a step that
    wedges in that hook still left a fresh lease at its step — the
    exact stale-lease signature the failure supervisor's
    ``--hang-timeout`` detects (``resilience.supervisor``). Pure
    host-side file I/O: no device interaction, no program change —
    heartbeats off is bit-identical and on adds zero retraces
    (pinned by tests/test_supervisor.py).

    ``KFAC_SANITIZE=transfer,nan,retrace`` (env var, r15): run the
    epoch under the runtime sanitizer gates — device->host transfer
    guard around warm step dispatches, ``jax.debug_nans`` on every
    dispatch, and an after-step retrace check against the builder's
    ``trace_counts``. See :mod:`analysis.sanitize`; unset (default)
    is the unsanitized path.
    """
    if static_cadence == 'auto':
        import inspect
        try:
            accepts = 'factor_update' in inspect.signature(
                step_fn).parameters
        except (TypeError, ValueError):
            accepts = False
        if accepts and 'factor_update_freq' in hyper and \
                'inv_update_freq' in hyper:
            static_cadence = (hyper['factor_update_freq'],
                              hyper['inv_update_freq'])
        else:
            static_cadence = None
            if accepts:
                import warnings
                warnings.warn(
                    'train_epoch: step_fn accepts static cadence flags '
                    "but hyper lacks 'factor_update_freq'/"
                    "'inv_update_freq' — falling back to on-device "
                    'cadence conds, which are 10-18x slower on TPU '
                    '(PERF.md). Add the freqs to hyper (e.g. via '
                    'KFACParamScheduler.params()) to enable the static '
                    'fast path.')
    if (static_cadence is not None and isinstance(state.kfac_state, dict)
            and 'step' in state.kfac_state):
        # Static cadence is only correct while the host counter driving
        # the factor/inverse flags stays in phase with the on-device
        # K-FAC counter (a caller that rebuilds TrainState without
        # restoring ``step`` would silently shift the schedule). Checked
        # BEFORE the epoch so a desynced state cannot train a whole
        # epoch on the wrong schedule; one device sync per epoch.
        # kfaclint: waive[host-sync] documented blocking point: ONE device sync per epoch, before any step is dispatched
        kstep = int(jax.device_get(state.kfac_state['step']))
        if kstep != state.step:
            raise RuntimeError(
                f'static-cadence phase error: host step counter '
                f'{state.step} != on-device K-FAC step {kstep}. '
                'TrainState.step must be restored alongside kfac_state '
                '(checkpoint resume restores both; see '
                "MIGRATION.md 'Checkpoint format').")
    # Pipelined inverse firing: the step builder advertises its chunk
    # count (DistributedKFAC.build_train_step); a schedule the chunks
    # cannot divide evenly (e.g. a KFACParamScheduler freq decay)
    # falls back to monolithic firing for the epoch rather than
    # mis-phasing the pipeline.
    built_chunks = int(getattr(step_fn, 'inv_pipeline_chunks', 1) or 1)
    chunks = built_chunks
    if (chunks > 1 and static_cadence is not None
            and int(static_cadence[1]) % chunks != 0):
        import warnings
        warnings.warn(
            f'inv_pipeline_chunks={chunks} does not divide this '
            f'epoch\'s inv_update_freq={static_cadence[1]} — firing '
            'monolithically for the epoch')
        chunks = 1
    # r14 overlap knobs, advertised by the step builder like the chunk
    # count. A schedule the shifted staleness phases cannot fit
    # (stride < 2, or a non-dividing chunk count, after a
    # KFACParamScheduler freq decay) falls back to eager MONOLITHIC
    # window-head firing for the epoch: the inv_update=True program
    # snapshots-then-fires (eager semantics), whereas any partial
    # chunk schedule against the BUILT chunk count would either
    # mis-phase the pipeline or leave the carried snapshot stale
    # forever. The check uses ``built_chunks`` — the chunk plan baked
    # into the compiled programs — not the fallen-back count.
    deferred_reduce = bool(getattr(step_fn, 'deferred_factor_reduction',
                                   False))
    inv_staleness = int(getattr(step_fn, 'inv_staleness', 0) or 0)
    if (deferred_reduce or inv_staleness) and static_cadence is None:
        # Fail BEFORE the epoch with the real reason: the step itself
        # would raise the same contract mid-epoch at trace time, right
        # after the 'falling back to on-device cadence conds' warning
        # promised a fallback that cannot exist for these knobs (a
        # dynamic cond cannot host the window-boundary reduce or the
        # frozen-snapshot firing schedule — both are static program
        # structure).
        raise RuntimeError(
            'deferred_factor_reduction/inv_staleness require the '
            'static-cadence fast path: pass static_cadence=(f, i) or '
            "include 'factor_update_freq'/'inv_update_freq' in hyper "
            '(the window-boundary reduce and the frozen-snapshot '
            'firing schedule are static program structure)')
    if (inv_staleness and static_cadence is not None
            and (int(static_cadence[1]) % built_chunks != 0
                 or int(static_cadence[1]) // built_chunks < 2)):
        import warnings
        warnings.warn(
            f'inv_staleness=1 with inv_pipeline_chunks='
            f'{built_chunks} does not fit this epoch\'s '
            f'inv_update_freq={static_cadence[1]} (needs freq/chunks '
            '>= 2) — firing eagerly/monolithically at window heads '
            'for the epoch')
        inv_staleness = 0
        chunks = 1
    # r15 runtime sanitizer gates (KFAC_SANITIZE=transfer,nan,retrace
    # — see analysis.sanitize). Env read once per epoch; unset = an
    # inert sanitizer whose step guard is a null context.
    sanitizer = _sanitize.Sanitizer.from_env()
    meters: dict[str, Metric] = {}
    t0 = time.perf_counter()
    n_batches = 0
    state_footprint = None  # computed lazily, once per epoch
    for batch in batches:
        if static_cadence is not None:
            f_freq, i_freq = static_cadence
            flags = cadence_flags(state.step, f_freq, i_freq, chunks,
                                  deferred_reduce=deferred_reduce,
                                  inv_staleness=inv_staleness)
        else:
            flags = {}
        wait_ms = None
        if barrier_probe is not None and (
                straggler_sample_every <= 1
                or state.step % straggler_sample_every == 0):
            # Straggler attribution: how long does THIS host wait for
            # the rest of the mesh before its next collective could
            # proceed? Measured before the dispatch so the wait is not
            # conflated with this step's own compute.
            wait_ms = barrier_probe()
        if cadence_policy is not None:
            # Straggler-aware cadence backoff (r12): may flip a
            # scheduled factor_update off while skew is sustained.
            # Applied BEFORE dispatch and before the fired-stage label
            # is derived, so attribution reflects what actually ran.
            flags = cadence_policy.adjust(state.step, flags, wait_ms)
        # Self-healing ladder (r16): escalated damping / quarantine
        # gates are traced-scalar VALUE changes on this step's hyper —
        # the dict structure is fixed at arming time, so the variant
        # cache never retraces. selfheal=None leaves hyper untouched.
        hyper_step = (hyper if selfheal is None
                      else selfheal.adjust_hyper(hyper))
        t_it = time.perf_counter()
        with sanitizer.step_guard(step_fn, flags):
            (state.params, state.opt_state, state.kfac_state,
             state.extra_vars, metrics) = step_fn(
                state.params, state.opt_state, state.kfac_state,
                state.extra_vars, batch, hyper_step, **flags)
        sanitizer.after_step(step_fn, state.step)
        dt = time.perf_counter() - t_it
        # A queued compile event right after the call means THIS step's
        # wall time is dominated by trace+XLA compile, not training
        # work. Label plain steps 'compile' so (a) the report's
        # step-time attribution names the real culprit and (b) the
        # health monitor's spike z-score excludes it — one absorbed
        # 20 s compile sample would otherwise inflate the running
        # stddev by orders of magnitude and blind the detector for the
        # whole run. Steps that also fired a K-FAC stage keep that
        # label (fired steps are excluded from spike stats anyway).
        fired = fired_stage(flags)
        if (fired and 'reduce' in fired
                and getattr(step_fn, 'hierarchical_reduce', False)):
            # r20: the window-boundary collective of a hierarchical
            # run crosses slices over DCN — relabel so the straggler
            # merger's wait_by_stage attributes DCN wait as its own
            # bucket (stragglers.stage_class routes 'dcn_reduce' to
            # 'dcn' before the generic 'reduce' match).
            fired = fired.replace('reduce', 'dcn_reduce')
        pending = getattr(step_fn, 'compile_events', None)
        if pending and fired is None:
            fired = 'compile'
        if metrics_sink is not None:
            # Enqueue only (device scalars + async host copy): the sink
            # converts to floats at drain time, far behind dispatch.
            metrics_sink.step_record(state.step, metrics,
                                     host_step_ms=dt * 1000.0,
                                     fired=fired)
            # Feed the dispatch timing into the host trace table too,
            # so epoch snapshots (and the report's stage table) carry a
            # per-stage row even when no phase is @trace-decorated.
            tracing.record('train_step_dispatch', dt)
            if memory_interval > 0 and state.step % memory_interval == 0:
                from distributed_kfac_pytorch_tpu.observability import (
                    memory as obs_memory,
                )
                if state_footprint is None:
                    state_footprint = obs_memory.state_footprint(
                        state.kfac_state)
                metrics_sink.memory_record(
                    state.step,
                    device=obs_memory.device_memory_stats(),
                    state=state_footprint)
        if rank_sink is not None:
            # Per-rank straggler shard: dispatch wall + barrier wait
            # only (the full metric set already rides the rank-0
            # stream; shards exist to compare HOSTS, not to duplicate
            # it).
            shard_metrics = {}
            if wait_ms is not None:
                from distributed_kfac_pytorch_tpu.observability import (
                    stragglers as obs_stragglers,
                )
                shard_metrics[obs_stragglers.BARRIER_WAIT_KEY] = wait_ms
            rank_sink.step_record(state.step, shard_metrics,
                                  host_step_ms=dt * 1000.0,
                                  fired=fired)
        if metrics_sink is not None:
            # Drain queued compile/retrace telemetry from the step
            # builder's variant cache (r10): rare, host-side, and
            # written as event records so the gate can regress the
            # retrace count offline. Duck-typed sinks that predate
            # event records (tests pass minimal step/epoch-only
            # stand-ins) just leave the queue in place.
            emit_event = getattr(metrics_sink, 'event_record', None)
            if pending and emit_event is not None:
                for ev in list(pending):
                    data = {k: v for k, v in ev.items() if k != 'event'}
                    emit_event(ev['event'], **data)
                pending.clear()
            # Autotune policy decisions (stretch/relax) ride the same
            # event channel so the report/gate can see them offline.
            if (cadence_policy is not None and emit_event is not None
                    and cadence_policy.pending_events):
                for ev in cadence_policy.drain_events():
                    data = {k: v for k, v in ev.items()
                            if k != 'event'}
                    emit_event(ev['event'], **data)
        if selfheal is not None:
            # Ladder observation (r16): host arithmetic except at its
            # window boundaries. May reset quarantined factor EWMAs in
            # state.kfac_state; may raise Rollback — the drain persists
            # the ladder's own escalation events on both paths, and
            # the except additionally flushes the sinks so the
            # completed steps' records survive the unwind, exactly
            # like a preemption.
            try:
                selfheal.observe(state, metrics)
            except BaseException:
                _drain_selfheal(selfheal, metrics_sink)
                if metrics_sink is not None:
                    metrics_sink.flush()
                if rank_sink is not None:
                    rank_sink.flush()
                raise
            _drain_selfheal(selfheal, metrics_sink)
        state.step += 1
        n_batches += 1
        for k, v in metrics.items():
            meters.setdefault(k, Metric(k)).update(v)
        if heartbeat is not None:
            # Liveness lease (r17): published before the checkpointer
            # hook so a hang inside it (the chaos hang fault, a wedged
            # collective save) leaves a fresh lease AT the hang step —
            # the supervisor then sees the lease stop advancing.
            heartbeat.beat(state.step)
        if checkpointer is not None:
            # May raise Preempted (after a blocking save). Flush the
            # sink first so the completed steps' records are durable
            # alongside the checkpoint the relaunch resumes from.
            try:
                checkpointer.after_step(
                    state, start_step_in_epoch + n_batches)
            except BaseException:
                if metrics_sink is not None:
                    metrics_sink.flush()
                if rank_sink is not None:
                    rank_sink.flush()
                raise
    elapsed = time.perf_counter() - t0
    if n_batches == 0:
        if start_step_in_epoch > 0:
            # Resumed exactly at the epoch boundary: nothing left to
            # replay; count the epoch as completed.
            state.epoch += 1
            return {'time_s': elapsed, 'ms_per_iter': 0.0}
        raise ValueError(
            'train_epoch: the batch iterator yielded ZERO batches — '
            'usually batch_size larger than the dataset (full batches '
            'are required for static shapes). Lower the batch size or '
            'enlarge the dataset.')
    out = {k: m.avg for k, m in meters.items()}
    out['time_s'] = elapsed
    out['ms_per_iter'] = elapsed / max(n_batches, 1) * 1000.0
    if metrics_sink is not None:
        metrics_sink.epoch_record(state.epoch, out,
                                  trace=tracing.snapshot_trace())
        metrics_sink.flush()
    if rank_sink is not None:
        rank_sink.flush()
    if log_writer is not None:
        for k, v in out.items():
            log_writer.scalar(f'train/{k}', v, state.epoch)
    if verbose:
        shown = {k: round(v, 4) for k, v in out.items()}
        print(f'epoch {state.epoch}: train {shown}')
    state.epoch += 1
    return out


def _replicated_specs(tree):
    """P() for every leaf (None leaves included) — shard_map boilerplate."""
    from jax.sharding import PartitionSpec as P
    return jax.tree.map(lambda _: P(), tree, is_leaf=lambda x: x is None)


def build_sgd_train_step(model, loss_fn, tx, mesh=None, *,
                         model_args_fn=None, model_kwargs_fn=None,
                         metrics_fn=None,
                         mutable_cols=(), batch_spec=None,
                         grad_accum_steps: int = 1,
                         donate: bool = True):
    """Plain data-parallel first-order train step (no K-FAC).

    The ``--kfac-update-freq 0`` path: the reference's examples fall back
    to bare SGD when K-FAC is disabled (cnn_utils/optimizers.py:28), so
    the same CLI flag must produce a working first-order baseline here.
    Signature matches ``DistributedKFAC.build_train_step``'s output
    (the ``kfac_state`` slot is threaded through untouched) so
    ``train_epoch`` works with either; ``grad_accum_steps`` splits the
    per-device shard into micro-batches with carry-summed gradients,
    keeping batch semantics identical to the K-FAC step it is compared
    against.

    The batch is sharded over the K-FAC data axes (same default as
    ``DistributedKFAC.build_train_step``); extra mesh axes are still
    averaged over so the step stays correct on any ``make_kfac_mesh``.

    ``model_kwargs_fn`` mirrors the K-FAC builder's parameter: a
    ``batch -> kwargs`` callable evaluated inside the (sharded) step,
    so it may use ``jax.lax.axis_index`` — e.g. the LM CLI's per-device
    dropout key fold (its SGD baseline needs the same dropout semantics
    as the K-FAC step it is compared against).
    """
    import optax
    from jax.sharding import PartitionSpec as P

    from distributed_kfac_pytorch_tpu.parallel.distributed import (
        KFAC_AXES,
        SLICE_AXIS,
    )

    if model_args_fn is None:
        model_args_fn = lambda batch: (batch[0],)
    mutable_cols = tuple(mutable_cols)
    data_axes = tuple(mesh.axis_names) if mesh is not None else ()
    if batch_spec is None and mesh is not None:
        batch_spec = P(tuple(a for a in (SLICE_AXIS,) + KFAC_AXES
                             if a in mesh.axis_names) or data_axes)
    if grad_accum_steps < 1:
        raise ValueError(f'{grad_accum_steps=} must be >= 1')

    def fwd_bwd(params, extra_vars, batch):
        kwargs = model_kwargs_fn(batch) if model_kwargs_fn else {}

        def wrapped(params):
            out = model.apply({'params': params, **extra_vars},
                              *model_args_fn(batch), **kwargs,
                              mutable=list(mutable_cols) or False)
            out, updated = out if mutable_cols else (out, {})
            extra = metrics_fn(out, batch) if metrics_fn else {}
            return loss_fn(out, batch), (extra, dict(updated))

        (loss, (extra_metrics, updated)), grads = jax.value_and_grad(
            wrapped, has_aux=True)(params)
        return loss, extra_metrics, updated, grads

    def local_step(params, opt_state, kstate, extra_vars, batch, hyper):
        if grad_accum_steps == 1:
            loss, extra_metrics, updated, grads = fwd_bwd(
                params, extra_vars, batch)
        else:
            from jax.sharding import PartitionSpec as P

            from distributed_kfac_pytorch_tpu.parallel.distributed import (
                normalize_batch_specs)
            specs = normalize_batch_specs(batch_spec, batch)

            def split(x, spec):
                if spec == P():
                    # Replicated per-step leaf (e.g. a PRNG key):
                    # broadcast, not sliced (same as the K-FAC step).
                    return jnp.broadcast_to(
                        x[None], (grad_accum_steps,) + x.shape)
                if x.shape[0] % grad_accum_steps:
                    raise ValueError(
                        f'per-device batch shard of {x.shape[0]} is not '
                        f'divisible by {grad_accum_steps=}')
                return x.reshape((grad_accum_steps,
                                  x.shape[0] // grad_accum_steps)
                                 + x.shape[1:])

            micro = jax.tree.map(split, batch, specs)
            first = jax.tree.map(lambda x: x[0], micro)
            shapes = jax.eval_shape(fwd_bwd, params, extra_vars, first)
            zeros = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                (shapes[0], shapes[1], shapes[3]))

            def body(carry, mb):
                extra_c, (loss_s, extras_s, grads_s) = carry
                loss, extra_metrics, updated, grads = fwd_bwd(
                    params, extra_c, mb)
                new_extra = ({**extra_c, **updated} if updated
                             else extra_c)
                sums = jax.tree.map(jnp.add,
                                    (loss_s, extras_s, grads_s),
                                    (loss, extra_metrics, grads))
                return (new_extra, sums), None

            (extra_out, sums), _ = jax.lax.scan(
                body, (extra_vars, zeros), micro)
            inv_n = 1.0 / grad_accum_steps
            loss, extra_metrics, grads = jax.tree.map(
                lambda x: x * inv_n, sums)
            updated = {c: extra_out[c] for c in mutable_cols
                       if c in extra_out}
        if data_axes:
            grads = jax.lax.pmean(grads, data_axes)
            loss = jax.lax.pmean(loss, data_axes)
            extra_metrics = jax.lax.pmean(extra_metrics, data_axes)
            if updated:
                updated = jax.lax.pmean(updated, data_axes)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if updated:
            extra_vars = {**extra_vars, **updated}
        metrics = {'loss': loss, **extra_metrics}
        return params, opt_state, kstate, extra_vars, metrics

    if mesh is None:
        return jax.jit(local_step,
                       donate_argnums=(0, 1, 3) if donate else ())

    def step(params, opt_state, kstate, extra_vars, batch, hyper):
        from distributed_kfac_pytorch_tpu.parallel.distributed import (
            normalize_batch_specs)
        batch_specs = normalize_batch_specs(batch_spec, batch)
        fn = jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(_replicated_specs(params),
                      _replicated_specs(opt_state),
                      _replicated_specs(kstate),
                      _replicated_specs(extra_vars),
                      batch_specs,
                      _replicated_specs(hyper)),
            out_specs=(_replicated_specs(params),
                       _replicated_specs(opt_state),
                       _replicated_specs(kstate),
                       _replicated_specs(extra_vars), P()),
            check_vma=False)
        return fn(params, opt_state, kstate, extra_vars, batch, hyper)

    return jax.jit(step, donate_argnums=(0, 1, 3) if donate else ())


def make_eval_step(model, loss_fn, mesh=None, *,
                   model_args_fn=None, model_kwargs=None, metrics_fn=None):
    """Jitted eval step: global-mean loss/accuracy over the mesh.

    Reference parity: engine.py:96-125 (test loop). With a mesh, the batch
    is sharded over the K-FAC axes and metrics are ``pmean``ed; without,
    it is a plain jitted forward. ``model_kwargs`` are static keyword
    arguments for the model call (e.g. ``{'train': False}``).
    """
    if model_args_fn is None:
        model_args_fn = lambda batch: (batch[0],)
    if metrics_fn is None:
        metrics_fn = lambda out, batch: {'acc': accuracy(out, batch[1])}
    model_kwargs = model_kwargs or {}

    def compute(params, extra_vars, batch):
        out = model.apply({'params': params, **extra_vars},
                          *model_args_fn(batch), **model_kwargs)
        metrics = {'loss': loss_fn(out, batch), **metrics_fn(out, batch)}
        if mesh is not None:
            metrics = jax.lax.pmean(metrics, KFAC_AXES)
        return metrics

    if mesh is None:
        return jax.jit(compute)

    from jax.sharding import PartitionSpec as P

    def step(params, extra_vars, batch):
        return jax.shard_map(
            compute, mesh=mesh,
            in_specs=(_replicated_specs(params),
                      _replicated_specs(extra_vars),
                      jax.tree.map(lambda _: P(KFAC_AXES), batch)),
            out_specs=P(), check_vma=False)(params, extra_vars, batch)

    return jax.jit(step)


def make_precise_bn_steps(model, mesh=None, *, model_args_fn=None,
                          stats_col: str = 'batch_stats'):
    """Jitted helpers for precise-BN recalibration (see
    :func:`precise_bn_recalibrate`); build once, reuse every epoch.

    Returns ``(momentum_fn, stat_fn)``:

    - ``momentum_fn(params, others, batch)`` extracts each BatchNorm
      leaf's EWMA momentum from the model itself by running the stats
      update from all-zeros and all-ones starting points (flax
      semantics: ``new = m*old + (1-m)*batch_stat`` is affine in
      ``old``, so ``u1 - u0 == m`` exactly, elementwise). This avoids
      requiring the caller to know every BN layer's momentum — any
      flax model with standard BatchNorm semantics works.
    - ``stat_fn(params, others, batch, m)`` returns that batch's raw
      statistics ``u0 / (1 - m)`` (mesh: ``pmean`` over the K-FAC data
      axes, i.e. the average of per-shard batch statistics).
    """
    from jax.sharding import PartitionSpec as P

    if model_args_fn is None:
        model_args_fn = lambda batch: (batch[0],)

    def updated(params, others, stats0, batch):
        _, upd = model.apply({'params': params, **others,
                              stats_col: stats0},
                             *model_args_fn(batch), mutable=[stats_col])
        return upd[stats_col]

    def momentum(params, others, batch, zeros, ones):
        u0 = updated(params, others, zeros, batch)
        u1 = updated(params, others, ones, batch)
        return jax.tree.map(
            lambda a, b: jnp.clip(b - a, 0.0, 1.0 - 1e-6), u0, u1)

    def stat(params, others, batch, m, zeros):
        u0 = updated(params, others, zeros, batch)
        s = jax.tree.map(lambda u, mm: u / (1.0 - mm), u0, m)
        if mesh is not None:
            s = jax.lax.pmean(s, KFAC_AXES)
        return s

    def wrap(fn, n_batch_arg):
        if mesh is None:
            return jax.jit(fn)

        def sharded(*args):
            in_specs = tuple(
                jax.tree.map(lambda _: P(KFAC_AXES), a)
                if i == n_batch_arg else _replicated_specs(a)
                for i, a in enumerate(args))
            # Both fns return a stats-shaped tree (arg 3's structure);
            # eval_shape can't trace fn here (the pmean needs the mesh).
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=_replicated_specs(args[3]),
                                 check_vma=False)(*args)

        return jax.jit(sharded)

    return wrap(momentum, 2), wrap(stat, 2)


def precise_bn_recalibrate(model, params, extra_vars: dict,
                           batches: Iterable, mesh=None, *,
                           model_args_fn=None,
                           stats_col: str = 'batch_stats',
                           steps=None) -> dict:
    """Re-estimate BatchNorm running statistics as the plain average of
    per-batch statistics over ``batches`` ("precise BN").

    Why: under K-FAC's large preconditioned steps the EWMA running
    statistics lag the weights, so eval-time normalization is stale —
    the round-3/4 convergence studies isolated exactly this interaction
    as the BN conv-net instability (GroupNorm control wins decisively;
    CONVERGENCE_CONV_{BN,GN}.json). A handful of forward-only batches
    re-estimates the statistics at the *current* weights, which is
    cheap (no backward pass) and touches nothing else: training state,
    params and optimizer are unchanged. The reference has no analogue —
    its eval loop consumes whatever running stats training left behind
    (examples/cnn_utils/engine.py:96-125).

    Models without a ``stats_col`` collection (GroupNorm nets) pass
    through unchanged. Returns a new ``extra_vars``; callers decide
    whether to use it for eval only or adopt it into training state.
    ``steps`` accepts the pair from :func:`make_precise_bn_steps` to
    reuse compiled programs across epochs.
    """
    stats = extra_vars.get(stats_col)
    if not stats:
        return extra_vars
    # Only dict-shaped entries are flax variable collections the model
    # can consume; framework state riding in extra_vars (e.g. the fp16
    # loss-scale pytree) is not passed to apply.
    others = {k: v for k, v in extra_vars.items()
              if k != stats_col and isinstance(v, dict)}
    momentum_fn, stat_fn = steps or make_precise_bn_steps(
        model, mesh, model_args_fn=model_args_fn, stats_col=stats_col)
    zeros = jax.tree.map(jnp.zeros_like, stats)
    ones = jax.tree.map(jnp.ones_like, stats)
    m = None
    total, n = None, 0
    for batch in batches:
        if m is None:
            m = momentum_fn(params, others, batch, zeros, ones)
        s = stat_fn(params, others, batch, m, zeros)
        total = s if total is None else jax.tree.map(jnp.add, total, s)
        n += 1
    if n == 0:
        raise ValueError('precise_bn_recalibrate: zero batches provided')
    new_stats = jax.tree.map(lambda t: t / n, total)
    return {**extra_vars, stats_col: new_stats}


def evaluate(eval_step, state: TrainState, batches: Iterable, *,
             log_writer=None, verbose: bool = False) -> dict[str, float]:
    """Run the eval loop; returns averaged metrics."""
    meters: dict[str, Metric] = {}
    n_batches = 0
    for batch in batches:
        metrics = eval_step(state.params, state.extra_vars, batch)
        n_batches += 1
        for k, v in metrics.items():
            meters.setdefault(k, Metric(k)).update(v)
    if n_batches == 0:
        raise ValueError(
            'evaluate: the batch iterator yielded ZERO batches — '
            'usually val_batch_size larger than the val set (full '
            'batches are required for static shapes). Lower the batch '
            'size or enlarge the dataset.')
    out = {k: m.avg for k, m in meters.items()}
    if log_writer is not None:
        for k, v in out.items():
            log_writer.scalar(f'val/{k}', v, state.epoch)
    if verbose:
        shown = {k: round(v, 4) for k, v in out.items()}
        print(f'epoch {state.epoch}: val {shown}')
    return out


class TensorBoardWriter:
    """Thin tf.summary wrapper (reference uses torch SummaryWriter,
    engine.py:89-93); no-ops cleanly if tensorflow is unavailable."""

    def __init__(self, log_dir: str):
        try:
            import tensorflow as tf
            self._writer = tf.summary.create_file_writer(log_dir)
            self._tf = tf
        except Exception:
            self._writer = None

    def scalar(self, tag: str, value, step: int):
        if self._writer is None:
            return
        with self._writer.as_default():
            self._tf.summary.scalar(tag, float(value), step=step)

    def flush(self):
        if self._writer is not None:
            self._writer.flush()
