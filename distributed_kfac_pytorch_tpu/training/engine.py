"""Train/eval epoch loops over the distributed K-FAC step.

Reference parity: examples/cnn_utils/engine.py (train/test loops with
allreduce-averaged metrics, progress display, TensorBoard scalars). The
per-step work (forward/backward, K-FAC, SGD, metric averaging) is entirely
inside the jitted step from ``DistributedKFAC.build_train_step``; the host
loop only feeds batches and accumulates metrics.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp

from distributed_kfac_pytorch_tpu.parallel.distributed import KFAC_AXES
from distributed_kfac_pytorch_tpu.training.utils import Metric, accuracy


@dataclasses.dataclass
class TrainState:
    """Everything a training step threads through (one pytree-of-pytrees).

    The analogue of the reference's (model, optimizer, preconditioner,
    schedulers) object group (torch_cifar10_resnet.py:153-176).
    """
    params: Any
    opt_state: Any
    kfac_state: Any
    extra_vars: dict
    step: int = 0
    epoch: int = 0


def train_epoch(step_fn, state: TrainState, batches: Iterable,
                hyper: dict, *, log_writer=None, verbose: bool = False,
                epoch_len: int | None = None) -> dict[str, float]:
    """One training epoch; returns averaged metrics.

    ``hyper`` holds this epoch's dynamic hyperparameters ('lr', 'damping',
    optionally cadence overrides) — the reference adjusts these per epoch
    via LambdaLR/KFACParamScheduler (engine.py:84-93).
    """
    meters: dict[str, Metric] = {}
    t0 = time.perf_counter()
    n_batches = 0
    for batch in batches:
        (state.params, state.opt_state, state.kfac_state, state.extra_vars,
         metrics) = step_fn(state.params, state.opt_state, state.kfac_state,
                            state.extra_vars, batch, hyper)
        state.step += 1
        n_batches += 1
        for k, v in metrics.items():
            meters.setdefault(k, Metric(k)).update(v)
    elapsed = time.perf_counter() - t0
    out = {k: m.avg for k, m in meters.items()}
    out['time_s'] = elapsed
    out['ms_per_iter'] = elapsed / max(n_batches, 1) * 1000.0
    if log_writer is not None:
        for k, v in out.items():
            log_writer.scalar(f'train/{k}', v, state.epoch)
    if verbose:
        shown = {k: round(v, 4) for k, v in out.items()}
        print(f'epoch {state.epoch}: train {shown}')
    state.epoch += 1
    return out


def make_eval_step(model, loss_fn, mesh=None, *,
                   model_args_fn=None, model_kwargs=None, metrics_fn=None):
    """Jitted eval step: global-mean loss/accuracy over the mesh.

    Reference parity: engine.py:96-125 (test loop). With a mesh, the batch
    is sharded over the K-FAC axes and metrics are ``pmean``ed; without,
    it is a plain jitted forward. ``model_kwargs`` are static keyword
    arguments for the model call (e.g. ``{'train': False}``).
    """
    if model_args_fn is None:
        model_args_fn = lambda batch: (batch[0],)
    if metrics_fn is None:
        metrics_fn = lambda out, batch: {'acc': accuracy(out, batch[1])}
    model_kwargs = model_kwargs or {}

    def compute(params, extra_vars, batch):
        out = model.apply({'params': params, **extra_vars},
                          *model_args_fn(batch), **model_kwargs)
        metrics = {'loss': loss_fn(out, batch), **metrics_fn(out, batch)}
        if mesh is not None:
            metrics = jax.lax.pmean(metrics, KFAC_AXES)
        return metrics

    if mesh is None:
        return jax.jit(compute)

    from jax.sharding import PartitionSpec as P
    rep = P()

    def step(params, extra_vars, batch):
        return jax.shard_map(
            compute, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: rep, params),
                      jax.tree.map(lambda _: rep, extra_vars),
                      jax.tree.map(lambda _: P(KFAC_AXES), batch)),
            out_specs=rep, check_vma=False)(params, extra_vars, batch)

    return jax.jit(step)


def evaluate(eval_step, state: TrainState, batches: Iterable, *,
             log_writer=None, verbose: bool = False) -> dict[str, float]:
    """Run the eval loop; returns averaged metrics."""
    meters: dict[str, Metric] = {}
    for batch in batches:
        metrics = eval_step(state.params, state.extra_vars, batch)
        for k, v in metrics.items():
            meters.setdefault(k, Metric(k)).update(v)
    out = {k: m.avg for k, m in meters.items()}
    if log_writer is not None:
        for k, v in out.items():
            log_writer.scalar(f'val/{k}', v, state.epoch)
    if verbose:
        shown = {k: round(v, 4) for k, v in out.items()}
        print(f'epoch {state.epoch}: val {shown}')
    return out


class TensorBoardWriter:
    """Thin tf.summary wrapper (reference uses torch SummaryWriter,
    engine.py:89-93); no-ops cleanly if tensorflow is unavailable."""

    def __init__(self, log_dir: str):
        try:
            import tensorflow as tf
            self._writer = tf.summary.create_file_writer(log_dir)
            self._tf = tf
        except Exception:
            self._writer = None

    def scalar(self, tag: str, value, step: int):
        if self._writer is None:
            return
        with self._writer.as_default():
            self._tf.summary.scalar(tag, float(value), step=step)

    def flush(self):
        if self._writer is not None:
            self._writer.flush()
