"""Training-application machinery (reference examples/ L4 layer).

Library-ized counterparts of the reference's example utilities:
``engine`` (train/eval loops), ``optimizers`` (SGD + K-FAC + scheduler
factory), ``datasets`` (CIFAR/ImageNet pipelines with synthetic
fallbacks), ``checkpoint`` (orbax save/auto-resume), ``utils``
(metrics, label smoothing, LR schedules).
"""

from distributed_kfac_pytorch_tpu.training import checkpoint
from distributed_kfac_pytorch_tpu.training import datasets
from distributed_kfac_pytorch_tpu.training import engine
from distributed_kfac_pytorch_tpu.training import optimizers
from distributed_kfac_pytorch_tpu.training import utils
