"""Input pipelines: CIFAR-10 and ImageNet, with synthetic fallbacks.

TPU-native counterpart of the reference's torchvision pipelines
(examples/cnn_utils/datasets.py): numpy-based host loaders feeding
globally-batched arrays that the jitted step shards over the mesh. Real
data is read from disk when present (CIFAR-10 python pickle batches;
ImageNet as a tf.data-readable directory tree); otherwise a deterministic
synthetic set of the same shapes keeps every example runnable offline
(the environment has no download egress — the reference instead
rank-0-downloads via torchvision, datasets.py:21-27).
"""

from __future__ import annotations

import os
import pickle
from typing import Iterator

import numpy as np

# Reference normalization constants (examples/cnn_utils/datasets.py:14-17,
# 37-44 — standard CIFAR/ImageNet mean/std).
CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR_STD = np.array([0.247, 0.243, 0.262], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

CIFAR_SEARCH_PATHS = (
    'data/cifar-10-batches-py',
    '/data/cifar-10-batches-py',
    os.path.expanduser('~/data/cifar-10-batches-py'),
)


def _load_cifar_pickles(root: str):
    xs, ys = [], []
    for name in [f'data_batch_{i}' for i in range(1, 6)]:
        with open(os.path.join(root, name), 'rb') as f:
            d = pickle.load(f, encoding='bytes')
        xs.append(d[b'data'])
        ys.extend(d[b'labels'])
    train = (np.concatenate(xs), np.array(ys, np.int32))
    with open(os.path.join(root, 'test_batch'), 'rb') as f:
        d = pickle.load(f, encoding='bytes')
    test = (d[b'data'], np.array(d[b'labels'], np.int32))

    def to_nhwc(flat):
        return flat.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)

    return ((to_nhwc(train[0]).astype(np.float32) / 255.0, train[1]),
            (to_nhwc(test[0]).astype(np.float32) / 255.0, test[1]))


def _synthetic_images(n: int, hw: int, n_classes: int, seed: int):
    """Deterministic class-conditional Gaussian images (learnable).

    Class prototypes are drawn from a fixed seed shared by every split, so
    a model trained on the synthetic train split generalizes to the
    synthetic test split; ``seed`` only varies the labels and noise.
    """
    protos = np.random.default_rng(1234).normal(
        size=(n_classes, hw, hw, 3)).astype(np.float32)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, size=n).astype(np.int32)
    x = 0.5 * protos[labels]
    x += rng.normal(scale=0.5, size=x.shape).astype(np.float32)
    return x.astype(np.float32), labels


def get_cifar(data_dir: str | None = None, synthetic_size: int = 2048):
    """((train_x, train_y), (test_x, test_y)) normalized NHWC CIFAR-10.

    Reads pickle batches from ``data_dir`` or the standard search paths;
    falls back to a synthetic set (``synthetic_size`` train / 1/4 test).
    ``KFAC_SYNTHETIC_CIFAR`` overrides the synthetic size from the
    environment — smoke tooling (e.g. the observability CI smoke) can
    bound a CLI run's data volume without a flag-surface change; real
    data directories are unaffected.
    """
    env_size = os.environ.get('KFAC_SYNTHETIC_CIFAR')
    if env_size:
        synthetic_size = int(env_size)
    roots = [data_dir] if data_dir else []
    roots += list(CIFAR_SEARCH_PATHS)
    for root in roots:
        if root and os.path.isfile(os.path.join(root, 'data_batch_1')):
            train, test = _load_cifar_pickles(root)
            break
    else:
        train = _synthetic_images(synthetic_size, 32, 10, seed=0)
        test = _synthetic_images(synthetic_size // 4, 32, 10, seed=1)
    norm = lambda x: (x - CIFAR_MEAN) / CIFAR_STD
    return (norm(train[0]), train[1]), (norm(test[0]), test[1])


def get_imagenet(data_dir: str | None = None, image_size: int = 224,
                 synthetic_size: int = 512, num_classes: int = 1000):
    """ImageNet pipelines; tf.data directory reader or synthetic.

    The reference uses ``torchvision.ImageFolder`` + DistributedSampler
    (datasets.py:31-51); here a ``tf.data`` JPEG pipeline when
    ``data_dir`` exists, else synthetic arrays shaped like ImageNet.
    Returns ((train_x, train_y), (val_x, val_y)) for the synthetic case or
    a pair of tf.data datasets for the real case (see ``imagenet_tfdata``).
    """
    if data_dir and os.path.isdir(os.path.join(data_dir, 'train')):
        return imagenet_tfdata(data_dir, image_size)
    train = _synthetic_images(synthetic_size, image_size, num_classes,
                              seed=0)
    # Val split matches the train size: a fraction of it (the old
    # synthetic_size // 4) was smaller than the default --val-batch-size,
    # which yields ZERO full batches and silently empty val metrics.
    val = _synthetic_images(synthetic_size, image_size, num_classes,
                            seed=1)
    norm = lambda x: (x - IMAGENET_MEAN) / IMAGENET_STD
    return (norm(train[0]), train[1]), (norm(val[0]), val[1])


def imagenet_tfdata(data_dir: str, image_size: int = 224):
    """tf.data train/val pipelines over an ImageFolder-style tree.

    Standard augmentation matching the reference (datasets.py:33-44):
    random-resized crop + horizontal flip for train; resize(256) +
    center-crop for eval; normalized NHWC float32.
    """
    import tensorflow as tf

    def class_table(split_dir):
        classes = sorted(os.listdir(split_dir))
        return {c: i for i, c in enumerate(classes)}

    def make(split, training):
        split_dir = os.path.join(data_dir, split)
        table = class_table(split_dir)
        files, labels = [], []
        for cls, idx in table.items():
            for fname in os.listdir(os.path.join(split_dir, cls)):
                files.append(os.path.join(split_dir, cls, fname))
                labels.append(idx)
        ds = tf.data.Dataset.from_tensor_slices(
            (tf.constant(files), tf.constant(labels, tf.int32)))
        if training:
            ds = ds.shuffle(len(files), seed=0,
                            reshuffle_each_iteration=True)

        def load(path, label):
            img = tf.io.decode_jpeg(tf.io.read_file(path), channels=3)
            img = tf.cast(img, tf.float32) / 255.0
            if training:
                img = tf.image.resize(img, (image_size + 32,
                                            image_size + 32))
                img = tf.image.random_crop(
                    img, (image_size, image_size, 3))
                img = tf.image.random_flip_left_right(img)
            else:
                img = tf.image.resize(img, (256, 256))
                off = (256 - image_size) // 2
                img = img[off:off + image_size, off:off + image_size]
            img = (img - IMAGENET_MEAN) / IMAGENET_STD
            return img, label

        return ds.map(load, num_parallel_calls=tf.data.AUTOTUNE)

    return make('train', True), make('val', False)


# ---------------------------------------------------------------------------
# Language-model corpora (reference examples/rnn_utils/utils.py,
# torch_language_model.py — PTB/WikiText-2 via torchnlp there; here plain
# tokenized text files with a synthetic fallback).
# ---------------------------------------------------------------------------

def get_lm_corpus(data_dir: str | None = None, *,
                  synthetic_size: int = 200_000,
                  vocab_size: int = 1000):
    """(train_ids, val_ids, vocab_size) token streams for LM training.

    Reads whitespace-tokenized ``train.txt`` / ``valid.txt`` under
    ``data_dir`` (PTB/WikiText layout), building the vocabulary from the
    train split. Without data, generates a synthetic Markov-chain corpus
    (learnable bigram structure, shared between splits).
    ``KFAC_SYNTHETIC_LM`` overrides the synthetic train-token count
    from the environment (the CI smokes bound the data volume this
    way, like ``KFAC_SYNTHETIC_CIFAR`` for the vision sets).
    """
    env_size = os.environ.get('KFAC_SYNTHETIC_LM')
    if env_size:
        synthetic_size = max(int(env_size), 10)
    if data_dir and os.path.isfile(os.path.join(data_dir, 'train.txt')):
        def read(split):
            with open(os.path.join(data_dir, f'{split}.txt')) as f:
                return f.read().replace('\n', ' <eos> ').split()
        train_tok = read('train')
        val_tok = read('valid')
        vocab = {w: i for i, w in enumerate(
            sorted(set(train_tok)) + ['<unk>'])}
        unk = vocab['<unk>']
        to_ids = lambda toks: np.array(
            [vocab.get(w, unk) for w in toks], np.int32)
        return to_ids(train_tok), to_ids(val_tok), len(vocab)

    # Synthetic: a sparse random bigram chain — the next token depends on
    # the current one, so an LSTM LM can beat the unigram entropy.
    rng = np.random.default_rng(1234)
    n_next = 8
    trans = rng.integers(0, vocab_size, size=(vocab_size, n_next))

    def gen(n, seed):
        r = np.random.default_rng(seed)
        out = np.empty(n, np.int32)
        tok = 0
        for i in range(n):
            out[i] = tok
            tok = trans[tok, r.integers(0, n_next)]
        return out

    return (gen(synthetic_size, 0), gen(synthetic_size // 10, 1),
            vocab_size)


def bptt_batches(ids: np.ndarray, batch_size: int, bptt: int, *,
                 shuffle_offset: bool = False, seed: int = 0,
                 epoch: int = 0, skip_batches: int = 0):
    """(inputs, targets) BPTT chunks of shape (batch, bptt).

    The stream is folded into ``batch_size`` parallel contiguous tracks
    (reference rnn_utils/utils.py:7-73 batchify + BPTT sampler); targets
    are inputs shifted by one. Hidden state can be carried across
    consecutive chunks of the same epoch.

    ``skip_batches`` drops the first K windows — mid-epoch resume
    (resilience r8): the per-epoch offset draw happens up front, so the
    remaining windows are bit-identical to the uninterrupted epoch's.
    """
    n = ids.shape[0]
    off = 0
    if shuffle_offset and (n - 1) // batch_size > bptt:
        off = int(np.random.default_rng(
            np.random.SeedSequence([seed, epoch])).integers(0, bptt))
    track = (n - 1 - off) // batch_size
    x = ids[off:off + batch_size * track].reshape(batch_size, track)
    t = ids[off + 1:off + 1 + batch_size * track].reshape(batch_size,
                                                          track)
    for bi, start in enumerate(range(0, track - 1, bptt)):
        stop = min(start + bptt, track)
        if stop - start < bptt:
            break  # keep shapes static for jit
        if bi < skip_batches:
            continue
        yield x[:, start:stop], t[:, start:stop]


def consume_augment_rng(rng: np.random.Generator, n: int) -> None:
    """Advance ``rng`` exactly as :func:`augment_cifar` would for a
    batch of ``n`` images, without the pixel work.

    Mid-epoch resume (resilience r8) skips already-trained batches but
    must leave the augmentation stream where the uninterrupted epoch
    would have it — otherwise every batch after the resume point draws
    different crops/flips and the replay is no longer bit-identical.
    MUST mirror augment_cifar's draw sequence (crop ys, crop xs, flip);
    tests/test_resilience.py pins the equivalence.
    """
    rng.integers(0, 9, size=n)
    rng.integers(0, 9, size=n)
    rng.random(n)


def augment_cifar(x: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """Pad-4 random crop + horizontal flip (reference datasets.py:14-17).

    Random draws happen here (numpy), then the per-pixel work runs in the
    native C++ kernel (``native.augment_batch``, threaded) when the
    toolchain built it, else in the numpy fallback — both bit-identical.
    The draw sequence is mirrored by :func:`consume_augment_rng` for
    mid-epoch resume; change one, change both.
    """
    from distributed_kfac_pytorch_tpu import native

    n, h, w, c = x.shape
    ys = rng.integers(0, 9, size=n).astype(np.int32)
    xs = rng.integers(0, 9, size=n).astype(np.int32)
    flip = (rng.random(n) < 0.5).astype(np.uint8)
    out = native.augment_batch(x, ys, xs, flip, pad=4)
    if out is not None:
        return out
    padded = np.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)), mode='reflect')
    out = np.empty_like(x)
    for i in range(n):
        img = padded[i, ys[i]:ys[i] + h, xs[i]:xs[i] + w]
        out[i] = img[:, ::-1] if flip[i] else img
    return out


def epoch_batches(x: np.ndarray, y: np.ndarray, batch_size: int, *,
                  shuffle: bool = True, seed: int = 0, epoch: int = 0,
                  augment: bool = False, drop_last: bool = True,
                  skip_batches: int = 0
                  ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Global-batch iterator (the mesh shards each batch on device).

    Replaces the reference's DistributedSampler (datasets.py:57-63): under
    GSPMD there is one logical batch per step; per-epoch reshuffling is
    seeded like ``sampler.set_epoch`` for reproducibility. Truncate with
    ``itertools.islice`` when only a few batches are needed (e.g. the
    precise-BN recalibration pass).

    ``skip_batches`` fast-forwards past the first K batches for
    mid-epoch resume (resilience r8): skipped batches are not built,
    but their augmentation RNG draws ARE consumed
    (:func:`consume_augment_rng`), so batch K+1 onward is bit-identical
    to the uninterrupted epoch's sequence.
    """
    n = x.shape[0]
    rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
    idx = rng.permutation(n) if shuffle else np.arange(n)
    end = n - (n % batch_size) if drop_last else n
    for bi, start in enumerate(range(0, end, batch_size)):
        sel = idx[start:start + batch_size]
        if bi < skip_batches:
            if augment:
                consume_augment_rng(rng, len(sel))
            continue
        xb = x[sel]
        if augment:
            xb = augment_cifar(xb, rng)
        yield xb, y[sel]
