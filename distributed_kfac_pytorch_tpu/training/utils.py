"""Shared training utilities: metrics, losses, LR schedules.

Reference parity: examples/utils.py (Metric, LabelSmoothLoss, accuracy,
create_lr_schedule). Collective averaging of metrics happens inside the
jitted steps (pmean), so the host-side Metric is a plain weighted mean.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import optax


class Metric:
    """Weighted running average of a scalar (loss, accuracy).

    The reference allreduce-averages each update (examples/utils.py:35-48);
    here values arriving from a jitted step are already globally averaged,
    so this just accumulates over batches.
    """

    def __init__(self, name: str):
        self.name = name
        self._sum = 0.0
        self._n = 0.0

    def update(self, value, n: float = 1.0):
        # No float() here: converting a just-computed device scalar
        # blocks the host on the step every update (~100+ ms per metric
        # per step through a device tunnel). Accumulating the device
        # array keeps the sync lazy until ``avg`` is read (epoch end).
        self._sum = self._sum + value * n
        self._n += n

    @property
    def avg(self) -> float:
        return float(self._sum) / max(self._n, 1e-12)


def accuracy(logits, labels) -> jnp.ndarray:
    """Top-1 accuracy of logits vs integer labels.

    Reference parity: examples/utils.py:6-8.
    """
    return (jnp.argmax(logits, axis=-1) == labels).mean()


def label_smooth_loss(logits, labels, smoothing: float = 0.0):
    """Cross entropy with label smoothing.

    Reference parity: examples/utils.py:21-33 (LabelSmoothLoss); with
    ``smoothing=0`` this is plain softmax cross entropy.
    """
    if smoothing <= 0.0:
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
    n = logits.shape[-1]
    one_hot = jnp.eye(n, dtype=logits.dtype)[labels]
    smoothed = one_hot * (1.0 - smoothing) + smoothing / n
    return optax.softmax_cross_entropy(logits, smoothed).mean()


def create_lr_schedule(workers: int, warmup_epochs: float,
                       decay_schedule: Sequence[int],
                       alpha: float = 0.1):
    """LR *factor* schedule over epochs: linear warmup then step decay.

    Reference parity: examples/utils.py:50-61 — warms from 1/workers up to
    ``workers``-scaled over ``warmup_epochs``, then multiplies by ``alpha``
    at each epoch in ``decay_schedule``. Returns ``f(epoch) -> factor`` to
    multiply with the base (per-worker) learning rate.
    """
    decay_schedule = sorted(decay_schedule)

    def schedule(epoch: float) -> float:
        if warmup_epochs > 0 and epoch < warmup_epochs:
            # epoch 0 -> 1.0 (base lr), epoch warmup -> workers (scaled).
            return 1.0 + (workers - 1.0) * (epoch / warmup_epochs)
        factor = float(workers)
        for e in decay_schedule:
            if epoch >= e:
                factor *= alpha
        return factor

    return schedule
