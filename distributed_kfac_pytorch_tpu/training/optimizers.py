"""Optimizer factory: SGD + distributed K-FAC + schedulers.

Reference parity: examples/cnn_utils/optimizers.py:8-74 (SGD with momentum
and L2, optional KFAC with CommMethod mapping, KFACParamScheduler, and a
warmup/decay LR schedule applied to both) — built on optax and the
functional preconditioner.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax.numpy as jnp
import optax

from distributed_kfac_pytorch_tpu.preconditioner import CommMethod, KFAC
from distributed_kfac_pytorch_tpu.scheduler import KFACParamScheduler
from distributed_kfac_pytorch_tpu.training.utils import create_lr_schedule

# CLI string -> CommMethod (reference optimizers.py:18-26).
COMM_METHODS = {
    'comm-opt': CommMethod.COMM_OPT,
    'mem-opt': CommMethod.MEM_OPT,
    'hybrid-opt': CommMethod.HYBRID_OPT,
    'hybrid_opt': CommMethod.HYBRID_OPT,
    'comm_opt': CommMethod.COMM_OPT,
    'mem_opt': CommMethod.MEM_OPT,
}


@dataclasses.dataclass
class OptimConfig:
    """Hyperparameters for the optimizer stack (reference CLI flags,
    torch_cifar10_resnet.py:46-97)."""
    base_lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 5e-4
    nesterov: bool = False
    warmup_epochs: float = 5.0
    lr_decay: Sequence[int] = (35, 75, 90)
    lr_decay_alpha: float = 0.1
    workers: int = 1                      # world size for LR scaling
    # K-FAC (0 update freq disables, like the reference's --kfac-update-freq 0)
    kfac_inv_update_freq: int = 10
    kfac_cov_update_freq: int = 1
    damping: float = 0.003
    factor_decay: float = 0.95
    kl_clip: float = 0.001
    use_eigen_decomp: bool | None = None  # None: follow inverse_method
    # 'auto' | 'eigen' | 'cholesky' | 'newton'; None (default) -> the
    # per-dim 'auto' dispatch (eigen below KFAC.auto_eigen_max_dim,
    # cholesky above — fast at every factor scale).
    inverse_method: str | None = None
    # 'auto' dispatch knobs (KFAC defaults: 640 / 'cholesky' — the
    # measured v5e crossover; see PERF.md round 4).
    auto_eigen_max_dim: int = 640
    auto_large_method: str = 'cholesky'
    # Randomized low-rank inverse path (r19, arXiv:2206.15397): with
    # rank > 0, dense factor dims >= inv_lowrank_dim_threshold
    # decompose as a rank-r truncated eigenpair (Gaussian range-finder
    # sketch, warm subspace-refresh + polish each firing — r·d^2
    # matmul work instead of the O(d^3) eigh/cholesky wall) and
    # precondition through the truncated basis plus the damping-only
    # tail complement (full-rank correct). 0 (default) = off, the
    # bit-identical exact path. rank must be < every engaged dim
    # (hard error at registration, never a silent fallback).
    inv_lowrank_rank: int = 0
    inv_lowrank_dim_threshold: int = 2048
    # 'auto' (default): warm-start basis polish seeded from the state's
    # previous eigenbasis (the TPU fast path — see ops.linalg.eigh_polish);
    # 'xla' | 'jacobi' | 'warm' as in KFAC.
    eigh_method: str = 'auto'
    eigh_polish_iters: int = 8
    # Fraction of the batch used for factor statistics (1.0 = reference
    # parity; < 1 thins the covariance sample within the step — see
    # KFAC.factor_batch_fraction).
    factor_batch_fraction: float = 1.0
    # bf16 factor storage/averaging AND bf16 covariance-matmul inputs
    # (the matmuls accumulate fp32; the EWMA running averages are kept in
    # bf16) — the reference's --fp16 factor mode. For bf16 matmuls with
    # fp32 running averages, pass factor_compute_dtype to KFAC directly.
    bf16_factors: bool = False
    # bf16 INVERSE storage (KFAC inv_dtype; decompositions stay fp32 —
    # the reference's configurable inv_dtype, base.py:435-441). Halves
    # K-FAC state; with bf16_factors it is what fits the monolithic
    # b256 ResNet-50 capture-free step on a 16 GB chip and speeds the
    # 'auto' firing 1.5x (PERF.md round 5).
    bf16_inverses: bool = False
    # bf16 precondition-contraction operands (KFAC
    # precond_compute_dtype; accumulation stays fp32) — the every-step
    # inverse·grad matmuls run on the MXU bf16 path, and with
    # bf16_inverses the stored inverses are consumed resident (no fp32
    # upcast-on-read). Default False = the bit-identical fp32 path.
    bf16_precond: bool = False
    # Pipelined inverse firing (r9): partition the per-firing inverse
    # work into k cost-balanced chunks and fire chunk j on step
    # j*inv_update_freq/k of each cadence window — smears the
    # decomposition spike across the window (step-time uniformity).
    # 1 (default) = reference parity, monolithic firing, bit-identical.
    inv_pipeline_chunks: int = 1
    # Deferred factor reduction (r14): accumulate factor statistics
    # locally on factor steps and reduce across replicas once per
    # cadence window (one bucketed collective where the eager path
    # pays a per-factor-step pmean). Mathematically exact by EMA
    # linearity; off (default) = bit-identical eager path.
    deferred_factor_reduction: bool = False
    # Hierarchical two-level factor reduction (r20, multi-slice
    # meshes only; mutually exclusive with deferred_factor_reduction):
    # intra-slice pmean on ICI every factor step, one bucketed
    # inter-slice DCN reduce per cadence window. Exact by the same
    # EMA-linearity argument; off (default) = flat reduce.
    hierarchical_reduce: bool = False
    # One-window-stale off-critical-path inverses (r14): 0 (default,
    # bit-identical) or 1 — decompositions for window w+1 are computed
    # from factors frozen at the end of window w and chunk-fired
    # across w+1's plain steps, so the eigh spike overlaps plain
    # compute instead of blocking the mesh. Convergence-gated like the
    # r9 chunk knob (PERF.md r14).
    inv_staleness: int = 0
    # Weight-sharing Kronecker approximation (r13, arXiv:2311.00636):
    # 'expand' (default — bit-identical pre-sharing path) or 'reduce'
    # (sequence/patch-shared Denses + patch-embed convs reduce over the
    # shared axis before the covariance: a factor-T cheaper factor
    # update; tied in/out embeddings then also share one factor pair).
    # See KFAC.kfac_approx / sharing.approx.
    kfac_approx: str = 'expand'
    # r21 fused hot-path Pallas kernels (ops.pallas_kernels; README
    # "Fused hot-path kernels"). Default off = bit-identical stock XLA
    # paths; each knob is gated by a once-per-process parity probe that
    # falls back to XLA with a recorded 'pallas_fallback' event.
    # fused_factor_contraction: symmetric packed x.T@x factor
    # contraction fused with the EMA blend (and the r14 accumulator
    # fold) in VMEM — only the symmetric triangle round-trips HBM.
    fused_factor_contraction: bool = False
    # fused_precondition: bucketed precondition matmul stacks with the
    # r6 KL-clip v·g partial reduced in the kernel epilogue (no second
    # full-tensor pass), on the single-chip, replicated COMM_OPT and
    # KAISA row-sharded branches.
    fused_precondition: bool = False
    # r7 observability: carry an on-device K-FAC metrics pytree in the
    # state (damping, KL-clip nu, grad/precond norms, firing counts —
    # see observability.metrics). Off (default) = bit-identical step.
    kfac_metrics: bool = False
    # Skip factor EWMA updates whose candidate factors are non-finite
    # (the on-device health guard; counted in metrics when they are on).
    nonfinite_guard: bool = False
    skip_layers: Sequence[str] = ()
    symmetry_aware_comm: bool = False
    comm_method: str = 'comm-opt'
    grad_worker_fraction: float = 0.25
    damping_alpha: float = 1.0
    damping_schedule: Sequence[int] = ()
    kfac_update_freq_alpha: float = 1.0
    kfac_update_freq_schedule: Sequence[int] = ()


#: OptimConfig fields the perf autotuner may override from a committed
#: ``TUNED_<workload>.json`` artifact (``autotune.apply_tuned``). The
#: set is restricted to per-KFAC knobs that leave the mesh topology
#: alone: mesh-shaping knobs (``comm_method``,
#: ``grad_worker_fraction``) would desync the already-constructed mesh
#: from the config, so they stay CLI-flag-only (the artifact records
#: them as provenance instead). An artifact naming a knob outside this
#: set is rejected whole (fail-closed) rather than applied partially.
TUNABLE_FIELDS = (
    'bf16_precond',
    'bf16_factors',
    'bf16_inverses',
    'inv_pipeline_chunks',
    'deferred_factor_reduction',
    'hierarchical_reduce',
    'inv_staleness',
    'factor_batch_fraction',
    'kfac_cov_update_freq',
    'kfac_inv_update_freq',
    'eigh_polish_iters',
    'kfac_approx',
    'inv_lowrank_rank',
    'inv_lowrank_dim_threshold',
    'fused_factor_contraction',
    'fused_precondition',
)


def make_sgd(cfg: OptimConfig) -> optax.GradientTransformation:
    """SGD with L2 and momentum, torch-ordered (wd before momentum).

    Matches torch.optim.SGD semantics used by the reference
    (optimizers.py:10-14): ``g += wd * p``; ``buf = m * buf + g``;
    ``p -= lr * buf``. The learning rate is injected so the engine can
    schedule it without rebuilding the transformation.
    """
    def tx(learning_rate):
        chain = []
        if cfg.weight_decay:
            chain.append(optax.add_decayed_weights(cfg.weight_decay))
        if cfg.momentum:
            chain.append(optax.trace(decay=cfg.momentum,
                                     nesterov=cfg.nesterov))
        chain.append(optax.scale_by_learning_rate(learning_rate))
        return optax.chain(*chain)

    return optax.inject_hyperparams(tx)(learning_rate=cfg.base_lr)


def set_lr(opt_state, lr):
    """Return opt_state with the injected learning rate replaced.

    Accepts the bare ``inject_hyperparams`` state or a ``chain`` state
    containing one (e.g. when gradient clipping is chained in front).
    """
    states = (opt_state,) if hasattr(opt_state, 'hyperparams') else (
        opt_state if isinstance(opt_state, tuple) else ())
    for s in states:
        if hasattr(s, 'hyperparams'):
            # Preserve the leaf's exact aval (array-ness, dtype AND
            # weak_type): writing a Python float where an array leaf
            # lived — or a strong-typed array where a weak one lived —
            # changes the jit argument signature and silently recompiles
            # the train step every epoch (~15-45 s per variant on TPU).
            prev = jnp.asarray(s.hyperparams['learning_rate'])
            if prev.weak_type:
                new = jnp.asarray(float(lr))
            else:
                new = jnp.asarray(lr, dtype=prev.dtype)
            s.hyperparams['learning_rate'] = new
            return opt_state
    raise ValueError('no injected learning_rate in optimizer state')


def get_optimizer(model, cfg: OptimConfig):
    """(tx, lr_schedule, kfac | None, kfac_scheduler | None).

    ``lr_schedule(epoch) -> lr`` (base_lr x warmup/decay factor, reference
    optimizers.py:68-72 applies the same LambdaLR to SGD and KFAC — here
    the engine feeds the same value to optax and to the KL-clip ``lr``).
    K-FAC is enabled when ``kfac_inv_update_freq > 0`` (reference
    optimizers.py:28).
    """
    tx = make_sgd(cfg)
    factor = create_lr_schedule(cfg.workers, cfg.warmup_epochs,
                                cfg.lr_decay, cfg.lr_decay_alpha)
    lr_schedule = lambda epoch: cfg.base_lr * factor(epoch)

    kfac = None
    kfac_scheduler = None
    if cfg.kfac_inv_update_freq > 0:
        kfac = KFAC(
            model,
            damping=cfg.damping,
            factor_decay=cfg.factor_decay,
            factor_update_freq=cfg.kfac_cov_update_freq,
            inv_update_freq=cfg.kfac_inv_update_freq,
            kl_clip=cfg.kl_clip,
            lr=cfg.base_lr,
            use_eigen_decomp=cfg.use_eigen_decomp,
            inverse_method=cfg.inverse_method,
            auto_eigen_max_dim=cfg.auto_eigen_max_dim,
            auto_large_method=cfg.auto_large_method,
            inv_lowrank_rank=cfg.inv_lowrank_rank,
            inv_lowrank_dim_threshold=cfg.inv_lowrank_dim_threshold,
            eigh_method=cfg.eigh_method,
            eigh_polish_iters=cfg.eigh_polish_iters,
            factor_batch_fraction=cfg.factor_batch_fraction,
            factor_dtype=jnp.bfloat16 if cfg.bf16_factors else None,
            factor_compute_dtype=(jnp.bfloat16 if cfg.bf16_factors
                                  else None),
            inv_dtype=(jnp.bfloat16 if cfg.bf16_inverses
                       else jnp.float32),
            precond_compute_dtype=(jnp.bfloat16 if cfg.bf16_precond
                                   else None),
            inv_pipeline_chunks=cfg.inv_pipeline_chunks,
            deferred_factor_reduction=cfg.deferred_factor_reduction,
            hierarchical_reduce=cfg.hierarchical_reduce,
            inv_staleness=cfg.inv_staleness,
            kfac_approx=cfg.kfac_approx,
            skip_layers=list(cfg.skip_layers) or None,
            symmetry_aware_comm=cfg.symmetry_aware_comm,
            comm_method=COMM_METHODS[cfg.comm_method.lower()],
            grad_worker_fraction=cfg.grad_worker_fraction,
            collect_metrics=cfg.kfac_metrics,
            nonfinite_guard=cfg.nonfinite_guard,
            fused_factor_contraction=cfg.fused_factor_contraction,
            fused_precondition=cfg.fused_precondition)
        kfac_scheduler = KFACParamScheduler(
            kfac,
            damping_alpha=cfg.damping_alpha,
            damping_schedule=list(cfg.damping_schedule) or None,
            update_freq_alpha=cfg.kfac_update_freq_alpha,
            update_freq_schedule=(
                list(cfg.kfac_update_freq_schedule) or None))
    return tx, lr_schedule, kfac, kfac_scheduler
