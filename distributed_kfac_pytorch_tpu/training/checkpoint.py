"""Checkpoint save / auto-resume via orbax.

Reference parity: examples/utils.py:10-19 (save_checkpoint bundling
model + optimizer + preconditioner + scheduler states) and the
auto-resume scan in torch_cifar10_resnet.py:147-151 (find the newest
epoch checkpoint and restore). K-FAC factors are saved but inverses are
recomputed on load (reference preconditioner.py:294-353, README.md:222-223)
— the caller passes ``kfac_state_dict`` already filtered by
``KFAC.state_dict``.

Orbax handles sharded arrays natively: distributed inverse stacks save
and restore with their shardings, so resume works across pod restarts.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

#: Filename recording WHY a bundle was moved to ``<label>.quarantined``
#: (written by :meth:`CheckpointManager.quarantine`, read back by
#: ``quarantine_info`` for the --resume-step refusal message).
QUARANTINE_REASON_FILE = 'QUARANTINE_REASON'


class CheckpointManager:
    """Epoch-indexed checkpoints with auto-resume.

    Stores one composite pytree per epoch under ``directory/<epoch>/``;
    ``latest_epoch()``/``restore()`` implement the reference's
    scan-downward resume (torch_cifar10_resnet.py:147-151) via orbax's
    step tracking.
    """

    def __init__(self, directory: str, max_to_keep: int | None = 2):
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
            # Explicit handler so ``item_metadata`` works on a FRESH
            # manager (a resumed process that has not saved yet has no
            # lazily-registered handler; without this, orbax returns
            # None and the elastic restore path cannot inspect saved
            # shapes before reading data). Same handler save/restore
            # already use via args=Standard{Save,Restore}.
            item_handlers=ocp.StandardCheckpointHandler())

    def save(self, epoch: int, tree: dict, *, force: bool = False,
             blocking: bool = False) -> None:
        """Save a checkpoint tree.

        Async by default: orbax snapshots the (device) arrays and writes
        in a background thread, so a multi-GB ImageNet-scale save does
        not stall the training loop (the step right after a save
        proceeds while bytes hit disk). Pending writes are joined by the
        next ``save``/``restore``/``latest_epoch``/``close`` call —
        orbax serializes them internally — or explicitly via
        :meth:`wait_until_finished`. Pass ``blocking=True`` (or call
        ``wait_until_finished``) where durability must be certain before
        proceeding, e.g. right before process exit.

        ``force=True`` additionally REPLACES an existing bundle at the
        same label (orbax's own ``force`` only bypasses the
        save-interval policy and still raises StepAlreadyExistsError):
        an in-process self-heal rollback (r16) rewinds the step/epoch
        counters, and the replay's saves land on labels whose
        pre-rollback bundles are stale garbage from an abandoned
        timeline — they must be overwritten, not fatal.
        """
        try:
            self._mgr.save(epoch, args=ocp.args.StandardSave(tree),
                           force=force)
        except Exception as e:
            if not force or \
                    type(e).__name__ != 'StepAlreadyExistsError':
                raise
            self._mgr.wait_until_finished()
            self._mgr.delete(epoch)
            self._mgr.save(epoch, args=ocp.args.StandardSave(tree),
                           force=True)
        if blocking:
            self._mgr.wait_until_finished()

    def wait_until_finished(self) -> None:
        """Block until all pending async saves are durable on disk."""
        self._mgr.wait_until_finished()

    def latest_epoch(self) -> int | None:
        self._mgr.wait_until_finished()  # join any pending async save
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        """Every finalized checkpoint label on disk, ascending. The
        verified-resume walk (``resilience.cli.resume`` /
        ``resilience.selfheal.rollback_restore``) iterates these
        newest-first, quarantining corrupt/torn bundles until one
        verifies (r16)."""
        self._mgr.wait_until_finished()
        return sorted(self._mgr.all_steps())

    def quarantine(self, label: int,
                   reason: str | None = None) -> str | None:
        """Move a corrupt bundle's directory aside
        (``<label>.quarantined[.N]`` — kept for forensics, invisible
        to orbax's integer-step scan) and resync the manager.

        Without the move, a run that resumed PAST the corrupt bundle
        re-reaches its step and orbax refuses the re-save
        (StepAlreadyExistsError) — the quarantined garbage would brick
        the very replay the verified walk just enabled. On shared
        multihost storage the first mover wins; losers see the dir
        gone and only resync. Returns the new path (None if another
        rank already moved it).

        ``reason`` is recorded as ``QUARANTINE_REASON`` inside the
        moved directory (best effort) so a later explicit
        ``--resume-step`` at this label can tell the operator WHY the
        bundle was moved, not just that it is gone (r17;
        :meth:`quarantine_info`)."""
        self._mgr.wait_until_finished()
        src = os.path.join(self.directory, str(label))
        dst = f'{src}.quarantined'
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = f'{src}.quarantined.{n}'
        moved = None
        try:
            os.replace(src, dst)
            moved = dst
        except FileNotFoundError:
            pass  # raced with another rank (or already gone)
        if moved is not None and reason:
            try:
                with open(os.path.join(moved,
                                       QUARANTINE_REASON_FILE),
                          'w') as f:
                    f.write(str(reason) + '\n')
            except OSError:
                pass  # forensics metadata must never fail the walk
        reload = getattr(self._mgr, 'reload', None)
        if reload is not None:
            reload()
        return moved

    def quarantined_paths(self, label: int) -> list[str]:
        """Quarantined copies of ``label`` on disk, oldest first
        (``<label>.quarantined``, ``.quarantined.1``, ...)."""
        src = os.path.join(self.directory, str(label))
        out = []
        dst = f'{src}.quarantined'
        n = 0
        while os.path.exists(dst):
            out.append(dst)
            n += 1
            dst = f'{src}.quarantined.{n}'
        return out

    def quarantine_info(self, label: int) -> tuple[str, str] | None:
        """``(path, reason)`` of the NEWEST quarantined copy of
        ``label`` — but only when no live bundle exists at that label
        (a live bundle supersedes its quarantined history: the replay
        re-saved it). None otherwise. The resume walk uses this to
        refuse an explicit ``--resume-step`` at a quarantined label
        with the real story instead of a bare not-found."""
        if os.path.exists(os.path.join(self.directory, str(label))):
            return None
        paths = self.quarantined_paths(label)
        if not paths:
            return None
        newest = paths[-1]
        reason = 'no recorded reason (pre-r17 quarantine)'
        try:
            with open(os.path.join(newest,
                                   QUARANTINE_REASON_FILE)) as f:
                reason = f.read().strip() or reason
        except OSError:
            pass
        return newest, reason

    def restore(self, epoch: int | None = None,
                like: dict | None = None) -> dict:
        """Restore a checkpoint (the latest when ``epoch`` is None).

        ``like`` provides the target pytree structure/shardings; restored
        arrays adopt its placements (replicated vs row-sharded state).

        WITHOUT ``like``, orbax falls back to the checkpoint's own
        recorded metadata: host-staged arrays laid out for the
        topology that SAVED them (orbax itself warns this is UNSAFE).
        That only works when the restoring world exactly matches the
        saving world — resuming a pod checkpoint at a different
        process/device count, or an SPMD checkpoint on one chip, gets
        wrong or failing placements. Engine/CLI resume paths therefore
        ALWAYS pass ``like`` (a live-state bundle of the same
        structure — ``resilience.cli.resume`` enforces this): restored
        arrays adopt the LIVE state's committed shardings regardless
        of what wrote the checkpoint, and
        ``DistributedKFAC.load_state_dict`` re-commits stray host
        leaves as a second line of defense. Regression-tested in
        tests/test_resilience.py (like= adopts the live placements;
        sharded SPMD kill-and-resume).

        Restoring onto a DIFFERENT topology is supported through the
        elastic path, not through this method's bare form: bundles
        record their saving world in ``topo_*`` scalars
        (``elastic.topology``), ``restore_replicated`` brings the
        bundle up replicated on any live mesh, and
        ``elastic.reshard`` repacks the K-FAC slot stacks for the new
        world — ``resilience.cli.resume(elastic=...)`` wires it all
        (README "Elastic training").
        """
        self._mgr.wait_until_finished()  # join any pending async save
        if epoch is None:
            epoch = self.latest_epoch()
        if epoch is None:
            raise FileNotFoundError(
                f'no checkpoints found under {self.directory}')
        steps = self._mgr.all_steps()
        if epoch not in steps:
            # Orbax's own failure for a missing step is an opaque
            # directory error; name the request and what IS on disk.
            raise FileNotFoundError(
                f'no checkpoint for step {epoch} under '
                f'{self.directory}; steps on disk: '
                f'{sorted(steps) if steps else "none"}')
        if like is not None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._mgr.restore(
                epoch, args=ocp.args.StandardRestore(abstract))
        # Explicit StandardRestore: a manager that has not saved in this
        # process has no handler registered for the step yet (a resumed
        # fresh process always starts this way).
        return self._mgr.restore(epoch, args=ocp.args.StandardRestore())

    def metadata_tree(self, epoch: int) -> dict:
        """Saved tree structure + per-leaf shape/dtype, WITHOUT reading
        array data (orbax ``item_metadata``). The elastic resume path
        uses this to decide between a same-topology ``like=`` restore
        and a cross-topology replicated restore, and to build the
        latter's template."""
        self._mgr.wait_until_finished()
        return self._mgr.item_metadata(epoch)

    def restore_replicated(self, epoch: int, mesh,
                           like: dict | None = None) -> dict:
        """Restore a bundle fully REPLICATED on ``mesh`` — the
        topology-independent layout any world can load.

        The template is built from the checkpoint's own metadata
        (saved shapes/dtypes, replicated shardings on the LIVE mesh),
        so it works regardless of what world wrote the bundle —
        multi-host safe, unlike the bare no-``like`` restore. Scalars
        (0-d leaves) come back as host scalars.

        ``like``: the live bundle template. Its ``opt_state`` subtree,
        when present, is used for that group's restore template
        instead of the metadata's — orbax metadata comes back in plain
        containers, and the optimizer state is the one bundle group
        holding custom pytree nodes (optax states) whose structure the
        caller needs preserved; its shapes are topology-independent,
        so the live template's are correct.
        """
        from jax.sharding import NamedSharding, PartitionSpec

        md = self.metadata_tree(epoch)
        rep = NamedSharding(mesh, PartitionSpec())

        def of_meta(m):
            shape = tuple(getattr(m, 'shape', ()) or ())
            # True scalars (python ints/floats in the bundle) restore
            # as host scalars; ARRAY leaves — 0-d included (the K-FAC
            # step / inv_chunk_phase counters) — must carry the live
            # replicated sharding: without one, orbax falls back to
            # the sharding FILE, which references the SAVING world's
            # devices and cannot materialize on a different topology.
            if isinstance(m, ocp.metadata.ScalarMetadata):
                return jax.ShapeDtypeStruct((), m.dtype)
            return jax.ShapeDtypeStruct(shape, m.dtype, sharding=rep)

        def of_live(x):
            # Mirror the save-side typing: array leaves (0-d optax
            # step counters included) were written as arrays and need
            # the live replicated sharding to deserialize; plain
            # python scalars were written as scalars and restore bare.
            if isinstance(x, (jax.Array, np.ndarray)):
                return jax.ShapeDtypeStruct(np.shape(x), x.dtype,
                                            sharding=rep)
            return jax.ShapeDtypeStruct((), np.asarray(x).dtype)

        template = {k: jax.tree.map(of_meta, v) for k, v in md.items()}
        if like is not None and 'opt_state' in like \
                and 'opt_state' in template:
            template['opt_state'] = jax.tree.map(of_live,
                                                 like['opt_state'])
        return self._mgr.restore(
            epoch, args=ocp.args.StandardRestore(template))

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()


def bundle_state(params, opt_state, kfac_state_dict, extra_vars,
                 schedulers: dict[str, Any] | None = None,
                 topology=None, integrity: bool | str = True,
                 **scalars) -> dict:
    """Assemble the composite checkpoint tree.

    Mirrors the reference's checkpoint dict {model, optimizer,
    preconditioner, schedulers} (examples/utils.py:10-19).

    ``scalars`` carries the resume point (r8 resilience format, see
    MIGRATION.md "Checkpoint format"): ``step`` (global optimizer
    step), ``epoch`` (the epoch to (re)enter on resume), and
    ``step_in_epoch`` + ``data_seed`` (the data-stream position,
    ``resilience.dataiter.DataStreamState``) — epoch-boundary bundles
    record ``step_in_epoch=0``.

    ``topology``: an ``elastic.topology.TopologySpec`` of the saving
    world; its ``topo_*`` int scalars are merged into ``scalars`` so
    the bundle can be resumed on a DIFFERENT topology (the r11
    elastic format — bundles without it are same-topology-only; see
    MIGRATION.md).

    ``integrity=True`` (default, the r16 format) additionally stamps a
    content checksum of the assembled tree into
    ``scalars['integrity_checksum']`` (``resilience.integrity``); the
    unified resume path verifies it and walks back past bundles that
    fail. ``integrity='template'`` carries the field with the
    unverified sentinel and SKIPS the host fetch + hash — for
    restore-template bundles (``resume(like=)``), whose digest nobody
    reads. ``False`` omits the field entirely — the pre-r16 format,
    only where unverified restores are acceptable (MIGRATION.md
    "Checkpoint integrity").
    """
    scalars = dict(scalars)
    if topology is not None:
        scalars.update(topology.scalars())
    tree = {'params': params,
            'opt_state': opt_state,
            'kfac': kfac_state_dict,
            'extra_vars': extra_vars,
            'scalars': scalars}
    if schedulers:
        tree['schedulers'] = {k: v.state_dict()
                              for k, v in schedulers.items()}
    if integrity:
        from distributed_kfac_pytorch_tpu.resilience import (
            integrity as integrity_lib,
        )
        # The digest is computed SYNCHRONOUSLY at assembly, not
        # deferred behind the async orbax write: the train step
        # donates its state buffers (donate_argnums), so the arrays
        # referenced here are invalidated by the very next dispatch —
        # a deferred hash would read freed buffers. The cost is one
        # host fetch + sha256 per SAVE (not per step); opt out with
        # integrity=False / 'template' where that gates cadence
        # (PERF.md r16).
        integrity_lib.stamp(tree, compute=integrity != 'template')
    return tree
