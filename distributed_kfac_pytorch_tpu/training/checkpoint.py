"""Checkpoint save / auto-resume via orbax.

Reference parity: examples/utils.py:10-19 (save_checkpoint bundling
model + optimizer + preconditioner + scheduler states) and the
auto-resume scan in torch_cifar10_resnet.py:147-151 (find the newest
epoch checkpoint and restore). K-FAC factors are saved but inverses are
recomputed on load (reference preconditioner.py:294-353, README.md:222-223)
— the caller passes ``kfac_state_dict`` already filtered by
``KFAC.state_dict``.

Orbax handles sharded arrays natively: distributed inverse stacks save
and restore with their shardings, so resume works across pod restarts.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


class CheckpointManager:
    """Epoch-indexed checkpoints with auto-resume.

    Stores one composite pytree per epoch under ``directory/<epoch>/``;
    ``latest_epoch()``/``restore()`` implement the reference's
    scan-downward resume (torch_cifar10_resnet.py:147-151) via orbax's
    step tracking.
    """

    def __init__(self, directory: str, max_to_keep: int | None = 2):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    def save(self, epoch: int, tree: dict, *, force: bool = False,
             blocking: bool = False) -> None:
        """Save a checkpoint tree.

        Async by default: orbax snapshots the (device) arrays and writes
        in a background thread, so a multi-GB ImageNet-scale save does
        not stall the training loop (the step right after a save
        proceeds while bytes hit disk). Pending writes are joined by the
        next ``save``/``restore``/``latest_epoch``/``close`` call —
        orbax serializes them internally — or explicitly via
        :meth:`wait_until_finished`. Pass ``blocking=True`` (or call
        ``wait_until_finished``) where durability must be certain before
        proceeding, e.g. right before process exit.
        """
        self._mgr.save(epoch, args=ocp.args.StandardSave(tree),
                       force=force)
        if blocking:
            self._mgr.wait_until_finished()

    def wait_until_finished(self) -> None:
        """Block until all pending async saves are durable on disk."""
        self._mgr.wait_until_finished()

    def latest_epoch(self) -> int | None:
        self._mgr.wait_until_finished()  # join any pending async save
        return self._mgr.latest_step()

    def restore(self, epoch: int | None = None,
                like: dict | None = None) -> dict:
        """Restore a checkpoint (the latest when ``epoch`` is None).

        ``like`` provides the target pytree structure/shardings; restored
        arrays adopt its placements (replicated vs row-sharded state).

        WITHOUT ``like``, orbax falls back to the checkpoint's own
        recorded metadata: host-staged arrays laid out for the
        topology that SAVED them (orbax itself warns this is UNSAFE).
        That only works when the restoring world exactly matches the
        saving world — resuming a pod checkpoint at a different
        process/device count, or an SPMD checkpoint on one chip, gets
        wrong or failing placements. Engine/CLI resume paths therefore
        ALWAYS pass ``like`` (a live-state bundle of the same
        structure — ``resilience.cli.resume`` enforces this): restored
        arrays adopt the LIVE state's committed shardings regardless
        of what wrote the checkpoint, and
        ``DistributedKFAC.load_state_dict`` re-commits stray host
        leaves as a second line of defense. Regression-tested in
        tests/test_resilience.py (like= adopts the live placements;
        sharded SPMD kill-and-resume).
        """
        self._mgr.wait_until_finished()  # join any pending async save
        if epoch is None:
            epoch = self.latest_epoch()
        if epoch is None:
            raise FileNotFoundError('no checkpoints found')
        if like is not None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._mgr.restore(
                epoch, args=ocp.args.StandardRestore(abstract))
        # Explicit StandardRestore: a manager that has not saved in this
        # process has no handler registered for the step yet (a resumed
        # fresh process always starts this way).
        return self._mgr.restore(epoch, args=ocp.args.StandardRestore())

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()


def bundle_state(params, opt_state, kfac_state_dict, extra_vars,
                 schedulers: dict[str, Any] | None = None,
                 **scalars) -> dict:
    """Assemble the composite checkpoint tree.

    Mirrors the reference's checkpoint dict {model, optimizer,
    preconditioner, schedulers} (examples/utils.py:10-19).

    ``scalars`` carries the resume point (r8 resilience format, see
    MIGRATION.md "Checkpoint format"): ``step`` (global optimizer
    step), ``epoch`` (the epoch to (re)enter on resume), and
    ``step_in_epoch`` + ``data_seed`` (the data-stream position,
    ``resilience.dataiter.DataStreamState``) — epoch-boundary bundles
    record ``step_in_epoch=0``.
    """
    tree = {'params': params,
            'opt_state': opt_state,
            'kfac': kfac_state_dict,
            'extra_vars': extra_vars,
            'scalars': dict(scalars)}
    if schedulers:
        tree['schedulers'] = {k: v.state_dict()
                              for k, v in schedulers.items()}
    return tree
