"""K-FAC observability: on-device telemetry, profiler scopes, sinks,
and health monitoring (ISSUE r7).

Four parts, one discipline — *observing a run must not change it*:

  - :mod:`metrics` — an on-device metrics pytree accumulated inside
    the jitted step (``KFAC(collect_metrics=True)``) and drained
    asynchronously; metrics-off is bit-identical to the
    pre-observability step (test-pinned).
  - :mod:`profiling` — ``annotate(name)`` scopes threaded through every
    hot path so an XLA profile attributes step time to named K-FAC
    stages; ``start_trace``/``stop_trace`` back the CLIs'
    ``--profile-dir``.
  - :mod:`sink` — schema-versioned JSONL writer (rank-0 gated, atomic
    write-then-rename, rotation, ``metrics_interval``).
  - :mod:`health` — non-finite / staleness / damping-trajectory
    monitors with warn / skip / raise actions (the on-device non-finite
    factor guard lives in the preconditioner).
  - :mod:`tracing` — the legacy host-side ``trace()`` table (still
    re-exported from ``distributed_kfac_pytorch_tpu.utils``).
  - :mod:`report` — ``python -m ...observability.report run.jsonl``
    offline step-time + health summary (``--json`` for machines).
  - :mod:`memory` — device HBM watermarks + resident K-FAC state
    footprint breakdown (the ``kind='memory'`` records, r10).
  - :mod:`stragglers` — per-rank sink shards, the pre-collective
    barrier-wait probe, and the cross-host skew merger (r10).
  - :mod:`gate` — ``python -m ...observability.gate run.jsonl
    --baseline BASELINE_OBS.json`` CI regression gate over step-time
    percentiles / peak HBM / retraces, plus online anomaly checks
    (r10).

Only the leaf modules (tracing, profiling) import eagerly — the rest
load on first attribute access so ``ops``/``layers`` can take profiler
scopes without import cycles.
"""

from __future__ import annotations

import importlib

from distributed_kfac_pytorch_tpu.observability import profiling, tracing

_LAZY = ('metrics', 'sink', 'health', 'report', 'cli', 'memory',
         'stragglers', 'gate')

__all__ = ['tracing', 'profiling', *_LAZY]


def __getattr__(name):
    if name in _LAZY:
        mod = importlib.import_module(
            f'distributed_kfac_pytorch_tpu.observability.{name}')
        globals()[name] = mod
        return mod
    raise AttributeError(name)
