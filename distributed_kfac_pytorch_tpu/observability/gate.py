"""Automated performance-regression gate over a recorded metrics JSONL.

    python -m distributed_kfac_pytorch_tpu.observability.gate \\
        run.jsonl --baseline BASELINE_OBS.json

The ROADMAP's "as fast as the hardware allows" north star finally gets
a tripwire (r10): the gate reduces a run's stream to a small metric
vector —

  - ``step_p50_ms`` / ``step_p95_ms`` / ``step_p99_ms``: the host
    dispatch step-time distribution (the same percentiles the report
    prints; p50 is throughput, p95/p99 are the firing-spike tail the
    r9 pipelined firing flattens);
  - ``max_over_median``: the spike ratio (step-time uniformity);
  - ``peak_hbm_bytes``: the highest device ``peak_bytes_in_use`` seen
    in the ``kind='memory'`` records (the KAISA memory axis — absent
    on backends without allocator stats, e.g. CPU);
  - ``retraces``: count of ``retrace`` events from the step builder's
    variant cache — the offline cross-check of the host-side
    ``trace_counts`` guard; any value above the baseline's (normally
    0) means a static-cadence program variant recompiled mid-run.
  - ``selfheal_rollbacks`` (r16): in-process rollback count from the
    self-healing ladder — a recovery, but a run that needed one
    regressed against a baseline that needed none.
  - ``supervisor_restarts`` (r17): failure-driven relaunch count from
    the supervisor (``supervisor_restart`` events, merged from the
    ``<jsonl>.supervisor`` sidecar) — same recovered-but-regressed
    logic one process level up.
  - ``fleet_quarantines`` (r18): quarantined-job count from the fleet
    scheduler's event stream (``fleet_quarantine`` events) — the same
    logic one level up again: the pool stayed healthy, but a job mix
    that quarantined a member regressed against one that ran clean.

— and compares it against a committed baseline with per-metric
relative tolerances, exiting non-zero on any breach so CI can block
the PR. ``--write-baseline`` reduces a known-good run to the committed
file (see ``BASELINE_OBS.json``, seeded by
``benchmarks/flagship_lm.py --obs-baseline``; PERF.md r10 has the
decision rule for which breaches block).

Independent of the baseline, the gate also replays the stream through
the ONLINE anomaly monitors (``observability.health``): the step-time
spike z-score and the monotonic memory-growth detector. A single 2x
spike moves no percentile but is still a regression symptom; a leak
is monotone long before it is an OOM. Anomalies gate like breaches
(``--no-anomaly`` opts out).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

from distributed_kfac_pytorch_tpu.observability import health as obs_health
from distributed_kfac_pytorch_tpu.observability import report as obs_report
from distributed_kfac_pytorch_tpu.observability.sink import (
    read_jsonl_tolerant,
)

BASELINE_FORMAT = 'kfac-obs-baseline-v1'

# Per-metric relative tolerances (fraction above baseline that still
# passes). 'retraces' is absolute: a baseline of 0 retraces tolerates
# exactly 0. Current values may always be BETTER than baseline.
DEFAULT_TOLERANCES = {
    'step_p50_ms': 0.10,
    'step_p95_ms': 0.15,
    'step_p99_ms': 0.25,
    'max_over_median': 0.25,
    'peak_hbm_bytes': 0.05,
    'retraces': 0.0,
    # r16 self-healing: in-process rollbacks are recoveries, but a run
    # that needed one regressed against a baseline that needed none —
    # the gate surfaces it (absolute count, like retraces). Baselines
    # predating the metric skip it ("not in baseline").
    'selfheal_rollbacks': 0.0,
    # r17 supervision: same logic one level up — a supervised run that
    # needed process-level restarts (crash/hang relaunches) recovered,
    # but it regressed against a baseline that ran clean. Counted from
    # supervisor_restart events (the <jsonl>.supervisor sidecar is
    # merged by main(); inline events count too).
    'supervisor_restarts': 0.0,
    # r18 fleet: quarantined jobs (crash loops, exhausted budgets,
    # rejected specs) are the fleet-level recovered-but-regressed
    # signal — the pool stayed healthy, but a job mix that quarantined
    # one regressed against a baseline mix that ran clean. Counted
    # from fleet_quarantine events when the gate is pointed at a fleet
    # scheduler's event stream (absolute count, like retraces).
    'fleet_quarantines': 0.0,
}
_ABSOLUTE_METRICS = ('retraces', 'selfheal_rollbacks',
                     'supervisor_restarts', 'fleet_quarantines')


def gate_metrics(records: list[dict]) -> dict:
    """Reduce a record stream to the gated metric vector."""
    from distributed_kfac_pytorch_tpu.observability.sink import (
        peak_hbm_bytes,
    )
    dist = obs_report.step_time_distribution(records)
    peak = peak_hbm_bytes(records)
    retraces = sum(1 for r in records
                   if r.get('kind') == 'event'
                   and r.get('event') == 'retrace')
    rollbacks = sum(1 for r in records
                    if r.get('kind') == 'event'
                    and r.get('event') == 'selfheal_rollback')
    sup_restarts = sum(1 for r in records
                       if r.get('kind') == 'event'
                       and r.get('event') == 'supervisor_restart')
    fleet_q = sum(1 for r in records
                  if r.get('kind') == 'event'
                  and r.get('event') == 'fleet_quarantine')
    out = {
        'n_steps': dist['n_steps'] if dist else 0,
        'step_p50_ms': dist['p50_ms'] if dist else None,
        'step_p95_ms': dist['p95_ms'] if dist else None,
        'step_p99_ms': dist['p99_ms'] if dist else None,
        'max_over_median': (dist['max_over_median'] if dist else None),
        'peak_hbm_bytes': peak,
        'retraces': retraces,
        'selfheal_rollbacks': rollbacks,
        'supervisor_restarts': sup_restarts,
        'fleet_quarantines': fleet_q,
    }
    for k, v in out.items():
        if isinstance(v, float) and not math.isfinite(v):
            out[k] = None
    return out


def compare(current: dict, baseline: dict,
            tolerances: dict | None = None,
            allow_missing: bool = False) -> tuple[list[dict], list[str]]:
    """Gate ``current`` against ``baseline``.

    Returns ``(breaches, skipped)``. A metric present in the baseline
    but absent from the current run is a breach (the regression the
    gate exists for could be hiding exactly there) unless
    ``allow_missing`` — the documented escape for platform differences
    (a CPU dev box has no HBM watermarks to compare against a TPU
    baseline). Metrics absent from the baseline are skipped: a
    baseline only vouches for what it measured.
    """
    tolerances = {**DEFAULT_TOLERANCES, **(tolerances or {})}
    breaches, skipped = [], []
    for metric, tol in tolerances.items():
        base = baseline.get(metric)
        if base is None:
            skipped.append(f'{metric}: not in baseline')
            continue
        cur = current.get(metric)
        if cur is None:
            if allow_missing:
                skipped.append(f'{metric}: absent from this run '
                               '(allowed)')
                continue
            breaches.append({'metric': metric, 'current': None,
                             'baseline': base, 'limit': None,
                             'kind': 'missing'})
            continue
        if metric in _ABSOLUTE_METRICS:
            limit = base + tol
        else:
            limit = base * (1.0 + tol)
        if cur > limit:
            breaches.append({'metric': metric, 'current': cur,
                             'baseline': base, 'limit': limit,
                             'kind': 'regression'})
    return breaches, skipped


def anomaly_events(records: list[dict], *,
                   spike_zscore: float = 8.0,
                   growth_windows: int = 6,
                   growth_min_frac: float = 0.05) -> list[str]:
    """Replay the stream through the online anomaly monitors.

    Returns only the perf-anomaly events (step-time spike, memory
    growth) — the numerics checks (non-finite, damping, staleness)
    have their own surface in the report/health path and are not this
    gate's business.
    """
    mon = obs_health.HealthMonitor(
        action='skip', step_spike_zscore=spike_zscore,
        memory_growth_windows=growth_windows,
        memory_growth_min_frac=growth_min_frac)
    for r in records:
        if r.get('kind') in ('step', 'memory'):
            mon.observe(r)
    return [e for e in mon.events
            if 'step-time spike' in e or 'memory grew' in e]


def write_baseline(metrics: dict, path: str,
                   meta: dict | None = None) -> dict:
    """Serialize a gate baseline file (the committed artifact)."""
    obj = {'format': BASELINE_FORMAT,
           'created_unix': int(time.time()),
           'meta': dict(meta or {}),
           'metrics': {k: v for k, v in metrics.items()
                       if v is not None}}
    with open(path, 'w') as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.write('\n')
    return obj


def read_baseline(path: str) -> dict:
    with open(path) as f:
        obj = json.load(f)
    if obj.get('format') != BASELINE_FORMAT:
        raise ValueError(
            f'{path}: not a {BASELINE_FORMAT} file '
            f'(format={obj.get("format")!r})')
    metrics = obj.get('metrics')
    if not isinstance(metrics, dict):
        raise ValueError(f'{path}: baseline has no metrics object')
    return obj


def _parse_tols(pairs: list[str]) -> dict:
    out = {}
    for pair in pairs:
        key, _, val = pair.partition('=')
        if key not in DEFAULT_TOLERANCES:
            raise ValueError(
                f'unknown gate metric {key!r} '
                f'(one of {sorted(DEFAULT_TOLERANCES)})')
        try:
            out[key] = float(val)
        except ValueError:
            raise ValueError(f'--tol {pair!r}: not KEY=FLOAT') from None
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog='python -m distributed_kfac_pytorch_tpu.observability'
             '.gate',
        description='Performance-regression gate over a K-FAC metrics '
                    'JSONL: step-time percentiles, peak HBM and '
                    'retrace count vs a committed baseline, plus '
                    'online anomaly checks. Exit 0 = pass, 1 = '
                    'breach/anomaly, 2 = usage/read error.')
    p.add_argument('jsonl', help='metrics stream from --kfac-metrics')
    p.add_argument('--baseline', default=None,
                   help='committed BASELINE_OBS.json to gate against')
    p.add_argument('--write-baseline', default=None, metavar='PATH',
                   help='reduce this (known-good) run to a baseline '
                        'file instead of gating')
    p.add_argument('--tol', action='append', default=[],
                   metavar='METRIC=FRAC',
                   help='override one tolerance (relative fraction; '
                        'retraces is an absolute count), e.g. '
                        '--tol step_p95_ms=0.2; repeatable')
    p.add_argument('--allow-missing', action='store_true',
                   help='a baseline metric absent from this run is '
                        'skipped instead of breaching (platform '
                        'differences, e.g. no HBM stats on CPU)')
    p.add_argument('--no-anomaly', action='store_true',
                   help='skip the online anomaly replay (spike '
                        'z-score, memory growth)')
    p.add_argument('--spike-zscore', type=float, default=8.0)
    p.add_argument('--growth-windows', type=int, default=6)
    p.add_argument('--growth-min-frac', type=float, default=0.05)
    p.add_argument('--json', action='store_true',
                   help='machine-readable verdict on stdout')
    args = p.parse_args(argv)

    try:
        records, torn = read_jsonl_tolerant(args.jsonl)
        tols = _parse_tols(args.tol)
        baseline = (read_baseline(args.baseline)
                    if args.baseline else None)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f'error: {e}', file=sys.stderr)
        return 2
    # Supervisor sidecar (r17): supervisor_restart events live in
    # <jsonl>.supervisor (the supervisor outlives child incarnations);
    # merge them so the supervisor_restarts metric sees the whole
    # session. Unreadable sidecar = skip, like the report.
    sidecar = args.jsonl + obs_report.SUPERVISOR_SIDECAR_SUFFIX
    if os.path.exists(sidecar):
        try:
            sup_records, sup_torn = read_jsonl_tolerant(sidecar)
            records = records + sup_records
            torn += sup_torn
        except (OSError, ValueError) as e:
            print(f'note: supervisor sidecar {sidecar} unreadable: '
                  f'{e}', file=sys.stderr)
    current = gate_metrics(records)
    # The tolerances actually applied (defaults + --tol overrides):
    # part of the verdict artifact, so a recorded gate run is
    # self-describing — without this you cannot tell from the output
    # which overrides were in effect.
    applied_tols = {**DEFAULT_TOLERANCES, **tols}

    if args.write_baseline:
        obj = write_baseline(current, args.write_baseline,
                             meta={'source': args.jsonl,
                                   'torn_lines': torn})
        print(f'wrote baseline {args.write_baseline}: '
              + json.dumps(obj['metrics'], sort_keys=True))
        if not args.baseline:
            return 0

    breaches, skipped = ([], [])
    if baseline is not None:
        breaches, skipped = compare(current, baseline['metrics'],
                                    applied_tols,
                                    allow_missing=args.allow_missing)
    anomalies = [] if args.no_anomaly else anomaly_events(
        records, spike_zscore=args.spike_zscore,
        growth_windows=args.growth_windows,
        growth_min_frac=args.growth_min_frac)
    failed = bool(breaches or anomalies)

    if args.json:
        print(json.dumps({'pass': not failed, 'current': current,
                          'baseline': (baseline or {}).get('metrics'),
                          'tolerances': applied_tols,
                          'breaches': breaches, 'skipped': skipped,
                          'anomalies': anomalies,
                          'torn_lines': torn}, sort_keys=True))
        return 1 if failed else 0

    print('== K-FAC observability gate ==')
    if torn:
        print(f'note: skipped {torn} torn trailing line(s)')
    print('current: ' + json.dumps(current, sort_keys=True))
    if baseline is not None:
        print('tolerances: ' + json.dumps(applied_tols,
                                          sort_keys=True))
    if baseline is None:
        print('no --baseline: anomaly checks only')
    for s in skipped:
        print(f'  skip   {s}')
    for b in breaches:
        if b['kind'] == 'missing':
            print(f"  BREACH {b['metric']}: absent from this run "
                  f"(baseline {b['baseline']:g}; --allow-missing to "
                  'skip)')
        else:
            print(f"  BREACH {b['metric']}: {b['current']:g} > limit "
                  f"{b['limit']:g} (baseline {b['baseline']:g})")
    for a in anomalies:
        print(f'  ANOMALY {a}')
    print('FAIL' if failed else 'PASS')
    return 1 if failed else 0


if __name__ == '__main__':
    sys.exit(main())
