"""On-device K-FAC step metrics (pure jnp; built *inside* the jitted step).

The metrics pytree rides in the K-FAC state (``state['metrics']``), so
enabling it changes no call signatures and adds NO host transfers to the
step: every entry is an on-device scalar updated by traced ops, and the
host drains the tree asynchronously whenever it likes (the engine's
JSONL sink enqueues the device arrays and converts to floats lazily —
:mod:`observability.sink`).

Tracked (schema in :data:`METRIC_KEYS`):

  - ``damping`` / ``nu``: the resolved dynamic damping and KL-clip
    scale this step (reference preconditioner.py:661-682's ν).
  - ``grad_norm`` / ``precond_norm``: global l2 norms of the registered
    layers' gradient matrices and of the ν-scaled preconditioned
    result — their ratio is the "how hard is K-FAC steering" health
    signal (KAISA tunes against exactly this kind of per-step evidence).
  - ``factor_updates`` / ``inv_updates``: cumulative firing counts of
    the two periodic stages (host-side staleness tracking derives from
    these without any extra device work).
  - ``nonfinite_skips``: cumulative count of factor updates whose
    candidate factors were non-finite (see the guard in
    ``KFAC.update_factors``).
  - ``eig_clipped``: number of eigenvalues currently sitting at the
    0.0 floor across all stored eigen slots (post-``clip``: a clipped
    eigenvalue is exactly 0, so the stored spectra are countable
    without touching the decomposition path).
  - ``bucket_norms/<shape>``: per precondition shape-bucket l2 norms of
    the preconditioned matrices (the unit ``KFAC._bucketed_precond_mats``
    and the KAISA row-sharded path batch over).

With ``collect_metrics=False`` (the default) none of this exists in the
state or the trace — the step is bit-identical to the pre-observability
program (pinned by tests/test_observability.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Scalar metric slots (beyond the per-model 'bucket_norms' subtree).
# 'inv_chunk_firings' counts pipelined chunk firings (r9: a chunk
# firing covers 1/k of the factor set, so it is tallied separately
# from the monolithic 'inv_updates' — k chunk firings = one window's
# worth of inverse work).
METRIC_KEYS = ('damping', 'nu', 'grad_norm', 'precond_norm',
               'factor_updates', 'inv_updates', 'inv_chunk_firings',
               'nonfinite_skips', 'eig_clipped')
_INT_KEYS = ('factor_updates', 'inv_updates', 'inv_chunk_firings',
             'nonfinite_skips', 'eig_clipped')


def shape_key(shape) -> str:
    """Stable string key for a gradient-matrix shape bucket."""
    return 'x'.join(str(int(s)) for s in shape)


def init_metrics(bucket_keys) -> dict:
    """Fresh metrics subtree for ``state['metrics']`` (all on-device)."""
    m = {k: (jnp.zeros((), jnp.int32) if k in _INT_KEYS
             else jnp.zeros((), jnp.float32))
         for k in METRIC_KEYS}
    m['nu'] = jnp.ones((), jnp.float32)
    m['bucket_norms'] = {k: jnp.zeros((), jnp.float32)
                         for k in bucket_keys}
    return m


def update_metrics(prev: dict, *, damping, stats: dict, did_factor,
                   did_inv, factor_finite, eig_clipped,
                   did_chunk=0) -> dict:
    """One traced metrics-state transition (call inside the step).

    ``stats`` comes from the preconditioner's ``with_stats`` pass
    (``nu`` / ``grad_norm`` / ``precond_norm`` / ``bucket_norms``);
    ``did_factor`` / ``did_inv`` / ``did_chunk`` are 0/1 cadence
    indicators (``did_chunk``: a pipelined chunk firing, r9) and
    ``factor_finite`` the 0/1 finiteness of this step's candidate
    factors (1 on non-factor steps).
    """
    return {
        'damping': jnp.asarray(damping, jnp.float32),
        'nu': stats['nu'].astype(jnp.float32),
        'grad_norm': stats['grad_norm'].astype(jnp.float32),
        'precond_norm': stats['precond_norm'].astype(jnp.float32),
        'factor_updates': prev['factor_updates'] + did_factor,
        'inv_updates': prev['inv_updates'] + did_inv,
        'inv_chunk_firings': (prev.get('inv_chunk_firings',
                                       jnp.zeros((), jnp.int32))
                              + did_chunk),
        'nonfinite_skips': (prev['nonfinite_skips']
                            + did_factor * (1 - factor_finite)),
        'eig_clipped': jnp.asarray(eig_clipped, jnp.int32),
        'bucket_norms': {k: v.astype(jnp.float32)
                         for k, v in stats['bucket_norms'].items()},
    }


def flatten_metrics(m: dict, prefix: str = 'kfac') -> dict:
    """Flatten a metrics subtree into scalar entries for a metrics dict
    (``'kfac/grad_norm'``, ``'kfac/bucket_norm/128x65'``, ...)."""
    out = {f'{prefix}/{k}': m[k] for k in METRIC_KEYS if k in m}
    for k, v in m.get('bucket_norms', {}).items():
        out[f'{prefix}/bucket_norm/{k}'] = v
    return out


def count_clipped_eigvals(inverses: dict) -> jax.Array:
    """Eigenvalues at the 0.0 clip floor in a per-layer inverse dict.

    Post-clip spectra: ``batched_eigh(clip=0.0)`` floors with
    ``max(d, 0)``, so a clipped eigenvalue is stored as exactly 0 and
    ``d <= 0`` counts precisely the floored set (values above the floor
    are untouched and stay positive).
    """
    total = jnp.zeros((), jnp.int32)
    for entry in inverses.values():
        for k in ('dA', 'dG'):
            if k in entry:
                total += jnp.sum(
                    (entry[k].astype(jnp.float32) <= 0.0)
                    .astype(jnp.int32))
    return total


def count_clipped_eigvals_stacks(inv_stacks: dict) -> jax.Array:
    """Row-local clipped-eigenvalue count over distributed inverse
    stacks (sum the caller psums over the inverse-group axis; identity
    padding slots hold d=1 and contribute nothing)."""
    total = jnp.zeros((), jnp.int32)
    for entry in inv_stacks.values():
        if 'd' in entry:
            total += jnp.sum(
                (entry['d'].astype(jnp.float32) <= 0.0)
                .astype(jnp.int32))
    return total


def precond_stats(grad_mats: dict, precond_mats: dict, nu) -> dict:
    """Norm statistics over one step's precondition pass.

    ``grad_mats`` / ``precond_mats`` map layer name -> matrix (any
    shapes); buckets group by matrix shape — the same grouping the
    bucketed precondition paths batch over, derived from static shapes
    so the metric keys are trace-constant.
    """
    gsq = jnp.zeros((), jnp.float32)
    bucket_sq: dict[str, jax.Array] = {}
    psq = jnp.zeros((), jnp.float32)
    nu32 = jnp.asarray(nu, jnp.float32)
    for name, gm in grad_mats.items():
        gsq += jnp.sum(jnp.square(gm.astype(jnp.float32)))
        vm = precond_mats[name].astype(jnp.float32)
        vsq = jnp.sum(jnp.square(vm)) * nu32 * nu32
        psq += vsq
        key = shape_key(gm.shape)
        bucket_sq[key] = bucket_sq.get(key, jnp.zeros((),
                                                      jnp.float32)) + vsq
    return {'nu': nu32,
            'grad_norm': jnp.sqrt(gsq),
            'precond_norm': jnp.sqrt(psq),
            'bucket_norms': {k: jnp.sqrt(v)
                             for k, v in bucket_sq.items()}}
