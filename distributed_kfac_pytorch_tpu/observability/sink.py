"""Schema-versioned JSONL metrics sink (rank-0 gated, atomic, rotating).

Design constraints (ISSUE r7):

  - **No host syncs in the step path.** ``step_record`` only *enqueues*
    the device scalars (kicking off an async device->host copy where
    the backend supports it); conversion to floats happens at drain
    time, by which point the host has dispatched well past the step
    that produced them.
  - **Rank-0 gating.** Every process constructs the sink with its
    ``process_index``; only rank 0 ever touches the filesystem, so a
    multihost run produces exactly one stream (covered by
    tests/multihost_worker.py mode='metrics').
  - **Atomic write-then-rename.** The current segment's lines are
    rewritten to ``<path>.tmp.<pid>`` and ``os.replace``d over the
    target on every drain — a reader (or a crashed run) never observes
    a torn/interleaved line. Rotation bounds the rewrite cost:
    a full segment is renamed to ``<path>.<n>`` and a fresh one starts.

Record schema (``schema`` = :data:`SCHEMA_VERSION`; the reader accepts
v1-v3 files too — v2 only *added* the ``event`` kind for the r8
resilience subsystem, v3 only adds the optional step ``fired`` field
for r9 step-time attribution, v4 only adds the ``memory`` kind for the
r10 memory telemetry):

  {"schema": 4, "kind": "step",  "step": int, "wall_time": float,
   "host_step_ms": float?, "fired": str?,
   "metrics": {flat name -> float}}
                     # "fired": the heaviest statically-gated K-FAC
                     # stage this step ran ('factor' / 'inverse' /
                     # 'chunk<j>'); absent on plain steps. The report's
                     # step-time outlier attribution keys on it.
  {"schema": 4, "kind": "epoch", "epoch": int, "wall_time": float,
   "metrics": {...averaged epoch metrics...}, "trace": {stage: {...}}}
  {"schema": 4, "kind": "meta",  "wall_time": float, "meta": {...}}
  {"schema": 4, "kind": "event", "event": str, "wall_time": float,
   "data": {...}}    # resilience: preemption / checkpoint_save (with
                     # latency_ms) / restore — always kept (no
                     # interval thinning) and flushed immediately,
                     # because the runs that emit them tend to die next.
                     # r10 adds compile/retrace events from the step
                     # builder's variant cache (data: variant,
                     # first_call_ms / trace_count).
  {"schema": 4, "kind": "memory", "step": int, "wall_time": float,
   "device": {bytes_in_use, peak_bytes_in_use, ...}?,
   "state": {total_bytes, by_group, by_dtype, ...}?}
                     # r10 memory telemetry: periodic device HBM
                     # watermarks (observability.memory
                     # .device_memory_stats — absent on backends
                     # without allocator stats) plus the host-side
                     # resident K-FAC state footprint breakdown
                     # (state_footprint). The gate's peak-HBM metric
                     # and the health monitor's growth detector read
                     # these.

``validate_record`` / ``read_jsonl`` are the single schema authority,
shared by the report CLI and the tests. ``read_jsonl_tolerant`` is the
crash-forensics reader: a process killed mid-append can leave a torn
FINAL line (the per-rank straggler shards append without the atomic
rewrite of the rank-0 stream); the tolerant reader skips-and-counts a
trailing undecodable line instead of refusing the whole stream.
"""

from __future__ import annotations

import json
import math
import os
import re
import time
from typing import Any

SCHEMA_VERSION = 4
ACCEPTED_SCHEMAS = (1, 2, 3, 4)
RECORD_KINDS = ('meta', 'step', 'epoch', 'event', 'memory')
# The ONE registry of event names a ``kind='event'`` record may carry
# (r15): report/gate consumers key on these strings, so every emitter
# in the tree must draw from here — ``analysis.surface`` statically
# checks that every literal ``event_record('x')`` / ``{'event': 'x'}``
# in the package names a registered kind, and ``tests/test_surface.py``
# is the semantic pin. Add the name HERE first when introducing a new
# event.
EVENT_KINDS = (
    'compile',            # first dispatch of a program variant (r10)
    'retrace',            # a variant re-traced — contract breach (r10)
    'preemption',         # resilience drain began (r8)
    'checkpoint_save',    # step checkpoint written (r8)
    'restore',            # resume restored a checkpoint (r8)
    'topology_change',    # elastic resume changed the world (r11)
    'autotune_apply',     # --tuned-config overlay applied (r12)
    'autotune_fallback',  # --tuned-config rejected, fail-closed (r12)
    'autotune_backoff',   # cadence-backoff stretch/relax (r12)
    # r16 self-healing ladder (resilience.selfheal; README
    # "Self-healing" — the report's self-healing section and the
    # gate's selfheal_rollbacks metric consume these):
    'selfheal_escalate',    # damping multiplier raised (rung 2)
    'selfheal_deescalate',  # damping multiplier decayed one notch
    'selfheal_quarantine',  # bucket gated to SGD direction (rung 3)
    'selfheal_readmit',     # parity probe passed, bucket re-admitted
    'selfheal_rollback',    # in-process last-good restore (rung 4)
    'ckpt_quarantine',      # corrupt/torn bundle skipped by the
                            # verified resume/rollback walk (r16)
    # r17 failure supervision (resilience.supervisor; README
    # "Supervision & failover" — written to the <metrics>.supervisor
    # sidecar stream the report's supervision section and the gate's
    # supervisor_restarts metric consume):
    'supervisor_restart',   # failure-driven or post-drain relaunch
    'supervisor_failover',  # shrink to the survivor mesh (dead rank /
                            # lost capacity / persistent straggler)
    'supervisor_growback',  # capacity returned — grow back to target
    'hang_detected',        # heartbeat leases expired; child killed
    'crash_loop',           # same step failed K consecutive launches;
                            # diagnostic bundle written, distinct exit
    'capacity_degraded',    # capacity file torn/unreadable mid-write:
                            # last known target kept, one warning per
                            # degradation episode (r18)
    # r18 fleet scheduler (fleet.scheduler; README "Fleet scheduling"
    # — written to the fleet's own <workdir>/fleet.jsonl stream, which
    # the report's fleet section and the gate's fleet_quarantines
    # metric consume):
    'fleet_admit',          # a queued job was placed on the pool
    'fleet_preempt',        # a running job's world shrank (urgent
                            # admission or pool capacity loss)
    'fleet_regrow',         # freed capacity grew a shrunken job back
    'fleet_quarantine',     # a job was isolated (crash loop / budget
                            # exhaustion / rejected spec) — the fleet
                            # keeps scheduling the rest
    'fleet_complete',       # a job ran to completion; data carries
                            # its SLO row (queue wait, run time,
                            # restarts, preemptions, gate verdict)
    # r21 fused hot-path kernels (ops.pallas_kernels; README "Fused
    # hot-path kernels"):
    'pallas_fallback',      # a fused kernel's probe failed or its
                            # dispatch degraded — the step runs the
                            # stock XLA path; data names the kernel
                            # and the reason (never a silent fallback)
)
# Dead incarnations kept per metrics path (<path>.prev.1 newest ..
# .prev.N oldest); older ones are pruned on relaunch.
PREV_INCARNATIONS_KEPT = 5
# Where the failure supervisor's event stream lives relative to the
# run's metrics path (r17): ``<path>.supervisor``. ONE constant for
# the writer (resilience.supervisor) and both readers (report, gate) —
# the sidecar is found by convention, so a suffix drift would silently
# orphan the supervision trail.
SUPERVISOR_SIDECAR_SUFFIX = '.supervisor'


def to_float(x) -> float:
    """Best-effort scalar coercion (device arrays, numbers, 'nan'/'inf'
    strings); anything non-numeric degrades to NaN instead of raising.
    Single point of truth shared with :mod:`health` and :mod:`report`.
    """
    try:
        return float(x)
    except (TypeError, ValueError):
        return float('nan')


def percentile(sorted_vals: list[float], q: float) -> float:
    """Linear-interpolated percentile of an ascending-sorted list.

    Single implementation shared by :mod:`report` (step-time
    distribution, hence :mod:`gate`'s baseline metrics) and
    :mod:`stragglers` (per-rank tables) — the gate compares report
    numbers against baseline numbers, so the math must not fork.
    """
    if not sorted_vals:
        return float('nan')
    pos = (len(sorted_vals) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (
        pos - lo)


def peak_hbm_bytes(records: list[dict]) -> float | None:
    """Highest device watermark across a stream's ``memory`` records
    (``peak_bytes_in_use``, falling back to ``bytes_in_use``); None
    when no record carries allocator stats. Shared by :mod:`report`
    and :mod:`gate` — one place to learn a new allocator key.
    """
    peak = None
    for r in records:
        if r.get('kind') != 'memory':
            continue
        dev = r.get('device', {})
        b = dev.get('peak_bytes_in_use', dev.get('bytes_in_use'))
        if isinstance(b, (int, float)):
            peak = b if peak is None else max(peak, b)
    return peak


def validate_record(rec: Any) -> None:
    """Raise ValueError unless ``rec`` is a schema-valid record dict."""
    if not isinstance(rec, dict):
        raise ValueError(f'record is not an object: {type(rec).__name__}')
    if rec.get('schema') not in ACCEPTED_SCHEMAS:
        raise ValueError(f'unknown schema version {rec.get("schema")!r} '
                         f'(accepted {ACCEPTED_SCHEMAS})')
    kind = rec.get('kind')
    if kind not in RECORD_KINDS:
        raise ValueError(f'unknown record kind {kind!r}')
    if not isinstance(rec.get('wall_time'), (int, float)):
        raise ValueError('missing/invalid wall_time')
    if kind == 'step':
        if not isinstance(rec.get('step'), int):
            raise ValueError('step record missing integer step')
        if 'fired' in rec and not isinstance(rec['fired'], str):
            raise ValueError('step record fired is not a string')
    if kind == 'epoch' and not isinstance(rec.get('epoch'), int):
        raise ValueError('epoch record missing integer epoch')
    if kind == 'event':
        if not isinstance(rec.get('event'), str) or not rec['event']:
            raise ValueError('event record missing event name')
        if 'data' in rec and not isinstance(rec['data'], dict):
            raise ValueError('event record data is not an object')
    if kind == 'memory':
        if not isinstance(rec.get('step'), int):
            raise ValueError('memory record missing integer step')
        for sub in ('device', 'state'):
            if sub in rec and not isinstance(rec[sub], dict):
                raise ValueError(f'memory record {sub} is not an object')
    if kind in ('step', 'epoch'):
        metrics = rec.get('metrics')
        if not isinstance(metrics, dict):
            raise ValueError(f'{kind} record missing metrics object')
        for k, v in metrics.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                continue
            if isinstance(v, str):
                # Non-finite values ride as 'nan'/'inf'/'-inf' strings
                # (JSON has no literals for them); float() round-trips.
                try:
                    float(v)
                    continue
                except ValueError:
                    pass
            raise ValueError(f'metric {k!r} is not a number: {v!r}')


def _rotated_segments(path: str) -> list[str]:
    """Existing rotated segments ``<path>.1 .. .N``, oldest first."""
    out = []
    n = 1
    while os.path.exists(f'{path}.{n}'):
        out.append(f'{path}.{n}')
        n += 1
    return out


def incarnation_paths(path: str) -> list[str]:
    """Surviving dead incarnations ``<path>.prev.1 .. .N``, newest
    first (``.prev.1`` is the most recently deceased run). Legacy
    single-slot ``<path>.prev`` files (pre-r9 layout) are listed last.
    Read entries with :func:`read_incarnation` — chained entries are
    complete ``read_jsonl`` streams (rotated segments ride along as
    ``<path>.prev.<n>.<m>``), but a legacy ``.prev`` entry must be
    read as a single file (see ``read_incarnation``).
    """
    out = []
    n = 1
    while os.path.exists(f'{path}.prev.{n}'):
        out.append(f'{path}.prev.{n}')
        n += 1
    if os.path.exists(f'{path}.prev'):
        out.append(f'{path}.prev')
    return out


def _move_incarnation(src: str, dst: str) -> None:
    """Move one incarnation (live file + its rotated segments)."""
    for seg in _rotated_segments(dst):
        os.unlink(seg)
    for seg in _rotated_segments(src):
        m = re.match(re.escape(src) + r'\.(\d+)$', seg)
        os.replace(seg, f'{dst}.{m.group(1)}')
    os.replace(src, dst)


def _unlink_incarnation(path: str) -> None:
    for seg in _rotated_segments(path):
        os.unlink(seg)
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass


def _chain_incarnation(path: str) -> None:
    """Push the existing stream at ``path`` onto the incarnation chain.

    ``<path>.prev.n`` shifts to ``.prev.n+1`` (newest-first chain, each
    with its rotated segments), the live ``path`` (+ its segments)
    becomes ``.prev.1``, and incarnations beyond
    :data:`PREV_INCARNATIONS_KEPT` are pruned oldest-first. A legacy
    single-slot ``<path>.prev`` (pre-r9 layout) is folded into the
    chain first so a second relaunch can no longer destroy the first
    dead incarnation's tail (the r8 layout overwrote it with one
    ``os.replace``).
    """
    if os.path.exists(f'{path}.prev'):
        # Legacy slot: adopt it as the newest chained incarnation
        # before the live file claims .prev.1.
        n = 1
        while os.path.exists(f'{path}.prev.{n}'):
            n += 1
        for i in range(n - 1, 0, -1):
            _move_incarnation(f'{path}.prev.{i}', f'{path}.prev.{i + 1}')
        os.replace(f'{path}.prev', f'{path}.prev.1')
    segs = _rotated_segments(path)
    if not os.path.exists(path) and not segs:
        return
    n = 1
    while os.path.exists(f'{path}.prev.{n}'):
        n += 1
    for i in range(n - 1, 0, -1):
        _move_incarnation(f'{path}.prev.{i}', f'{path}.prev.{i + 1}')
    if os.path.exists(path):
        for seg in segs:
            m = re.match(re.escape(path) + r'\.(\d+)$', seg)
            os.replace(seg, f'{path}.prev.1.{m.group(1)}')
        os.replace(path, f'{path}.prev.1')
    else:
        # Crash window: the dead run rotated its live segment away
        # (flush() renames live -> <path>.N before republishing a
        # fresh live file) and died in between, leaving rotated
        # segments with no live file. Those segments alone ARE the
        # dead incarnation — chain them (newest segment becomes the
        # chained live slot so read order stays oldest-segments-then-
        # live) instead of leaving them behind, where the new run's
        # ``read_jsonl`` would stitch them into a chimeric stream.
        for seg in segs[:-1]:
            m = re.match(re.escape(path) + r'\.(\d+)$', seg)
            os.replace(seg, f'{path}.prev.1.{m.group(1)}')
        os.replace(segs[-1], f'{path}.prev.1')
    n = PREV_INCARNATIONS_KEPT + 1
    while os.path.exists(f'{path}.prev.{n}'):
        _unlink_incarnation(f'{path}.prev.{n}')
        n += 1


def read_jsonl(path: str, validate: bool = True) -> list[dict]:
    """Load (and by default schema-validate) every record of a run.

    Rotated segments ``<path>.1 .. .N`` are read first (oldest-first),
    then the live file — one call reconstructs the full stream.
    """
    paths = _rotated_segments(path)
    if os.path.exists(path):
        paths.append(path)
    if not paths:
        raise FileNotFoundError(path)
    records = []
    for p in paths:
        records.extend(_read_jsonl_file(p, validate))
    return records


def read_jsonl_tolerant(path: str, validate: bool = True
                        ) -> tuple[list[dict], int]:
    """:func:`read_jsonl`, but tolerant of a torn FINAL line per file.

    A process killed mid-append (the per-rank straggler shards, or any
    external writer without the atomic rewrite) leaves at most one
    truncated trailing line per physical file. That line is skipped and
    counted — returns ``(records, n_torn)`` so the report can surface
    the skip instead of refusing the whole stream. An undecodable line
    anywhere *else* is still corruption and raises: only the crash
    window at the tail is a known-benign failure mode.
    """
    paths = _rotated_segments(path)
    if os.path.exists(path):
        paths.append(path)
    if not paths:
        raise FileNotFoundError(path)
    records, torn = [], 0
    for p in paths:
        recs, t = _read_jsonl_file(p, validate, tolerate_torn_tail=True)
        records.extend(recs)
        torn += t
    return records, torn


def _read_jsonl_file(p: str, validate: bool,
                     tolerate_torn_tail: bool = False
                     ) -> list[dict] | tuple[list[dict], int]:
    # Streaming with one deferred failure: a decode error is only
    # "torn" if no further non-empty line follows it (the crash
    # window is the tail by construction) — O(1) extra memory even on
    # unrotated multi-GB streams.
    records, torn = [], 0
    deferred: tuple[int, Exception] | None = None
    with open(p) as f:
        for i, raw in enumerate(f):
            line = raw.strip()
            if not line:
                continue
            if deferred is not None:
                di, de = deferred
                raise ValueError(f'{p}:{di + 1}: torn/invalid JSON '
                                 f'line: {de}') from de
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                if tolerate_torn_tail:
                    deferred = (i, e)
                    continue
                raise ValueError(f'{p}:{i + 1}: torn/invalid JSON '
                                 f'line: {e}') from e
            if validate:
                validate_record(rec)
            records.append(rec)
    if deferred is not None:
        torn += 1
    if tolerate_torn_tail:
        return records, torn
    return records


def read_incarnation(path: str, validate: bool = False) -> list[dict]:
    """Read one entry of :func:`incarnation_paths`.

    Chained incarnations (``<path>.prev.<n>``) read like any run —
    their ``.prev.<n>.<m>`` rotated segments stitch in oldest-first. A
    LEGACY single-slot ``<path>.prev`` (pre-r9 layout) must read the
    exact file only: r8 never preserved rotated segments, and its
    ``<path>.prev.<n>`` *neighbors* are chain entries — different
    runs — that ``read_jsonl``'s segment stitching would wrongly
    concatenate into the legacy stream.
    """
    if path.endswith('.prev'):
        return _read_jsonl_file(path, validate)
    return read_jsonl(path, validate)


class JsonlMetricsSink:
    """Asynchronous JSONL writer for per-step K-FAC metrics.

    Args:
      path: target ``.jsonl`` file (parent dirs are created).
      interval: keep every Nth step record (``metrics_interval``; epoch
        and meta records are always kept).
      process_index: this process's rank; non-zero ranks become no-op
        sinks (safe to call unconditionally from SPMD code).
      rotate_bytes: rotate the live segment past this size. Bounds both
        segment size and the atomic-rewrite cost *per drain* (each
        drain republishes the current segment — crash-durable at drain
        granularity). None disables.
      drain_every: drain-and-publish after this many enqueued records
        (keeps host memory flat, bounds telemetry loss on a crash, and
        sets the health monitor's reaction latency — all while staying
        far behind the dispatch frontier).
      monitor: optional :class:`observability.health.HealthMonitor`;
        every drained record is fed to it (its action — warn / skip /
        raise — fires at drain time, off the step path, and always
        AFTER the drained records are persisted).
      meta: optional run-config dict written once as the leading
        ``kind='meta'`` record.
    """

    def __init__(self, path: str, *, interval: int = 1,
                 process_index: int = 0,
                 rotate_bytes: int | None = 4 * 1024 * 1024,
                 drain_every: int = 64,
                 monitor=None,
                 meta: dict | None = None):
        if interval < 1:
            raise ValueError(f'{interval=} must be >= 1')
        self.path = path
        self.interval = interval
        self.enabled = process_index == 0
        self.rotate_bytes = rotate_bytes
        self.drain_every = drain_every
        self.monitor = monitor
        self._pending: list[dict] = []    # records w/ device scalars
        self._lines: list[str] = []       # serialized current segment
        self._bytes = 0
        self._segments = 0
        self._step_seen = 0
        if not self.enabled:
            return
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # A fresh sink owns its path: the previous run's stream must
        # not be stitched into this one (``read_jsonl`` would build a
        # chimeric stream from two runs' individually-valid records —
        # e.g. on the CLIs' default <log-dir> path), but it must not be
        # destroyed either: a relaunch after preemption reuses the same
        # path, and the dead incarnation's tail holds its final records
        # — preemption and forced-save events included — exactly the
        # telemetry a post-mortem needs (r8). The whole prior stream
        # (live segment + rotations) therefore moves onto the
        # incarnation chain ``<path>.prev.1`` (newest) .. ``.prev.N``,
        # bounded at PREV_INCARNATIONS_KEPT with the oldest pruned —
        # the r8 single-slot ``<path>.prev`` let a SECOND relaunch
        # silently overwrite the first incarnation (r9 satellite fix).
        # ``observability.report`` lists the surviving incarnations.
        _chain_incarnation(path)
        if meta is not None:
            self._pending.append({'schema': SCHEMA_VERSION,
                                  'kind': 'meta',
                                  'wall_time': time.time(),
                                  'meta': dict(meta)})

    # -- enqueue (step path: no syncs) ---------------------------------

    def step_record(self, step: int, metrics: dict,
                    host_step_ms: float | None = None,
                    fired: str | None = None) -> None:
        """Enqueue one step's metrics (every ``interval``-th kept).

        ``metrics`` values may be device scalars; an async copy to host
        is kicked off here and the float conversion happens at drain.
        ``fired`` labels the heaviest statically-gated K-FAC stage the
        step ran ('factor' / 'inverse' / 'chunk<j>', see
        ``engine.fired_stage``) — the report's step-time outlier
        attribution keys on it.
        """
        self._step_seen += 1
        if not self.enabled or (self._step_seen - 1) % self.interval:
            return
        rec = {'schema': SCHEMA_VERSION, 'kind': 'step',
               'step': int(step), 'wall_time': time.time(),
               'metrics': dict(metrics)}
        if host_step_ms is not None:
            rec['host_step_ms'] = float(host_step_ms)
        if fired is not None:
            rec['fired'] = str(fired)
        for v in rec['metrics'].values():
            copy_async = getattr(v, 'copy_to_host_async', None)
            if copy_async is not None:
                try:
                    copy_async()
                except Exception:
                    pass
        self._pending.append(rec)
        if len(self._pending) >= self.drain_every:
            # Full flush, not just an in-memory drain: a crash between
            # drains must not lose the run's telemetry, and the health
            # monitor must see records at drain cadence (not only at
            # epoch end). The atomic segment rewrite is bounded by
            # rotate_bytes.
            self.flush()

    def epoch_record(self, epoch: int, metrics: dict,
                     trace: dict | None = None) -> None:
        """Record epoch-level averages plus a host trace-table snapshot."""
        if not self.enabled:
            return
        rec = {'schema': SCHEMA_VERSION, 'kind': 'epoch',
               'epoch': int(epoch), 'wall_time': time.time(),
               'metrics': dict(metrics)}
        if trace:
            rec['trace'] = trace
        self._pending.append(rec)

    def meta_record(self, meta: dict) -> None:
        """Append a ``kind='meta'`` record mid-stream.

        For run provenance that only exists AFTER sink construction —
        e.g. the per-layer K-FAC approximation map, resolved at layer
        registration (the CLIs build the sink before the model). The
        reader treats every meta record as provenance; multiple are
        fine (the leading constructor meta stays the run header).
        Flushed immediately like events: provenance must survive an
        early crash.
        """
        if not self.enabled:
            return
        self._pending.append({'schema': SCHEMA_VERSION, 'kind': 'meta',
                              'wall_time': time.time(),
                              'meta': dict(meta)})
        self.flush()

    def event_record(self, name: str, **data) -> None:
        """Record a resilience/lifecycle event (preemption, checkpoint
        save + latency, restore — r8). Events bypass interval thinning
        and are flushed IMMEDIATELY: they mark moments where the
        process is about to exit (preemption) or just came back
        (restore), exactly when pending telemetry must not be lost.
        ``data`` values must be JSON-serializable scalars/strings.
        """
        if not self.enabled:
            return
        self._pending.append({'schema': SCHEMA_VERSION, 'kind': 'event',
                              'event': str(name),
                              'wall_time': time.time(),
                              'data': dict(data)})
        self.flush()

    def memory_record(self, step: int, device: dict | None = None,
                      state: dict | None = None) -> None:
        """Record one memory-telemetry sample (r10).

        ``device``: allocator watermarks from
        ``observability.memory.device_memory_stats`` (bytes_in_use /
        peak_bytes_in_use; omit on backends without stats). ``state``:
        the host-side K-FAC state footprint breakdown from
        ``state_footprint``. Bypasses interval thinning (the engine
        already samples on its own ``memory_interval`` cadence) but
        drains with the normal flush cadence — watermarks are periodic
        telemetry, not last-words events.
        """
        if not self.enabled:
            return
        rec: dict = {'schema': SCHEMA_VERSION, 'kind': 'memory',
                     'step': int(step), 'wall_time': time.time()}
        if device:
            rec['device'] = dict(device)
        if state:
            rec['state'] = dict(state)
        self._pending.append(rec)

    # -- drain / write (off the step path) -----------------------------

    def _drain(self) -> list[dict]:
        """Serialize pending records into the current segment.

        Pending is cleared up front and every record is serialized
        before any monitor sees it — a raising health action can then
        neither lose nor duplicate records (see the callers: the
        segment is written before the exception propagates).
        """
        drained, self._pending = self._pending, []
        for rec in drained:
            if 'metrics' in rec:
                cleaned = {}
                for k, v in rec['metrics'].items():
                    f = to_float(v)
                    # JSON has no inf/nan literals; stringify so the
                    # reader sees the signal instead of a parse error.
                    cleaned[k] = f if math.isfinite(f) else repr(f)
                rec['metrics'] = cleaned
            self._lines.append(json.dumps(rec, sort_keys=True))
        return drained

    def _observe(self, drained: list[dict]) -> None:
        if self.monitor is None:
            return
        for rec in drained:
            self.monitor.observe(rec)

    def _write_segment(self) -> None:
        data = '\n'.join(self._lines) + ('\n' if self._lines else '')
        tmp = f'{self.path}.tmp.{os.getpid()}'
        with open(tmp, 'w') as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._bytes = len(data)

    def flush(self) -> None:
        """Drain pending records and atomically publish the segment.

        The health monitor runs AFTER the write: an action='raise'
        propagates with the full stream already on disk (the run that
        dies on a health event needs its telemetry most).
        """
        if not self.enabled:
            return
        drained = self._drain()
        self._write_segment()
        if self.rotate_bytes and self._bytes >= self.rotate_bytes:
            self._segments += 1
            os.replace(self.path, f'{self.path}.{self._segments}')
            self._lines = []
            self._write_segment()
        self._observe(drained)

    def close(self) -> None:
        self.flush()
