"""Memory telemetry: device HBM watermarks + K-FAC state footprint.

Two complementary sources, both host-side and sync-free (r10):

  - :func:`device_memory_stats` — the live allocator watermarks
    (``bytes_in_use`` / ``peak_bytes_in_use``) from
    ``jax.Device.memory_stats()``. On TPU/GPU this is the HBM truth the
    paper's memory/communication trade-off (KAISA, arXiv:2107.01739)
    is argued over; the CPU backend reports nothing and the function
    degrades to ``{}`` instead of raising, so callers can emit records
    unconditionally.
  - :func:`state_footprint` — a shape/dtype walk over the resident
    K-FAC state pytree (factors / inverses / bucket stacks, by dtype).
    No device transfer happens: ``jax.Array`` carries shape and dtype
    on the host, so the breakdown is exact and free. This is what
    finally makes the r6 bf16-resident-inverse and KAISA
    grad-worker-fraction memory claims auditable from a run's JSONL
    alone — the ``kind='memory'`` records carry both sources
    (``observability.sink.JsonlMetricsSink.memory_record``).

The engine samples every ``memory_interval`` steps
(``train_epoch(memory_interval=)``, ``--memory-interval`` in the CLIs);
``observability.report`` prints the last/peak watermarks and the
footprint table, and ``observability.gate`` regresses peak HBM against
a committed baseline.
"""

from __future__ import annotations

from typing import Any

import jax

# state_footprint groups. Top-level K-FAC state keys outside this map
# fold into 'other' (scalars like step / inv_chunk_phase). The SPMD
# bucket stacks ('inv_stacks') and the replicated single-chip
# 'inverses' both count as inverse storage, so the same report reads
# on either path.
STATE_GROUPS = {
    'factors': 'factors',
    'inverses': 'inverses',
    'inv_stacks': 'inverses',
    'diag_inv': 'inverses',
    'grouped_inv': 'inverses',
    'metrics': 'metrics',
    # r14 overlap state: the deferred-reduction accumulator is a full
    # factor-sized copy per device, and the staleness snapshot another
    # replicated factor copy — worth their own rows in the footprint
    # (they are the knobs' HBM price).
    'factor_accum': 'factor_accum',
    'frozen_factors': 'frozen_factors',
}


def device_memory_stats(device=None) -> dict:
    """Allocator watermarks of one device (``{}`` when unavailable).

    Keys are backend-defined; TPU/GPU expose at least ``bytes_in_use``
    and ``peak_bytes_in_use``. Only int/float values pass through (the
    JSONL record must stay scalar-valued). ``device`` defaults to the
    first local device — with the replicated/SPMD layouts this
    framework builds, every local device holds the same resident state,
    so one device's watermark is the per-chip number the gate compares.
    """
    if device is None:
        devs = jax.local_devices()
        if not devs:
            return {}
        device = devs[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return {}
    if not stats:
        return {}
    return {k: v for k, v in stats.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)}


def _leaf_bytes(x) -> int:
    """Per-device resident bytes of one leaf.

    Sharded leaves (the row-sharded SPMD inverse stacks) count their
    per-device shard, not the global logical size — the footprint must
    line up with the per-chip allocator watermarks it is reported next
    to (this is exactly the KAISA axis: grad_worker_fraction trades
    per-chip inverse residency against communication).
    """
    sharding = getattr(x, 'sharding', None)
    shape = getattr(x, 'shape', None)
    dtype = getattr(x, 'dtype', None)
    if sharding is not None and shape is not None and dtype is not None:
        try:
            shard_shape = sharding.shard_shape(tuple(shape))
            n = 1
            for s in shard_shape:
                n *= int(s)
            return n * dtype.itemsize
        except Exception:
            pass
    nbytes = getattr(x, 'nbytes', None)
    if isinstance(nbytes, int):
        return nbytes
    return 0


def _leaf_dtype(x) -> str:
    dt = getattr(x, 'dtype', None)
    return str(dt) if dt is not None else type(x).__name__


def state_footprint(state: Any) -> dict:
    """Byte breakdown of a (K-FAC) state pytree, by group and dtype.

    Pure host arithmetic over shapes/dtypes — no device sync, no
    transfer. Returns::

      {'total_bytes': int,
       'by_group': {'factors': int, 'inverses': int, ...},
       'by_dtype': {'float32': int, 'bfloat16': int, ...},
       'by_group_dtype': {'inverses/bfloat16': int, ...}}

    Grouping keys on the state's top-level entries per
    :data:`STATE_GROUPS` (single-chip ``inverses`` and the SPMD
    ``inv_stacks``/``diag_inv``/``grouped_inv`` all fold into
    'inverses', so the same report reads on either path); non-dict
    states (the SGD baseline threads ``None`` through the kfac slot)
    return an all-zero breakdown.
    """
    out = {'total_bytes': 0, 'by_group': {}, 'by_dtype': {},
           'by_group_dtype': {}}
    if not isinstance(state, dict):
        return out
    for key, sub in state.items():
        group = STATE_GROUPS.get(key, 'other')
        for leaf in jax.tree.leaves(sub):
            n = _leaf_bytes(leaf)
            if not n:
                continue
            dt = _leaf_dtype(leaf)
            out['total_bytes'] += n
            out['by_group'][group] = out['by_group'].get(group, 0) + n
            out['by_dtype'][dt] = out['by_dtype'].get(dt, 0) + n
            gk = f'{group}/{dt}'
            out['by_group_dtype'][gk] = (
                out['by_group_dtype'].get(gk, 0) + n)
    return out


def format_bytes(n: float) -> str:
    """Human-readable byte count for the report tables."""
    try:
        n = float(n)
    except (TypeError, ValueError):
        return '-'
    for unit in ('B', 'KiB', 'MiB', 'GiB', 'TiB'):
        if abs(n) < 1024.0 or unit == 'TiB':
            return (f'{n:.0f} {unit}' if unit == 'B'
                    else f'{n:.2f} {unit}')
        n /= 1024.0
    return f'{n:.2f} TiB'
