"""Host-side wall-clock tracing (the legacy ``utils.py`` trace table).

Decorator-based wall-clock tracing for host-side phases and dispatched
device work, folded into the observability subsystem in r7 (the
``trace``/``get_trace``/``clear_trace`` names stay re-exported from
``distributed_kfac_pytorch_tpu.utils`` for reference-parity callers).
``sync=True`` calls ``jax.block_until_ready`` on the result (the XLA
analogue of the reference's pre/post ``backend.barrier()`` — without it,
timings measure async dispatch only).

Reference bugs fixed (SURVEY.md §8): ``clear_trace`` actually clears
(utils.py:11-12 rebinds a local) and ``get_trace`` has no undefined
variable (utils.py:18-19 ``max_times``).

This table is the host-visible *stage* attribution: phases a CLI or
benchmark decorates (data loading, eval, checkpoint, whole-step
dispatch). Stages *inside* the jitted step are attributed by the
profiler scopes in :mod:`observability.profiling` instead, and the
JSONL sink (:mod:`observability.sink`) snapshots this table into each
epoch record so ``observability.report`` can print the breakdown
offline.
"""

from __future__ import annotations

import functools
import time
from typing import Callable

import jax

_FUNC_TRACES: dict[str, list[float]] = {}


def trace(sync: bool = False, name: str | None = None) -> Callable:
    """Decorator appending each call's duration to the module trace table.

    Args:
      sync: block on the result (and on a dummy device sync before
        starting) so the measurement covers device execution, not just
        dispatch.
      name: trace key (defaults to the function's __name__).
    """
    def decorator(fn):
        key = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if sync:
                jax.block_until_ready(
                    [a for a in args if isinstance(a, jax.Array)])
            start = time.perf_counter()
            out = fn(*args, **kwargs)
            if sync:
                jax.block_until_ready(out)
            _FUNC_TRACES.setdefault(key, []).append(
                time.perf_counter() - start)
            return out

        return wrapper

    return decorator


def get_trace(average: bool = True, max_history: int | None = None
              ) -> dict[str, float]:
    """Per-key mean (or total) duration in seconds.

    ``max_history`` restricts to the most recent N samples.
    """
    out = {}
    for key, times in _FUNC_TRACES.items():
        window = times[-max_history:] if max_history else times
        if not window:
            continue
        out[key] = (sum(window) / len(window)) if average else sum(window)
    return out


def print_trace(average: bool = True, max_history: int | None = None
                ) -> None:
    for key, val in sorted(get_trace(average, max_history).items()):
        print(f'{key}: {val * 1000:.3f} ms')


def clear_trace() -> None:
    _FUNC_TRACES.clear()


def record(key: str, seconds: float) -> None:
    """Append one externally-measured duration to the trace table.

    For callers that already hold a timing (e.g. the engine's per-step
    dispatch measurement) — same table as the ``@trace`` decorator, so
    the JSONL epoch snapshots and the report's stage table see both.
    """
    _FUNC_TRACES.setdefault(key, []).append(seconds)


def snapshot_trace() -> dict[str, dict[str, float]]:
    """``{key: {'mean_ms', 'total_ms', 'count'}}`` for JSONL records.

    The sink embeds this into epoch records so the report CLI can
    reconstruct the per-stage step-time breakdown offline without the
    live process.
    """
    out = {}
    for key, times in _FUNC_TRACES.items():
        if not times:
            continue
        total = sum(times)
        out[key] = {'mean_ms': total / len(times) * 1000.0,
                    'total_ms': total * 1000.0,
                    'count': len(times)}
    return out
