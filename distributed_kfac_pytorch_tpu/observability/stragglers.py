"""Per-host straggler attribution: rank shards, barrier probe, merger.

The rank-0 JSONL stream (``observability.sink``) sees one host's view
of the run. On a pod, the number that decides throughput is the
*slowest* host — every COMM_OPT/KAISA collective in
``parallel/distributed.py`` (factor pmean, inverse all_gather, gradient
psum) runs at the straggler's pace, and the rank-0 stream cannot even
see which host that is. Three pieces close the gap (r10):

  - **Rank shards** (:func:`make_rank_shard_sink`): every process
    writes its OWN sink shard ``<path>.rank<r>`` — same atomic
    write-then-rename, rotation and incarnation chaining as the rank-0
    stream (it *is* a ``JsonlMetricsSink``, force-enabled for its
    rank). Each step record carries that host's dispatch wall time
    plus its pre-collective barrier wait.
  - **Barrier probe** (:func:`build_barrier_probe`, surfaced as
    ``DistributedKFAC.build_barrier_probe``): a minimal ``psum`` over
    the same mesh axes the K-FAC collectives reduce over, dispatched
    and blocked on from the host. Because the device stream is
    in-order, the blocking time is (own queue drain) + (wait for the
    slowest participant to arrive) — i.e. exactly the wait the step's
    first collective experiences. A fast host measures large waits; the
    straggler measures ~0. NOTE: blocking the host each probe
    serializes dispatch with device completion, so the probe is opt-in
    (``--straggler-shards``) and its cost is documented in PERF.md —
    the skew numbers are the point of such a run.
  - **Merger** (:func:`merge_shards` / :func:`straggler_summary`):
    ``observability.report`` reads the shards next to a stream and
    prints per-host skew, slowest-rank frequency, and barrier-wait
    attribution; the ``--json`` output feeds CI.

Shard streams use the torn-tolerant reader: a host that dies
mid-append loses at most its final line, not its whole shard.
"""

from __future__ import annotations

import os
import re
import time

from distributed_kfac_pytorch_tpu.observability import sink as obs_sink
from distributed_kfac_pytorch_tpu.observability.sink import (
    percentile as _percentile,
    to_float as _num,
)

# Metrics key carrying the probe measurement inside shard step records.
BARRIER_WAIT_KEY = 'host/barrier_wait_ms'


def rank_shard_path(path: str, rank: int) -> str:
    """``run.jsonl`` -> ``run.jsonl.rank<r>`` (one shard per host)."""
    return f'{path}.rank{int(rank)}'


def make_rank_shard_sink(path: str, process_index: int, *,
                         rotate_bytes: int | None = 4 * 1024 * 1024,
                         drain_every: int = 64,
                         meta: dict | None = None
                         ) -> obs_sink.JsonlMetricsSink:
    """A per-rank shard sink at ``rank_shard_path(path, rank)``.

    Every process gets a WRITING sink (``process_index=0`` inside — the
    shard path itself is the rank gate), unlike the rank-0-gated main
    stream. The shard's meta record pins its rank so the merger can
    cross-check the filename against the content.
    """
    shard_meta = {'rank': int(process_index), **(meta or {})}
    return obs_sink.JsonlMetricsSink(
        rank_shard_path(path, process_index), process_index=0,
        rotate_bytes=rotate_bytes, drain_every=drain_every,
        meta=shard_meta)


def find_shards(path: str) -> dict[int, str]:
    """Rank shards written next to a stream: ``{rank: shard_path}``.

    Matches exactly ``<basename>.rank<digits>`` in the stream's
    directory — rotated shard segments (``.rank0.1``) and incarnations
    (``.rank0.prev.1``) belong to their shard's own reader, not here.
    """
    parent = os.path.dirname(os.path.abspath(path)) or '.'
    base = os.path.basename(path)
    pat = re.compile(re.escape(base) + r'\.rank(\d+)$')
    out = {}
    try:
        names = os.listdir(parent)
    except FileNotFoundError:
        return {}
    for name in names:
        m = pat.match(name)
        if m:
            out[int(m.group(1))] = os.path.join(parent, name)
    return dict(sorted(out.items()))


def merge_shards(path: str, validate: bool = True
                 ) -> tuple[dict[int, list[dict]], int, dict[int, str]]:
    """Read every rank shard of a stream (torn- and fault-tolerant).

    Returns ``({rank: records}, total_torn_lines, {rank: error})``.
    Each shard is a full ``read_jsonl`` stream (rotated segments
    stitch in), read with the tolerant tail. A shard that fails to
    read ANYWAY (mid-file corruption, schema-invalid line — e.g. an
    NFS half-write from a sick host) is skipped and reported in the
    errors map rather than raised: one bad host must not make the
    whole mesh's telemetry — or the intact rank-0 report — unreadable.
    """
    shards, torn, errors = {}, 0, {}
    for rank, shard in find_shards(path).items():
        try:
            records, t = obs_sink.read_jsonl_tolerant(shard, validate)
        except (OSError, ValueError) as e:
            errors[rank] = str(e)
            continue
        shards[rank] = records
        torn += t
    return shards, torn, errors


def stage_class(fired) -> str:
    """Comm-wait attribution class of a step's ``fired`` label.

    'dcn' = steps that pay the r20 inter-slice DCN factor reduce
    (hierarchical runs relabel the window-boundary 'reduce' to
    'dcn_reduce' — its wait is slow-interconnect wait, the number the
    r20 flat-vs-hierarchical decision rule reads, so it gets its own
    bucket rather than folding into 'factor'); 'factor' = steps that
    pay an ICI factor-statistics collective (the eager per-step pmean,
    the r14 deferred window-boundary 'reduce', and compound
    firing+reduce labels); 'firing' = collective-free inverse/chunk
    decomposition steps; 'compile' = first-call compile steps (their
    timing is compile wall, not steady state); 'plain' = everything
    else. The factor-vs-plain wait split is how an overlap win (r14
    deferred reduce / staleness) reads directly from the JSONL,
    without a profile timeline (PERF.md r7 rule).
    """
    if isinstance(fired, str) and 'dcn' in fired:
        # Must precede the generic 'reduce' match: 'dcn_reduce' (and
        # compound 'inverse+dcn_reduce') contain 'reduce' too.
        return 'dcn'
    if isinstance(fired, str) and 'reduce' in fired:
        # 'reduce' alone, or a compound 'inverse+reduce'/'chunkJ+reduce'
        # firing step: the step pays the per-window factor collective,
        # which is the wait the factor class exists to attribute.
        return 'factor'
    if fired == 'factor':
        return 'factor'
    if fired == 'inverse' or (isinstance(fired, str)
                              and fired.startswith('chunk')):
        return 'firing'
    if fired == 'compile':
        return 'compile'
    return 'plain'


def wait_attribution(shards: dict[int, list[dict]]) -> dict | None:
    """Barrier-wait stats per stage class, over every rank's shard.

    ``{class: {'n', 'mean_wait_ms', 'max_wait_ms'}}`` for the classes
    that recorded any wait (sampled probes — ``--straggler-sample-every``
    — simply contribute fewer points; steps without a wait field are
    skipped, so sparse shards merge cleanly). None when no step
    carried a wait.
    """
    buckets: dict[str, list[float]] = {}
    for records in shards.values():
        for r in records:
            if r.get('kind') != 'step':
                continue
            w = _num(r.get('metrics', {}).get(BARRIER_WAIT_KEY))
            if w != w:  # NaN: no wait recorded on this step
                continue
            buckets.setdefault(stage_class(r.get('fired')),
                               []).append(w)
    if not buckets:
        return None
    return {cls: {'n': len(vals),
                  'mean_wait_ms': sum(vals) / len(vals),
                  'max_wait_ms': max(vals)}
            for cls, vals in sorted(buckets.items())}


def straggler_summary(shards: dict[int, list[dict]]) -> dict | None:
    """Cross-host skew analysis over merged rank shards.

    Per rank: step count, p50/p95 dispatch ms, mean/max barrier-wait
    ms. Across ranks (over steps every shard recorded): how often each
    rank was the slowest (``slowest_counts`` — the straggler
    attribution: a uniform spread is jitter, one dominant rank is a
    sick host), and the mean/max per-step skew (slowest minus fastest
    dispatch). Wait-time inverts the picture — the rank that waits
    LEAST at the barrier is the one everyone else waits FOR.

    Multi-slice runs (r20): shards whose meta record carries a
    ``slice`` id (the CLIs stamp ``slice_of_rank(...)`` into the shard
    meta) additionally aggregate into ``per_slice`` rows — per-slice
    rank list, p50/p95 over the slice's pooled dispatch times and
    slowest-rank share, so inter-slice skew (a slow DCN domain, a sick
    slice) reads directly from the report without eyeballing N rank
    rows.
    """
    per_rank: dict[int, dict] = {}
    step_times: dict[int, dict[int, float]] = {}
    rank_slice: dict[int, int] = {}
    rank_times: dict[int, list[float]] = {}
    for rank, records in shards.items():
        times, waits = [], []
        for r in records:
            if (r.get('kind') == 'meta'
                    and isinstance(r.get('meta'), dict)
                    and r['meta'].get('slice') is not None):
                rank_slice[rank] = int(r['meta']['slice'])
            if r.get('kind') != 'step':
                continue
            ms = r.get('host_step_ms')
            if isinstance(ms, (int, float)):
                times.append(float(ms))
                step_times.setdefault(int(r['step']), {})[rank] = float(
                    ms)
            w = _num(r.get('metrics', {}).get(BARRIER_WAIT_KEY))
            if w == w:  # not NaN
                waits.append(w)
        if not times:
            continue
        rank_times[rank] = times
        svals = sorted(times)
        per_rank[rank] = {
            'n_steps': len(times),
            'p50_ms': _percentile(svals, 50),
            'p95_ms': _percentile(svals, 95),
            'mean_wait_ms': (sum(waits) / len(waits) if waits else None),
            'max_wait_ms': (max(waits) if waits else None),
        }
    if not per_rank:
        return None
    slowest: dict[int, int] = {r: 0 for r in per_rank}
    skews = []
    common = [s for s, by_rank in step_times.items()
              if len(by_rank) == len(per_rank)]
    for s in common:
        by_rank = step_times[s]
        worst = max(by_rank, key=by_rank.get)
        slowest[worst] += 1
        skews.append(max(by_rank.values()) - min(by_rank.values()))
    per_slice = None
    if rank_slice and any(rank in per_rank for rank in rank_slice):
        groups: dict[int, list[int]] = {}
        for rank in per_rank:
            if rank in rank_slice:
                groups.setdefault(rank_slice[rank], []).append(rank)
        per_slice = {}
        for sl, ranks in sorted(groups.items()):
            pooled = sorted(t for r in ranks for t in rank_times[r])
            per_slice[sl] = {
                'ranks': sorted(ranks),
                'n_steps': len(pooled),
                'p50_ms': _percentile(pooled, 50),
                'p95_ms': _percentile(pooled, 95),
                'slowest_count': sum(slowest[r] for r in ranks),
            }
    return {
        'n_ranks': len(per_rank),
        'per_rank': per_rank,
        'n_common_steps': len(common),
        'slowest_counts': slowest,
        'mean_skew_ms': (sum(skews) / len(skews) if skews else None),
        'max_skew_ms': (max(skews) if skews else None),
        # Comm-wait attribution by fired-stage class (r14): how much
        # of the barrier wait sits on factor-collective steps vs plain
        # steps — the number the deferred-reduce overlap moves.
        'wait_by_stage': wait_attribution(shards),
        # Per-slice skew rows (r20) — None on flat runs (no slice ids
        # in the shard meta), so pre-r20 report JSON consumers see the
        # key but not new structure unless multi-slice is on.
        'per_slice': per_slice,
    }


def build_barrier_probe(mesh, axes):
    """Compile + warm a minimal psum barrier over ``axes`` of ``mesh``.

    Returns ``probe() -> wait_ms``: dispatch a scalar psum over the
    same axes the K-FAC collectives reduce over and block until it
    completes. The measured wall time is this host's pre-collective
    barrier wait (own-queue drain + slowest-participant arrival; see
    the module docstring for why that is the right number and what it
    costs). The program is compiled and run once HERE so the first
    measured probe is not a compile.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_kfac_pytorch_tpu.observability import profiling

    axes = tuple(axes)

    def reduce(v):
        with profiling.annotate('kfac/comm/barrier_probe'):
            return jax.lax.psum(v, axes)

    fn = jax.jit(jax.shard_map(reduce, mesh=mesh, in_specs=P(),
                               out_specs=P(), check_vma=False))
    x = jnp.zeros((), jnp.float32)
    jax.block_until_ready(fn(x))  # compile outside the measured window

    def probe() -> float:
        t0 = time.perf_counter()
        jax.block_until_ready(fn(x))
        return (time.perf_counter() - t0) * 1000.0

    return probe
