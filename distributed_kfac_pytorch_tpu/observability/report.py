"""Offline run report over a recorded K-FAC metrics JSONL.

    python -m distributed_kfac_pytorch_tpu.observability.report run.jsonl

Prints, from the recorded stream alone (no live process needed):

  - run/meta header and record inventory;
  - the per-stage step-time breakdown (host trace-table snapshots from
    epoch records — the stages CLIs/benchmarks decorate with
    ``observability.tracing.trace`` — plus per-step host dispatch
    time);
  - K-FAC health: factor/inverse firing counts, non-finite skips,
    eigenvalue-floor clips, damping/ν trajectory, grad vs
    preconditioned-grad norm ratio;
  - per precondition-bucket norms (last recorded step);
  - resilience events (r8): preemption / checkpoint-save / restore
    counts with checkpoint-save latency stats;
  - memory telemetry (r10): device HBM watermarks (last/peak) and the
    resident K-FAC state footprint by group/dtype;
  - compile/retrace telemetry (r10): per-variant first-call wall time
    from the step builder's (factor, inv, chunk) variant cache, and
    any retrace events (the offline echo of the ``trace_counts``
    guard);
  - straggler attribution (r10): when per-rank shards
    (``run.jsonl.rank<r>``, ``--straggler-shards``) sit next to the
    stream, per-host skew, slowest-rank frequency and barrier-wait
    stats;
  - self-healing (r16): the escalation ladder's decision trail —
    damping escalations/decays, bucket quarantines/readmits,
    in-process rollbacks, and checkpoint quarantines from the
    verified resume walk (``resilience.selfheal``);
  - supervision (r17): the failure supervisor's decision trail —
    restarts, hang detections, survivor-mesh failovers/grow-backs,
    crash loops — merged from the ``run.jsonl.supervisor`` sidecar
    the supervisor writes next to the stream
    (``resilience.supervisor``);
  - fleet scheduling (r18): when pointed at a fleet scheduler's own
    event stream (``<fleet-workdir>/fleet.jsonl``), the scheduler's
    decision counts (admits, preempts/regrows, quarantines) plus one
    SLO row per finished job — queue wait, run time, restarts,
    preemption count, final gate verdict — carried by its
    ``fleet_complete``/``fleet_quarantine`` events
    (``fleet.scheduler``).

A torn/truncated FINAL line (a host crashed mid-append) is skipped and
counted in the header instead of refusing the stream; torn lines
anywhere else are corruption and still fail. Exit status is non-zero
when the file fails schema validation, so the CI smoke can gate on it
directly. ``--json`` emits the machine-readable summary the
regression gate and CI consume (key set pinned by
tests/test_obs_perf.py).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

from distributed_kfac_pytorch_tpu.observability.health import (
    HealthMonitor,
)
from distributed_kfac_pytorch_tpu.observability.sink import (
    SUPERVISOR_SIDECAR_SUFFIX,
    peak_hbm_bytes,
    percentile as _percentile,
    read_jsonl_tolerant,
    to_float as _num,
)


def _fmt(v: float, unit: str = '') -> str:
    if math.isnan(v):
        return '-'
    return f'{v:.4g}{unit}'


def step_time_distribution(records: list[dict]) -> dict | None:
    """Step-time percentiles + outlier attribution by fired stage.

    Backend-independent (host dispatch wall time per step, recorded by
    the engine for every step record): p50/p95/p99/max ms/iter, the
    max/median spike ratio — the step-time-uniformity metric the
    pipelined inverse firing (r9) targets — and, for outlier steps
    (> 2x the median, the firing-spike signature), counts and mean ms
    per fired stage ('factor' / 'inverse' / 'chunk<j>' / plain).
    """
    host = [(r['host_step_ms'], r.get('fired', 'plain'))
            for r in records
            if r.get('kind') == 'step' and 'host_step_ms' in r]
    if not host:
        return None
    vals = sorted(v for v, _ in host)
    p50 = _percentile(vals, 50)
    dist = {
        'n_steps': len(vals),
        'p50_ms': p50,
        'p95_ms': _percentile(vals, 95),
        'p99_ms': _percentile(vals, 99),
        'max_ms': vals[-1],
        'max_over_median': (vals[-1] / p50 if p50 else float('nan')),
    }
    threshold = 2.0 * p50
    dist['outlier_threshold_ms'] = threshold
    stages: dict[str, dict] = {}
    for v, f in host:
        s = stages.setdefault(f, {'count': 0, 'total_ms': 0.0,
                                  'outliers': 0, 'outlier_ms': 0.0})
        s['count'] += 1
        s['total_ms'] += v
        if v > threshold:
            s['outliers'] += 1
            s['outlier_ms'] += v
    dist['stages'] = {
        f: {'count': s['count'],
            'mean_ms': s['total_ms'] / s['count'],
            'outliers': s['outliers'],
            'outlier_mean_ms': (s['outlier_ms'] / s['outliers']
                                if s['outliers'] else float('nan'))}
        for f, s in stages.items()}
    return dist


# The supervisor's event vocabulary (registered in sink.EVENT_KINDS).
# Supervisor events normally live in a SIDECAR stream next to the
# run's JSONL (``<path>.supervisor`` — the supervisor outlives child
# incarnations, so its decisions cannot ride the rank-0 stream that
# each relaunch rotates away); ``main`` merges the sidecar, and
# ``summarize`` also picks up any supervision events recorded inline.
_SUPERVISION_KINDS = ('supervisor_restart', 'supervisor_failover',
                      'supervisor_growback', 'hang_detected',
                      'crash_loop', 'capacity_degraded')

# The fleet scheduler's event vocabulary (registered in
# sink.EVENT_KINDS). Fleet events live in the fleet's OWN stream
# (``<fleet-workdir>/fleet.jsonl`` — the scheduler outlives every job
# it packs); pointing this report at that stream renders the fleet
# section with one SLO row per job, built from the data each
# fleet_complete / fleet_quarantine event carries.
_FLEET_KINDS = ('fleet_admit', 'fleet_preempt', 'fleet_regrow',
                'fleet_quarantine', 'fleet_complete')

#: The per-job SLO row keys a fleet_complete / fleet_quarantine event
#: contributes to the report's ``fleet.jobs`` table (pinned by
#: tests/test_fleet.py — the --json consumer's contract).
FLEET_SLO_KEYS = ('outcome', 'rc', 'devices', 'queue_wait_s', 'run_s',
                  'restarts', 'preemptions', 'gate', 'reason')


def _series(records, key):
    out = []
    for r in records:
        if r.get('kind') == 'step' and key in r.get('metrics', {}):
            out.append((r['step'], _num(r['metrics'][key])))
    return out


def summarize(records: list[dict],
              supervisor_records: list[dict] | None = None) -> dict:
    """Structured summary of a record stream (the report's data model).

    ``supervisor_records``: the supervisor's sidecar stream
    (``<path>.supervisor``), merged into the supervision section only —
    its events describe the whole supervised session, while the main
    stream may hold just the newest incarnation.
    """
    steps = [r for r in records if r.get('kind') == 'step']
    epochs = [r for r in records if r.get('kind') == 'epoch']
    meta = next((r['meta'] for r in records if r.get('kind') == 'meta'),
                {})

    # Per-stage breakdown: the LAST epoch record's trace snapshot holds
    # the cumulative table (snapshot_trace accumulates over the run).
    stages = {}
    for r in epochs:
        for k, v in r.get('trace', {}).items():
            stages[k] = v

    host_ms = [r['host_step_ms'] for r in steps if 'host_step_ms' in r]
    loss = _series(records, 'loss')
    gn = _series(records, 'kfac/grad_norm')
    pn = _series(records, 'kfac/precond_norm')
    ratio = [(s, p / g if g else float('nan'))
             for (s, g), (_, p) in zip(gn, pn)]
    damping = _series(records, 'kfac/damping')
    nu = _series(records, 'kfac/nu')

    last = steps[-1]['metrics'] if steps else {}
    buckets = {k.split('/', 2)[-1]: _num(v) for k, v in last.items()
               if k.startswith('kfac/bucket_norm/')}

    monitor = HealthMonitor(action='skip')
    for r in records:
        monitor.observe(r)

    # Resilience events (r8): counts per kind plus checkpoint-save
    # latency stats (the forced preemption save is the one that gates
    # process exit — its latency is the grace budget consumed).
    events = [r for r in records if r.get('kind') == 'event']
    event_counts: dict[str, int] = {}
    for r in events:
        event_counts[r['event']] = event_counts.get(r['event'], 0) + 1
    save_lat = [_num(r.get('data', {}).get('latency_ms'))
                for r in events if r['event'] == 'checkpoint_save']
    save_lat = [v for v in save_lat if not math.isnan(v)]

    # Memory telemetry (r10): device watermarks + state footprint.
    mem_records = [r for r in records if r.get('kind') == 'memory']
    memory = None
    if mem_records:
        peak = peak_hbm_bytes(mem_records)
        last_state = next((r['state'] for r in reversed(mem_records)
                           if r.get('state')), {})
        memory = {'n_samples': len(mem_records),
                  'peak_hbm_bytes': peak,
                  'last_device': dict(mem_records[-1].get('device',
                                                          {})),
                  'last_state': dict(last_state)}

    # Compile/retrace telemetry (r10): the step builder's variant
    # cache emits one 'compile' event per variant (first-call wall =
    # trace + XLA compile + first dispatch) and a 'retrace' event if a
    # variant ever re-traces — which the static-cadence contract
    # forbids (trace_counts guard).
    compiles = [dict(r.get('data', {})) for r in events
                if r['event'] == 'compile']
    retraces = [dict(r.get('data', {})) for r in events
                if r['event'] == 'retrace']

    # Autotune decision events (r12): policy backoff/relax decisions
    # and the fail-closed --tuned-config load outcome. Rendered in
    # their own section (and pinned in the --json key set) so a run's
    # effective configuration story is auditable from the stream.
    # Counts cover the whole stream; the per-event detail list keeps
    # only the newest window — a mesh oscillating around the skew
    # threshold emits stretch/relax pairs indefinitely, and neither
    # the report nor its --json consumer should scale with that (the
    # full sequence is on disk in the stream itself).
    # Self-healing ladder events (r16): escalation/de-escalation,
    # bucket quarantine/readmit, in-process rollbacks, and the verified
    # resume walk's checkpoint quarantines. Same newest-window cap
    # discipline as the autotune section (an oscillating ladder must
    # not grow the report); the full sequence is in the stream.
    selfheal_events = [{'event': r['event'], **dict(r.get('data', {}))}
                       for r in events
                       if r['event'].startswith('selfheal')
                       or r['event'] == 'ckpt_quarantine']
    selfheal = None
    if selfheal_events:
        count = lambda kind: sum(1 for e in selfheal_events
                                 if e['event'] == kind)
        selfheal = {
            'n_events': len(selfheal_events),
            'events': selfheal_events[-50:],
            'escalations': count('selfheal_escalate'),
            'deescalations': count('selfheal_deescalate'),
            'quarantines': count('selfheal_quarantine'),
            'readmits': count('selfheal_readmit'),
            'rollbacks': count('selfheal_rollback'),
            'ckpt_quarantines': count('ckpt_quarantine'),
        }

    # Failure supervision (r17): the supervisor's decision trail —
    # restarts, hang detections, failover/grow-back resizes, crash
    # loops. Usually from the sidecar stream (the supervisor outlives
    # every child incarnation); inline events count too. Same
    # newest-window cap discipline as the other event sections.
    sup_source = list(events)  # inline events (filtered above) ...
    for r in (supervisor_records or []):
        if r.get('kind') == 'event':
            sup_source.append(r)  # ... plus the sidecar's
    supervision_events = [{'event': r['event'],
                           **dict(r.get('data', {}))}
                          for r in sup_source
                          if r['event'] in _SUPERVISION_KINDS]
    supervision = None
    if supervision_events:
        count = lambda kind: sum(1 for e in supervision_events
                                 if e['event'] == kind)
        supervision = {
            'n_events': len(supervision_events),
            'events': supervision_events[-50:],
            'restarts': count('supervisor_restart'),
            'failovers': count('supervisor_failover'),
            'growbacks': count('supervisor_growback'),
            'hangs': count('hang_detected'),
            'crash_loops': count('crash_loop'),
        }

    # Fleet scheduling (r18): per-job SLO rows plus scheduler decision
    # counts. The terminal events (fleet_complete / fleet_quarantine)
    # carry each job's SLO data, so the table needs no second stream;
    # same newest-window cap discipline for the event detail list.
    fleet_events = [{'event': r['event'], **dict(r.get('data', {}))}
                    for r in events if r['event'] in _FLEET_KINDS]
    fleet = None
    if fleet_events:
        count = lambda kind: sum(1 for e in fleet_events
                                 if e['event'] == kind)
        jobs: dict[str, dict] = {}
        for e in fleet_events:
            if e['event'] not in ('fleet_complete', 'fleet_quarantine'):
                continue
            row = {k: e.get(k) for k in FLEET_SLO_KEYS}
            row['outcome'] = ('complete'
                              if e['event'] == 'fleet_complete'
                              else 'quarantined')
            jobs[str(e.get('job'))] = row
        fleet = {
            'n_events': len(fleet_events),
            'events': fleet_events[-50:],
            'admits': count('fleet_admit'),
            'preempts': count('fleet_preempt'),
            'regrows': count('fleet_regrow'),
            'quarantines': count('fleet_quarantine'),
            'completes': count('fleet_complete'),
            'jobs': jobs,
        }

    autotune_events = [{'event': r['event'], **dict(r.get('data', {}))}
                       for r in events
                       if r['event'].startswith('autotune')]
    autotune = None
    if autotune_events:
        autotune = {
            'n_events': len(autotune_events),
            'events': autotune_events[-50:],
            'backoffs': sum(1 for e in autotune_events
                            if e['event'] == 'autotune_backoff'
                            and e.get('action') == 'stretch'),
            'relaxes': sum(1 for e in autotune_events
                           if e['event'] == 'autotune_backoff'
                           and e.get('action') == 'relax'),
            'fallbacks': sum(1 for e in autotune_events
                             if e['event'] == 'autotune_fallback'),
            'applies': sum(1 for e in autotune_events
                           if e['event'] == 'autotune_apply'),
        }

    return {
        'autotune': autotune,
        'selfheal': selfheal,
        'supervision': supervision,
        'fleet': fleet,
        'memory': memory,
        'compiles': compiles,
        'retraces': retraces,
        'events': events,
        'event_counts': event_counts,
        'save_latency_ms': ((sum(save_lat) / len(save_lat),
                             max(save_lat)) if save_lat else None),
        'meta': meta,
        'n_records': len(records),
        'n_steps': len(steps),
        'n_epochs': len(epochs),
        'step_range': ((steps[0]['step'], steps[-1]['step'])
                       if steps else None),
        'stages': stages,
        'host_step_ms': (sum(host_ms) / len(host_ms) if host_ms
                         else float('nan')),
        'step_time': step_time_distribution(records),
        'loss': loss,
        'precond_ratio': ratio,
        'damping': damping,
        'nu': nu,
        'factor_updates': _num(last.get('kfac/factor_updates')),
        'inv_updates': _num(last.get('kfac/inv_updates')),
        'inv_chunk_firings': _num(last.get('kfac/inv_chunk_firings')),
        'nonfinite_skips': _num(last.get('kfac/nonfinite_skips')),
        'eig_clipped': _num(last.get('kfac/eig_clipped')),
        'bucket_norms': buckets,
        'health_events': list(monitor.events),
        # Per-check-kind counts (r16 satellite: HealthMonitor.summary
        # now classifies; only nonfinite_skips used to survive here).
        'health_event_counts': monitor.summary()['by_kind'],
    }


def _print_event_detail(w, events: list[dict], n_events: int,
                        cap: int = 10) -> None:
    """Shared newest-window event renderer (self-healing + autotune
    sections): '(newest K of N)' note plus one sorted-detail line per
    event — one place to change the cap or the formatting."""
    shown = events[-cap:]
    if n_events > len(shown):
        w(f"  (newest {len(shown)} of {n_events}; the full "
          'sequence is in the stream)')
    for e in shown:
        detail = ', '.join(f'{k}={v}' for k, v in sorted(e.items())
                           if k != 'event')
        w(f'  ! {e["event"]}: {detail}')


def print_report(s: dict, out=None, torn: int = 0,
                 stragglers: dict | None = None) -> None:
    out = out or sys.stdout
    w = lambda line='': print(line, file=out)
    w('== K-FAC run report ==')
    if torn:
        w(f'note: skipped {torn} torn trailing line(s) (crash '
          'mid-write; the rest of the stream is intact)')
    if s['meta']:
        w('meta: ' + ', '.join(f'{k}={v}' for k, v in
                               sorted(s['meta'].items())))
    rng = s['step_range']
    w(f"records: {s['n_records']} ({s['n_steps']} step / "
      f"{s['n_epochs']} epoch)"
      + (f", steps {rng[0]}..{rng[1]}" if rng else ''))
    w()
    w('-- step time --')
    w(f"host dispatch: {_fmt(s['host_step_ms'], ' ms/step')}")
    d = s.get('step_time')
    if d:
        w(f"distribution ({d['n_steps']} steps): "
          f"p50 {_fmt(d['p50_ms'])}  p95 {_fmt(d['p95_ms'])}  "
          f"p99 {_fmt(d['p99_ms'])}  max {_fmt(d['max_ms'])} ms/iter  "
          f"(max/median {_fmt(d['max_over_median'], 'x')})")
        outliers = {f: v for f, v in d['stages'].items()
                    if v['outliers']}
        if outliers:
            w(f"outlier steps (> {_fmt(d['outlier_threshold_ms'])} ms "
              '= 2x median), by fired stage:')
            for f in sorted(outliers):
                v = outliers[f]
                w(f'  {f:<10} x{v["outliers"]:<5} '
                  f'mean {_fmt(v["outlier_mean_ms"], " ms")}  '
                  f'(stage mean over all its steps: '
                  f'{_fmt(v["mean_ms"], " ms")})')
        else:
            w('no outlier steps (> 2x median).')
    if s['stages']:
        w('stage                              mean ms    total ms  calls')
        for k in sorted(s['stages']):
            v = s['stages'][k]
            w(f"{k:<34} {v['mean_ms']:>8.3f} {v['total_ms']:>11.3f}"
              f"  {v['count']:>5}")
    else:
        w('(no host trace-table snapshots in the records — epoch '
          'records absent or no host phase was timed; see '
          'observability.tracing)')
    w()
    w('-- K-FAC health --')
    w(f"factor updates: {_fmt(s['factor_updates'])}   "
      f"inverse updates: {_fmt(s['inv_updates'])}   "
      f"chunk firings: {_fmt(s['inv_chunk_firings'])}")
    w(f"non-finite skips: {_fmt(s['nonfinite_skips'])}   "
      f"eigenvalues at clip floor: {_fmt(s['eig_clipped'])}")
    for name, series in (('loss', s['loss']),
                         ('damping', s['damping']),
                         ('kl-clip nu', s['nu']),
                         ('precond/grad norm ratio',
                          s['precond_ratio'])):
        if series:
            vals = [v for _, v in series if not math.isnan(v)]
            if vals:
                w(f'{name}: first {_fmt(series[0][1])}  '
                  f'last {_fmt(series[-1][1])}  '
                  f'min {_fmt(min(vals))}  max {_fmt(max(vals))}')
    if s['bucket_norms']:
        w()
        w('-- precondition buckets (last step, |v| per shape) --')
        for k in sorted(s['bucket_norms']):
            w(f'{k:<16} {_fmt(s["bucket_norms"][k])}')
    if s.get('memory'):
        from distributed_kfac_pytorch_tpu.observability.memory import (
            format_bytes,
        )
        m = s['memory']
        w()
        w(f"-- memory ({m['n_samples']} samples) --")
        if m['peak_hbm_bytes'] is not None:
            w(f"peak device HBM: {format_bytes(m['peak_hbm_bytes'])}")
        dev = m['last_device']
        if dev:
            parts = [f'{k}={format_bytes(v)}' for k, v in sorted(
                dev.items()) if k in ('bytes_in_use',
                                      'peak_bytes_in_use',
                                      'bytes_limit')]
            if parts:
                w('last sample: ' + '  '.join(parts))
        else:
            w('(no device allocator stats on this backend — state '
              'footprint only)')
        st = m['last_state']
        if st.get('total_bytes'):
            w('resident K-FAC state (per device): '
              f"{format_bytes(st['total_bytes'])}")
            for gk in sorted(st.get('by_group_dtype', {})):
                w(f'  {gk:<24} '
                  f"{format_bytes(st['by_group_dtype'][gk])}")
    if s.get('compiles') or s.get('retraces'):
        w()
        w(f"-- compile/retrace ({len(s['compiles'])} variant "
          'compile(s)) --')
        for ev in s['compiles']:
            w(f"  compile {ev.get('variant', '?'):<28} "
              f"first call {_fmt(_num(ev.get('first_call_ms')), ' ms')}")
        if s['retraces']:
            w(f"  ! {len(s['retraces'])} RETRACE event(s) — a "
              'static-cadence variant recompiled mid-run '
              '(trace_counts contract violated):')
            for ev in s['retraces']:
                w(f"    {ev.get('variant', '?')} trace #"
                  f"{ev.get('trace_count', '?')}")
    if stragglers:
        w()
        w(f"-- stragglers ({stragglers['n_ranks']} rank shard(s), "
          f"{stragglers['n_common_steps']} common steps) --")
        for rank in sorted(stragglers.get('unreadable', {})):
            w(f"  ! rank {rank} shard unreadable: "
              f"{stragglers['unreadable'][rank]}")
        for rank in sorted(stragglers['per_rank']):
            pr = stragglers['per_rank'][rank]
            wait = ('' if pr['mean_wait_ms'] is None else
                    f"  wait mean {_fmt(pr['mean_wait_ms'], ' ms')}"
                    f" max {_fmt(pr['max_wait_ms'], ' ms')}")
            w(f"  rank {rank}: {pr['n_steps']} steps  "
              f"p50 {_fmt(pr['p50_ms'], ' ms')}  "
              f"p95 {_fmt(pr['p95_ms'], ' ms')}{wait}")
        ps = stragglers.get('per_slice')
        if ps:
            # Per-slice skew rows (r20): pooled per-slice dispatch
            # percentiles + slowest-rank share, so a slow DCN domain
            # or sick slice reads in S rows instead of N rank rows.
            for sl in sorted(ps):
                row = ps[sl]
                ranks = ','.join(str(r) for r in row['ranks'])
                w(f"  slice {sl} (ranks {ranks}): "
                  f"{row['n_steps']} steps  "
                  f"p50 {_fmt(row['p50_ms'], ' ms')}  "
                  f"p95 {_fmt(row['p95_ms'], ' ms')}  "
                  f"slowest x{row['slowest_count']}")
        wbs = stragglers.get('wait_by_stage')
        if wbs:
            # Comm-wait attribution (r14): the factor-step vs plain-
            # step barrier-wait split is where a deferred-reduce /
            # staleness overlap win shows up, readable from the JSONL
            # alone (PERF.md r7 rule).
            parts = [f"{cls} mean {_fmt(v['mean_wait_ms'], ' ms')}"
                     f" max {_fmt(v['max_wait_ms'], ' ms')}"
                     f" (n={v['n']})"
                     for cls, v in sorted(wbs.items())]
            w('  comm wait by stage: ' + '  |  '.join(parts))
        if stragglers['n_common_steps']:
            counts = ', '.join(
                f'r{r}x{n}' for r, n in sorted(
                    stragglers['slowest_counts'].items()) if n)
            w(f'  slowest-rank frequency: {counts or "-"}')
            mean_skew = stragglers['mean_skew_ms']
            max_skew = stragglers['max_skew_ms']
            w(f"  per-step skew (slowest-fastest): mean "
              f"{_fmt(float('nan') if mean_skew is None else mean_skew, ' ms')}"
              f"  max "
              f"{_fmt(float('nan') if max_skew is None else max_skew, ' ms')}")
    if s.get('fleet'):
        fl = s['fleet']
        w()
        w(f"-- fleet ({fl['n_events']} scheduler event(s), "
          f"{len(fl['jobs'])} finished job(s)) --")
        w(f"admits: {fl['admits']}   preempts: {fl['preempts']} / "
          f"regrows: {fl['regrows']}   completes: {fl['completes']}   "
          f"quarantines: {fl['quarantines']}")
        for name in sorted(fl['jobs']):
            row = fl['jobs'][name]
            gate_note = ('' if row.get('gate') is None
                         else f"  gate {row['gate']}")
            w(f"  {name:<20} {row['outcome']:<12} rc {row['rc']}  "
              f"wait {_fmt(_num(row['queue_wait_s']), ' s')}  "
              f"run {_fmt(_num(row['run_s']), ' s')}  "
              f"restarts {row['restarts']}  "
              f"preemptions {row['preemptions']}{gate_note}")
        _print_event_detail(w, fl['events'], fl['n_events'])
    if s.get('supervision'):
        sup = s['supervision']
        w()
        w(f"-- supervision ({sup['n_events']} supervisor event(s)) --")
        w(f"restarts: {sup['restarts']}   hangs detected: "
          f"{sup['hangs']}   failovers: {sup['failovers']} / "
          f"grow-backs: {sup['growbacks']}   crash loops: "
          f"{sup['crash_loops']}")
        _print_event_detail(w, sup['events'], sup['n_events'])
    if s.get('selfheal'):
        sh = s['selfheal']
        w()
        w(f"-- self-healing ({sh['n_events']} ladder event(s)) --")
        w(f"damping escalations: {sh['escalations']} up / "
          f"{sh['deescalations']} decayed   quarantine: "
          f"{sh['quarantines']} gated / {sh['readmits']} re-admitted")
        w(f"rollbacks: {sh['rollbacks']} in-process   checkpoint "
          f"quarantines: {sh['ckpt_quarantines']}")
        _print_event_detail(w, sh['events'], sh['n_events'])
    if s.get('autotune'):
        a = s['autotune']
        w()
        w(f"-- autotune ({a['n_events']} decision event(s)) --")
        w(f"policy backoffs: {a['backoffs']} stretch / "
          f"{a['relaxes']} relax   tuned-config: {a['applies']} "
          f"applied / {a['fallbacks']} fell back to defaults")
        _print_event_detail(w, a['events'], a['n_events'])
    # Compile/retrace, autotune and self-healing events have their own
    # sections above; everything else in the event stream is
    # resilience lifecycle (r8).
    resil_counts = {k: v for k, v in s['event_counts'].items()
                    if k not in ('compile', 'retrace',
                                 'ckpt_quarantine')
                    and k not in _SUPERVISION_KINDS
                    and k not in _FLEET_KINDS
                    and not k.startswith('autotune')
                    and not k.startswith('selfheal')}
    if resil_counts:
        w()
        w('-- resilience events --')
        for name in sorted(resil_counts):
            w(f'{name:<18} x{resil_counts[name]}')
        if s['save_latency_ms']:
            mean, worst = s['save_latency_ms']
            w(f'checkpoint save latency: mean {_fmt(mean, " ms")}  '
              f'max {_fmt(worst, " ms")}')
        for r in s['events']:
            # Lifecycle moments worth a per-event line: preemptions,
            # restores, and topology changes (elastic resizes) — the
            # r11 grow/shrink events show up here alongside the
            # preemption that drained the old world.
            if r['event'] in ('preemption', 'restore',
                              'topology_change'):
                detail = ', '.join(f'{k}={v}' for k, v in
                                   sorted(r.get('data', {}).items()))
                w(f'  ! {r["event"]}: {detail}')
    w()
    if s['health_events']:
        w(f"-- {len(s['health_events'])} health event(s) --")
        for e in s['health_events']:
            w(f'  ! {e}')
    else:
        w('no health events.')


def _json_safe(x):
    """Recursively replace non-finite floats (json.dumps would emit
    bare NaN/Infinity, which strict parsers — and the gate — reject)
    and coerce tuple keys/values into JSON-clean structures."""
    if isinstance(x, dict):
        return {str(k): _json_safe(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_json_safe(v) for v in x]
    if isinstance(x, float) and not math.isfinite(x):
        return None
    return x


def summary_json(s: dict, *, torn: int = 0,
                 stragglers: dict | None = None) -> dict:
    """The machine-readable report (``--json``; consumed by the gate
    and CI). Top-level key set is part of the contract — pinned by
    tests/test_obs_perf.py; extend, don't rename."""
    return _json_safe({
        'meta': s['meta'],
        'n_records': s['n_records'],
        'n_steps': s['n_steps'],
        'n_epochs': s['n_epochs'],
        'step_range': s['step_range'],
        'step_time': s['step_time'],
        'stages': s['stages'],
        'memory': s['memory'],
        'compiles': s['compiles'],
        'retraces': s['retraces'],
        'autotune': s['autotune'],
        'selfheal': s['selfheal'],
        'supervision': s['supervision'],
        'fleet': s['fleet'],
        'event_counts': s['event_counts'],
        'kfac': {
            'factor_updates': s['factor_updates'],
            'inv_updates': s['inv_updates'],
            'inv_chunk_firings': s['inv_chunk_firings'],
            'nonfinite_skips': s['nonfinite_skips'],
            'eig_clipped': s['eig_clipped'],
            'bucket_norms': s['bucket_norms'],
        },
        'health_events': s['health_events'],
        'health_event_counts': s['health_event_counts'],
        'stragglers': stragglers,
        'torn_lines': torn,
    })


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog='python -m distributed_kfac_pytorch_tpu.observability'
             '.report',
        description='Summarize a recorded K-FAC metrics JSONL '
                    '(schema-validates; non-zero exit on invalid '
                    'files). A torn FINAL line is skipped and counted, '
                    'not fatal.')
    p.add_argument('jsonl', help='metrics file from --kfac-metrics '
                                 '(rotated segments are read too)')
    p.add_argument('--json', action='store_true',
                   help='machine-readable summary on stdout (the gate/'
                        'CI input; key set pinned by tests)')
    args = p.parse_args(argv)
    from distributed_kfac_pytorch_tpu.observability import (
        stragglers as straggler_mod,
    )
    try:
        records, torn = read_jsonl_tolerant(args.jsonl)
        shards, shard_torn, shard_errors = straggler_mod.merge_shards(
            args.jsonl)
    except (OSError, ValueError) as e:
        print(f'error: {e}', file=sys.stderr)
        return 1
    torn += shard_torn
    # Supervisor sidecar (r17): the supervision decision trail lives
    # next to the stream, written by a different process — torn-
    # tolerant like the shards, and an unreadable sidecar degrades the
    # supervision section rather than the report.
    supervisor_records = None
    sidecar = args.jsonl + SUPERVISOR_SIDECAR_SUFFIX
    if os.path.exists(sidecar):
        try:
            supervisor_records, sup_torn = read_jsonl_tolerant(sidecar)
            torn += sup_torn
        except (OSError, ValueError) as e:
            print(f'note: supervisor sidecar {sidecar} unreadable: {e}',
                  file=sys.stderr)
    stragglers = straggler_mod.straggler_summary(shards)
    if shard_errors:
        # Unreadable shards degrade the straggler section, never the
        # main report (one sick host must not hide the run summary).
        if stragglers is None:
            stragglers = {'n_ranks': 0, 'per_rank': {},
                          'n_common_steps': 0, 'slowest_counts': {},
                          'mean_skew_ms': None, 'max_skew_ms': None,
                          'wait_by_stage': None, 'per_slice': None}
        stragglers['unreadable'] = shard_errors
    s = summarize(records, supervisor_records=supervisor_records)
    if args.json:
        print(json.dumps(summary_json(s, torn=torn,
                                      stragglers=stragglers),
                         sort_keys=True))
        return 0
    print_report(s, torn=torn, stragglers=stragglers)
    from distributed_kfac_pytorch_tpu.observability.sink import (
        incarnation_paths,
        read_incarnation,
    )
    prev = incarnation_paths(args.jsonl)
    if prev:
        print()
        print(f'-- {len(prev)} surviving prior incarnation(s) '
              '(newest first; each readable with this report CLI) --')
        for path in prev:
            try:
                n = len(read_incarnation(path))
                note = f'{n} records'
            except (OSError, ValueError) as e:
                note = f'unreadable: {e}'
            print(f'  {path}  ({note})')
    return 0


if __name__ == '__main__':
    sys.exit(main())
