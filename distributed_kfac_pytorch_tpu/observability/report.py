"""Offline run report over a recorded K-FAC metrics JSONL.

    python -m distributed_kfac_pytorch_tpu.observability.report run.jsonl

Prints, from the recorded stream alone (no live process needed):

  - run/meta header and record inventory;
  - the per-stage step-time breakdown (host trace-table snapshots from
    epoch records — the stages CLIs/benchmarks decorate with
    ``observability.tracing.trace`` — plus per-step host dispatch
    time);
  - K-FAC health: factor/inverse firing counts, non-finite skips,
    eigenvalue-floor clips, damping/ν trajectory, grad vs
    preconditioned-grad norm ratio;
  - per precondition-bucket norms (last recorded step);
  - resilience events (r8): preemption / checkpoint-save / restore
    counts with checkpoint-save latency stats.

Exit status is non-zero when the file fails schema validation, so the
CI smoke can gate on it directly.
"""

from __future__ import annotations

import argparse
import math
import sys

from distributed_kfac_pytorch_tpu.observability.health import (
    HealthMonitor,
)
from distributed_kfac_pytorch_tpu.observability.sink import (
    read_jsonl,
    to_float as _num,
)


def _fmt(v: float, unit: str = '') -> str:
    if math.isnan(v):
        return '-'
    return f'{v:.4g}{unit}'


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Linear-interpolated percentile of an ascending-sorted list."""
    if not sorted_vals:
        return float('nan')
    pos = (len(sorted_vals) - 1) * q / 100.0
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (
        pos - lo)


def step_time_distribution(records: list[dict]) -> dict | None:
    """Step-time percentiles + outlier attribution by fired stage.

    Backend-independent (host dispatch wall time per step, recorded by
    the engine for every step record): p50/p95/p99/max ms/iter, the
    max/median spike ratio — the step-time-uniformity metric the
    pipelined inverse firing (r9) targets — and, for outlier steps
    (> 2x the median, the firing-spike signature), counts and mean ms
    per fired stage ('factor' / 'inverse' / 'chunk<j>' / plain).
    """
    host = [(r['host_step_ms'], r.get('fired', 'plain'))
            for r in records
            if r.get('kind') == 'step' and 'host_step_ms' in r]
    if not host:
        return None
    vals = sorted(v for v, _ in host)
    p50 = _percentile(vals, 50)
    dist = {
        'n_steps': len(vals),
        'p50_ms': p50,
        'p95_ms': _percentile(vals, 95),
        'p99_ms': _percentile(vals, 99),
        'max_ms': vals[-1],
        'max_over_median': (vals[-1] / p50 if p50 else float('nan')),
    }
    threshold = 2.0 * p50
    dist['outlier_threshold_ms'] = threshold
    stages: dict[str, dict] = {}
    for v, f in host:
        s = stages.setdefault(f, {'count': 0, 'total_ms': 0.0,
                                  'outliers': 0, 'outlier_ms': 0.0})
        s['count'] += 1
        s['total_ms'] += v
        if v > threshold:
            s['outliers'] += 1
            s['outlier_ms'] += v
    dist['stages'] = {
        f: {'count': s['count'],
            'mean_ms': s['total_ms'] / s['count'],
            'outliers': s['outliers'],
            'outlier_mean_ms': (s['outlier_ms'] / s['outliers']
                                if s['outliers'] else float('nan'))}
        for f, s in stages.items()}
    return dist


def _series(records, key):
    out = []
    for r in records:
        if r.get('kind') == 'step' and key in r.get('metrics', {}):
            out.append((r['step'], _num(r['metrics'][key])))
    return out


def summarize(records: list[dict]) -> dict:
    """Structured summary of a record stream (the report's data model)."""
    steps = [r for r in records if r.get('kind') == 'step']
    epochs = [r for r in records if r.get('kind') == 'epoch']
    meta = next((r['meta'] for r in records if r.get('kind') == 'meta'),
                {})

    # Per-stage breakdown: the LAST epoch record's trace snapshot holds
    # the cumulative table (snapshot_trace accumulates over the run).
    stages = {}
    for r in epochs:
        for k, v in r.get('trace', {}).items():
            stages[k] = v

    host_ms = [r['host_step_ms'] for r in steps if 'host_step_ms' in r]
    loss = _series(records, 'loss')
    gn = _series(records, 'kfac/grad_norm')
    pn = _series(records, 'kfac/precond_norm')
    ratio = [(s, p / g if g else float('nan'))
             for (s, g), (_, p) in zip(gn, pn)]
    damping = _series(records, 'kfac/damping')
    nu = _series(records, 'kfac/nu')

    last = steps[-1]['metrics'] if steps else {}
    buckets = {k.split('/', 2)[-1]: _num(v) for k, v in last.items()
               if k.startswith('kfac/bucket_norm/')}

    monitor = HealthMonitor(action='skip')
    for r in records:
        monitor.observe(r)

    # Resilience events (r8): counts per kind plus checkpoint-save
    # latency stats (the forced preemption save is the one that gates
    # process exit — its latency is the grace budget consumed).
    events = [r for r in records if r.get('kind') == 'event']
    event_counts: dict[str, int] = {}
    for r in events:
        event_counts[r['event']] = event_counts.get(r['event'], 0) + 1
    save_lat = [_num(r.get('data', {}).get('latency_ms'))
                for r in events if r['event'] == 'checkpoint_save']
    save_lat = [v for v in save_lat if not math.isnan(v)]

    return {
        'events': events,
        'event_counts': event_counts,
        'save_latency_ms': ((sum(save_lat) / len(save_lat),
                             max(save_lat)) if save_lat else None),
        'meta': meta,
        'n_records': len(records),
        'n_steps': len(steps),
        'n_epochs': len(epochs),
        'step_range': ((steps[0]['step'], steps[-1]['step'])
                       if steps else None),
        'stages': stages,
        'host_step_ms': (sum(host_ms) / len(host_ms) if host_ms
                         else float('nan')),
        'step_time': step_time_distribution(records),
        'loss': loss,
        'precond_ratio': ratio,
        'damping': damping,
        'nu': nu,
        'factor_updates': _num(last.get('kfac/factor_updates')),
        'inv_updates': _num(last.get('kfac/inv_updates')),
        'inv_chunk_firings': _num(last.get('kfac/inv_chunk_firings')),
        'nonfinite_skips': _num(last.get('kfac/nonfinite_skips')),
        'eig_clipped': _num(last.get('kfac/eig_clipped')),
        'bucket_norms': buckets,
        'health_events': list(monitor.events),
    }


def print_report(s: dict, out=None) -> None:
    out = out or sys.stdout
    w = lambda line='': print(line, file=out)
    w('== K-FAC run report ==')
    if s['meta']:
        w('meta: ' + ', '.join(f'{k}={v}' for k, v in
                               sorted(s['meta'].items())))
    rng = s['step_range']
    w(f"records: {s['n_records']} ({s['n_steps']} step / "
      f"{s['n_epochs']} epoch)"
      + (f", steps {rng[0]}..{rng[1]}" if rng else ''))
    w()
    w('-- step time --')
    w(f"host dispatch: {_fmt(s['host_step_ms'], ' ms/step')}")
    d = s.get('step_time')
    if d:
        w(f"distribution ({d['n_steps']} steps): "
          f"p50 {_fmt(d['p50_ms'])}  p95 {_fmt(d['p95_ms'])}  "
          f"p99 {_fmt(d['p99_ms'])}  max {_fmt(d['max_ms'])} ms/iter  "
          f"(max/median {_fmt(d['max_over_median'], 'x')})")
        outliers = {f: v for f, v in d['stages'].items()
                    if v['outliers']}
        if outliers:
            w(f"outlier steps (> {_fmt(d['outlier_threshold_ms'])} ms "
              '= 2x median), by fired stage:')
            for f in sorted(outliers):
                v = outliers[f]
                w(f'  {f:<10} x{v["outliers"]:<5} '
                  f'mean {_fmt(v["outlier_mean_ms"], " ms")}  '
                  f'(stage mean over all its steps: '
                  f'{_fmt(v["mean_ms"], " ms")})')
        else:
            w('no outlier steps (> 2x median).')
    if s['stages']:
        w('stage                              mean ms    total ms  calls')
        for k in sorted(s['stages']):
            v = s['stages'][k]
            w(f"{k:<34} {v['mean_ms']:>8.3f} {v['total_ms']:>11.3f}"
              f"  {v['count']:>5}")
    else:
        w('(no host trace-table snapshots in the records — epoch '
          'records absent or no host phase was timed; see '
          'observability.tracing)')
    w()
    w('-- K-FAC health --')
    w(f"factor updates: {_fmt(s['factor_updates'])}   "
      f"inverse updates: {_fmt(s['inv_updates'])}   "
      f"chunk firings: {_fmt(s['inv_chunk_firings'])}")
    w(f"non-finite skips: {_fmt(s['nonfinite_skips'])}   "
      f"eigenvalues at clip floor: {_fmt(s['eig_clipped'])}")
    for name, series in (('loss', s['loss']),
                         ('damping', s['damping']),
                         ('kl-clip nu', s['nu']),
                         ('precond/grad norm ratio',
                          s['precond_ratio'])):
        if series:
            vals = [v for _, v in series if not math.isnan(v)]
            if vals:
                w(f'{name}: first {_fmt(series[0][1])}  '
                  f'last {_fmt(series[-1][1])}  '
                  f'min {_fmt(min(vals))}  max {_fmt(max(vals))}')
    if s['bucket_norms']:
        w()
        w('-- precondition buckets (last step, |v| per shape) --')
        for k in sorted(s['bucket_norms']):
            w(f'{k:<16} {_fmt(s["bucket_norms"][k])}')
    if s['event_counts']:
        w()
        w('-- resilience events --')
        for name in sorted(s['event_counts']):
            w(f'{name:<18} x{s["event_counts"][name]}')
        if s['save_latency_ms']:
            mean, worst = s['save_latency_ms']
            w(f'checkpoint save latency: mean {_fmt(mean, " ms")}  '
              f'max {_fmt(worst, " ms")}')
        for r in s['events']:
            if r['event'] in ('preemption', 'restore'):
                detail = ', '.join(f'{k}={v}' for k, v in
                                   sorted(r.get('data', {}).items()))
                w(f'  ! {r["event"]}: {detail}')
    w()
    if s['health_events']:
        w(f"-- {len(s['health_events'])} health event(s) --")
        for e in s['health_events']:
            w(f'  ! {e}')
    else:
        w('no health events.')


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog='python -m distributed_kfac_pytorch_tpu.observability'
             '.report',
        description='Summarize a recorded K-FAC metrics JSONL '
                    '(schema-validates; non-zero exit on invalid '
                    'files).')
    p.add_argument('jsonl', help='metrics file from --kfac-metrics '
                                 '(rotated segments are read too)')
    args = p.parse_args(argv)
    try:
        records = read_jsonl(args.jsonl)
    except (OSError, ValueError) as e:
        print(f'error: {e}', file=sys.stderr)
        return 1
    print_report(summarize(records))
    from distributed_kfac_pytorch_tpu.observability.sink import (
        incarnation_paths,
        read_incarnation,
    )
    prev = incarnation_paths(args.jsonl)
    if prev:
        print()
        print(f'-- {len(prev)} surviving prior incarnation(s) '
              '(newest first; each readable with this report CLI) --')
        for path in prev:
            try:
                n = len(read_incarnation(path))
                note = f'{n} records'
            except (OSError, ValueError) as e:
                note = f'unreadable: {e}'
            print(f'  {path}  ({note})')
    return 0


if __name__ == '__main__':
    sys.exit(main())
