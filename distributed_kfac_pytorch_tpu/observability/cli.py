"""Shared CLI wiring for the observability flags.

All three example entry points (CIFAR / ImageNet / LM) expose the same
observability surface; this module is its single implementation:

    add_observability_args(parser)       # --kfac-metrics / --metrics-
                                         # interval / --health-action /
                                         # --profile-dir / --memory-
                                         # interval / --straggler-shards
    sink = make_metrics_sink(args, info, meta={...})
    rank_sink = make_rank_shard_sink(args, info)     # r10 stragglers
    profile_epoch(args, info, epoch, start_epoch)   # context manager
"""

from __future__ import annotations

import contextlib
import os

from distributed_kfac_pytorch_tpu.observability import health as obs_health
from distributed_kfac_pytorch_tpu.observability import profiling
from distributed_kfac_pytorch_tpu.observability import sink as obs_sink


def add_observability_args(p) -> None:
    """Observability flags (r7; see README "Observability")."""
    p.add_argument('--kfac-metrics', nargs='?', const='auto',
                   default=None, metavar='PATH',
                   help='collect on-device K-FAC step metrics (damping, '
                        'KL-clip nu, grad/precond norms, firing counts, '
                        'non-finite events) into a schema-versioned '
                        'JSONL — default PATH <log-dir>/'
                        'kfac_metrics.jsonl, rank-0 only, no host '
                        'syncs added to the step. Summarize with: '
                        'python -m distributed_kfac_pytorch_tpu'
                        '.observability.report PATH')
    p.add_argument('--metrics-interval', type=int, default=10,
                   help='keep every Nth step record in the metrics '
                        'JSONL (epoch records always kept)')
    p.add_argument('--health-action', default=None,
                   choices=['warn', 'skip', 'raise'],
                   help='K-FAC health monitoring over the drained '
                        'metrics (non-finite events, factor staleness, '
                        'damping jumps). skip/raise also arm the '
                        'on-device non-finite factor-update guard — '
                        'which protects the FACTOR STATISTICS only; '
                        'for a whole-step skip of params/optimizer on '
                        'non-finite grads use --fp16 (dynamic loss '
                        'scaling, GradScaler parity). Requires '
                        '--kfac-metrics')
    p.add_argument('--profile-dir', default=None,
                   help='capture a jax.profiler trace of the first '
                        'trained epoch into this dir (kfac/* named '
                        'stage scopes attribute step time; rank 0 only)')
    p.add_argument('--memory-interval', type=int, default=100,
                   help='emit a memory-telemetry record (device HBM '
                        'watermarks + resident K-FAC state footprint '
                        'by group/dtype) every N steps into the '
                        'metrics JSONL; 0 disables. Host-side reads '
                        'only — the step program is untouched. '
                        'Requires --kfac-metrics')
    p.add_argument('--no-perf-anomalies', action='store_true',
                   help='disable the LIVE perf-anomaly monitors '
                        '(plain-step spike z-score, monotonic memory '
                        'growth) that --health-action otherwise arms '
                        'alongside the numerics checks. Use with '
                        '--health-action raise when a run must die on '
                        'NaNs but survive host jitter; the offline '
                        'gate still replays both checks from the '
                        'recorded stream')
    p.add_argument('--straggler-shards', action='store_true',
                   help='every host writes its own sink shard '
                        '(PATH.rank<r>) with per-step dispatch wall '
                        'time and pre-collective barrier-wait, for '
                        'mesh-wide straggler attribution '
                        '(observability.report merges the shards). '
                        'The barrier probe blocks the host on device '
                        'completion each step — costs async-dispatch '
                        'pipelining, so only enable when hunting '
                        'skew. Requires --kfac-metrics')
    p.add_argument('--straggler-sample-every', type=int, default=1,
                   metavar='N',
                   help='run the barrier-wait probe only every Nth '
                        'step (r14): amortizes the probe\'s host-sync '
                        'cost to 1/N so straggler attribution can '
                        'stay on in long runs. Every rank samples the '
                        'same steps (a pure function of the global '
                        'step), so the merged skew analysis still '
                        'lines up; non-sampled steps carry no wait '
                        'field. 1 = the r10 every-step probe. '
                        'Requires --straggler-shards')


def wants_guard(args) -> bool:
    """True when the on-device non-finite factor guard should be armed
    ('warn' observes only; 'skip'/'raise' protect the state)."""
    return getattr(args, 'health_action', None) in ('skip', 'raise')


def make_metrics_sink(args, info, meta: dict | None = None):
    """JSONL sink (+ optional health monitor) for a CLI, or None.

    Rank gating happens inside the sink (non-zero ranks get a no-op
    sink), so callers need no is_main branches. The monitor's
    factor-staleness threshold derives from the CLI's cov-update
    cadence (10x the expected interval — a schedule bug signature, not
    normal jitter); without that wiring the check would be dead from
    the CLIs (its constructor default is off).
    """
    if args.health_action and not args.kfac_metrics:
        raise SystemExit('--health-action requires --kfac-metrics '
                         '(the monitor consumes the drained metrics)')
    if getattr(args, 'straggler_shards', False) and not args.kfac_metrics:
        raise SystemExit('--straggler-shards requires --kfac-metrics '
                         '(shards live next to the metrics path)')
    if getattr(args, 'straggler_sample_every', 1) < 1:
        raise SystemExit('--straggler-sample-every must be >= 1')
    if (getattr(args, 'straggler_sample_every', 1) > 1
            and not getattr(args, 'straggler_shards', False)):
        raise SystemExit('--straggler-sample-every requires '
                         '--straggler-shards (it paces the barrier '
                         'probe those shards record)')
    if not args.kfac_metrics:
        return None
    path = metrics_path(args)
    monitor = None
    if args.health_action:
        cov_freq = max(1, int(getattr(args, 'kfac_cov_update_freq', 1)))
        # r10 online anomaly monitors: a plain step landing 8 sigmas
        # off the running mean, or the device watermark climbing
        # monotonically — the same signatures the gate checks offline,
        # surfaced live through the warn/skip/raise action. Opt out
        # with --no-perf-anomalies (e.g. raise-on-NaN CI on a noisy
        # shared host, where jitter must not abort the run).
        perf = not getattr(args, 'no_perf_anomalies', False)
        monitor = obs_health.HealthMonitor(
            action=args.health_action,
            stale_after_steps=10 * cov_freq,
            step_spike_zscore=8.0 if perf else None,
            memory_growth_windows=6 if perf else 0)
    return obs_sink.JsonlMetricsSink(
        path, interval=args.metrics_interval,
        process_index=info['process_index'], monitor=monitor,
        meta=meta)


def emit_layer_meta(sink, kfac) -> None:
    """Append the per-layer K-FAC registry provenance to the metrics
    stream (r13): the resolved weight-sharing approximation per layer
    (``KFAC.approx_summary`` — 'expand' / 'reduce' / '<approx>+tied')
    plus the global setting. Called by the CLIs AFTER registration
    (the sink is built before the model exists, so this rides as a
    second ``kind='meta'`` record). No-ops on None sinks, non-K-FAC
    runs, and duck-typed sinks without ``meta_record``.
    """
    if sink is None or kfac is None:
        return
    emit = getattr(sink, 'meta_record', None)
    if emit is None:
        return
    emit({'kfac_approx': kfac.approx_summary(),
          'kfac_approx_setting': (kfac.kfac_approx
                                  if isinstance(kfac.kfac_approx, str)
                                  else dict(kfac.kfac_approx)),
          'tied_embeddings': bool(kfac.tied_embeddings)})


def metrics_path(args) -> str:
    """The resolved --kfac-metrics path (single point of truth for the
    main stream, the rank shards, and any post-run report/gate call)."""
    return (os.path.join(args.log_dir, 'kfac_metrics.jsonl')
            if args.kfac_metrics == 'auto' else args.kfac_metrics)


def make_rank_shard_sink(args, info, meta: dict | None = None):
    """Per-rank straggler shard sink for a CLI (or None when off).

    Every process gets a WRITING sink at ``<metrics-path>.rank<r>``
    (the inverse of the main stream's rank-0 gate). The shard's meta
    carries ``launch.host_metadata()`` so the merged report can name
    the slow machine, not just its rank.
    """
    if not getattr(args, 'straggler_shards', False):
        return None
    from distributed_kfac_pytorch_tpu import launch
    from distributed_kfac_pytorch_tpu.observability import stragglers

    shard_meta = {**launch.host_metadata(), **(meta or {})}
    return stragglers.make_rank_shard_sink(
        metrics_path(args), info['process_index'], meta=shard_meta)


@contextlib.contextmanager
def profile_epoch(args, info, epoch: int, start_epoch: int):
    """Profile exactly the first trained epoch when --profile-dir is set.

    Compile time of the step variants lands inside this window too —
    that is deliberate (the profile then shows compile vs steady-state);
    steady-state-only captures can re-run with checkpoints resumed.
    """
    active = (args.profile_dir is not None and epoch == start_epoch
              and profiling.start_trace(
                  args.profile_dir,
                  process_index=info['process_index']))
    try:
        yield
    finally:
        if active:
            profiling.stop_trace()
