"""Host-side K-FAC health monitoring over drained metric records.

The on-device half lives in the preconditioner (the non-finite factor
guard: a NaN/Inf candidate factor update is *skipped* on device and
counted in ``metrics['nonfinite_skips']``, so the running factors are
never poisoned). This module is the host half: it watches the drained
JSONL records and turns anomalies into events with a configurable
``action``:

  - ``'warn'``  — ``warnings.warn`` once per event (default);
  - ``'skip'``  — record the event silently (the device guard already
    protected the state; useful for unattended sweeps);
  - ``'raise'`` — raise :class:`HealthError` (fail fast in CI or when a
    run's numerics must be pristine).

Checks (each one host-arithmetic over scalars — zero device work):

  - **non-finite events**: ``nonfinite_skips`` increments, or any
    non-finite ``loss`` / ``grad_norm`` / ``precond_norm``;
  - **factor staleness**: steps since ``factor_updates`` last
    incremented exceeds ``stale_after_steps``;
  - **damping trajectory**: the per-step damping jumps by more than
    ``damping_jump_factor`` between consecutive records (a scheduler
    bug signature), or goes non-positive/non-finite;
  - **eigenvalue floor**: ``eig_clipped`` (eigenvalues pinned at the
    0.0 clip floor) rises past ``eig_clip_limit`` — rising-edge
    detection, so a persistently floored (stable, damping-covered)
    spectrum fires once per new high, not once per record.
  - **step-time spike** (r10, ``step_spike_zscore``): a step's host
    dispatch time lands more than z sigmas above the running
    mean/stddev of the plain (non-firing) steps seen so far. Steps
    carrying a ``fired`` stage are excluded from both the statistics
    and the detection — factor/inverse firings are *expected* spikes
    with their own attribution in the report, and the engine labels a
    step whose wall time absorbed a variant trace+XLA-compile
    ``fired='compile'`` for the same reason (one absorbed 20 s
    compile sample would inflate the running stddev enough to blind
    the detector for the rest of the run). This check exists for
    the unexpected spikes (a data-loader stall, a host page-in, a
    sick chip). The stddev is floored at 1%% of the mean so
    near-constant step streams don't turn fp jitter into infinite z.
  - **memory growth** (r10, ``memory_growth_windows``): the
    ``kind='memory'`` records' ``bytes_in_use`` watermark rises over N
    consecutive samples by more than ``memory_growth_min_frac`` of the
    run's starting value — the leak signature (a healthy run's resident
    state is flat after warmup; a retrace leak or host-buffer
    accumulation is monotone). Fires once per sustained climb (latched
    until the watermark dips), not per sample.

The monitor runs at sink drain time (off the step path) — see
``JsonlMetricsSink(monitor=...)`` — or standalone over records from
``sink.read_jsonl`` (that is how ``observability.gate`` replays a
recorded stream through the same anomaly checks offline).
"""

from __future__ import annotations

import math
import warnings

from distributed_kfac_pytorch_tpu.observability.sink import (
    to_float as _num,  # shared coercion ('nan'/'inf' strings round-trip)
)

ACTIONS = ('warn', 'skip', 'raise')


class HealthError(RuntimeError):
    """Raised by a monitor with ``action='raise'`` on a health event."""


class HealthMonitor:
    """Stateful record-stream watcher (one instance per run)."""

    def __init__(self, action: str = 'warn', *,
                 stale_after_steps: int | None = None,
                 damping_jump_factor: float = 10.0,
                 eig_clip_limit: int = 0,
                 step_spike_zscore: float | None = None,
                 step_spike_warmup: int = 16,
                 memory_growth_windows: int = 0,
                 memory_growth_min_frac: float = 0.05):
        if action not in ACTIONS:
            raise ValueError(f'action must be one of {ACTIONS}, '
                             f'got {action!r}')
        if step_spike_zscore is not None and step_spike_zscore <= 0:
            raise ValueError(f'{step_spike_zscore=} must be positive')
        self.action = action
        self.stale_after_steps = stale_after_steps
        self.damping_jump_factor = damping_jump_factor
        self.eig_clip_limit = eig_clip_limit
        self.step_spike_zscore = step_spike_zscore
        self.step_spike_warmup = max(2, int(step_spike_warmup))
        self.memory_growth_windows = int(memory_growth_windows)
        self.memory_growth_min_frac = memory_growth_min_frac
        self.events: list[str] = []
        # Parallel per-event check kinds (same order as ``events``):
        # the machine-readable classification ``summary()`` counts by
        # (r16 satellite — the text messages alone forced consumers to
        # regex the category back out).
        self.event_kinds: list[str] = []
        self._last_factor_updates: float | None = None
        self._last_factor_step: int | None = None
        self._last_damping: float | None = None
        self._nonfinite_skips = 0.0
        self._max_eig_clipped = float(eig_clip_limit)
        # Welford accumulators over plain (unfired) steps' dispatch ms.
        self._ms_n = 0
        self._ms_mean = 0.0
        self._ms_m2 = 0.0
        # Memory-growth run state (consecutive-rise tracking).
        self._mem_prev: float | None = None
        self._mem_run_start: float | None = None
        self._mem_run_len = 0
        self._mem_latched = False

    # -- the checks ----------------------------------------------------

    def observe(self, rec: dict) -> list[str]:
        """Consume one record; returns (and acts on) new events."""
        if rec.get('kind') == 'memory':
            return self._record(self._observe_memory(rec))
        if rec.get('kind') != 'step':
            return []
        step = int(rec.get('step', 0))
        m = rec.get('metrics', {})
        events: list[tuple[str, str]] = []  # (kind, message)

        ms = rec.get('host_step_ms')
        if self.step_spike_zscore is not None and \
                isinstance(ms, (int, float)) and math.isfinite(ms) \
                and 'fired' not in rec:
            # Plain steps only: firing steps are expected outliers with
            # their own report attribution. Spike check BEFORE the
            # Welford update so the spike cannot vouch for itself.
            if self._ms_n >= self.step_spike_warmup:
                var = self._ms_m2 / (self._ms_n - 1)
                std = max(math.sqrt(max(var, 0.0)),
                          0.01 * self._ms_mean, 1e-9)
                z = (ms - self._ms_mean) / std
                if z > self.step_spike_zscore:
                    events.append((
                        'step_spike',
                        f'step {step}: step-time spike {ms:.3g} ms is '
                        f'{z:.1f} sigma above the plain-step mean '
                        f'{self._ms_mean:.3g} ms (threshold '
                        f'{self.step_spike_zscore:g}) — no K-FAC stage '
                        'fired this step; suspect host/data/chip'))
            self._ms_n += 1
            delta = ms - self._ms_mean
            self._ms_mean += delta / self._ms_n
            self._ms_m2 += delta * (ms - self._ms_mean)

        skips = _num(m.get('kfac/nonfinite_skips'))
        if not math.isnan(skips) and skips > self._nonfinite_skips:
            events.append((
                'nonfinite',
                f'step {step}: non-finite candidate factor update '
                f'(total {int(skips)}) — gradients/captures contained '
                "NaN/Inf (skipped on device when the guard is armed, "
                "i.e. --health-action skip/raise)"))
            self._nonfinite_skips = skips
        for key in ('loss', 'kfac/grad_norm', 'kfac/precond_norm'):
            if key in m and not math.isfinite(_num(m[key])):
                events.append(('nonfinite',
                               f'step {step}: non-finite {key} = '
                               f'{m[key]!r}'))

        fu = _num(m.get('kfac/factor_updates'))
        if not math.isnan(fu):
            if self._last_factor_updates is None or \
                    fu > self._last_factor_updates:
                self._last_factor_updates = fu
                self._last_factor_step = step
            elif (self.stale_after_steps is not None
                  and self._last_factor_step is not None
                  and step - self._last_factor_step
                  > self.stale_after_steps):
                events.append((
                    'factor_stale',
                    f'step {step}: factors stale — no factor update '
                    f'for {step - self._last_factor_step} steps '
                    f'(limit {self.stale_after_steps})'))

        damping = _num(m.get('kfac/damping'))
        if 'kfac/damping' in m:
            if not math.isfinite(damping) or damping <= 0.0:
                events.append(('damping',
                               f'step {step}: damping '
                               f'{m["kfac/damping"]!r}'
                               ' is not a positive finite value'))
            elif self._last_damping is not None and self._last_damping > 0:
                ratio = max(damping / self._last_damping,
                            self._last_damping / damping)
                if ratio > self.damping_jump_factor:
                    events.append((
                        'damping',
                        f'step {step}: damping jumped {ratio:.1f}x '
                        f'({self._last_damping:g} -> {damping:g})'))
            if math.isfinite(damping):
                self._last_damping = damping

        # Rising-edge only: the stored spectra persist between inverse
        # firings, so a rank-deficient factor would otherwise re-fire
        # on EVERY drained record (warn-storm under 'warn', instant
        # abort under 'raise' — floored-but-stable eigenvalues are
        # numerically harmless, the damping carries them).
        clipped = _num(m.get('kfac/eig_clipped'))
        if not math.isnan(clipped) and clipped > self._max_eig_clipped:
            events.append((
                'eig_floor',
                f'step {step}: {int(clipped)} eigenvalues at the 0.0 '
                f'clip floor (limit {self.eig_clip_limit}, previous '
                f'high {int(self._max_eig_clipped)}) — factors are '
                'rank-deficient or numerically indefinite'))
            self._max_eig_clipped = clipped

        return self._record(events)

    def _record(self, events: list[tuple[str, str]]) -> list[str]:
        msgs = [msg for _kind, msg in events]
        self.events.extend(msgs)
        self.event_kinds.extend(kind for kind, _msg in events)
        for e in msgs:
            self._act(e)
        return msgs

    def _observe_memory(self, rec: dict) -> list[str]:
        """Monotonic device-memory-growth detection (leak signature)."""
        if not self.memory_growth_windows:
            return []
        b = rec.get('device', {}).get('bytes_in_use')
        if not isinstance(b, (int, float)) or not math.isfinite(b):
            return []
        b = float(b)
        events: list[tuple[str, str]] = []
        if self._mem_prev is None or b <= self._mem_prev:
            # Flat or falling watermark: a healthy steady state. Reset
            # the run and re-arm the latch.
            self._mem_run_start = b
            self._mem_run_len = 0
            self._mem_latched = False
        else:
            self._mem_run_len += 1
            start = self._mem_run_start or b
            grown = (b - start) / start if start > 0 else 0.0
            if (not self._mem_latched
                    and self._mem_run_len >= self.memory_growth_windows
                    and grown > self.memory_growth_min_frac):
                events.append((
                    'memory_growth',
                    f"step {rec.get('step', '?')}: device memory grew "
                    f'monotonically over {self._mem_run_len} samples '
                    f'({start:.4g} -> {b:.4g} bytes_in_use, '
                    f'+{grown * 100:.1f}%) — leak signature (resident '
                    'K-FAC state should be flat after warmup)'))
                self._mem_latched = True
        self._mem_prev = b
        return events

    def _act(self, event: str) -> None:
        if self.action == 'raise':
            raise HealthError(event)
        if self.action == 'warn':
            # stacklevel: warn -> _act -> _record -> observe -> CALLER
            # (the r16 _record hop added one frame; keep the warning
            # attributed to whoever fed the record in).
            warnings.warn(f'KFAC health: {event}', RuntimeWarning,
                          stacklevel=4)

    def summary(self) -> dict:
        """Run-level health summary.

        ``by_kind`` (r16 satellite) counts events per CHECK KIND
        ('step_spike' / 'nonfinite' / 'factor_stale' / 'damping' /
        'eig_floor' / 'memory_growth') — before it, only the
        aggregate count and ``nonfinite_skips`` survived to the
        summary and every consumer had to regex the text messages.
        ``report --json`` carries it as ``health_event_counts``
        (key-set pinned by tests/test_obs_perf.py).
        """
        by_kind: dict[str, int] = {}
        for kind in self.event_kinds:
            by_kind[kind] = by_kind.get(kind, 0) + 1
        return {'events': len(self.events),
                'by_kind': by_kind,
                'nonfinite_skips': int(self._nonfinite_skips),
                'last_damping': self._last_damping}
