"""Host-side K-FAC health monitoring over drained metric records.

The on-device half lives in the preconditioner (the non-finite factor
guard: a NaN/Inf candidate factor update is *skipped* on device and
counted in ``metrics['nonfinite_skips']``, so the running factors are
never poisoned). This module is the host half: it watches the drained
JSONL records and turns anomalies into events with a configurable
``action``:

  - ``'warn'``  — ``warnings.warn`` once per event (default);
  - ``'skip'``  — record the event silently (the device guard already
    protected the state; useful for unattended sweeps);
  - ``'raise'`` — raise :class:`HealthError` (fail fast in CI or when a
    run's numerics must be pristine).

Checks (each one host-arithmetic over scalars — zero device work):

  - **non-finite events**: ``nonfinite_skips`` increments, or any
    non-finite ``loss`` / ``grad_norm`` / ``precond_norm``;
  - **factor staleness**: steps since ``factor_updates`` last
    incremented exceeds ``stale_after_steps``;
  - **damping trajectory**: the per-step damping jumps by more than
    ``damping_jump_factor`` between consecutive records (a scheduler
    bug signature), or goes non-positive/non-finite;
  - **eigenvalue floor**: ``eig_clipped`` (eigenvalues pinned at the
    0.0 clip floor) rises past ``eig_clip_limit`` — rising-edge
    detection, so a persistently floored (stable, damping-covered)
    spectrum fires once per new high, not once per record.

The monitor runs at sink drain time (off the step path) — see
``JsonlMetricsSink(monitor=...)`` — or standalone over records from
``sink.read_jsonl``.
"""

from __future__ import annotations

import math
import warnings

from distributed_kfac_pytorch_tpu.observability.sink import (
    to_float as _num,  # shared coercion ('nan'/'inf' strings round-trip)
)

ACTIONS = ('warn', 'skip', 'raise')


class HealthError(RuntimeError):
    """Raised by a monitor with ``action='raise'`` on a health event."""


class HealthMonitor:
    """Stateful record-stream watcher (one instance per run)."""

    def __init__(self, action: str = 'warn', *,
                 stale_after_steps: int | None = None,
                 damping_jump_factor: float = 10.0,
                 eig_clip_limit: int = 0):
        if action not in ACTIONS:
            raise ValueError(f'action must be one of {ACTIONS}, '
                             f'got {action!r}')
        self.action = action
        self.stale_after_steps = stale_after_steps
        self.damping_jump_factor = damping_jump_factor
        self.eig_clip_limit = eig_clip_limit
        self.events: list[str] = []
        self._last_factor_updates: float | None = None
        self._last_factor_step: int | None = None
        self._last_damping: float | None = None
        self._nonfinite_skips = 0.0
        self._max_eig_clipped = float(eig_clip_limit)

    # -- the checks ----------------------------------------------------

    def observe(self, rec: dict) -> list[str]:
        """Consume one record; returns (and acts on) new events."""
        if rec.get('kind') != 'step':
            return []
        step = int(rec.get('step', 0))
        m = rec.get('metrics', {})
        events: list[str] = []

        skips = _num(m.get('kfac/nonfinite_skips'))
        if not math.isnan(skips) and skips > self._nonfinite_skips:
            events.append(
                f'step {step}: non-finite candidate factor update '
                f'(total {int(skips)}) — gradients/captures contained '
                "NaN/Inf (skipped on device when the guard is armed, "
                "i.e. --health-action skip/raise)")
            self._nonfinite_skips = skips
        for key in ('loss', 'kfac/grad_norm', 'kfac/precond_norm'):
            if key in m and not math.isfinite(_num(m[key])):
                events.append(f'step {step}: non-finite {key} = '
                              f'{m[key]!r}')

        fu = _num(m.get('kfac/factor_updates'))
        if not math.isnan(fu):
            if self._last_factor_updates is None or \
                    fu > self._last_factor_updates:
                self._last_factor_updates = fu
                self._last_factor_step = step
            elif (self.stale_after_steps is not None
                  and self._last_factor_step is not None
                  and step - self._last_factor_step
                  > self.stale_after_steps):
                events.append(
                    f'step {step}: factors stale — no factor update '
                    f'for {step - self._last_factor_step} steps '
                    f'(limit {self.stale_after_steps})')

        damping = _num(m.get('kfac/damping'))
        if 'kfac/damping' in m:
            if not math.isfinite(damping) or damping <= 0.0:
                events.append(f'step {step}: damping {m["kfac/damping"]!r}'
                              ' is not a positive finite value')
            elif self._last_damping is not None and self._last_damping > 0:
                ratio = max(damping / self._last_damping,
                            self._last_damping / damping)
                if ratio > self.damping_jump_factor:
                    events.append(
                        f'step {step}: damping jumped {ratio:.1f}x '
                        f'({self._last_damping:g} -> {damping:g})')
            if math.isfinite(damping):
                self._last_damping = damping

        # Rising-edge only: the stored spectra persist between inverse
        # firings, so a rank-deficient factor would otherwise re-fire
        # on EVERY drained record (warn-storm under 'warn', instant
        # abort under 'raise' — floored-but-stable eigenvalues are
        # numerically harmless, the damping carries them).
        clipped = _num(m.get('kfac/eig_clipped'))
        if not math.isnan(clipped) and clipped > self._max_eig_clipped:
            events.append(
                f'step {step}: {int(clipped)} eigenvalues at the 0.0 '
                f'clip floor (limit {self.eig_clip_limit}, previous '
                f'high {int(self._max_eig_clipped)}) — factors are '
                'rank-deficient or numerically indefinite')
            self._max_eig_clipped = clipped

        self.events.extend(events)
        for e in events:
            self._act(e)
        return events

    def _act(self, event: str) -> None:
        if self.action == 'raise':
            raise HealthError(event)
        if self.action == 'warn':
            warnings.warn(f'KFAC health: {event}', RuntimeWarning,
                          stacklevel=3)

    def summary(self) -> dict:
        return {'events': len(self.events),
                'nonfinite_skips': int(self._nonfinite_skips),
                'last_damping': self._last_damping}
