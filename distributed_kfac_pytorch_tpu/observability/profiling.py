"""Profiler scopes for the K-FAC hot paths.

Two complementary mechanisms behind one ``annotate(name)`` context
manager:

  - ``jax.named_scope``: prefixes the HLO metadata of every op traced
    under it, so an XLA profile (``jax.profiler.start_trace`` /
    TensorBoard) attributes device time inside the ONE jitted train
    step to named K-FAC stages (``kfac/factors/...``,
    ``kfac/precond/...``, ``kfac/comm/...``). Pure metadata: the
    compiled program is numerically and structurally identical, so the
    scopes are always on — no knob, no bit-identity risk.
  - ``jax.profiler.TraceAnnotation``: a host-timeline range for the
    eager/dispatch side (visible in the profiler's python/host lanes).

Scope-name convention (what shows up in the profile):

  kfac/factors/<layer-kind>   covariance contraction per layer kind
  kfac/eigh/<method>          bucketed eigendecompositions
  kfac/inverse/<method>       bucketed damped inverses
  kfac/precond/<branch>       precondition_dispatch branches
  kfac/comm/<collective>      factor pmean / inverse all_gather /
                              gradient psum (COMM_OPT & KAISA paths)

``start_trace``/``stop_trace`` wrap ``jax.profiler`` with rank gating
and idempotence so the example CLIs can expose a bare ``--profile-dir``
flag (capture one epoch, rank 0 only).

Caveat (measured, PERF.md r7): after a profiler session, a small
per-dispatch overhead persists in the process even once the trace is
stopped — take steady-state timing numbers from a run WITHOUT
``--profile-dir``, and keep A/B rows all-profiled or all-unprofiled.
"""

from __future__ import annotations

import contextlib
import functools

import jax


def annotate(name: str):
    """Combined XLA named scope + host trace annotation for one stage.

    Usable around traced (in-jit) and eager code alike; cheap enough to
    leave on unconditionally (metadata only — never changes numerics or
    program structure).
    """
    stack = contextlib.ExitStack()
    stack.enter_context(jax.named_scope(name))
    try:
        stack.enter_context(jax.profiler.TraceAnnotation(name))
    except Exception:
        pass  # host annotation is best-effort (older jaxlibs)
    return stack


def scope(name: str):
    """Decorator form of :func:`annotate` (wraps the whole function).

    Used on the hot-path stage functions (factor contractions,
    precondition branches, SPMD pipeline stages) so their ops carry the
    stage name into XLA profiles without reindenting the bodies.
    """
    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with annotate(name):
                return fn(*args, **kwargs)
        return wrapper
    return decorator


_ACTIVE_TRACE_DIR: str | None = None


def start_trace(log_dir: str, *, process_index: int | None = None) -> bool:
    """Start an XLA profiler trace into ``log_dir`` (rank-0 gated).

    Returns True when a trace actually started. Idempotent: a second
    call while a trace is active is a no-op (the CLIs call this at the
    top of the profiled epoch without tracking state themselves).
    """
    global _ACTIVE_TRACE_DIR
    if _ACTIVE_TRACE_DIR is not None:
        return False
    if process_index is None:
        process_index = jax.process_index()
    if process_index != 0:
        return False
    jax.profiler.start_trace(log_dir)
    _ACTIVE_TRACE_DIR = log_dir
    return True


def stop_trace() -> str | None:
    """Stop the active profiler trace; returns its dir (None if none).

    Blocks on outstanding device work first (a fresh computation is
    enqueued behind everything already dispatched on the default
    device's in-order stream, plus an effects barrier) so the captured
    window contains the complete steps dispatched inside it — without
    this, async dispatch truncates the tail steps from the capture.
    """
    global _ACTIVE_TRACE_DIR
    if _ACTIVE_TRACE_DIR is None:
        return None
    out = _ACTIVE_TRACE_DIR
    try:
        import jax.numpy as jnp
        jax.block_until_ready(jnp.zeros(()) + 0)
        jax.effects_barrier()
    except Exception:
        pass  # best-effort: never lose the capture over the barrier
    jax.profiler.stop_trace()
    _ACTIVE_TRACE_DIR = None
    return out
