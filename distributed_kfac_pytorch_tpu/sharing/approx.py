"""Weight-sharing Kronecker approximation policy (KFAC-expand/reduce).

*K-FAC for Modern Neural Network Architectures* (arXiv:2311.00636)
formalizes the choice a weight-shared layer (one whose weight sees
every sequence position / image patch of each example) forces on any
Kronecker factorization:

  - **expand** — treat every shared-axis position as an independent
    covariance row: flatten ``(B, T, d)`` to ``B*T`` rows. This is this
    repo's historical ``collapse_batch_dims`` behavior and the
    exact-parity default (all-expand is bit-identical to the
    pre-sharing code path, test-pinned).
  - **reduce** — reduce over the shared axis BEFORE the covariance:
    activations are *averaged* and output-grads *summed* over T, so the
    factor contraction sees ``B`` rows. The mean/sum split is the
    paper's Eq. 22 convention — with mean-reduced activations the
    appended bias column stays exactly 1, and the summed grads keep the
    bias gradient ``sum_t g_t`` exact. A factor ``T`` cheaper per
    factor update, and exact whenever activations are constant across
    the shared axis (pinned against a dense-Fisher oracle in
    tests/test_sharing.py).

This module is pure host-side policy: which registered layer gets which
approximation. The resolved choice is carried in the capture registry
(``capture.LayerSpec.kfac_approx``) so the factor math
(``layers.compute_a_factor`` / ``compute_g_factor``) dispatches on the
spec alone — static program structure, zero retraces, and the
single-chip and SPMD paths cannot drift (both read the same specs).

Setting grammar (``KFAC(kfac_approx=...)``):

  - ``'expand'`` (default): every layer expand — bit-identical.
  - ``'reduce'``: the automatic by-module-kind policy — reduce for
    sequence/patch-shared Denses (attention q/k/v/o, MLP in/out — any
    Dense registered with a >2-D input) and for patch-embedding convs
    (stride == kernel, zero padding: the ViT signature, the paper's ViT
    treatment); expand everywhere else (embeddings, grouped convs,
    overlapping convs, 2-D-input Denses — where reduce is either
    undefined or degenerate).
  - ``{pattern: 'expand' | 'reduce'}``: explicit per-layer control.
    A pattern matches a layer when it equals the layer name or is a
    substring of it (the ``skip_layers`` matching idiom); unmatched
    layers stay expand. A pattern that matches nothing, or forces
    reduce onto a kind without a reduce path, raises at init — silence
    here would hide a mis-preconditioned model.
"""

from __future__ import annotations

import dataclasses

from distributed_kfac_pytorch_tpu.capture import (
    CONV2D,
    KFAC_APPROXES,
    KFAC_EXPAND,
    KFAC_REDUCE,
    LINEAR,
    LayerSpec,
)


def is_patch_conv(spec: LayerSpec) -> bool:
    """True for a non-overlapping patch-embedding conv (stride ==
    kernel, zero padding — the ViT ``patch_embed`` signature).

    Only this conv family gets the automatic reduce treatment: its
    "shared axis" is the clean set of disjoint patches the paper's ViT
    experiments reduce over. Overlapping convs keep the reference
    conv2d factor convention (their spatial sharing is already folded
    into that math's normalization).
    """
    if spec.kind != CONV2D or spec.kernel_size is None:
        return False
    if tuple(spec.strides or ()) != tuple(spec.kernel_size):
        return False
    pad = spec.padding
    if pad == 'VALID':
        return True
    if isinstance(pad, str):
        return False
    try:
        return all(int(lo) == 0 and int(hi) == 0 for lo, hi in pad)
    except (TypeError, ValueError):
        return False


def layer_is_shared(spec: LayerSpec) -> bool:
    """Does this layer's weight see multiple shared-axis positions?

    The automatic policy's eligibility test: a Dense registered with a
    sequence/patch axis (>2-D input at registration), or a
    patch-embedding conv. Reduce degenerates to expand at T=1, so
    non-shared layers simply have nothing to gain.
    """
    if spec.kind == LINEAR:
        return spec.shared_positions > 1
    return is_patch_conv(spec)


def _supports_reduce(spec: LayerSpec) -> bool:
    """Kinds with an implemented reduce path (dense + patch conv)."""
    return spec.kind == LINEAR or is_patch_conv(spec)


def resolve_approx(setting, specs: dict[str, LayerSpec]
                   ) -> dict[str, str]:
    """Per-layer approximation map for a registered spec dict.

    ``setting`` follows the module-docstring grammar. Deterministic
    (registration order), host-side, and validated loudly: every trace
    — and the single-chip vs SPMD paths — sees the identical map.
    """
    if setting is None:
        setting = KFAC_EXPAND
    if isinstance(setting, str):
        if setting not in KFAC_APPROXES:
            raise ValueError(
                f'kfac_approx={setting!r}: expected one of '
                f'{KFAC_APPROXES} or a {{pattern: approx}} dict')
        if setting == KFAC_EXPAND:
            return {name: KFAC_EXPAND for name in specs}
        # 'reduce': the automatic by-module-kind policy.
        return {name: (KFAC_REDUCE if layer_is_shared(spec)
                       else KFAC_EXPAND)
                for name, spec in specs.items()}
    if not isinstance(setting, dict):
        raise ValueError(
            f'kfac_approx must be a string or dict, got '
            f'{type(setting).__name__}')
    out = {name: KFAC_EXPAND for name in specs}
    for pattern, approx in setting.items():
        if approx not in KFAC_APPROXES:
            raise ValueError(
                f'kfac_approx[{pattern!r}]={approx!r}: expected one of '
                f'{KFAC_APPROXES}')
        matched = [name for name in specs
                   if pattern == name or pattern in name]
        if not matched:
            raise ValueError(
                f'kfac_approx pattern {pattern!r} matches no registered '
                f'layer (have {sorted(specs)})')
        for name in matched:
            if approx == KFAC_REDUCE and not _supports_reduce(
                    specs[name]):
                raise ValueError(
                    f'kfac_approx[{pattern!r}]=reduce: layer {name!r} '
                    f'(kind {specs[name].kind!r}) has no reduce path — '
                    'reduce is defined for Dense layers and '
                    'non-overlapping patch-embedding convs')
            out[name] = approx
    return out


def annotate_specs(specs: dict[str, LayerSpec], setting
                   ) -> dict[str, LayerSpec]:
    """Rebuild a spec dict with each layer's resolved ``kfac_approx``.

    The one mutation point of the registry: after this, every consumer
    (factor math, observability meta, repr) reads the spec field.
    """
    resolved = resolve_approx(setting, specs)
    return {name: (spec if spec.kfac_approx == resolved[name]
                   else dataclasses.replace(
                       spec, kfac_approx=resolved[name]))
            for name, spec in specs.items()}


def approx_summary(specs: dict[str, LayerSpec]) -> dict[str, str]:
    """{layer name: approx} for the metrics meta / run provenance.

    Tied-embedding registrations are labeled ``expand+tied`` so the
    recorded meta distinguishes a lookup-only embedding from the
    in/out-tied pair sharing one factor pair.
    """
    out = {}
    for name, spec in specs.items():
        label = spec.kfac_approx
        if spec.tied_calls:
            label += '+tied'
        out[name] = label
    return out
