"""KFAC-expand/reduce weight-sharing approximations (arXiv:2311.00636).

Policy layer for transformer/ViT preconditioning: which registered
layer treats its sequence/patch axis as extra batch (expand, the
exact-parity default) vs reducing over it before the covariance
(reduce, a factor-T cheaper statistic). See ``sharing.approx``.
"""

from distributed_kfac_pytorch_tpu.sharing.approx import (
    annotate_specs,
    approx_summary,
    is_patch_conv,
    layer_is_shared,
    resolve_approx,
)

__all__ = [
    'annotate_specs',
    'approx_summary',
    'is_patch_conv',
    'layer_is_shared',
    'resolve_approx',
]
