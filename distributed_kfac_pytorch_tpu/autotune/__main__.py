import sys

from distributed_kfac_pytorch_tpu.autotune.driver import main

if __name__ == '__main__':
    sys.exit(main())
