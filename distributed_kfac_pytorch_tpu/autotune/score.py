"""Candidate ranking over the r10 gate metric vector.

Hard constraints first (a violated one disqualifies the candidate
regardless of its timings):

  - probe-level disqualification (invalid construction, retraces —
    :mod:`autotune.probe` marks these);
  - any non-finite-guard trip during the probe (a config that produces
    non-finite factor statistics must never be auto-committed);
  - empty probe (no scored steps);
  - ``peak_hbm_bytes`` above an optional ceiling (the KAISA memory
    axis — a config that fits is a precondition, not a tradeoff).

Then the objective, over the surviving candidates (lower is better):

  - ``weighted`` (default): ``0.7*p50 + 0.2*p95 + 0.1*p99`` ms — a
    composed step-time proxy. p50 is throughput (most steps are plain
    steps); p95/p99 weight the firing-spike tail the r9 pipelined
    firing exists to flatten, so a candidate that buys median speed by
    concentrating spikes is penalized in proportion to how rarely the
    spikes land.
  - ``lexicographic``: p50 quantized to a 2% grain, then p99, then
    max/median. "Fastest typical step wins; ties (within timing
    noise) break toward the flattest tail" — the same priority order
    as the PERF.md r10 gate tolerances (10% / 25% / 25%).

Both objectives are pure functions of the metric vector, so a
committed artifact's candidate table can be re-ranked offline without
re-probing.
"""

from __future__ import annotations

import math

OBJECTIVES = ('weighted', 'lexicographic')
WEIGHTS = {'step_p50_ms': 0.7, 'step_p95_ms': 0.2, 'step_p99_ms': 0.1}
#: lexicographic p50 grain: two candidates within this relative band
#: tie on the primary key (probe timing noise floor).
LEXI_P50_GRAIN = 0.02


def hard_violation(result, *, hbm_ceiling: float | None = None
                   ) -> str | None:
    """The first hard constraint ``result`` (a ProbeResult or its
    ``to_row()`` dict) violates, or None."""
    row = result if isinstance(result, dict) else result.to_row()
    if row.get('disqualified'):
        return row['disqualified']
    if row.get('retraces'):
        return 'retraces: a static-cadence variant recompiled mid-probe'
    skips = row.get('nonfinite_skips') or 0.0
    if skips and skips > 0:
        return f'nonfinite_guard tripped {skips:g} time(s)'
    metrics = row.get('metrics') or {}
    if not metrics.get('n_steps'):
        return 'empty probe (no scored steps)'
    if metrics.get('step_p50_ms') is None:
        return 'no step-time samples in the probe stream'
    if hbm_ceiling is not None:
        peak = metrics.get('peak_hbm_bytes')
        if peak is not None and peak > hbm_ceiling:
            return (f'peak HBM {peak:g} B above ceiling '
                    f'{hbm_ceiling:g} B')
    return None


def objective_value(metrics: dict, objective: str = 'weighted'):
    """Reduce a gate metric vector to a comparable score.

    ``weighted`` returns a float; ``lexicographic`` returns a tuple
    (JSON-serialized as a list in artifacts). Both compare with ``<``.
    """
    if objective == 'weighted':
        return sum(w * float(metrics[k]) for k, w in WEIGHTS.items())
    if objective == 'lexicographic':
        p50 = float(metrics['step_p50_ms'])
        grain = max(p50 * LEXI_P50_GRAIN, 1e-9)
        spike = metrics.get('max_over_median')
        return (round(p50 / grain),
                round(float(metrics['step_p99_ms']), 6),
                round(float(spike), 6) if spike is not None
                else float('inf'))
    raise ValueError(f'unknown objective {objective!r} '
                     f'(one of {OBJECTIVES})')


def rank_candidates(results, *, objective: str = 'weighted',
                    hbm_ceiling: float | None = None) -> list[dict]:
    """Score + rank probe results; best first, disqualified last.

    Returns rows (``ProbeResult.to_row()`` shape) extended with
    ``score`` (None when disqualified) and ``disqualified`` set to the
    violated hard constraint. Ties keep probe order (stable sort), so
    the earlier-enumerated — more default-like — candidate wins.
    """
    rows = []
    for r in results:
        row = r if isinstance(r, dict) else r.to_row()
        row = dict(row)
        reason = hard_violation(row, hbm_ceiling=hbm_ceiling)
        if reason is not None:
            row['disqualified'] = reason
            row['score'] = None
        else:
            row['score'] = objective_value(row['metrics'], objective)
        rows.append(row)

    def key(row):
        if row['score'] is None:
            return (1, ())
        s = row['score']
        return (0, tuple(s) if isinstance(s, (tuple, list)) else (s,))

    return sorted(rows, key=key)


def scores_close(a, b, rel_tol: float) -> bool:
    """Are two objective values within ``rel_tol`` of each other?

    The driver's self-check: the best candidate re-probed must re-score
    within tolerance, or the probe was measuring noise. Lexicographic
    tuples compare on their p50 grain (first element).
    """
    av = a[0] if isinstance(a, (tuple, list)) else a
    bv = b[0] if isinstance(b, (tuple, list)) else b
    av, bv = float(av), float(bv)
    if not (math.isfinite(av) and math.isfinite(bv)):
        return False
    denom = max(abs(av), abs(bv), 1e-12)
    return abs(av - bv) / denom <= rel_tol
