"""Autotune driver: probe -> score -> commit a tuned-config artifact.

    python -m distributed_kfac_pytorch_tpu.autotune \\
        --workload flagship_lm --out TUNED_flagship_lm.json

Enumerates the knob space (:mod:`autotune.space`), probes each
candidate through short warm segments (:mod:`autotune.probe`), ranks
them on the r10 gate metrics (:mod:`autotune.score`), re-probes the
winner as a reproducibility self-check, and writes the committed
per-workload artifact ``TUNED_<workload>.json``:

  {"format": "kfac-autotune-v1", "workload": ..., "platform": "cpu",
   "topology": {topo_* ints}, "sink_schema": 4,
   "best": {knob: value}, "best_score": ..., "objective": ...,
   "candidates": [{knobs, metrics, score, disqualified}, ...],
   "self_check": {...}, "probe": {...}, "created_unix": ...}

The best candidate's recorded probe stream lands next to the artifact
as ``<out>.probe.jsonl`` — the evidence the committed numbers came
from, exactly like ``BASELINE_OBS.json.source.jsonl`` (r10).

Loading is **fail-closed** (:func:`load_tuned_config`): an unreadable
/ torn / wrong-format artifact, a platform mismatch, a topology
(world-size) mismatch, or a knob outside ``TUNABLE_FIELDS`` all fall
back to defaults and queue exactly one ``autotune_fallback`` event for
the metrics stream; a clean load queues one ``autotune_apply`` event.
The example CLIs consume this via ``--tuned-config``
(:mod:`autotune.cli`).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

ARTIFACT_FORMAT = 'kfac-autotune-v1'


# ---------------------------------------------------------------------------
# Artifact IO + fail-closed loading
# ---------------------------------------------------------------------------

def tuned_path(workload: str) -> str:
    return f'TUNED_{workload}.json'


def write_tuned(path: str, obj: dict) -> dict:
    obj = {'format': ARTIFACT_FORMAT, **obj}
    with open(path, 'w') as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.write('\n')
    return obj


def read_tuned(path: str) -> dict:
    """Strict artifact read (the replay/bench consumer); raises on any
    problem — fail-closed consumers use :func:`load_tuned_config`."""
    with open(path) as f:
        obj = json.load(f)
    if obj.get('format') != ARTIFACT_FORMAT:
        raise ValueError(f'{path}: not a {ARTIFACT_FORMAT} file '
                         f'(format={obj.get("format")!r})')
    if not isinstance(obj.get('best'), dict):
        raise ValueError(f'{path}: artifact has no best-knobs object')
    return obj


def live_world() -> dict:
    """The world-size slice of the live topology, for artifact
    validation before any mesh exists (the CLIs load tuned configs
    before mesh construction — the full KAISA grid may itself depend
    on flags the artifact tunes)."""
    import jax
    return {'devices': int(jax.device_count()),
            'processes': int(jax.process_count())}


def load_tuned_config(path: str, *, platform: str | None = None,
                      world: dict | None = None
                      ) -> tuple[dict | None, list[dict]]:
    """Fail-closed artifact load: ``(knobs | None, events)``.

    ``platform`` is the live ``jax.default_backend()``; ``world`` is
    :func:`live_world` (or a checkpoint ``TopologySpec``'s
    process/device counts). Validation compares the artifact's
    recorded platform and ``topo_devices``/``topo_processes``/
    ``topo_seq`` world scalars — the tuning evidence only transfers
    within the world it was measured on. The KAISA grid scalars
    (``topo_rows``/``topo_cols``) are provenance, not preconditions:
    the artifact may legitimately be applied under different
    mesh-shaping flags, which the tuned knob set cannot touch
    (``TUNABLE_FIELDS``).

    Every outcome queues exactly one event dict (``autotune_fallback``
    with a ``reason``, or ``autotune_apply``); flush them into a
    metrics sink with :func:`emit_events` once one exists.
    """
    from distributed_kfac_pytorch_tpu.training.optimizers import (
        TUNABLE_FIELDS,
    )

    def fallback(reason: str, **data) -> tuple[None, list[dict]]:
        return None, [{'event': 'autotune_fallback', 'path': str(path),
                       'reason': reason, **data}]

    try:
        obj = read_tuned(path)
    except FileNotFoundError:
        return fallback('missing')
    except (OSError, ValueError, json.JSONDecodeError) as e:
        return fallback('unreadable', error=str(e)[:200])

    if platform is not None:
        recorded = obj.get('platform')
        if recorded != platform:
            return fallback('platform_mismatch',
                            artifact=str(recorded), live=str(platform))
    topo = obj.get('topology') or {}
    if world is not None:
        for live_key, topo_key, default in (
                ('devices', 'topo_devices', None),
                ('processes', 'topo_processes', None),
                ('seq', 'topo_seq', 1)):
            if live_key not in world:
                continue
            recorded = topo.get(topo_key, default)
            if recorded is None or int(recorded) != int(world[live_key]):
                return fallback('topology_mismatch', key=topo_key,
                                artifact=-1 if recorded is None
                                else int(recorded),
                                live=int(world[live_key]))
    knobs = dict(obj['best'])
    unknown = sorted(set(knobs) - set(TUNABLE_FIELDS))
    if unknown:
        return fallback('unknown_knobs', knobs=','.join(unknown))
    return knobs, [{'event': 'autotune_apply', 'path': str(path),
                    'workload': str(obj.get('workload')),
                    'knobs': json.dumps(knobs, sort_keys=True)}]


def apply_tuned(cfg, knobs: dict) -> tuple:
    """Overlay tuned knobs on an ``OptimConfig``: ``(new_cfg, error)``.

    ``error`` is non-None when the MERGED config violates a validity
    constraint (e.g. the artifact tuned ``inv_pipeline_chunks`` against
    a different ``--kfac-update-freq`` than the CLI now runs) — the
    caller falls back to the un-tuned config, fail-closed.
    """
    from distributed_kfac_pytorch_tpu.autotune import space as space_mod
    from distributed_kfac_pytorch_tpu.training.optimizers import (
        TUNABLE_FIELDS,
    )
    unknown = sorted(set(knobs) - set(TUNABLE_FIELDS))
    if unknown:
        return cfg, f'unknown knob(s) {unknown}'
    new_cfg = dataclasses.replace(cfg, **knobs)
    merged = dataclasses.asdict(new_cfg)
    violated = [c.doc for c in space_mod.BASE_CONSTRAINTS
                if not c.ok(merged)]
    if violated:
        return cfg, '; '.join(violated)
    return new_cfg, None


def emit_events(sink, events: list[dict]) -> None:
    """Flush queued autotune events into a metrics sink (None ok)."""
    if sink is None:
        return
    emit = getattr(sink, 'event_record', None)
    if emit is None:
        return
    for ev in events:
        emit(ev['event'], **{k: v for k, v in ev.items()
                             if k != 'event'})


def kfac_overrides(knobs: dict) -> tuple[dict, int | None, list[str]]:
    """Map tuned OptimConfig knobs to raw ``KFAC(...)`` kwargs.

    For consumers that build a bare ``KFAC`` instead of going through
    ``get_optimizer`` (``benchmarks/step_breakdown.py``'s
    ``tuned_vs_default`` row). Returns ``(kwargs, inv_update_freq,
    ignored)`` — ``ignored`` lists knobs the consumer's harness cannot
    express (e.g. a scan-based bench fires monolithically, so
    ``inv_pipeline_chunks`` is surfaced rather than silently dropped).
    """
    import jax.numpy as jnp
    kwargs: dict = {}
    inv_freq = None
    ignored: list[str] = []
    for name, value in knobs.items():
        if name == 'bf16_precond':
            if value:
                kwargs['precond_compute_dtype'] = jnp.bfloat16
        elif name == 'bf16_factors':
            if value:
                kwargs['factor_dtype'] = jnp.bfloat16
                kwargs['factor_compute_dtype'] = jnp.bfloat16
        elif name == 'bf16_inverses':
            if value:
                kwargs['inv_dtype'] = jnp.bfloat16
        elif name == 'factor_batch_fraction':
            kwargs['factor_batch_fraction'] = float(value)
        elif name == 'eigh_polish_iters':
            kwargs['eigh_polish_iters'] = int(value)
        elif name == 'kfac_approx':
            kwargs['kfac_approx'] = str(value)
        elif name == 'inv_lowrank_rank':
            kwargs['inv_lowrank_rank'] = int(value)
        elif name == 'inv_lowrank_dim_threshold':
            kwargs['inv_lowrank_dim_threshold'] = int(value)
        elif name in ('fused_factor_contraction', 'fused_precondition'):
            # Trace-time kernel dispatch (r21): plain ctor kwargs, no
            # engine schedule involved — a bare-KFAC harness expresses
            # them directly.
            if value:
                kwargs[name] = True
        elif name == 'kfac_inv_update_freq':
            inv_freq = int(value)
        elif name in ('deferred_factor_reduction', 'inv_staleness',
                      'hierarchical_reduce'):
            # Engine-scheduled knobs (window-boundary reduce /
            # frozen-snapshot chunk phases / the r20 two-level reduce,
            # which additionally needs a multi-slice mesh): a bare-KFAC
            # scan harness fires monolithically with no factor_reduce/
            # factor_snapshot schedule, so constructing with them on
            # would leave the accumulator un-reduced forever. Surfaced
            # as ignored, never silently dropped.
            if value:
                ignored.append(name)
        else:
            ignored.append(name)
    return kwargs, inv_freq, sorted(ignored)


# ---------------------------------------------------------------------------
# The tuning run
# ---------------------------------------------------------------------------

def tune(workload_name: str, *, out: str | None = None,
         steps: int = 8, warmup_windows: int = 2,
         inv_update_freq: int = 4, cov_update_freq: int = 1,
         objective: str = 'weighted', hbm_ceiling: float | None = None,
         max_candidates: int | None = None, pruner: str = 'auto',
         space_overrides: dict | None = None, seed: int = 0,
         self_check: bool = True, self_check_tol: float = 0.75,
         mesh=None, log=print) -> dict:
    """Run the probe -> score -> commit loop; returns the artifact."""
    import jax

    from distributed_kfac_pytorch_tpu import elastic as elastic_lib
    from distributed_kfac_pytorch_tpu.autotune import probe as probe_mod
    from distributed_kfac_pytorch_tpu.autotune import score as score_mod
    from distributed_kfac_pytorch_tpu.autotune import space as space_mod
    from distributed_kfac_pytorch_tpu.observability.sink import (
        SCHEMA_VERSION,
    )
    from distributed_kfac_pytorch_tpu.parallel import distributed as D
    from distributed_kfac_pytorch_tpu.training import optimizers

    workload = probe_mod.get_workload(workload_name)
    out = out or tuned_path(workload_name)
    base_cfg = optimizers.OptimConfig(
        kfac_inv_update_freq=int(inv_update_freq),
        kfac_cov_update_freq=int(cov_update_freq))
    base = {f: getattr(base_cfg, f)
            for f in optimizers.TUNABLE_FIELDS}
    if (not workload.weight_shared
            and 'kfac_approx' not in (space_overrides or {})):
        # No weight-shared layers -> 'reduce' resolves to the identical
        # program as 'expand' (sharing.approx auto-policy): probing
        # both would double the table for zero information. An explicit
        # override still wins.
        space_overrides = {**(space_overrides or {}),
                           'kfac_approx': ['expand']}
        log(f'autotune[{workload_name}]: kfac_approx knob dropped '
            '(workload has no weight-shared layers; reduce == expand)')
    if (workload.max_factor_dim
            and workload.max_factor_dim
            < base_cfg.inv_lowrank_dim_threshold
            and 'inv_lowrank_rank' not in (space_overrides or {})):
        # No factor dim can reach the engagement threshold -> every
        # rank value compiles the identical exact-dispatch program;
        # probing them would pad the table with duplicates. An
        # explicit override (e.g. probing a lowered threshold) wins.
        space_overrides = {**(space_overrides or {}),
                           'inv_lowrank_rank': [0]}
        log(f'autotune[{workload_name}]: inv_lowrank_rank knob '
            f'dropped (max factor dim {workload.max_factor_dim} < '
            f'threshold {base_cfg.inv_lowrank_dim_threshold}; the '
            'low-rank path cannot engage)')
    space = space_mod.default_space(space_overrides)

    if mesh is None:
        mesh = D.make_kfac_mesh(
            comm_method=optimizers.COMM_METHODS[
                base_cfg.comm_method.lower()],
            grad_worker_fraction=base_cfg.grad_worker_fraction)
    topo = elastic_lib.TopologySpec.of_mesh(mesh)

    candidates = space.enumerate(base)
    dropped = 0
    if max_candidates is not None and len(candidates) > max_candidates:
        dropped = len(candidates) - max_candidates
        candidates = candidates[:max_candidates]
    log(f'autotune[{workload_name}]: {len(candidates)} candidate(s)'
        + (f' ({dropped} dropped by --max-candidates)' if dropped
           else '') + f', probe {steps} step(s) @ '
        f'f{cov_update_freq}/i{inv_update_freq}, '
        f'objective={objective}')

    def run_probe(knobs: dict, n_steps: int) -> probe_mod.ProbeResult:
        return probe_mod.probe_candidate(
            workload, base_cfg, knobs, steps=n_steps,
            warmup_windows=warmup_windows, mesh=mesh, seed=seed)

    # Probe scores are only comparable at EQUAL probe length (a probe
    # always starts on a firing step, so the firing-spike fraction in
    # the percentiles scales with 1/steps): the committed winner must
    # be picked among full-length probes only. Pruners therefore
    # nominate a winner themselves (their short-rung scores order
    # candidates within a rung, never across rungs), every nominee is
    # guaranteed a full-length probe, and the final ranking below runs
    # over the full-length rows alone. Shorter-rung rows stay in the
    # artifact's candidate table as provenance (their metrics carry
    # n_steps, so the table is self-describing).
    results: list[probe_mod.ProbeResult] = []

    def pruner_eval(knobs, n_steps):
        r = run_probe(knobs, n_steps)
        results.append(r)
        reason = score_mod.hard_violation(r, hbm_ceiling=hbm_ceiling)
        if reason is not None:
            return None
        return score_mod.objective_value(r.metrics, objective)

    if pruner == 'auto':
        pruner = 'full' if len(candidates) <= 8 else 'halving'
    if pruner == 'full':
        for knobs in candidates:
            r = run_probe(knobs, steps)
            results.append(r)
            log(f'  probe {json.dumps(knobs, sort_keys=True)}: '
                + (f'DISQUALIFIED ({r.disqualified})'
                   if r.disqualified else
                   f"p50 {r.metrics.get('step_p50_ms'):.3g} ms"))
    elif pruner == 'halving':
        winner, _ = space_mod.successive_halving(
            candidates, pruner_eval, min_steps=max(2, steps // 4),
            max_steps=steps)
        if winner is not None and not any(
                r.knobs == winner
                and r.metrics.get('n_steps', 0) >= steps
                for r in results):
            # The last rung may have raced below the full budget.
            results.append(run_probe(winner, steps))
    elif pruner == 'coordinate':
        winner, _ = space_mod.coordinate_descent(
            space, base, lambda knobs: pruner_eval(knobs, steps))
    else:
        raise ValueError(f'unknown pruner {pruner!r}')

    full_length = [r for r in results
                   if r.disqualified is not None
                   or r.metrics.get('n_steps', 0) >= steps]
    ranked = score_mod.rank_candidates(full_length or results,
                                       objective=objective,
                                       hbm_ceiling=hbm_ceiling)
    best = next((r for r in ranked if r['disqualified'] is None), None)
    if best is None:
        all_rows = score_mod.rank_candidates(
            results, objective=objective, hbm_ceiling=hbm_ceiling)
        raise SystemExit(
            f'autotune[{workload_name}]: every candidate was '
            'disqualified — nothing to commit. Reasons: '
            + '; '.join(sorted({r['disqualified'] for r in all_rows
                                if r['disqualified']})))
    table = score_mod.rank_candidates(results, objective=objective,
                                      hbm_ceiling=hbm_ceiling)

    # Reproducibility self-check: re-probe the winner (fresh build,
    # same seed) and keep its recorded stream as the artifact evidence.
    check: dict = {'enabled': bool(self_check)}
    stream_path = out + '.probe.jsonl'
    rescore = probe_mod.probe_candidate(
        workload, base_cfg, best['knobs'], steps=steps,
        warmup_windows=warmup_windows, mesh=mesh, seed=seed,
        keep_stream=stream_path)
    if self_check:
        reason = score_mod.hard_violation(rescore,
                                          hbm_ceiling=hbm_ceiling)
        if reason is not None:
            check.update({'pass': False, 'reason': reason})
        else:
            s2 = score_mod.objective_value(rescore.metrics, objective)
            ok = score_mod.scores_close(best['score'], s2,
                                        self_check_tol)
            check.update({
                'pass': bool(ok), 'tol': self_check_tol,
                'rescore': list(s2) if isinstance(s2, tuple) else s2,
                'rescore_metrics': rescore.metrics})
        log(f"  self-check: {'PASS' if check.get('pass') else 'FAIL'} "
            f"({json.dumps({k: v for k, v in check.items() if k not in ('rescore_metrics',)}, sort_keys=True)})")

    def _json_score(s):
        return list(s) if isinstance(s, tuple) else s

    artifact = write_tuned(out, {
        'created_unix': int(time.time()),
        'workload': workload_name,
        'platform': jax.default_backend(),
        'topology': topo.scalars(),
        'sink_schema': SCHEMA_VERSION,
        'objective': objective,
        'base': {k: (list(v) if isinstance(v, tuple) else v)
                 for k, v in base.items()},
        'best': best['knobs'],
        'best_score': _json_score(best['score']),
        'best_metrics': best['metrics'],
        'candidates': [{**r, 'score': _json_score(r['score'])}
                       for r in table],
        'self_check': check,
        'probe': {'steps': int(steps),
                  'warmup_windows': int(warmup_windows),
                  'cov_update_freq': int(cov_update_freq),
                  'inv_update_freq': int(inv_update_freq),
                  'seed': int(seed), 'pruner': pruner,
                  'hbm_ceiling': hbm_ceiling,
                  'stream': stream_path},
    })
    log(f'wrote {out}: best={json.dumps(best["knobs"], sort_keys=True)}'
        f' score={best["score"]}')
    return artifact


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    from distributed_kfac_pytorch_tpu.autotune import probe as probe_mod
    from distributed_kfac_pytorch_tpu.autotune import score as score_mod

    p = argparse.ArgumentParser(
        prog='python -m distributed_kfac_pytorch_tpu.autotune',
        description='Closed-loop perf autotuner: probe candidate '
                    'configs through short warm segments, score them '
                    'on the r10 gate metrics, commit the winner as a '
                    'per-workload TUNED_<workload>.json the example '
                    'CLIs load via --tuned-config (fail-closed).')
    p.add_argument('--workload', default='flagship_lm',
                   choices=sorted(probe_mod.WORKLOADS))
    p.add_argument('--out', default=None,
                   help='artifact path (default TUNED_<workload>.json; '
                        'the best probe stream lands at '
                        '<out>.probe.jsonl)')
    p.add_argument('--steps', type=int, default=8,
                   help='recorded probe steps per candidate')
    p.add_argument('--warmup-windows', type=int, default=2,
                   help='unrecorded cadence windows compiled+run '
                        'before the recorded segment')
    p.add_argument('--inv-update-freq', type=int, default=4,
                   help='probe inverse cadence (the recorded segment '
                        'covers steps/freq firing windows)')
    p.add_argument('--cov-update-freq', type=int, default=1)
    p.add_argument('--objective', default='weighted',
                   choices=score_mod.OBJECTIVES)
    p.add_argument('--hbm-ceiling', type=float, default=None,
                   metavar='BYTES',
                   help='hard-disqualify candidates whose probe peak '
                        'HBM exceeds this')
    p.add_argument('--max-candidates', type=int, default=None,
                   help='truncate the enumerated space (deterministic '
                        'order) — the CI smoke uses 2')
    p.add_argument('--pruner', default='auto',
                   choices=['auto', 'full', 'halving', 'coordinate'],
                   help='auto = full enumeration up to 8 candidates, '
                        'successive halving beyond')
    p.add_argument('--set', action='append', default=[],
                   metavar='KNOB=V1,V2',
                   help="override a knob's value list, e.g. --set "
                        'inv_pipeline_chunks=1,2,4; an empty list '
                        '(KNOB=) drops the knob; repeatable')
    p.add_argument('--seed', type=int, default=0)
    p.add_argument('--no-self-check', action='store_true',
                   help='skip the winner re-probe reproducibility '
                        'check')
    p.add_argument('--self-check-tol', type=float, default=0.75,
                   help='max relative score drift between the two '
                        'winner probes')
    p.add_argument('--strict-self-check', action='store_true',
                   help='exit non-zero when the self-check fails '
                        '(default: record the failure in the artifact '
                        'and warn)')
    p.add_argument('--list', action='store_true',
                   help='print the constraint-filtered candidate '
                        'table and exit without probing')
    args = p.parse_args(argv)

    overrides = {}
    for item in args.set:
        name, _, raw = item.partition('=')
        vals = []
        for tok in filter(None, raw.split(',')):
            low = tok.lower()
            if low in ('true', 'false'):
                vals.append(low == 'true')
            else:
                try:
                    vals.append(int(tok))
                except ValueError:
                    vals.append(float(tok))
        overrides[name] = vals

    if args.list:
        from distributed_kfac_pytorch_tpu.autotune import (
            space as space_mod,
        )
        from distributed_kfac_pytorch_tpu.training import optimizers
        base_cfg = optimizers.OptimConfig(
            kfac_inv_update_freq=args.inv_update_freq,
            kfac_cov_update_freq=args.cov_update_freq)
        base = {f: getattr(base_cfg, f)
                for f in optimizers.TUNABLE_FIELDS}
        for cand in space_mod.default_space(
                overrides or None).enumerate(base):
            print(json.dumps(cand, sort_keys=True))
        return 0

    artifact = tune(
        args.workload, out=args.out, steps=args.steps,
        warmup_windows=args.warmup_windows,
        inv_update_freq=args.inv_update_freq,
        cov_update_freq=args.cov_update_freq,
        objective=args.objective, hbm_ceiling=args.hbm_ceiling,
        max_candidates=args.max_candidates, pruner=args.pruner,
        space_overrides=overrides or None, seed=args.seed,
        self_check=not args.no_self_check,
        self_check_tol=args.self_check_tol)
    check = artifact.get('self_check', {})
    if check.get('enabled') and not check.get('pass'):
        print('warning: self-check failed — the probe may be '
              'measuring noise; re-run with more --steps before '
              'committing this artifact', file=sys.stderr)
        if args.strict_self_check:
            return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
