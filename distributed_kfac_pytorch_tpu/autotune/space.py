"""Declarative knob search space for the perf autotuner.

The tunable surface is the set of :class:`OptimConfig` fields the
r6-r9 rounds made dynamic (``training.optimizers.TUNABLE_FIELDS``):
precondition compute dtype, pipelined-firing chunk count, factor
cadence and batch fraction, storage dtypes. A :class:`SearchSpace` is
a list of :class:`Knob` value sets plus :class:`Constraint` validity
predicates over the *merged* config (base OptimConfig values overlaid
with a candidate assignment) — the same constraints the runtime
enforces at construction time (e.g. ``inv_pipeline_chunks`` must
divide ``kfac_inv_update_freq``), checked here so invalid candidates
are pruned before a probe is ever paid for them.

Two pruners keep the space tractable beyond plain Cartesian
enumeration:

  - :func:`coordinate_descent`: sweep one knob at a time from the base
    config, keeping the best value per knob — O(sum of value counts)
    probes instead of O(product).
  - :func:`successive_halving`: evaluate every candidate on a short
    probe, keep the best half, double the probe length, repeat — the
    classic budgeted racing scheme (cf. KAISA's per-workload tradeoff
    sweep, arXiv:2107.01739).

Both treat ``evaluate`` as a black box returning a score (lower is
better) or ``None`` (disqualified — retraces, invalid construction,
non-finite trips; see :mod:`autotune.score`).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Sequence


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable config field and its candidate values."""
    name: str
    values: tuple
    doc: str = ''

    def __post_init__(self):
        if not self.values:
            raise ValueError(f'knob {self.name!r} has no values')


@dataclasses.dataclass(frozen=True)
class Constraint:
    """Validity predicate over a merged (base + assignment) config."""
    doc: str
    fn: Callable[[dict], bool]

    def ok(self, cfg: dict) -> bool:
        try:
            return bool(self.fn(cfg))
        except (KeyError, TypeError, ZeroDivisionError):
            # A constraint that cannot even evaluate over this config
            # marks it invalid rather than silently passing it.
            return False


def _divides_inv_freq(cfg: dict) -> bool:
    k = int(cfg.get('inv_pipeline_chunks', 1))
    freq = int(cfg.get('kfac_inv_update_freq', 0))
    return k >= 1 and (k == 1 or (freq > 0 and freq % k == 0))


def _staleness_fits_window(cfg: dict) -> bool:
    # inv_staleness=1 fires chunk j at phase j*stride+1, which needs
    # inv_update_freq/inv_pipeline_chunks >= 2 (the KFAC constructor's
    # constraint, checked here so invalid candidates are pruned before
    # a probe is paid for them).
    if int(cfg.get('inv_staleness', 0) or 0) == 0:
        return True
    k = max(1, int(cfg.get('inv_pipeline_chunks', 1)))
    freq = int(cfg.get('kfac_inv_update_freq', 0))
    return freq > 0 and freq % k == 0 and freq // k >= 2


def _bf16_dispatch_supported(cfg: dict) -> bool:
    # bf16 precondition operands require the r6 dispatch branches;
    # every in-tree inverse method threads precond_compute_dtype, so
    # the constraint gates only on methods this build actually knows.
    if not cfg.get('bf16_precond'):
        return True
    return cfg.get('inverse_method') in (
        None, 'auto', 'eigen', 'cholesky', 'newton')


def _lowrank_rank_valid(cfg: dict) -> bool:
    # The runtime constraint is rank < every ENGAGED dim; engaged dims
    # are >= inv_lowrank_dim_threshold, so rank < threshold is the
    # config-level proxy that guarantees validity on any model —
    # pruned here so a construction error is never probed. rank 0 =
    # knob off, always valid.
    rank = int(cfg.get('inv_lowrank_rank', 0) or 0)
    if rank == 0:
        return True
    thr = int(cfg.get('inv_lowrank_dim_threshold', 2048) or 0)
    return rank > 0 and thr >= 2 and rank < thr


#: constraints every candidate must satisfy regardless of the space.
BASE_CONSTRAINTS = (
    Constraint('inv_lowrank_rank must be 0 (off) or positive and '
               'below inv_lowrank_dim_threshold (>= 2), so the rank '
               'is below every engaged factor dim',
               _lowrank_rank_valid),
    Constraint('inv_pipeline_chunks must divide kfac_inv_update_freq',
               _divides_inv_freq),
    Constraint('bf16_precond requires a dispatch branch that supports '
               'precond_compute_dtype', _bf16_dispatch_supported),
    Constraint('factor_batch_fraction must be in (0, 1]',
               lambda c: 0.0 < float(c.get('factor_batch_fraction',
                                           1.0)) <= 1.0),
    Constraint('kfac_cov_update_freq must be >= 1',
               lambda c: int(c.get('kfac_cov_update_freq', 1)) >= 1),
    Constraint("kfac_approx must be 'expand' or 'reduce'",
               lambda c: c.get('kfac_approx', 'expand') in ('expand',
                                                            'reduce')),
    Constraint('inv_staleness must be 0 or 1',
               lambda c: int(c.get('inv_staleness', 0) or 0) in (0, 1)),
    Constraint('inv_staleness=1 needs kfac_inv_update_freq/'
               'inv_pipeline_chunks >= 2', _staleness_fits_window),
    Constraint('deferred_factor_reduction must be a bool',
               lambda c: isinstance(
                   c.get('deferred_factor_reduction', False), bool)),
)


class SearchSpace:
    """An ordered set of knobs plus validity constraints."""

    def __init__(self, knobs: Sequence[Knob],
                 constraints: Sequence[Constraint] = ()):
        names = [k.name for k in knobs]
        if len(set(names)) != len(names):
            raise ValueError(f'duplicate knob names: {names}')
        self.knobs = tuple(knobs)
        self.constraints = tuple(BASE_CONSTRAINTS) + tuple(constraints)

    def violations(self, base: dict, assignment: dict) -> list[str]:
        """Docs of every constraint the merged config violates."""
        cfg = {**base, **assignment}
        return [c.doc for c in self.constraints if not c.ok(cfg)]

    def enumerate(self, base: dict) -> list[dict]:
        """Cartesian product of knob values, constraint-filtered.

        Deterministic order (knob declaration order, value order) so a
        candidate table is reproducible run to run.
        """
        out = []
        for combo in itertools.product(*(k.values for k in self.knobs)):
            assignment = dict(zip((k.name for k in self.knobs), combo))
            if not self.violations(base, assignment):
                out.append(assignment)
        return out


def default_space(overrides: dict[str, Sequence] | None = None
                  ) -> SearchSpace:
    """The stock knob set (mesh-shape knobs excluded — see driver docs).

    ``overrides`` replaces a knob's value list (``{'name': [v, ...]}``);
    an empty/None entry drops the knob from the space entirely.
    """
    stock = [
        Knob('bf16_precond', (False, True),
             'bf16 precondition-contraction operands (r6)'),
        Knob('inv_pipeline_chunks', (1, 2),
             'pipelined inverse firing chunk count (r9)'),
        Knob('factor_batch_fraction', (1.0, 0.5),
             'fraction of the batch used for factor statistics'),
        Knob('kfac_cov_update_freq', (1, 2),
             'factor-statistics update cadence'),
        Knob('kfac_approx', ('expand', 'reduce'),
             'weight-sharing Kronecker approximation (r13): reduce '
             'collapses the shared sequence/patch axis before the '
             'covariance — factor-T cheaper factor updates on '
             'transformer/ViT workloads, a no-op elsewhere'),
        Knob('deferred_factor_reduction', (False, True),
             'deferred window-boundary factor reduction (r14): one '
             'bucketed collective per cadence window instead of a '
             'per-factor-step pmean; exact by EMA linearity'),
        Knob('inv_staleness', (0, 1),
             'one-window-stale off-critical-path inverses (r14): '
             'chunk-fire decompositions of the frozen window-head '
             'snapshot across plain steps — convergence-gated like '
             'the r9 chunk knob'),
        Knob('inv_lowrank_rank', (0, 128),
             'randomized truncated-eigendecomposition rank for large '
             'factor dims (r19, arXiv:2206.15397): rank-r sketch + '
             'warm subspace polish at r*d^2 instead of the O(d^3) '
             'exact firing; engages only on dims >= '
             'inv_lowrank_dim_threshold, a no-op on workloads without '
             'transformer-scale factors'),
        Knob('fused_factor_contraction', (False, True),
             'fused symmetric packed factor contraction + EMA Pallas '
             'kernel (r21): only the symmetric triangle round-trips '
             'HBM; probe-gated with XLA fallback, so an unsupported '
             'backend probes once and runs stock'),
        Knob('fused_precondition', (False, True),
             'fused bucketed precondition + KL-clip v·g epilogue '
             'Pallas kernel (r21): drops the separate full-tensor '
             'clip pass; probe-gated with XLA fallback'),
    ]
    if overrides:
        unknown = set(overrides) - {k.name for k in stock}
        if unknown:
            raise ValueError(f'unknown knob override(s): '
                             f'{sorted(unknown)}')
        out = []
        for k in stock:
            if k.name in overrides:
                vals = tuple(overrides[k.name])
                if not vals:
                    continue  # dropped from the space
                k = Knob(k.name, vals, k.doc)
            out.append(k)
        stock = out
    return SearchSpace(stock)


# ---------------------------------------------------------------------------
# Pruners
# ---------------------------------------------------------------------------

def coordinate_descent(space: SearchSpace, base: dict,
                       evaluate: Callable[[dict], float | None],
                       *, rounds: int = 1
                       ) -> tuple[dict, list[dict]]:
    """One-knob-at-a-time descent from the base config.

    Each round sweeps every knob in declaration order, fixing the best
    value found so far before moving to the next knob. ``evaluate``
    returns a score (lower is better) or None (disqualified). Returns
    ``(best_assignment, table)`` where the table rows carry every
    evaluated assignment with its score (memoized — an assignment is
    never probed twice).
    """
    current = {k.name: base.get(k.name, k.values[0])
               for k in space.knobs}
    cache: dict[tuple, float | None] = {}
    table: list[dict] = []

    def score_of(assignment: dict) -> float | None:
        key = tuple(sorted(assignment.items()))
        if key not in cache:
            if space.violations(base, assignment):
                cache[key] = None
            else:
                cache[key] = evaluate(assignment)
            table.append({'knobs': dict(assignment),
                          'score': cache[key]})
        return cache[key]

    best_score = score_of(dict(current))
    for _ in range(max(1, rounds)):
        improved = False
        for knob in space.knobs:
            for value in knob.values:
                cand = {**current, knob.name: value}
                s = score_of(cand)
                if s is not None and (best_score is None
                                      or s < best_score):
                    current, best_score, improved = cand, s, True
        if not improved:
            break
    return dict(current), table


def successive_halving(candidates: Sequence[dict],
                       evaluate: Callable[[dict, int], float | None],
                       *, min_steps: int, max_steps: int, eta: int = 2
                       ) -> tuple[dict | None, list[dict]]:
    """Budgeted racing: short probes for everyone, longer for survivors.

    ``evaluate(candidate, steps)`` probes a candidate for ``steps``
    steps. Each rung keeps the best ``1/eta`` fraction (at least one)
    and multiplies the probe length by ``eta`` until ``max_steps`` is
    reached or one candidate remains. Returns ``(best, table)``; best
    is None when every candidate was disqualified at the first rung.
    """
    if eta < 2:
        raise ValueError(f'{eta=} must be >= 2')
    alive = [dict(c) for c in candidates]
    table: list[dict] = []
    steps = max(1, int(min_steps))
    while alive:
        scored = []
        for cand in alive:
            s = evaluate(cand, steps)
            table.append({'knobs': dict(cand), 'score': s,
                          'steps': steps})
            if s is not None:
                scored.append((s, cand))
        scored.sort(key=lambda x: x[0])
        if not scored:
            return None, table
        if len(scored) == 1 or steps >= max_steps:
            return scored[0][1], table
        keep = max(1, len(scored) // eta)
        alive = [c for _, c in scored[:keep]]
        steps = min(steps * eta, int(max_steps))
    return None, table
