"""Closed-loop perf autotuner over the observability stack (r12).

Turns the r6-r10 telemetry into automatic configuration (ROADMAP open
item 5): probe candidate knob settings through short warm segments
(:mod:`autotune.probe`), score them on the r10 gate metrics
(:mod:`autotune.score`), commit the winner as a per-workload
``TUNED_<workload>.json`` artifact (:mod:`autotune.driver`) the
example CLIs load fail-closed via ``--tuned-config``
(:mod:`autotune.cli`) — plus the first dynamic in-run policy, the
straggler-aware cadence backoff (:mod:`autotune.policy`).

    python -m distributed_kfac_pytorch_tpu.autotune --workload flagship_lm
"""

from distributed_kfac_pytorch_tpu.autotune import cli  # noqa: F401
from distributed_kfac_pytorch_tpu.autotune import space  # noqa: F401
from distributed_kfac_pytorch_tpu.autotune.driver import (  # noqa: F401
    ARTIFACT_FORMAT,
    apply_tuned,
    emit_events,
    kfac_overrides,
    load_tuned_config,
    read_tuned,
    tune,
    tuned_path,
    write_tuned,
)
from distributed_kfac_pytorch_tpu.autotune.policy import (  # noqa: F401
    BackoffConfig,
    StragglerCadencePolicy,
)
from distributed_kfac_pytorch_tpu.autotune.probe import (  # noqa: F401
    WORKLOADS,
    ProbeResult,
    Workload,
    get_workload,
    probe_candidate,
)
from distributed_kfac_pytorch_tpu.autotune.score import (  # noqa: F401
    hard_violation,
    objective_value,
    rank_candidates,
)
