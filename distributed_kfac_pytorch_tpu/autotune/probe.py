"""Short warm probe segments per candidate config.

A probe builds the candidate's full optimizer stack (the same
``OptimConfig -> get_optimizer -> DistributedKFAC.build_train_step``
path the example CLIs use), runs one unrecorded warm epoch so every
static-cadence program variant is compiled, then one recorded epoch
through ``engine.train_epoch`` with the r7 JSONL sink — the candidate
is scored on exactly the telemetry the r10 gate consumes
(``gate.gate_metrics`` over the recorded stream).

Disqualification is structural, not statistical:

  - a candidate the runtime refuses to construct (e.g.
    ``inv_pipeline_chunks`` exceeding the model's inverse work items)
    is marked ``invalid: <reason>``;
  - a candidate that re-traces a static-cadence variant mid-probe
    (the ``trace_counts`` guard, r9) is marked ``retraces`` — its
    timings would blend compile into step time and mis-score it;
  - ``fired='compile'`` step samples are excluded from the scored
    records for the same reason (belt and braces: the warm epoch
    should leave none).

Probe workloads are deliberately tiny CPU-shaped stand-ins for the
real workloads (``flagship_lm`` probes a scaled-down decoder LM, not
the xl config): the RELATIVE ordering of candidates is what the probe
measures, and the committed artifact records the probe platform so the
fail-closed loader refuses to apply a CPU-tuned artifact on TPU (see
PERF.md r12 for when an artifact may be committed).
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax


@dataclasses.dataclass(frozen=True)
class Workload:
    """A probe-sized workload: model + synthetic batch factory."""
    name: str
    make_model: Callable[[], Any]
    make_batch: Callable[[int], tuple]      # batch index -> batch tuple
    loss_fn: Callable                        # (model_out, batch) -> loss
    batch_size: int
    mutable_cols: tuple = ()
    model_kwargs_fn: Callable | None = None  # batch -> model kwargs
    init_kwargs: dict = dataclasses.field(default_factory=dict)
    # Does the model have weight-shared (sequence/patch-axis) layers
    # the r13 kfac_approx knob can act on? False lets the driver drop
    # that knob from the space: on a conv/MLP workload 'reduce'
    # resolves to the identical program as 'expand', and probing both
    # would double the candidate table for zero information.
    weight_shared: bool = False
    # Largest dense factor dim of the probe model. Lets the driver
    # drop the r19 inv_lowrank_rank knob when no dim can reach the
    # engagement threshold (the knob is then a literal no-op: every
    # rank value compiles the identical exact-dispatch program).
    # 0 = unknown, keep the knob.
    max_factor_dim: int = 0


def _lm_loss(out, batch):
    logits = out[0] if isinstance(out, tuple) else out
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, batch[1]).mean()


def _make_flagship_lm() -> Workload:
    from distributed_kfac_pytorch_tpu.models import transformer_lm
    vocab, seq, batch = 64, 16, 8

    def make_model():
        return transformer_lm.get_model(
            vocab_size=vocab, size='tiny', d_model=32, num_heads=2,
            num_layers=2, max_len=seq, dropout=0.0)

    def make_batch(i):
        rng = np.random.default_rng(1000 + i)
        ids = rng.integers(0, vocab, size=(batch, seq)).astype(np.int32)
        tgt = rng.integers(0, vocab, size=(batch, seq)).astype(np.int32)
        return jnp.asarray(ids), jnp.asarray(tgt)

    return Workload(name='flagship_lm', make_model=make_model,
                    make_batch=make_batch, loss_fn=_lm_loss,
                    batch_size=batch,
                    model_kwargs_fn=lambda b: {'train': False},
                    init_kwargs={'train': False},
                    weight_shared=True,
                    # tiny d32: FFN 128/129 are the largest dims.
                    max_factor_dim=129)


def _make_cifar_resnet20() -> Workload:
    from distributed_kfac_pytorch_tpu.models import cifar_resnet
    batch = 16

    def make_batch(i):
        rng = np.random.default_rng(2000 + i)
        x = rng.standard_normal((batch, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 10, size=(batch,)).astype(np.int32)
        return jnp.asarray(x), jnp.asarray(y)

    def loss(out, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            out, batch[1]).mean()

    return Workload(name='cifar_resnet20',
                    make_model=lambda: cifar_resnet.get_model(
                        'resnet20'),
                    make_batch=make_batch, loss_fn=loss,
                    batch_size=batch, mutable_cols=('batch_stats',),
                    # resnet20: 3x3x64+1 = 577 is the largest dim.
                    max_factor_dim=577)


def _make_tiny_mlp() -> Workload:
    """Fast-tier stand-in: two Dense layers, compiles in seconds."""
    import flax.linen as nn
    batch = 16

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.tanh(nn.Dense(16, name='d0')(x))
            return nn.Dense(8, name='head')(x)

    def make_batch(i):
        rng = np.random.default_rng(3000 + i)
        x = rng.standard_normal((batch, 8)).astype(np.float32)
        y = rng.integers(0, 8, size=(batch,)).astype(np.int32)
        return jnp.asarray(x), jnp.asarray(y)

    def loss(out, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            out, batch[1]).mean()

    return Workload(name='tiny_mlp', make_model=TinyMLP,
                    make_batch=make_batch, loss_fn=loss,
                    batch_size=batch,
                    # d0 A-side 8+1, G 16; head G 8: max is 17.
                    max_factor_dim=17)


WORKLOADS: dict[str, Callable[[], Workload]] = {
    'flagship_lm': _make_flagship_lm,
    'cifar_resnet20': _make_cifar_resnet20,
    'tiny_mlp': _make_tiny_mlp,
}


def get_workload(name: str) -> Workload:
    if name not in WORKLOADS:
        raise ValueError(f'unknown workload {name!r} '
                         f'(one of {sorted(WORKLOADS)})')
    return WORKLOADS[name]()


@dataclasses.dataclass
class ProbeResult:
    """One candidate's probe outcome (the scorer's input)."""
    knobs: dict
    metrics: dict = dataclasses.field(default_factory=dict)
    disqualified: str | None = None
    n_steps: int = 0
    retraces: int = 0
    nonfinite_skips: float = 0.0
    stream_path: str | None = None

    def to_row(self) -> dict:
        return {'knobs': dict(self.knobs),
                'metrics': dict(self.metrics),
                'disqualified': self.disqualified,
                'n_steps': self.n_steps,
                'retraces': self.retraces,
                'nonfinite_skips': self.nonfinite_skips}


def probe_candidate(workload: Workload, base_cfg, knobs: dict, *,
                    steps: int = 8, warmup_windows: int = 2,
                    mesh=None, seed: int = 0,
                    keep_stream: str | None = None) -> ProbeResult:
    """Run one candidate's warm probe segment and reduce it to metrics.

    ``base_cfg`` is an ``OptimConfig``; ``knobs`` overlays
    ``TUNABLE_FIELDS`` onto it. The probe always enables the metrics
    pytree and the non-finite guard (collect-only): a candidate that
    trips the guard is data the scorer's hard constraints need.
    ``keep_stream`` persists the recorded JSONL at that path (the
    committed-artifact evidence); otherwise it lives in a temp dir.
    """
    import dataclasses as _dc

    from distributed_kfac_pytorch_tpu import launch
    from distributed_kfac_pytorch_tpu.observability import (
        gate as obs_gate,
        sink as obs_sink,
    )
    from distributed_kfac_pytorch_tpu.parallel import distributed as D
    from distributed_kfac_pytorch_tpu.training import engine, optimizers

    result = ProbeResult(knobs=dict(knobs))
    unknown = set(knobs) - set(optimizers.TUNABLE_FIELDS)
    if unknown:
        result.disqualified = f'invalid: unknown knob(s) ' \
                              f'{sorted(unknown)}'
        return result
    cfg = _dc.replace(base_cfg, kfac_metrics=True, nonfinite_guard=True,
                      **knobs)

    try:
        model = workload.make_model()
        tx, _, kfac, _ = optimizers.get_optimizer(model, cfg)
        if kfac is None:
            raise ValueError('candidate disables K-FAC '
                             '(kfac_inv_update_freq == 0)')
        batch0 = workload.make_batch(0)
        variables, _ = kfac.init(jax.random.PRNGKey(seed), batch0[0],
                                 **workload.init_kwargs)
        params = variables['params']
        extra = {k: v for k, v in variables.items() if k != 'params'}
        if mesh is None:
            mesh = D.make_kfac_mesh(
                comm_method=optimizers.COMM_METHODS[
                    cfg.comm_method.lower()],
                grad_worker_fraction=cfg.grad_worker_fraction)
        params, extra = launch.replicate_on_mesh(mesh, (params, extra))
        dkfac = D.DistributedKFAC(kfac, mesh, params)
        kstate = dkfac.init_state(params)
        step_fn = dkfac.build_train_step(
            workload.loss_fn, tx,
            model_kwargs_fn=workload.model_kwargs_fn,
            mutable_cols=workload.mutable_cols, donate=False)
    except (ValueError, TypeError) as e:
        result.disqualified = f'invalid: {e}'
        return result

    opt_state = tx.init(params)
    f_freq = int(cfg.kfac_cov_update_freq)
    i_freq = int(cfg.kfac_inv_update_freq)
    hyper = {'lr': cfg.base_lr, 'damping': cfg.damping,
             'factor_update_freq': f_freq, 'inv_update_freq': i_freq}
    state = engine.TrainState(params=params, opt_state=opt_state,
                              kfac_state=kstate, extra_vars=extra)
    n_warm = max(2, int(warmup_windows)) * i_freq
    batches = [workload.make_batch(i % 4) for i in range(n_warm)]

    # Warm epoch: every program variant a full cadence window touches
    # compiles here, outside the recorded segment. TWO windows minimum
    # — the first window's firing consumes the freshly-committed
    # (replicate_on_mesh) state, the second consumes epoch-output
    # state. Those can carry different shardings, and jax's executable
    # cache is sharding-keyed BELOW the trace cache: a variant first
    # called on committed inputs silently compiles a second executable
    # on its first steady-state call, with no retrace and no compile
    # event (measured: ~2 s on a tiny CPU workload). One window would
    # leak exactly that compile into the recorded segment's first
    # firing and mis-score every candidate by its tail metrics.
    engine.train_epoch(step_fn, state, batches, hyper,
                       metrics_sink=None)
    state.epoch -= 1  # the probe is one logical segment, not epochs
    step_fn.compile_events.clear()  # warm-up compiles are expected

    tmp = None
    if keep_stream is None:
        tmp = tempfile.mkdtemp(prefix='kfac_autotune_')
        stream = os.path.join(tmp, 'probe.jsonl')
    else:
        stream = keep_stream
    sink = obs_sink.JsonlMetricsSink(
        stream, meta={'autotune_probe': workload.name,
                      'knobs': {k: repr(v) for k, v in knobs.items()},
                      'backend': jax.default_backend()})
    measured = [workload.make_batch(i % 4) for i in range(int(steps))]
    engine.train_epoch(step_fn, state, measured, hyper,
                       metrics_sink=sink,
                       memory_interval=max(1, i_freq))
    sink.close()

    records, _ = obs_sink.read_jsonl_tolerant(stream)
    # Compile-labeled samples are trace+XLA wall time, not step time.
    scored = [r for r in records
              if not (r.get('kind') == 'step'
                      and r.get('fired') == 'compile')]
    result.metrics = obs_gate.gate_metrics(scored)
    result.n_steps = result.metrics.get('n_steps', 0)
    result.retraces = sum(
        1 for r in records
        if r.get('kind') == 'event' and r.get('event') == 'retrace')
    if max(step_fn.trace_counts.values(), default=1) > 1:
        result.retraces = max(result.retraces, 1)
    step_records = [r for r in records if r.get('kind') == 'step']
    if step_records:
        result.nonfinite_skips = float(obs_sink.to_float(
            step_records[-1].get('metrics', {}).get(
                'kfac/nonfinite_skips', 0.0)))
        if not np.isfinite(result.nonfinite_skips):
            result.nonfinite_skips = float('inf')
    if result.retraces:
        result.disqualified = 'retraces: a static-cadence variant ' \
                              'recompiled mid-probe'
    if keep_stream is not None:
        result.stream_path = stream
    elif tmp is not None:
        # Temp streams are evidence only while the probe runs.
        for name in os.listdir(tmp):
            os.unlink(os.path.join(tmp, name))
        os.rmdir(tmp)
    return result
