"""First dynamic in-run policy: straggler-aware cadence backoff.

The r10 barrier-wait probe measures, per step, how long THIS host
waits for the rest of the mesh before its next collective can proceed.
Sustained skew means some rank is slower than the cadence assumes —
and every factor update then *adds* synchronous collective work (the
factor all-reduce) on top of the wait. The backoff policy stretches
the factor-update cadence while the skew persists and relaxes it back
when the mesh recovers, trading factor freshness for step time inside
a bounded envelope (*Smart Parallelism*, arXiv:2107.06533, makes the
same freshness-for-throughput trade explicit).

Mechanics — and why this is retrace-free: the engine's static cadence
drives the K-FAC stage flags from the HOST step counter
(``engine.cadence_flags``); the policy only ever flips a scheduled
``factor_update=True`` to ``False``. The resulting
``(factor=False, ...)`` flag combinations may not have been compiled
yet (under ``factor_update_freq=1`` the unstretched schedule never
emits them), so the FIRST suppression per combination pays a one-time
variant compile through the step builder's lazy cache — bounded by
the handful of inverse-flag combinations, recorded as a normal r10
``compile`` event (and labeled in the stream), and amortized over the
sustained skew the backoff exists for. Each variant still compiles
exactly once, ever: zero RETRACES, pinned with suppression active by
tests/test_autotune.py. The policy never touches
``inv_update``/``inv_chunk`` (the inverse pipeline's phase structure
stays intact; inverses simply decompose the freshest factors that
exist) and never suppresses step 0 (the monolithic warmup every slot
depends on).

Off by default: ``train_epoch(cadence_policy=None)`` is the unchanged
pre-policy path, and a constructed-but-idle policy (skew never above
threshold) passes flags through untouched — both pinned bit-identical
by tests/test_autotune.py (single-chip and 8-device SPMD).

Every stretch/relax decision queues an ``autotune_backoff`` event the
engine drains into the metrics stream; ``observability.report``
renders them in the autotune section.
"""

from __future__ import annotations

import dataclasses

#: queue bound for decision events awaiting a sink drain: a run wired
#: without --kfac-metrics has no drain, and a mesh oscillating around
#: the threshold emits stretch/relax pairs indefinitely — keep the
#: newest window instead of growing without bound.
MAX_PENDING_EVENTS = 256


@dataclasses.dataclass(frozen=True)
class BackoffConfig:
    """Envelope for the cadence backoff (all host-side).

    ``skew_threshold_ms``: barrier wait above this counts as skew.
    ``sustain_steps``: consecutive skewed steps before stretching.
    ``recover_steps``: consecutive calm steps before relaxing.
    ``max_stretch``: the bound — the effective factor interval never
    exceeds ``max_stretch *`` the scheduled one (factor staleness is
    bounded, the convergence contract the envelope exists for).
    """
    skew_threshold_ms: float = 5.0
    sustain_steps: int = 8
    recover_steps: int = 32
    max_stretch: int = 4

    def __post_init__(self):
        if self.skew_threshold_ms < 0:
            raise ValueError(f'{self.skew_threshold_ms=} must be >= 0')
        if self.sustain_steps < 1 or self.recover_steps < 1:
            raise ValueError('sustain_steps/recover_steps must be >= 1')
        if self.max_stretch < 1:
            raise ValueError(f'{self.max_stretch=} must be >= 1')


class StragglerCadencePolicy:
    """Stateful per-run backoff controller (one per training process).

    The engine calls :meth:`adjust` once per step with the step's
    static cadence flags and the measured barrier wait (None when no
    probe is wired — the policy is then inert) and drains
    :attr:`pending_events` into the metrics sink alongside the compile
    telemetry. Deterministic: decisions depend only on the wait
    sequence, so every rank wired to the same probe values makes the
    same schedule (ranks observe different waits in practice — wire
    the policy on all ranks only with a mesh-agreed signal, or accept
    rank-local schedules; factor all-reduces are collective, so the
    SPMD CLIs arm it from the rank-0-agreed probe value only when all
    ranks run the identical flag sequence. The single-controller CLIs
    here satisfy this trivially: every process computes flags from the
    same host step counter and the probe is a collective psum, so all
    ranks see the same wait).
    """

    def __init__(self, config: BackoffConfig | None = None):
        self.config = config or BackoffConfig()
        self.stretch = 1
        self.pending_events: list[dict] = []
        self._above = 0
        self._below = 0
        self._sched = 0       # scheduled factor firings seen (step>0)
        self._suppressed = 0

    def _observe(self, step: int, wait_ms: float) -> None:
        cfg = self.config
        if wait_ms > cfg.skew_threshold_ms:
            self._above += 1
            self._below = 0
            if (self._above >= cfg.sustain_steps
                    and self.stretch < cfg.max_stretch):
                self.stretch = min(self.stretch * 2, cfg.max_stretch)
                self._above = 0
                self.pending_events.append({
                    'event': 'autotune_backoff', 'action': 'stretch',
                    'stretch': self.stretch, 'step': int(step),
                    'skew_ms': float(wait_ms)})
        else:
            self._below += 1
            self._above = 0
            if self._below >= cfg.recover_steps and self.stretch > 1:
                self.stretch //= 2
                self._below = 0
                self.pending_events.append({
                    'event': 'autotune_backoff', 'action': 'relax',
                    'stretch': self.stretch, 'step': int(step),
                    'skew_ms': float(wait_ms)})

    def adjust(self, step: int, flags: dict,
               wait_ms: float | None) -> dict:
        """Apply the current stretch to one step's cadence flags."""
        if wait_ms is not None:
            self._observe(step, float(wait_ms))
            if len(self.pending_events) > MAX_PENDING_EVENTS:
                del self.pending_events[:-MAX_PENDING_EVENTS]
        if not flags.get('factor_update') or step == 0:
            return flags
        idx = self._sched
        self._sched += 1
        if self.stretch > 1 and idx % self.stretch != 0:
            self._suppressed += 1
            flags = dict(flags)
            flags['factor_update'] = False
        return flags

    def drain_events(self) -> list[dict]:
        events, self.pending_events = self.pending_events, []
        return events

    @property
    def suppressed_firings(self) -> int:
        return self._suppressed
