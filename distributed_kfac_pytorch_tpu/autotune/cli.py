"""Shared CLI wiring for the autotune surface.

All three example entry points expose the same two autotune features
through this module:

    add_autotune_args(parser)        # --tuned-config / --cadence-
                                     # backoff + its envelope knobs
    cfg, events = maybe_apply_tuned(args, cfg)   # fail-closed overlay
    policy = make_cadence_policy(args)           # or None (default)

``maybe_apply_tuned`` runs BEFORE the optimizer/mesh are built (the
tuned knobs feed OptimConfig) but the metrics sink does not exist yet
— the queued events are flushed later with
``autotune.emit_events(metrics_sink, events)`` so the fail-closed /
apply decision is always on the record.
"""

from __future__ import annotations

import jax

from distributed_kfac_pytorch_tpu.autotune import driver as _driver
from distributed_kfac_pytorch_tpu.autotune import policy as _policy


def add_autotune_args(p) -> None:
    p.add_argument('--tuned-config', default=None, metavar='PATH',
                   help='load a committed TUNED_<workload>.json '
                        '(python -m distributed_kfac_pytorch_tpu'
                        '.autotune) and overlay its tuned knobs on '
                        'this run. FAIL-CLOSED: an unreadable/'
                        'mismatched-platform/mismatched-topology '
                        'artifact falls back to the flag defaults and '
                        'logs one autotune_fallback event in the '
                        'metrics stream')
    p.add_argument('--cadence-backoff', action='store_true',
                   help='straggler-aware factor-cadence backoff: when '
                        'the barrier-wait probe shows sustained skew, '
                        'stretch the factor-update cadence within a '
                        'bounded envelope (and relax when the mesh '
                        'recovers). Arms the per-step barrier probe '
                        '(same host-sync cost note as '
                        '--straggler-shards). Off by default — the '
                        'default path is bit-identical to pre-policy '
                        'runs')
    p.add_argument('--backoff-skew-ms', type=float, default=5.0,
                   help='barrier wait above this counts as skew')
    p.add_argument('--backoff-sustain-steps', type=int, default=8,
                   help='consecutive skewed steps before stretching')
    p.add_argument('--backoff-recover-steps', type=int, default=32,
                   help='consecutive calm steps before relaxing')
    p.add_argument('--backoff-max-stretch', type=int, default=4,
                   help='bound on the effective factor-interval '
                        'multiplier (factor staleness stays bounded)')


def maybe_apply_tuned(args, cfg) -> tuple:
    """``(cfg, events)``: overlay --tuned-config fail-closed.

    ``events`` must be flushed into the metrics sink once it exists
    (``autotune.emit_events``). Requires the K-FAC step: a tuned
    artifact cannot apply to the SGD baseline.
    """
    if not getattr(args, 'tuned_config', None):
        return cfg, []
    if cfg.kfac_inv_update_freq <= 0:
        raise SystemExit('--tuned-config requires the K-FAC step '
                         '(--kfac-update-freq > 0)')
    knobs, events = _driver.load_tuned_config(
        args.tuned_config, platform=jax.default_backend(),
        world=_driver.live_world())
    if knobs is None:
        return cfg, events
    new_cfg, err = _driver.apply_tuned(cfg, knobs)
    if err is not None:
        return cfg, [{'event': 'autotune_fallback',
                      'path': str(args.tuned_config),
                      'reason': 'invalid_merge', 'error': err}]
    return new_cfg, events


def make_cadence_policy(args):
    """The in-run policy (or None when --cadence-backoff is absent)."""
    if not getattr(args, 'cadence_backoff', False):
        return None
    return _policy.StragglerCadencePolicy(_policy.BackoffConfig(
        skew_threshold_ms=args.backoff_skew_ms,
        sustain_steps=args.backoff_sustain_steps,
        recover_steps=args.backoff_recover_steps,
        max_stretch=args.backoff_max_stretch))
