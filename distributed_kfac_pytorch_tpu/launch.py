"""Multi-host launch: process-group init and host-local data feeding.

Reference L5 parity (scripts/launch_node_torch_imagenet.sh,
scripts/slurm/*.slurm): where the reference bridges mpiexec/SLURM rank
env-vars into ``torch.distributed.launch`` per node
(launch_node_torch_imagenet.sh:45-48), the JAX runtime replaces the whole
MPI machinery with ``jax.distributed.initialize`` — on TPU pods the
coordinator and process ranks come from the TPU metadata, on SLURM from
the SLURM env (both auto-detected), or explicitly from arguments.
"""

from __future__ import annotations

import os

import jax
import numpy as np


def initialize_multihost(coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> dict:
    """Initialize the JAX multi-host runtime (idempotent, single-host safe).

    Auto-detects TPU pod / SLURM / Open MPI environments like
    ``jax.distributed.initialize`` does; explicit arguments override.
    Returns a summary dict (process_index, process_count, device counts).
    """
    # Manual launch support: JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES
    # / JAX_PROCESS_ID env vars (jax.distributed.initialize itself only
    # auto-detects SLURM / Open MPI / TPU-pod environments) — the
    # generic analogue of the reference's MASTER_ADDR / RANK env chain
    # (launch_node_torch_imagenet.sh:45-68).
    if coordinator_address is None:
        coordinator_address = os.environ.get('JAX_COORDINATOR_ADDRESS')
    if num_processes is None and \
            os.environ.get('JAX_NUM_PROCESSES', '').isdigit():
        num_processes = int(os.environ['JAX_NUM_PROCESSES'])
    if process_id is None and \
            os.environ.get('JAX_PROCESS_ID', '').isdigit():
        process_id = int(os.environ['JAX_PROCESS_ID'])
    explicit = (coordinator_address or num_processes
                or process_id is not None)
    # Initialize only when explicitly configured OR the environment
    # actually declares >1 process. Presence of a cluster-ish env var
    # alone is NOT enough: single-host environments export lookalikes
    # (observed live: the axon TPU runtime injects
    # TPU_WORKER_HOSTNAMES=localhost into every interpreter via
    # sitecustomize, and jax.distributed.initialize then dies with
    # 'coordinator_address should be defined' — which broke every CLI
    # on the dev chip while the 'skip when single' path was gated on
    # the env var's absence).
    if explicit or _detected_world_size() > 1:
        try:
            # Cross-process collectives on the CPU backend need an
            # implementation selected before the backend initializes;
            # harmless on TPU (ICI/DCN collectives are native). This is
            # what lets the multi-host path run on plain hosts (and the
            # 2-process integration test, tests/test_multihost.py).
            jax.config.update('jax_cpu_collectives_implementation',
                              'gloo')
        except Exception:  # config knob absent/renamed: non-fatal
            pass
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        except RuntimeError as e:
            # Double-init is benign. A job whose env declares >1 process
            # must fail loudly, or every host would silently train alone
            # on its own shard.
            if 'should only be called once' not in str(e).lower():
                raise
    # Cross-check the env-scan against the live runtime: the scan
    # silently returns 1 when no known variable matches, and a launch
    # chain that half-exports its env (e.g. SLURM_NTASKS set on some
    # hosts only, or a typo'd JAX_NUM_PROCESSES) would otherwise split
    # the world without a trace. Explicit arguments opt out — they
    # override the env by design, so a disagreement there is intended.
    if not explicit:
        _check_world_size(_detected_world_size(), jax.process_count())
    return {'process_index': jax.process_index(),
            'process_count': jax.process_count(),
            'local_devices': jax.local_device_count(),
            'global_devices': jax.device_count()}


def _check_world_size(detected: int, actual: int) -> None:
    """Warn when the env-declared world size disagrees with the
    initialized runtime's ``jax.process_count()`` (split out for
    testability — the runtime value is authoritative, so this is a
    diagnostic, not a failure)."""
    if detected == actual:
        return
    import warnings

    warnings.warn(
        f'launch environment declares {detected} process(es) '
        f'(_detected_world_size: SLURM/OMPI/JAX_NUM_PROCESSES/'
        f'TPU_WORKER_HOSTNAMES scan) but the initialized JAX runtime '
        f'reports {actual} — the runtime value wins, but check the '
        'launch chain: a half-exported env var here usually means '
        'some hosts are about to train alone on their own shard.')


def host_metadata() -> dict:
    """Identity of THIS host for per-rank telemetry (r10).

    The straggler shards (``observability.stragglers``) stamp this into
    each shard's meta record so a skewed rank in a merged report can be
    mapped back to a machine — 'rank 13 is slow' is actionable only
    once rank 13 has a hostname.
    """
    import platform

    return {'process_index': jax.process_index(),
            'process_count': jax.process_count(),
            'hostname': platform.node(),
            'backend': jax.default_backend(),
            'local_devices': jax.local_device_count()}


def _detected_world_size() -> int:
    """Process count declared by the launch environment (1 if unknown)."""
    for var in ('SLURM_NTASKS', 'OMPI_COMM_WORLD_SIZE',
                'JAX_NUM_PROCESSES'):
        if os.environ.get(var, '').isdigit():
            return int(os.environ[var])
    hosts = os.environ.get('TPU_WORKER_HOSTNAMES', '')
    if hosts:
        return len([h for h in hosts.split(',') if h.strip()])
    return 1


def replicate_on_mesh(mesh, tree):
    """Commit a pytree REPLICATED over the mesh (multi-host safe).

    Model/optimizer init leaves arrive uncommitted on one device; the
    jitted step would replicate them lazily, but the r8 resume path
    builds its orbax restore template (``like=``) from the live state
    *before* any step runs — an uncommitted template makes a pod
    checkpoint restore single-device (caught by the multihost kill
    test). Single-process: a plain ``device_put``. Multi-process: a
    global replicated array assembled from each host's (identical)
    copy — ``device_put`` cannot target non-addressable shardings.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec())

    def put(x):
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x))
        return jax.device_put(x, sharding)

    return jax.tree.map(put, tree)


def host_local_batch_to_global(mesh, batch, pspec):
    """Assemble a global sharded batch from per-host local arrays.

    Multi-host analogue of the reference's DistributedSampler sharding
    (each rank loads its slice, examples/cnn_utils/datasets.py:57-63):
    each host feeds its local shard; the result is one global jax.Array
    laid out per ``pspec`` over the mesh.
    """
    from jax.sharding import NamedSharding

    def make(x, spec):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_process_local_data(sharding,
                                                      np.asarray(x))

    return jax.tree.map(lambda x: make(x, pspec), batch)


def process_local_slice(n_global: int) -> slice:
    """Index range of this host's share of a globally-indexed dataset."""
    per = n_global // jax.process_count()
    start = jax.process_index() * per
    return slice(start, start + per)


def global_batches(mesh, batches, batch_spec=None, *,
                   already_sharded: bool = False):
    """Adapt an iterator of host-identical global batches for multi-host.

    The multi-host feeding glue between a dataset iterator and a jitted
    ``shard_map`` train step — the analogue of the reference's
    ``DistributedSampler`` + per-rank loader chain
    (examples/cnn_utils/datasets.py:53-68, launch chain
    launch_node_torch_imagenet.sh:45-68 -> torch_imagenet_resnet.py:113):

      - single-process: yields batches unchanged (jit shards them onto
        the local mesh per its in_specs — no wrapping needed);
      - multi-process: every host generates the *same* global batch
        (same seed/epoch => same permutation, like DistributedSampler's
        shared-seed shuffle); each host keeps only its
        :func:`process_local_slice` of every batch-sharded leaf and
        assembles one global ``jax.Array`` per leaf spec, so the jitted
        step sees a fully-addressable global batch.

    ``batch_spec``: a single PartitionSpec (broadcast over leaves) or a
    pytree of specs matching the batch — same convention as
    ``DistributedKFAC.build_train_step``. ``None`` defaults to sharding
    the leading dim over the K-FAC mesh axes. Leaves with a
    fully-replicated spec (``P()``) are passed whole from every host.
    Supported specs shard the *leading* dim across processes; later
    spec dims may only map to mesh axes contained within one process
    (e.g. single-host sequence parallelism) — anything else raises.

    ``already_sharded=True``: the iterator yields *per-process local*
    batches (e.g. a tf.data pipeline sharded with
    ``ds.shard(process_count, process_index)``) — no slicing, each
    host's data is used as its local shard directly. Prefer this at
    scale: the default shared-global-batch mode costs every host the
    full global input pipeline (simple and exact for in-memory
    datasets, wasteful for a 32-host ImageNet job).
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    if jax.process_count() == 1:
        yield from batches
        return
    from distributed_kfac_pytorch_tpu.parallel.distributed import (
        KFAC_AXES,
        SLICE_AXIS,
        normalize_batch_specs,
    )
    if batch_spec is None:
        # Default: leading dim over the K-FAC data axes — including
        # the outer slice axis on a multi-slice mesh (r20), mirroring
        # DistributedKFAC.batch_axes.
        axes = (((SLICE_AXIS,) if SLICE_AXIS in mesh.axis_names else ())
                + KFAC_AXES)
        batch_spec = P(axes)
    nproc = jax.process_count()

    def axis_spans_processes(name) -> bool:
        """Does moving along mesh axis ``name`` cross a process?"""
        idx = mesh.axis_names.index(name)
        rows = np.moveaxis(mesh.devices, idx, -1)
        rows = rows.reshape(-1, rows.shape[-1])
        return any(len({d.process_index for d in row}) > 1
                   for row in rows)

    def _axes(entry):
        if entry is None:
            return ()
        return entry if isinstance(entry, tuple) else (entry,)

    def check_spec(spec):
        for entry in tuple(spec)[1:]:
            for ax in _axes(entry):
                if axis_spans_processes(ax):
                    raise NotImplementedError(
                        f'global_batches only shards the leading batch '
                        f'dim across processes; spec {spec} shards a '
                        f'later dim over mesh axis {ax!r} which spans '
                        'multiple processes — assemble such leaves '
                        'yourself with host_local_batch_to_global')

    def assemble(x, spec):
        sharding = NamedSharding(mesh, spec)
        if spec == P():
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x))
        check_spec(spec)
        if already_sharded:
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x))
        n = x.shape[0]
        if n % nproc:
            raise ValueError(
                f'global batch of {n} does not divide evenly over '
                f'{nproc} processes')
        local = np.asarray(x)[process_local_slice(n)]
        return jax.make_array_from_process_local_data(sharding, local)

    for batch in batches:
        specs = normalize_batch_specs(batch_spec, batch)
        yield jax.tree.map(assemble, batch, specs)
