"""Multi-host launch: process-group init and host-local data feeding.

Reference L5 parity (scripts/launch_node_torch_imagenet.sh,
scripts/slurm/*.slurm): where the reference bridges mpiexec/SLURM rank
env-vars into ``torch.distributed.launch`` per node
(launch_node_torch_imagenet.sh:45-48), the JAX runtime replaces the whole
MPI machinery with ``jax.distributed.initialize`` — on TPU pods the
coordinator and process ranks come from the TPU metadata, on SLURM from
the SLURM env (both auto-detected), or explicitly from arguments.
"""

from __future__ import annotations

import os

import jax
import numpy as np


def initialize_multihost(coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> dict:
    """Initialize the JAX multi-host runtime (idempotent, single-host safe).

    Auto-detects TPU pod / SLURM / Open MPI environments like
    ``jax.distributed.initialize`` does; explicit arguments override.
    Returns a summary dict (process_index, process_count, device counts).
    """
    explicit = coordinator_address or num_processes or process_id
    multi_env = any(v in os.environ for v in (
        'SLURM_JOB_ID', 'OMPI_COMM_WORLD_SIZE', 'TPU_WORKER_HOSTNAMES',
        'JAX_COORDINATOR_ADDRESS'))
    if explicit or multi_env:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        except RuntimeError as e:
            # Double-init is benign, as is auto-detection firing after the
            # backend is already live *when the env says single-process*
            # (notebooks/tests where the platform runtime exports
            # TPU_WORKER_HOSTNAMES=localhost etc.). A job whose env
            # declares >1 process must fail loudly, or every host would
            # silently train alone on its own shard.
            msg = str(e).lower()
            benign = ('should only be called once' in msg
                      or (_detected_world_size() <= 1
                          and not explicit
                          and 'must be called before' in msg))
            if not benign:
                raise
    return {'process_index': jax.process_index(),
            'process_count': jax.process_count(),
            'local_devices': jax.local_device_count(),
            'global_devices': jax.device_count()}


def _detected_world_size() -> int:
    """Process count declared by the launch environment (1 if unknown)."""
    for var in ('SLURM_NTASKS', 'OMPI_COMM_WORLD_SIZE',
                'JAX_NUM_PROCESSES'):
        if os.environ.get(var, '').isdigit():
            return int(os.environ[var])
    hosts = os.environ.get('TPU_WORKER_HOSTNAMES', '')
    if hosts:
        return len([h for h in hosts.split(',') if h.strip()])
    return 1


def host_local_batch_to_global(mesh, batch, pspec):
    """Assemble a global sharded batch from per-host local arrays.

    Multi-host analogue of the reference's DistributedSampler sharding
    (each rank loads its slice, examples/cnn_utils/datasets.py:57-63):
    each host feeds its local shard; the result is one global jax.Array
    laid out per ``pspec`` over the mesh.
    """
    from jax.sharding import NamedSharding

    def make(x, spec):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_process_local_data(sharding,
                                                      np.asarray(x))

    return jax.tree.map(lambda x: make(x, pspec), batch)


def process_local_slice(n_global: int) -> slice:
    """Index range of this host's share of a globally-indexed dataset."""
    per = n_global // jax.process_count()
    start = jax.process_index() * per
    return slice(start, start + per)
