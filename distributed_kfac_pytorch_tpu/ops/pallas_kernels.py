"""Pallas TPU kernels for the K-FAC hot ops.

The O(n^3) factor inversion is the framework's make-or-break kernel
(SURVEY.md §7 "Hard parts"; reference does it with sequential cuSOLVER
calls per layer, kfac/layers/base.py:432-441). Two properties make a
custom kernel pay off on TPU:

  - the iteration that replaces the factorization (Newton–Schulz, see
    ``ops.linalg.newton_schulz_inverse``) is matmul-only, so it runs on
    the MXU at full tilt; and
  - between iterations nothing needs to leave the chip — a VMEM-resident
    kernel holds ``M`` and the iterate ``X`` on-chip for the whole solve,
    eliminating the HBM round trip per matmul that a stock XLA lowering
    of the same loop pays (2 reads + 1 write of n^2 floats per matmul,
    ~60x the arithmetic-intensity at n=512).

``batched_inverse`` dispatches: Pallas kernel on TPU for matrices that
fit VMEM (padded to lane multiples), plain-XLA Newton–Schulz elsewhere.
Both paths are bit-compatible in structure (same iteration, fp32).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Matrices up to this dim run in the VMEM-resident kernel. Measured scoped
# VMEM on v5e is ~45 B/element (M/out blocks double-buffered by Mosaic +
# X carry + Y temp): n_pad=640 allocates 18.7 MB and OOMs the 16 MB limit,
# n_pad=512 ~12 MB fits. Larger factors fall back to the stock-XLA
# Newton–Schulz (still matmul-only, just HBM-streamed between iterations).
MAX_PALLAS_DIM = 512
_LANE = 128


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _ns_inverse_kernel(m_ref, out_ref, *, iters: int, n_pad: int,
                       tol: float):
    """One matrix per grid cell: damped-inverse Newton–Schulz in VMEM.

    The damping is already folded into the input; padding rows/cols carry
    an identity block so the padded inverse is the inverse of the padded
    matrix (sliced away by the caller). Early-exits on the residual
    ``max|M X - I|`` like :func:`ops.linalg.newton_schulz_inverse`.
    """
    m = m_ref[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 1)
    eye = (rows == cols).astype(jnp.float32)
    bound = jnp.maximum(jnp.max(jnp.sum(jnp.abs(m), axis=-1)), 1e-30)
    x0 = eye * (1.0 / bound)

    dot = functools.partial(jnp.dot, preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)

    def cond_fn(state):
        k, _, res = state
        return jnp.logical_and(k < iters, res > tol)

    def body(state):
        k, x, _ = state
        y = dot(m, x)
        res = jnp.max(jnp.abs(y - eye))
        return k + 1, 2.0 * x - dot(x, y), res

    _, out, _ = jax.lax.while_loop(
        cond_fn, body, (jnp.zeros((), jnp.int32), x0,
                        jnp.full((), jnp.inf, jnp.float32)))
    out_ref[0] = out


@functools.partial(jax.jit, static_argnames=('iters', 'tol', 'interpret'))
def _pallas_batched_ns_inverse(mats: jax.Array, damping, *,
                               iters: int = 100, tol: float = 1e-5,
                               interpret: bool = False) -> jax.Array:
    """(B, n, n) stack -> damped inverses via the VMEM-resident kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, _ = mats.shape
    n_pad = _round_up(max(n, 8), _LANE)
    m = mats.astype(jnp.float32)
    m = m + damping * jnp.eye(n, dtype=jnp.float32)
    if n_pad != n:
        # Identity padding block: keeps the padded matrix SPD and leaves
        # the top-left inverse block equal to the unpadded inverse.
        m = jnp.pad(m, ((0, 0), (0, n_pad - n), (0, n_pad - n)))
        pad_eye = (jnp.eye(n_pad, dtype=jnp.float32)
                   .at[:n, :n].set(0.0))
        m = m + pad_eye[None]

    kernel = functools.partial(_ns_inverse_kernel, iters=iters, n_pad=n_pad,
                               tol=tol)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_pad, n_pad), jnp.float32),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(m)
    return out[:, :n, :n]


def batched_inverse(mats: jax.Array, damping, *, iters: int = 100,
                    tol: float = 1e-5,
                    force_pallas: bool | None = None,
                    interpret: bool = False) -> jax.Array:
    """Damped SPD inverses of a (B, n, n) stack, TPU-kernel accelerated.

    Dispatch is static (trace-time): the Pallas path is taken on TPU
    backends for dims that fit VMEM, or when ``force_pallas`` is set
    (tests use ``force_pallas=True, interpret=True`` to exercise the
    kernel on CPU).
    """
    n = mats.shape[-1]
    if damping is None:
        damping = 0.0  # the Pallas path folds damping into the input
    use_pallas = force_pallas
    if use_pallas is None:
        use_pallas = (jax.default_backend() == 'tpu'
                      and n <= MAX_PALLAS_DIM)
    if use_pallas:
        return _pallas_batched_ns_inverse(mats, damping, iters=iters,
                                          tol=tol, interpret=interpret)
    from distributed_kfac_pytorch_tpu.ops import linalg
    return jax.vmap(
        lambda m: linalg.newton_schulz_inverse(m, damping, iters=iters,
                                               tol=tol)
    )(mats)


def _jacobi_eigh_kernel(m_ref, q_ref, d_ref, *, n_pad: int, sweeps: int):
    """One matrix per grid cell: Brent–Luk Jacobi entirely in VMEM.

    The slot iteration (ops.linalg.jacobi_slot_iteration) is pure
    elementwise/slice/concat work, so it runs unchanged inside the
    kernel; A and the eigenvector accumulator V stay on-chip for all
    ``sweeps * (n-1)`` rounds. Outputs are in final slot order — the
    caller sorts by eigenvalue outside (argsort is not Mosaic-friendly,
    and it is O(n log n) host-level work).
    """
    from distributed_kfac_pytorch_tpu.ops import linalg

    a = m_ref[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 1)
    eye = (rows == cols).astype(jnp.float32)
    a, v = linalg.jacobi_slot_iteration(a, eye, sweeps)
    q_ref[0] = v
    # The d block is (1, 8, n_pad) — Mosaic requires the last two block
    # dims to be (8, 128)-tileable — so replicate the eigenvalue row
    # across the sublane dim; the caller reads row 0.
    d = jnp.sum(a * eye, axis=1)
    d_ref[0] = jnp.broadcast_to(d[None, :], (8, n_pad))


@functools.partial(jax.jit, static_argnames=('sweeps', 'interpret'))
def _pallas_batched_jacobi_eigh(mats: jax.Array, *, sweeps: int,
                                interpret: bool = False):
    """(B, n, n) SPD stack -> (Q, d) ascending via the VMEM kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, _ = mats.shape
    n_pad = n + (n % 2)
    m = mats.astype(jnp.float32)
    if n_pad != n:
        # Decoupled unit eigenvalue in the pad slot (stripped after sort).
        m = jnp.pad(m, ((0, 0), (0, 1), (0, 1)))
        pad_eye = jnp.zeros((n_pad, n_pad), jnp.float32).at[n, n].set(1.0)
        m = m + pad_eye[None]

    kernel = functools.partial(_jacobi_eigh_kernel, n_pad=n_pad,
                               sweeps=sweeps)
    q, d = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((b, n_pad, n_pad), jnp.float32),
                   jax.ShapeDtypeStruct((b, 8, n_pad), jnp.float32)),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 8, n_pad), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(m)
    d = d[:, 0, :]
    # Sort ascending (and strip the pad eigenpair) at the JAX level.
    order = jnp.argsort(d, axis=-1)
    d = jnp.take_along_axis(d, order, axis=-1)
    q = jnp.take_along_axis(q, order[:, None, :], axis=-1)
    if n_pad != n:
        keep = q[:, n, :] < 0.5                  # pad eigvec is exactly e_n
        idx = jax.vmap(lambda k: jnp.nonzero(k, size=n)[0])(keep)
        q = jax.vmap(lambda qq, ii: jnp.take(qq[:n], ii, axis=1))(q, idx)
        d = jnp.take_along_axis(d, idx, axis=-1)
    return q, d


def batched_jacobi_eigh(mats: jax.Array, sweeps: int | None = None, *,
                        force_pallas: bool | None = None,
                        interpret: bool = False):
    """Batched Brent–Luk eigh; the VMEM Pallas kernel is opt-in.

    Default is always the vmapped pure-JAX iteration. The Pallas kernel
    runs only with ``force_pallas=True`` and on real TPU fits VMEM only
    for n <= 64 (see the dispatch comment below for the v5e data);
    ``force_pallas=True, interpret=True`` exercises it on CPU.
    """
    from distributed_kfac_pytorch_tpu.ops import linalg

    n = mats.shape[-1]
    if sweeps is None:
        sweeps = linalg.default_jacobi_sweeps(n)
    # Hardware-validated on TPU v5e (2026-07): the kernel lowers and is
    # bit-correct (recon err ~2e-5 at n=64), but the slice/concat systolic
    # exchange makes Mosaic's scoped-VMEM stack hold several full-matrix
    # temporaries per round — n=128 already needs 18.7 MB against the
    # 16 MB limit, and at n<=64 the kernel (62 ms/8 mats) loses to the
    # stock vmapped XLA eigh. So the kernel stays opt-in for study
    # (force_pallas=True; tests exercise it in interpret mode) and the
    # default everywhere is the vmapped pure-JAX iteration. The
    # production fast path for large factors is the Newton-Schulz
    # inverse kernel above (flat ~25 ms/8 mats through n=512 on v5e,
    # vs 105 ms for batched XLA eigh at n=512).
    if force_pallas:
        return _pallas_batched_jacobi_eigh(mats, sweeps=sweeps,
                                           interpret=interpret)
    return jax.vmap(lambda m: linalg.jacobi_eigh(m, sweeps))(
        mats.astype(jnp.float32))


def damped_inverse_stack(stack: jax.Array, damping, method: str,
                         iters: int = 100) -> jax.Array:
    """Shared newton/cholesky dispatch for a same-size factor stack.

    Single point of truth for the single-device bucketed path
    (preconditioner.KFAC._bucketed_inverse) and the SPMD path
    (parallel.distributed._spmd_update_inverses), so algorithm changes
    stay in lockstep across both.
    """
    if method == 'newton':
        return batched_inverse(stack, damping, iters=iters)
    from distributed_kfac_pytorch_tpu.ops import linalg
    return jax.vmap(lambda m: linalg.get_inverse(m, damping=damping))(stack)


# ---------------------------------------------------------------------------
# Fused im2col + covariance kernel for conv A factors
# ---------------------------------------------------------------------------
#
# The conv A factor is cov(patches) where patches is the im2col expansion
# of the layer input — a KH*KW x blowup that the stock XLA lowering
# *materializes in HBM* (write + read of a ~300 MB tensor per stage-1
# CIFAR conv at batch 512). Measured on v5e, that traffic made the factor
# EWMA ~14 ms/iter of the tracked CIFAR config — the single largest
# K-FAC cost after round 1 eliminated the decompositions. This kernel
# fuses patch extraction into the covariance contraction: per grid step
# it loads a block of images into VMEM once, forms the patch block with
# static (strided) slices + one lane concat, and accumulates
#   A += P^T P      (MXU, fp32 accumulation)
#   s += ones @ P   (bias column sums, same pass)
# so HBM traffic is one read of x plus one (D, D) output — no patch
# tensor ever exists outside VMEM.

def _patch_cov_kernel(x_ref, a_ref, s_ref, *, kh, kw, sh, sw,
                      pads, oh, ow, mult_dtype):
    """One image block per grid step; accumulates into the same output.

    ``x_ref``: (bb, H, W, C) input block. ``a_ref``: (D, D) fp32
    accumulator, D = kh*kw*C in (ki, kj, c) feature order (matching the
    flattened flax kernel — the basis ops.factors.conv2d_a_factor
    permutes *to*; here it is constructed directly). ``s_ref``: (8, D)
    fp32 column-sum accumulator (row 0 meaningful; 8 rows for sublane
    tiling).
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        a_ref[...] = jnp.zeros_like(a_ref)
        s_ref[...] = jnp.zeros_like(s_ref)

    # Cast BEFORE assembly: the per-shift slices and the concatenated
    # patch block are the large VMEM temporaries — in bf16 they are
    # half-size, which is what lets deep-stage blocks (e.g. 56x56x64,
    # D=576: ~3.6 MB patch block) fit alongside the (D, D) accumulator.
    x = x_ref[...].astype(mult_dtype)
    bb, h, w, c = x.shape
    (ph_lo, ph_hi), (pw_lo, pw_hi) = pads
    if ph_lo or ph_hi or pw_lo or pw_hi:
        x = jnp.pad(x, ((0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi), (0, 0)))
    pieces = []
    for ki in range(kh):
        for kj in range(kw):
            sl = jax.lax.slice(
                x, (0, ki, kj, 0),
                (bb, ki + sh * (oh - 1) + 1, kj + sw * (ow - 1) + 1, c),
                (1, sh, sw, 1))
            pieces.append(sl.reshape(bb * oh * ow, c))
    p = jnp.concatenate(pieces, axis=1)
    # bf16 multiplicands ride the MXU fast path (the default covariance
    # precision contract); fp32 multiplicands request HIGHEST for the
    # strict-fp32 contract (ops.factors.get_cov).
    prec = (None if mult_dtype == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)
    a_ref[...] += jnp.dot(p.T, p, preferred_element_type=jnp.float32,
                          precision=prec)
    ones = jnp.ones((8, p.shape[0]), mult_dtype)
    s_ref[...] += jnp.dot(ones, p, preferred_element_type=jnp.float32,
                          precision=prec)


@functools.partial(
    jax.jit, static_argnames=('kernel_size', 'strides', 'pads',
                              'block_batch', 'mult_bf16', 'interpret'))
def _pallas_patch_cov(x: jax.Array, *, kernel_size, strides, pads,
                      block_batch: int, mult_bf16: bool,
                      interpret: bool = False):
    """(B, H, W, C) NHWC -> (cov (D, D) fp32, colsum (D,) fp32).

    ``cov`` is the *sum* over all B*OH*OW patch rows of p p^T (caller
    applies the 1/scale); ``colsum`` the per-feature row sum.
    """
    from jax.experimental import pallas as pl  # noqa: F811 (module use)
    from jax.experimental.pallas import tpu as pltpu

    b, h, w, c = x.shape
    kh, kw = kernel_size
    sh, sw = strides
    (ph_lo, ph_hi), (pw_lo, pw_hi) = pads
    oh = (h + ph_lo + ph_hi - kh) // sh + 1
    ow = (w + pw_lo + pw_hi - kw) // sw + 1
    d = kh * kw * c
    if b % block_batch:
        raise ValueError(f'batch {b} not divisible by {block_batch=}')
    mult_dtype = jnp.bfloat16 if mult_bf16 else jnp.float32

    kernel = functools.partial(
        _patch_cov_kernel, kh=kh, kw=kw, sh=sh, sw=sw, pads=pads,
        oh=oh, ow=ow, mult_dtype=mult_dtype)
    cov, s = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((d, d), jnp.float32),
                   jax.ShapeDtypeStruct((8, d), jnp.float32)),
        grid=(b // block_batch,),
        in_specs=[pl.BlockSpec((block_batch, h, w, c),
                               lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((d, d), lambda i: (0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((8, d), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(x)
    return cov, s[0]


@functools.lru_cache(maxsize=1)
def fused_patch_cov_supported() -> bool:
    """One-time probe: can the fused kernel compile AND run here?

    Mosaic failures (VMEM overflow, unsupported lowering) surface at
    jit-compile or run time — not as catchable trace-time errors at the
    dispatch site — so the dispatcher calls this once per process and
    falls back to the XLA path for good if the probe fails. The kernel
    itself is opt-in (KFAC_FUSED_PATCH_COV=1 at the dispatch site,
    factors.conv2d_a_factor) — not opting in is the only disable switch.
    """
    if jax.default_backend() != 'tpu':
        return False
    try:
        import numpy as np

        from distributed_kfac_pytorch_tpu.ops import factors as F
        x = jnp.asarray(np.linspace(0, 1, 4 * 8 * 8 * 3, dtype='float32')
                        .reshape(4, 8, 8, 3))
        # Reference computed INLINE (not via conv2d_a_factor, whose TPU
        # dispatch would re-enter this probe): same formula/scale/bias
        # assembly as conv_a_factor_fused.
        p2 = np.asarray(F.extract_conv2d_patches(
            x, (3, 3), (1, 1), 'SAME')).reshape(-1, 27).astype(np.float64)
        spatial = 64
        rows = p2.shape[0]
        cov = (p2.T @ p2) / (rows * spatial * spatial)
        bias_col = p2.mean(0) / (spatial * spatial)
        # kfaclint: waive[host-np-asarray] documented blocking point: once-per-process kernel parity probe, off the step path
        ref = np.asarray(F._assemble_bias_factor(
            jnp.asarray(cov, jnp.float32), jnp.asarray(bias_col,
                                                       jnp.float32),
            1.0 / (spatial * spatial)))
        got = np.asarray(conv_a_factor_fused(
            x, (3, 3), (1, 1), 'SAME', True, mult_bf16=True))
        rel = (np.abs(got - ref).max()
               / max(float(np.abs(ref).max()), 1e-30))
        return bool(np.isfinite(got).all()) and rel < 5e-2
    except Exception:
        return False


def conv_a_factor_fused(a: jax.Array, kernel_size, strides, padding,
                        has_bias: bool, *, mult_bf16: bool = True,
                        block_batch: int | None = None,
                        interpret: bool = False) -> jax.Array:
    """Conv A factor via the fused VMEM patch-covariance kernel.

    Drop-in equal to ``ops.factors.conv2d_a_factor`` (same value up to
    matmul rounding; same (kh, kw, c) feature basis and bias assembly)
    for symmetric spatial padding. ``mult_bf16`` matches the default
    covariance precision contract (bf16 multiplicands, fp32
    accumulation — see ops.factors.get_cov); pass False for strict-fp32
    multiplicands.
    """
    from distributed_kfac_pytorch_tpu.ops import factors as F

    b, h, w, c = a.shape
    kh, kw = kernel_size
    sh, sw = strides
    pads = _canonical_pad(padding, (kh, kw), (h, w), (sh, sw))
    (ph_lo, ph_hi), (pw_lo, pw_hi) = pads
    oh = (h + ph_lo + ph_hi - kh) // sh + 1
    ow = (w + pw_lo + pw_hi - kw) // sw + 1
    if block_batch is None:
        # VMEM budget: the patch block materializes ~twice (per-shift
        # pieces + their concat), plus the padded x copy (mult dtype),
        # plus the fp32 input block (x2 for Mosaic double-buffering);
        # the fp32 (D, D) + (8, D) accumulators are resident throughout
        # (x1.5 headroom). Target <= ~10 MB of the ~16 MB/core.
        mult_bytes = 2 if mult_bf16 else 4
        d_full = kh * kw * c
        fixed = int(1.5 * (d_full * d_full + 8 * d_full) * 4)
        bytes_per_img = (2 * oh * ow * d_full * mult_bytes
                         + (h + ph_lo + ph_hi) * (w + pw_lo + pw_hi)
                         * c * mult_bytes
                         + 2 * h * w * c * 4)
        # Mosaic's scoped-vmem accounting runs ~2.5x this byte model
        # (measured: a 10 MB target allocated 24.4 MB of the 16 MB
        # limit at (512,32,32,16)); target 4 MB so real usage stays
        # within limits in any surrounding program.
        budget = int(4e6) - fixed
        block_batch = max(1, budget // max(1, bytes_per_img))
        while b % block_batch:
            block_batch -= 1
    spatial = oh * ow
    rows = b * spatial
    cov, colsum = _pallas_patch_cov(
        a, kernel_size=(kh, kw), strides=(sh, sw), pads=pads,
        block_batch=block_batch, mult_bf16=mult_bf16,
        interpret=interpret)
    cov = cov * (1.0 / (rows * spatial * spatial))
    if not has_bias:
        return cov
    bias_col = colsum * (1.0 / (rows * spatial * spatial))
    return F._assemble_bias_factor(cov, bias_col, 1.0 / (spatial * spatial))


def _canonical_pad(padding, kernel_size, spatial, strides):
    """Per-axis (lo, hi) pad amounts matching XLA conventions.

    'SAME' follows the XLA/TF formula — total = max((ceil(dim/s)-1)*s
    + k - dim, 0), lo = total // 2, hi = total - lo (extra on the high
    side; asymmetric for strided convs) — so the kernel reproduces
    conv_general_dilated_patches exactly. Also accepts 'VALID', int,
    and explicit ((lo, hi), (lo, hi)) pairs.
    """
    kh, kw = kernel_size
    h, w = spatial
    sh, sw = strides
    if isinstance(padding, str):
        if padding.upper() == 'VALID':
            return ((0, 0), (0, 0))
        if padding.upper() == 'SAME':
            out = []
            for dim, k, s in ((h, kh, sh), (w, kw, sw)):
                o = -(-dim // s)
                total = max((o - 1) * s + k - dim, 0)
                out.append((total // 2, total - total // 2))
            return tuple(out)
        raise ValueError(f'unsupported padding {padding!r}')
    if isinstance(padding, int):
        return ((padding, padding), (padding, padding))
    (a, b), (c, d) = padding
    return ((a, b), (c, d))
