"""Pallas TPU kernels for the K-FAC hot ops.

The O(n^3) factor inversion is the framework's make-or-break kernel
(SURVEY.md §7 "Hard parts"; reference does it with sequential cuSOLVER
calls per layer, kfac/layers/base.py:432-441). Two properties make a
custom kernel pay off on TPU:

  - the iteration that replaces the factorization (Newton–Schulz, see
    ``ops.linalg.newton_schulz_inverse``) is matmul-only, so it runs on
    the MXU at full tilt; and
  - between iterations nothing needs to leave the chip — a VMEM-resident
    kernel holds ``M`` and the iterate ``X`` on-chip for the whole solve,
    eliminating the HBM round trip per matmul that a stock XLA lowering
    of the same loop pays (2 reads + 1 write of n^2 floats per matmul,
    ~60x the arithmetic-intensity at n=512).

``batched_inverse`` dispatches: Pallas kernel on TPU for matrices that
fit VMEM (padded to lane multiples), plain-XLA Newton–Schulz elsewhere.
Both paths are bit-compatible in structure (same iteration, fp32).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Matrices up to this dim run in the VMEM-resident kernel. Measured scoped
# VMEM on v5e is ~45 B/element (M/out blocks double-buffered by Mosaic +
# X carry + Y temp): n_pad=640 allocates 18.7 MB and OOMs the 16 MB limit,
# n_pad=512 ~12 MB fits. Larger factors fall back to the stock-XLA
# Newton–Schulz (still matmul-only, just HBM-streamed between iterations).
MAX_PALLAS_DIM = 512
_LANE = 128


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _ns_inverse_kernel(m_ref, out_ref, *, iters: int, n_pad: int,
                       tol: float):
    """One matrix per grid cell: damped-inverse Newton–Schulz in VMEM.

    The damping is already folded into the input; padding rows/cols carry
    an identity block so the padded inverse is the inverse of the padded
    matrix (sliced away by the caller). Early-exits on the residual
    ``max|M X - I|`` like :func:`ops.linalg.newton_schulz_inverse`.
    """
    m = m_ref[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 1)
    eye = (rows == cols).astype(jnp.float32)
    bound = jnp.maximum(jnp.max(jnp.sum(jnp.abs(m), axis=-1)), 1e-30)
    x0 = eye * (1.0 / bound)

    dot = functools.partial(jnp.dot, preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)

    def cond_fn(state):
        k, _, res = state
        return jnp.logical_and(k < iters, res > tol)

    def body(state):
        k, x, _ = state
        y = dot(m, x)
        res = jnp.max(jnp.abs(y - eye))
        return k + 1, 2.0 * x - dot(x, y), res

    _, out, _ = jax.lax.while_loop(
        cond_fn, body, (jnp.zeros((), jnp.int32), x0,
                        jnp.full((), jnp.inf, jnp.float32)))
    out_ref[0] = out


@functools.partial(jax.jit, static_argnames=('iters', 'tol', 'interpret'))
def _pallas_batched_ns_inverse(mats: jax.Array, damping, *,
                               iters: int = 100, tol: float = 1e-5,
                               interpret: bool = False) -> jax.Array:
    """(B, n, n) stack -> damped inverses via the VMEM-resident kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, _ = mats.shape
    n_pad = _round_up(max(n, 8), _LANE)
    m = mats.astype(jnp.float32)
    m = m + damping * jnp.eye(n, dtype=jnp.float32)
    if n_pad != n:
        # Identity padding block: keeps the padded matrix SPD and leaves
        # the top-left inverse block equal to the unpadded inverse.
        m = jnp.pad(m, ((0, 0), (0, n_pad - n), (0, n_pad - n)))
        pad_eye = (jnp.eye(n_pad, dtype=jnp.float32)
                   .at[:n, :n].set(0.0))
        m = m + pad_eye[None]

    kernel = functools.partial(_ns_inverse_kernel, iters=iters, n_pad=n_pad,
                               tol=tol)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_pad, n_pad), jnp.float32),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(m)
    return out[:, :n, :n]


def batched_inverse(mats: jax.Array, damping, *, iters: int = 100,
                    tol: float = 1e-5,
                    force_pallas: bool | None = None,
                    interpret: bool = False) -> jax.Array:
    """Damped SPD inverses of a (B, n, n) stack, TPU-kernel accelerated.

    Dispatch is static (trace-time): the Pallas path is taken on TPU
    backends for dims that fit VMEM, or when ``force_pallas`` is set
    (tests use ``force_pallas=True, interpret=True`` to exercise the
    kernel on CPU).
    """
    n = mats.shape[-1]
    if damping is None:
        damping = 0.0  # the Pallas path folds damping into the input
    use_pallas = force_pallas
    if use_pallas is None:
        use_pallas = (jax.default_backend() == 'tpu'
                      and n <= MAX_PALLAS_DIM)
    if use_pallas:
        return _pallas_batched_ns_inverse(mats, damping, iters=iters,
                                          tol=tol, interpret=interpret)
    from distributed_kfac_pytorch_tpu.ops import linalg
    return jax.vmap(
        lambda m: linalg.newton_schulz_inverse(m, damping, iters=iters,
                                               tol=tol)
    )(mats)


def _jacobi_eigh_kernel(m_ref, q_ref, d_ref, *, n_pad: int, sweeps: int):
    """One matrix per grid cell: Brent–Luk Jacobi entirely in VMEM.

    The slot iteration (ops.linalg.jacobi_slot_iteration) is pure
    elementwise/slice/concat work, so it runs unchanged inside the
    kernel; A and the eigenvector accumulator V stay on-chip for all
    ``sweeps * (n-1)`` rounds. Outputs are in final slot order — the
    caller sorts by eigenvalue outside (argsort is not Mosaic-friendly,
    and it is O(n log n) host-level work).
    """
    from distributed_kfac_pytorch_tpu.ops import linalg

    a = m_ref[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 1)
    eye = (rows == cols).astype(jnp.float32)
    a, v = linalg.jacobi_slot_iteration(a, eye, sweeps)
    q_ref[0] = v
    # The d block is (1, 8, n_pad) — Mosaic requires the last two block
    # dims to be (8, 128)-tileable — so replicate the eigenvalue row
    # across the sublane dim; the caller reads row 0.
    d = jnp.sum(a * eye, axis=1)
    d_ref[0] = jnp.broadcast_to(d[None, :], (8, n_pad))


@functools.partial(jax.jit, static_argnames=('sweeps', 'interpret'))
def _pallas_batched_jacobi_eigh(mats: jax.Array, *, sweeps: int,
                                interpret: bool = False):
    """(B, n, n) SPD stack -> (Q, d) ascending via the VMEM kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, _ = mats.shape
    n_pad = n + (n % 2)
    m = mats.astype(jnp.float32)
    if n_pad != n:
        # Decoupled unit eigenvalue in the pad slot (stripped after sort).
        m = jnp.pad(m, ((0, 0), (0, 1), (0, 1)))
        pad_eye = jnp.zeros((n_pad, n_pad), jnp.float32).at[n, n].set(1.0)
        m = m + pad_eye[None]

    kernel = functools.partial(_jacobi_eigh_kernel, n_pad=n_pad,
                               sweeps=sweeps)
    q, d = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((b, n_pad, n_pad), jnp.float32),
                   jax.ShapeDtypeStruct((b, 8, n_pad), jnp.float32)),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 8, n_pad), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(m)
    d = d[:, 0, :]
    # Sort ascending (and strip the pad eigenpair) at the JAX level.
    order = jnp.argsort(d, axis=-1)
    d = jnp.take_along_axis(d, order, axis=-1)
    q = jnp.take_along_axis(q, order[:, None, :], axis=-1)
    if n_pad != n:
        keep = q[:, n, :] < 0.5                  # pad eigvec is exactly e_n
        idx = jax.vmap(lambda k: jnp.nonzero(k, size=n)[0])(keep)
        q = jax.vmap(lambda qq, ii: jnp.take(qq[:n], ii, axis=1))(q, idx)
        d = jnp.take_along_axis(d, idx, axis=-1)
    return q, d


def batched_jacobi_eigh(mats: jax.Array, sweeps: int | None = None, *,
                        force_pallas: bool | None = None,
                        interpret: bool = False):
    """Batched Brent–Luk eigh; the VMEM Pallas kernel is opt-in.

    Default is always the vmapped pure-JAX iteration. The Pallas kernel
    runs only with ``force_pallas=True`` and on real TPU fits VMEM only
    for n <= 64 (see the dispatch comment below for the v5e data);
    ``force_pallas=True, interpret=True`` exercises it on CPU.
    """
    from distributed_kfac_pytorch_tpu.ops import linalg

    n = mats.shape[-1]
    if sweeps is None:
        sweeps = linalg.default_jacobi_sweeps(n)
    # Hardware-validated on TPU v5e (2026-07): the kernel lowers and is
    # bit-correct (recon err ~2e-5 at n=64), but the slice/concat systolic
    # exchange makes Mosaic's scoped-VMEM stack hold several full-matrix
    # temporaries per round — n=128 already needs 18.7 MB against the
    # 16 MB limit, and at n<=64 the kernel (62 ms/8 mats) loses to the
    # stock vmapped XLA eigh. So the kernel stays opt-in for study
    # (force_pallas=True; tests exercise it in interpret mode) and the
    # default everywhere is the vmapped pure-JAX iteration. The
    # production fast path for large factors is the Newton-Schulz
    # inverse kernel above (flat ~25 ms/8 mats through n=512 on v5e,
    # vs 105 ms for batched XLA eigh at n=512).
    if force_pallas:
        return _pallas_batched_jacobi_eigh(mats, sweeps=sweeps,
                                           interpret=interpret)
    return jax.vmap(lambda m: linalg.jacobi_eigh(m, sweeps))(
        mats.astype(jnp.float32))


def damped_inverse_stack(stack: jax.Array, damping, method: str,
                         iters: int = 100) -> jax.Array:
    """Shared newton/cholesky dispatch for a same-size factor stack.

    Single point of truth for the single-device bucketed path
    (preconditioner.KFAC._bucketed_inverse) and the SPMD path
    (parallel.distributed._spmd_update_inverses), so algorithm changes
    stay in lockstep across both.
    """
    if method == 'newton':
        return batched_inverse(stack, damping, iters=iters)
    from distributed_kfac_pytorch_tpu.ops import linalg
    return jax.vmap(lambda m: linalg.get_inverse(m, damping=damping))(stack)
