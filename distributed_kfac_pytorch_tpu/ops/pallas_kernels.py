"""Pallas TPU kernels for the K-FAC hot ops.

The O(n^3) factor inversion is the framework's make-or-break kernel
(SURVEY.md §7 "Hard parts"; reference does it with sequential cuSOLVER
calls per layer, kfac/layers/base.py:432-441). Two properties make a
custom kernel pay off on TPU:

  - the iteration that replaces the factorization (Newton–Schulz, see
    ``ops.linalg.newton_schulz_inverse``) is matmul-only, so it runs on
    the MXU at full tilt; and
  - between iterations nothing needs to leave the chip — a VMEM-resident
    kernel holds ``M`` and the iterate ``X`` on-chip for the whole solve,
    eliminating the HBM round trip per matmul that a stock XLA lowering
    of the same loop pays (2 reads + 1 write of n^2 floats per matmul,
    ~60x the arithmetic-intensity at n=512).

``batched_inverse`` dispatches: Pallas kernel on TPU for matrices that
fit VMEM (padded to lane multiples), plain-XLA Newton–Schulz elsewhere.
Both paths are bit-compatible in structure (same iteration, fp32).
"""

from __future__ import annotations

import functools
import os
import warnings

import jax
import jax.numpy as jnp

# Matrices up to this dim run in the VMEM-resident kernel. Measured scoped
# VMEM on v5e is ~45 B/element (M/out blocks double-buffered by Mosaic +
# X carry + Y temp): n_pad=640 allocates 18.7 MB and OOMs the 16 MB limit,
# n_pad=512 ~12 MB fits. Larger factors fall back to the stock-XLA
# Newton–Schulz (still matmul-only, just HBM-streamed between iterations).
MAX_PALLAS_DIM = 512
_LANE = 128


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


# ---------------------------------------------------------------------------
# Fallback events (r21)
# ---------------------------------------------------------------------------
#
# Every probe failure and in-dispatch degradation is RECORDED, not
# swallowed: a fleet run must be able to tell "ran fused" from
# "silently fell back to XLA". Events accumulate here and are drained
# into the step function's ``compile_events`` list (the same channel
# the compile/retrace events ride — build_train_step drains after each
# dispatch, engine.train_epoch forwards to the metrics sink).

_PENDING_EVENTS: list = []

#: block_batch floor for the fused patch-cov kernel: below this the
#: per-grid-step matmul is too thin to amortize the patch assembly
#: (block_batch=1 on a prime batch size was measured as the silent
#: worst case) — the dispatcher falls back to XLA instead.
MIN_FUSED_BLOCK_BATCH = 8


def record_fallback(kernel: str, reason: str) -> None:
    """Record (and warn about) one kernel's fallback to the XLA path."""
    warnings.warn(
        f'pallas kernel {kernel!r} falling back to XLA: {reason}',
        RuntimeWarning, stacklevel=2)
    _PENDING_EVENTS.append({'event': 'pallas_fallback', 'kernel': kernel,
                            'reason': reason})


def drain_pallas_events() -> list:
    """Pop all pending fallback events (oldest first)."""
    out = list(_PENDING_EVENTS)
    _PENDING_EVENTS.clear()
    return out


def _forced_fallback() -> bool:
    """KFAC_PALLAS_FALLBACK=1 forces every probe to fail (recorded):
    the smoke test's forced-fallback leg and a field kill switch."""
    return os.environ.get('KFAC_PALLAS_FALLBACK', '') not in ('', '0')


def _ns_inverse_kernel(m_ref, out_ref, *, iters: int, n_pad: int,
                       tol: float):
    """One matrix per grid cell: damped-inverse Newton–Schulz in VMEM.

    The damping is already folded into the input; padding rows/cols carry
    an identity block so the padded inverse is the inverse of the padded
    matrix (sliced away by the caller). Early-exits on the residual
    ``max|M X - I|`` like :func:`ops.linalg.newton_schulz_inverse`.
    """
    m = m_ref[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 1)
    eye = (rows == cols).astype(jnp.float32)
    bound = jnp.maximum(jnp.max(jnp.sum(jnp.abs(m), axis=-1)), 1e-30)
    x0 = eye * (1.0 / bound)

    dot = functools.partial(jnp.dot, preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)

    def cond_fn(state):
        k, _, res = state
        return jnp.logical_and(k < iters, res > tol)

    def body(state):
        k, x, _ = state
        y = dot(m, x)
        res = jnp.max(jnp.abs(y - eye))
        return k + 1, 2.0 * x - dot(x, y), res

    _, out, _ = jax.lax.while_loop(
        cond_fn, body, (jnp.zeros((), jnp.int32), x0,
                        jnp.full((), jnp.inf, jnp.float32)))
    out_ref[0] = out


@functools.partial(jax.jit, static_argnames=('iters', 'tol', 'interpret'))
def _pallas_batched_ns_inverse(mats: jax.Array, damping, *,
                               iters: int = 100, tol: float = 1e-5,
                               interpret: bool = False) -> jax.Array:
    """(B, n, n) stack -> damped inverses via the VMEM-resident kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, _ = mats.shape
    n_pad = _round_up(max(n, 8), _LANE)
    m = mats.astype(jnp.float32)
    m = m + damping * jnp.eye(n, dtype=jnp.float32)
    if n_pad != n:
        # Identity padding block: keeps the padded matrix SPD and leaves
        # the top-left inverse block equal to the unpadded inverse.
        m = jnp.pad(m, ((0, 0), (0, n_pad - n), (0, n_pad - n)))
        pad_eye = (jnp.eye(n_pad, dtype=jnp.float32)
                   .at[:n, :n].set(0.0))
        m = m + pad_eye[None]

    kernel = functools.partial(_ns_inverse_kernel, iters=iters, n_pad=n_pad,
                               tol=tol)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n_pad, n_pad), jnp.float32),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(m)
    return out[:, :n, :n]


def batched_inverse(mats: jax.Array, damping, *, iters: int = 100,
                    tol: float = 1e-5,
                    force_pallas: bool | None = None,
                    interpret: bool = False) -> jax.Array:
    """Damped SPD inverses of a (B, n, n) stack, TPU-kernel accelerated.

    Dispatch is static (trace-time): the Pallas path is taken on TPU
    backends for dims that fit VMEM, or when ``force_pallas`` is set
    (tests use ``force_pallas=True, interpret=True`` to exercise the
    kernel on CPU).
    """
    n = mats.shape[-1]
    if damping is None:
        damping = 0.0  # the Pallas path folds damping into the input
    use_pallas = force_pallas
    if use_pallas is None:
        use_pallas = (jax.default_backend() == 'tpu'
                      and n <= MAX_PALLAS_DIM)
    if use_pallas:
        return _pallas_batched_ns_inverse(mats, damping, iters=iters,
                                          tol=tol, interpret=interpret)
    from distributed_kfac_pytorch_tpu.ops import linalg
    return jax.vmap(
        lambda m: linalg.newton_schulz_inverse(m, damping, iters=iters,
                                               tol=tol)
    )(mats)


def _jacobi_eigh_kernel(m_ref, q_ref, d_ref, *, n_pad: int, sweeps: int):
    """One matrix per grid cell: Brent–Luk Jacobi entirely in VMEM.

    The slot iteration (ops.linalg.jacobi_slot_iteration) is pure
    elementwise/slice/concat work, so it runs unchanged inside the
    kernel; A and the eigenvector accumulator V stay on-chip for all
    ``sweeps * (n-1)`` rounds. Outputs are in final slot order — the
    caller sorts by eigenvalue outside (argsort is not Mosaic-friendly,
    and it is O(n log n) host-level work).
    """
    from distributed_kfac_pytorch_tpu.ops import linalg

    a = m_ref[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n_pad, n_pad), 1)
    eye = (rows == cols).astype(jnp.float32)
    a, v = linalg.jacobi_slot_iteration(a, eye, sweeps)
    q_ref[0] = v
    # The d block is (1, 8, n_pad) — Mosaic requires the last two block
    # dims to be (8, 128)-tileable — so replicate the eigenvalue row
    # across the sublane dim; the caller reads row 0.
    d = jnp.sum(a * eye, axis=1)
    d_ref[0] = jnp.broadcast_to(d[None, :], (8, n_pad))


@functools.partial(jax.jit, static_argnames=('sweeps', 'interpret'))
def _pallas_batched_jacobi_eigh(mats: jax.Array, *, sweeps: int,
                                interpret: bool = False):
    """(B, n, n) SPD stack -> (Q, d) ascending via the VMEM kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, _ = mats.shape
    n_pad = n + (n % 2)
    m = mats.astype(jnp.float32)
    if n_pad != n:
        # Decoupled unit eigenvalue in the pad slot (stripped after sort).
        m = jnp.pad(m, ((0, 0), (0, 1), (0, 1)))
        pad_eye = jnp.zeros((n_pad, n_pad), jnp.float32).at[n, n].set(1.0)
        m = m + pad_eye[None]

    kernel = functools.partial(_jacobi_eigh_kernel, n_pad=n_pad,
                               sweeps=sweeps)
    q, d = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((b, n_pad, n_pad), jnp.float32),
                   jax.ShapeDtypeStruct((b, 8, n_pad), jnp.float32)),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((1, n_pad, n_pad), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 8, n_pad), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(m)
    d = d[:, 0, :]
    # Sort ascending (and strip the pad eigenpair) at the JAX level.
    order = jnp.argsort(d, axis=-1)
    d = jnp.take_along_axis(d, order, axis=-1)
    q = jnp.take_along_axis(q, order[:, None, :], axis=-1)
    if n_pad != n:
        keep = q[:, n, :] < 0.5                  # pad eigvec is exactly e_n
        idx = jax.vmap(lambda k: jnp.nonzero(k, size=n)[0])(keep)
        q = jax.vmap(lambda qq, ii: jnp.take(qq[:n], ii, axis=1))(q, idx)
        d = jnp.take_along_axis(d, idx, axis=-1)
    return q, d


def batched_jacobi_eigh(mats: jax.Array, sweeps: int | None = None, *,
                        force_pallas: bool | None = None,
                        interpret: bool = False):
    """Batched Brent–Luk eigh; the VMEM Pallas kernel is opt-in.

    Default is always the vmapped pure-JAX iteration. The Pallas kernel
    runs only with ``force_pallas=True`` and on real TPU fits VMEM only
    for n <= 64 (see the dispatch comment below for the v5e data);
    ``force_pallas=True, interpret=True`` exercises it on CPU.
    """
    from distributed_kfac_pytorch_tpu.ops import linalg

    n = mats.shape[-1]
    if sweeps is None:
        sweeps = linalg.default_jacobi_sweeps(n)
    # Hardware-validated on TPU v5e (2026-07): the kernel lowers and is
    # bit-correct (recon err ~2e-5 at n=64), but the slice/concat systolic
    # exchange makes Mosaic's scoped-VMEM stack hold several full-matrix
    # temporaries per round — n=128 already needs 18.7 MB against the
    # 16 MB limit, and at n<=64 the kernel (62 ms/8 mats) loses to the
    # stock vmapped XLA eigh. So the kernel stays opt-in for study
    # (force_pallas=True; tests exercise it in interpret mode) and the
    # default everywhere is the vmapped pure-JAX iteration. The
    # production fast path for large factors is the Newton-Schulz
    # inverse kernel above (flat ~25 ms/8 mats through n=512 on v5e,
    # vs 105 ms for batched XLA eigh at n=512).
    if force_pallas:
        return _pallas_batched_jacobi_eigh(mats, sweeps=sweeps,
                                           interpret=interpret)
    return jax.vmap(lambda m: linalg.jacobi_eigh(m, sweeps))(
        mats.astype(jnp.float32))


def damped_inverse_stack(stack: jax.Array, damping, method: str,
                         iters: int = 100) -> jax.Array:
    """Shared newton/cholesky dispatch for a same-size factor stack.

    Single point of truth for the single-device bucketed path
    (preconditioner.KFAC._bucketed_inverse) and the SPMD path
    (parallel.distributed._spmd_update_inverses), so algorithm changes
    stay in lockstep across both.
    """
    if method == 'newton':
        return batched_inverse(stack, damping, iters=iters)
    from distributed_kfac_pytorch_tpu.ops import linalg
    return jax.vmap(lambda m: linalg.get_inverse(m, damping=damping))(stack)


# ---------------------------------------------------------------------------
# Fused im2col + covariance kernel for conv A factors
# ---------------------------------------------------------------------------
#
# The conv A factor is cov(patches) where patches is the im2col expansion
# of the layer input — a KH*KW x blowup that the stock XLA lowering
# *materializes in HBM* (write + read of a ~300 MB tensor per stage-1
# CIFAR conv at batch 512). Measured on v5e, that traffic made the factor
# EWMA ~14 ms/iter of the tracked CIFAR config — the single largest
# K-FAC cost after round 1 eliminated the decompositions. This kernel
# fuses patch extraction into the covariance contraction: per grid step
# it loads a block of images into VMEM once, forms the patch block with
# static (strided) slices + one lane concat, and accumulates
#   A += P^T P      (MXU, fp32 accumulation)
#   s += ones @ P   (bias column sums, same pass)
# so HBM traffic is one read of x plus one (D, D) output — no patch
# tensor ever exists outside VMEM.

def _patch_cov_kernel(x_ref, a_ref, s_ref, *, kh, kw, sh, sw,
                      pads, oh, ow, mult_dtype):
    """One image block per grid step; accumulates into the same output.

    ``x_ref``: (bb, H, W, C) input block. ``a_ref``: (D, D) fp32
    accumulator, D = kh*kw*C in (ki, kj, c) feature order (matching the
    flattened flax kernel — the basis ops.factors.conv2d_a_factor
    permutes *to*; here it is constructed directly). ``s_ref``: (8, D)
    fp32 column-sum accumulator (row 0 meaningful; 8 rows for sublane
    tiling).
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        a_ref[...] = jnp.zeros_like(a_ref)
        s_ref[...] = jnp.zeros_like(s_ref)

    # Cast BEFORE assembly: the per-shift slices and the concatenated
    # patch block are the large VMEM temporaries — in bf16 they are
    # half-size, which is what lets deep-stage blocks (e.g. 56x56x64,
    # D=576: ~3.6 MB patch block) fit alongside the (D, D) accumulator.
    x = x_ref[...].astype(mult_dtype)
    bb, h, w, c = x.shape
    (ph_lo, ph_hi), (pw_lo, pw_hi) = pads
    if ph_lo or ph_hi or pw_lo or pw_hi:
        x = jnp.pad(x, ((0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi), (0, 0)))
    pieces = []
    for ki in range(kh):
        for kj in range(kw):
            sl = jax.lax.slice(
                x, (0, ki, kj, 0),
                (bb, ki + sh * (oh - 1) + 1, kj + sw * (ow - 1) + 1, c),
                (1, sh, sw, 1))
            pieces.append(sl.reshape(bb * oh * ow, c))
    p = jnp.concatenate(pieces, axis=1)
    # bf16 multiplicands ride the MXU fast path (the default covariance
    # precision contract); fp32 multiplicands request HIGHEST for the
    # strict-fp32 contract (ops.factors.get_cov).
    prec = (None if mult_dtype == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)
    a_ref[...] += jnp.dot(p.T, p, preferred_element_type=jnp.float32,
                          precision=prec)
    ones = jnp.ones((8, p.shape[0]), mult_dtype)
    s_ref[...] += jnp.dot(ones, p, preferred_element_type=jnp.float32,
                          precision=prec)


@functools.partial(
    jax.jit, static_argnames=('kernel_size', 'strides', 'pads',
                              'block_batch', 'mult_bf16', 'interpret'))
def _pallas_patch_cov(x: jax.Array, *, kernel_size, strides, pads,
                      block_batch: int, mult_bf16: bool,
                      interpret: bool = False):
    """(B, H, W, C) NHWC -> (cov (D, D) fp32, colsum (D,) fp32).

    ``cov`` is the *sum* over all B*OH*OW patch rows of p p^T (caller
    applies the 1/scale); ``colsum`` the per-feature row sum.
    """
    from jax.experimental import pallas as pl  # noqa: F811 (module use)
    from jax.experimental.pallas import tpu as pltpu

    b, h, w, c = x.shape
    kh, kw = kernel_size
    sh, sw = strides
    (ph_lo, ph_hi), (pw_lo, pw_hi) = pads
    oh = (h + ph_lo + ph_hi - kh) // sh + 1
    ow = (w + pw_lo + pw_hi - kw) // sw + 1
    d = kh * kw * c
    if b % block_batch:
        raise ValueError(f'batch {b} not divisible by {block_batch=}')
    mult_dtype = jnp.bfloat16 if mult_bf16 else jnp.float32

    kernel = functools.partial(
        _patch_cov_kernel, kh=kh, kw=kw, sh=sh, sw=sw, pads=pads,
        oh=oh, ow=ow, mult_dtype=mult_dtype)
    cov, s = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((d, d), jnp.float32),
                   jax.ShapeDtypeStruct((8, d), jnp.float32)),
        grid=(b // block_batch,),
        in_specs=[pl.BlockSpec((block_batch, h, w, c),
                               lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((d, d), lambda i: (0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((8, d), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(x)
    return cov, s[0]


@functools.lru_cache(maxsize=1)
def fused_patch_cov_supported() -> bool:
    """One-time probe: can the fused kernel compile AND run here?

    Mosaic failures (VMEM overflow, unsupported lowering) surface at
    jit-compile or run time — not as catchable trace-time errors at the
    dispatch site — so the dispatcher calls this once per process and
    falls back to the XLA path for good if the probe fails. The kernel
    itself is opt-in (KFAC_FUSED_PATCH_COV=1 at the dispatch site,
    factors.conv2d_a_factor) — not opting in is the only disable switch.
    """
    if _forced_fallback():
        record_fallback('patch_cov', 'forced by KFAC_PALLAS_FALLBACK')
        return False
    if jax.default_backend() != 'tpu':
        return False
    try:
        import numpy as np

        from distributed_kfac_pytorch_tpu.ops import factors as F
        x = jnp.asarray(np.linspace(0, 1, 4 * 8 * 8 * 3, dtype='float32')
                        .reshape(4, 8, 8, 3))
        # Reference computed INLINE (not via conv2d_a_factor, whose TPU
        # dispatch would re-enter this probe): same formula/scale/bias
        # assembly as conv_a_factor_fused.
        p2 = np.asarray(F.extract_conv2d_patches(
            x, (3, 3), (1, 1), 'SAME')).reshape(-1, 27).astype(np.float64)
        spatial = 64
        rows = p2.shape[0]
        cov = (p2.T @ p2) / (rows * spatial * spatial)
        bias_col = p2.mean(0) / (spatial * spatial)
        # kfaclint: waive[host-np-asarray] documented blocking point: once-per-process kernel parity probe, off the step path
        ref = np.asarray(F._assemble_bias_factor(
            jnp.asarray(cov, jnp.float32), jnp.asarray(bias_col,
                                                       jnp.float32),
            1.0 / (spatial * spatial)))
        got = np.asarray(conv_a_factor_fused(
            x, (3, 3), (1, 1), 'SAME', True, mult_bf16=True))
        rel = (np.abs(got - ref).max()
               / max(float(np.abs(ref).max()), 1e-30))
        ok = bool(np.isfinite(got).all()) and rel < 5e-2
        if not ok:
            record_fallback('patch_cov',
                            f'parity probe rel error {rel:.3g} >= 5e-2')
        return ok
    except Exception as e:
        record_fallback('patch_cov',
                        f'probe failed: {type(e).__name__}: {e}')
        return False


def _fused_block_batch(b: int, bytes_per_img: int, budget: int) -> int:
    """Largest divisor of ``b`` whose image block fits ``budget`` bytes.

    Returns 0 when every fitting divisor sits below
    ``MIN_FUSED_BLOCK_BATCH`` (prime batch sizes degrade all the way to
    block_batch=1 — one image per grid step, a matmul far too thin to
    amortize the patch assembly): the caller warns and falls back to
    the XLA path rather than silently running the degenerate kernel.
    Batches smaller than the floor are exempt (the whole batch is one
    block; nothing was degraded).
    """
    block = max(1, budget // max(1, bytes_per_img))
    block = min(block, b)
    while b % block:
        block -= 1
    if block < min(b, MIN_FUSED_BLOCK_BATCH):
        return 0
    return block


def conv_a_factor_fused(a: jax.Array, kernel_size, strides, padding,
                        has_bias: bool, *, mult_bf16: bool = True,
                        block_batch: int | None = None,
                        interpret: bool = False) -> jax.Array:
    """Conv A factor via the fused VMEM patch-covariance kernel.

    Drop-in equal to ``ops.factors.conv2d_a_factor`` (same value up to
    matmul rounding; same (kh, kw, c) feature basis and bias assembly)
    for symmetric spatial padding. ``mult_bf16`` matches the default
    covariance precision contract (bf16 multiplicands, fp32
    accumulation — see ops.factors.get_cov); pass False for strict-fp32
    multiplicands.
    """
    from distributed_kfac_pytorch_tpu.ops import factors as F

    b, h, w, c = a.shape
    kh, kw = kernel_size
    sh, sw = strides
    pads = _canonical_pad(padding, (kh, kw), (h, w), (sh, sw))
    (ph_lo, ph_hi), (pw_lo, pw_hi) = pads
    oh = (h + ph_lo + ph_hi - kh) // sh + 1
    ow = (w + pw_lo + pw_hi - kw) // sw + 1
    if block_batch is None:
        # VMEM budget: the patch block materializes ~twice (per-shift
        # pieces + their concat), plus the padded x copy (mult dtype),
        # plus the fp32 input block (x2 for Mosaic double-buffering);
        # the fp32 (D, D) + (8, D) accumulators are resident throughout
        # (x1.5 headroom). Target <= ~10 MB of the ~16 MB/core.
        mult_bytes = 2 if mult_bf16 else 4
        d_full = kh * kw * c
        fixed = int(1.5 * (d_full * d_full + 8 * d_full) * 4)
        bytes_per_img = (2 * oh * ow * d_full * mult_bytes
                         + (h + ph_lo + ph_hi) * (w + pw_lo + pw_hi)
                         * c * mult_bytes
                         + 2 * h * w * c * 4)
        # Mosaic's scoped-vmem accounting runs ~2.5x this byte model
        # (measured: a 10 MB target allocated 24.4 MB of the 16 MB
        # limit at (512,32,32,16)); target 4 MB so real usage stays
        # within limits in any surrounding program.
        budget = int(4e6) - fixed
        block_batch = _fused_block_batch(b, bytes_per_img, budget)
        if not block_batch:
            record_fallback(
                'patch_cov',
                f'batch {b} has no divisor >= {MIN_FUSED_BLOCK_BATCH} '
                f'within the VMEM budget for shape {a.shape} — the '
                'degraded block would destroy kernel efficiency')
            raise ValueError(
                f'no usable block_batch for batch {b} at this shape')
    spatial = oh * ow
    rows = b * spatial
    cov, colsum = _pallas_patch_cov(
        a, kernel_size=(kh, kw), strides=(sh, sw), pads=pads,
        block_batch=block_batch, mult_bf16=mult_bf16,
        interpret=interpret)
    cov = cov * (1.0 / (rows * spatial * spatial))
    if not has_bias:
        return cov
    bias_col = colsum * (1.0 / (rows * spatial * spatial))
    return F._assemble_bias_factor(cov, bias_col, 1.0 / (spatial * spatial))


def _canonical_pad(padding, kernel_size, spatial, strides):
    """Per-axis (lo, hi) pad amounts matching XLA conventions.

    'SAME' follows the XLA/TF formula — total = max((ceil(dim/s)-1)*s
    + k - dim, 0), lo = total // 2, hi = total - lo (extra on the high
    side; asymmetric for strided convs) — so the kernel reproduces
    conv_general_dilated_patches exactly. Also accepts 'VALID', int,
    and explicit ((lo, hi), (lo, hi)) pairs.
    """
    kh, kw = kernel_size
    h, w = spatial
    sh, sw = strides
    if isinstance(padding, str):
        if padding.upper() == 'VALID':
            return ((0, 0), (0, 0))
        if padding.upper() == 'SAME':
            out = []
            for dim, k, s in ((h, kh, sh), (w, kw, sw)):
                o = -(-dim // s)
                total = max((o - 1) * s + k - dim, 0)
                out.append((total // 2, total - total // 2))
            return tuple(out)
        raise ValueError(f'unsupported padding {padding!r}')
    if isinstance(padding, int):
        return ((padding, padding), (padding, padding))
    (a, b), (c, d) = padding
    return ((a, b), (c, d))


# ---------------------------------------------------------------------------
# Fused symmetric factor contraction + EMA kernel (r21)
# ---------------------------------------------------------------------------
#
# The per-step factor cost every user pays is the rank-k contraction
# A^T A plus the EMA blend against the running factor — stock XLA
# writes the full (d, d) covariance to HBM, reads it back for the
# blend, and writes the full (d, d) result. This kernel keeps the
# accumulator in VMEM across the row blocks, folds the bias
# row/column and the EMA blend into the finalize step, and writes only
# the symmetry-packed (d/2+1, d) triangle to HBM (the block-symmetry
# layout factors.pack_symmetric already uses on the wire): roughly
# half the output traffic and no intermediate covariance round trip.
# With decay=0 / old=None it degenerates to a packed contraction-only
# kernel (the SPMD local-contribution path, where a collective sits
# between contraction and EMA).

def _factor_ema_kernel(x_ref, old_ref, decay_ref, out_ref, acc_ref,
                       s_ref, *, nsteps: int, scale: float, rows: int,
                       d_in: int, has_bias: bool, corner: float,
                       d_pad: int, mult_dtype):
    """One row block per grid step; finalize on the last step.

    ``x_ref``: (block_rows, d_pad) zero-padded input rows. ``old_ref``:
    (d_pad, d_pad) zero-padded running factor. ``decay_ref``: (1, 1)
    SMEM EMA coefficient (alpha; the blend is
    ``alpha * old + (1 - alpha) * cov``, factors.update_running_avg).
    ``out_ref``: the (d_pad//2+1, d_pad) packed triangle.
    ``acc_ref``/``s_ref``: VMEM scratch — the fp32 covariance
    accumulator and the (8, d_pad) bias column-sum (row 0 meaningful).
    """
    from jax.experimental import pallas as pl

    from distributed_kfac_pytorch_tpu.ops import factors as F

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        s_ref[...] = jnp.zeros_like(s_ref)

    xb = x_ref[...].astype(mult_dtype)
    # bf16 multiplicands ride the MXU fast path (the default covariance
    # precision contract); fp32 multiplicands request HIGHEST for the
    # strict-fp32 contract (ops.factors.get_cov).
    prec = (None if mult_dtype == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)
    acc_ref[...] += jnp.dot(xb.T, xb, preferred_element_type=jnp.float32,
                            precision=prec)
    if has_bias:
        ones = jnp.ones((8, xb.shape[0]), mult_dtype)
        s_ref[...] += jnp.dot(ones, xb,
                              preferred_element_type=jnp.float32,
                              precision=prec)

    @pl.when(i == nsteps - 1)
    def _finalize():
        acc = acc_ref[...]
        cov = (acc + acc.T) * (0.5 / scale)
        if has_bias:
            # The analytic bias assembly of F._assemble_bias_factor in
            # padded space: the bias row/column live at index d_in
            # (zero in the accumulator — the padded features are zero),
            # written as the two rank-1 outer products via 2-D masks.
            ri = jax.lax.broadcasted_iota(jnp.int32, (d_pad, d_pad), 0)
            ci = jax.lax.broadcasted_iota(jnp.int32, (d_pad, d_pad), 1)
            oh_r = (ri == d_in).astype(jnp.float32)
            oh_c = (ci == d_in).astype(jnp.float32)
            bias_row = s_ref[...][0:1, :] * (1.0 / rows)
            b_cols = (jnp.broadcast_to(bias_row, (d_pad, d_pad))
                      + (corner / 2.0) * oh_c)
            cov = cov + oh_r * b_cols + oh_c * b_cols.T
        dec = decay_ref[0, 0]
        ema = dec * old_ref[...] + (1.0 - dec) * cov
        # Only the packed triangle leaves VMEM. pack_symmetric is
        # gather-free (triu/tril/slice/concat) so it traces inside the
        # kernel; d_pad is lane-padded (even), so no internal repad.
        out_ref[...] = F.pack_symmetric(ema)


@functools.partial(
    jax.jit, static_argnames=('scale', 'rows', 'd_in', 'has_bias',
                              'corner', 'block_rows', 'mult_bf16',
                              'interpret'))
def _pallas_factor_ema(x: jax.Array, old: jax.Array, decay: jax.Array,
                       *, scale: float, rows: int, d_in: int,
                       has_bias: bool, corner: float, block_rows: int,
                       mult_bf16: bool, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows_pad, d_pad = x.shape
    nsteps = rows_pad // block_rows
    k1 = d_pad // 2 + 1
    mult_dtype = jnp.bfloat16 if mult_bf16 else jnp.float32
    kernel = functools.partial(
        _factor_ema_kernel, nsteps=nsteps, scale=scale, rows=rows,
        d_in=d_in, has_bias=has_bias, corner=corner, d_pad=d_pad,
        mult_dtype=mult_dtype)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((k1, d_pad), jnp.float32),
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((block_rows, d_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d_pad, d_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((k1, d_pad), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((d_pad, d_pad), jnp.float32),
                        pltpu.VMEM((8, d_pad), jnp.float32)],
        interpret=interpret,
    )(x, old, decay)


def fused_factor_ema(x: jax.Array, old: jax.Array | None, decay, *,
                     scale: float | None = None, has_bias: bool = False,
                     corner: float = 1.0, compute_dtype=None,
                     interpret: bool = False) -> jax.Array:
    """Covariance factor + EMA blend in one packed-output VMEM kernel.

    Drop-in for ``update_running_avg(linear_a_factor(x, has_bias), old,
    decay)`` (and the G-side / conv-G analogues via ``scale``): ``x``
    is the (rows, d_in) collapsed activation/grad tensor, ``old`` the
    dense (d, d) running factor (``d = d_in + 1`` with bias), ``decay``
    the EMA alpha (traced OK — it is a kernel input, not a variant
    key). ``old=None`` means contraction-only (decay pinned to 0): the
    SPMD local-contribution form, and the r14 accumulator fold reuses
    the blend with ``old=accum``. Returns the dense (d, d) fp32 factor;
    only the packed triangle crossed HBM out of the kernel.

    ``compute_dtype`` follows the ops.factors.get_cov contract: None ->
    backend-native multiplicands (bf16 on TPU), float32 -> strict fp32
    at HIGHEST, bfloat16 -> explicit bf16 multiplicands. Accumulation
    is always fp32.
    """
    from distributed_kfac_pytorch_tpu.ops import factors as F

    x = x.reshape(-1, x.shape[-1])
    rows, d_in = x.shape
    n = d_in + 1 if has_bias else d_in
    if scale is None:
        scale = rows
    d_pad = _round_up(max(n, 8), _LANE)
    block_rows = 512 if rows >= 512 else _round_up(rows, 8)
    rows_pad = _round_up(rows, block_rows)
    mult_bf16 = (
        (compute_dtype is not None
         and jnp.dtype(compute_dtype) == jnp.bfloat16)
        or (compute_dtype is None and jax.default_backend() == 'tpu'))
    xp = jnp.pad(x.astype(jnp.float32),
                 ((0, rows_pad - rows), (0, d_pad - d_in)))
    if old is None:
        oldp = jnp.zeros((d_pad, d_pad), jnp.float32)
        decay = 0.0
    else:
        oldp = jnp.pad(old.astype(jnp.float32),
                       ((0, d_pad - n), (0, d_pad - n)))
    dec = jnp.asarray(decay, jnp.float32).reshape(1, 1)
    packed = _pallas_factor_ema(
        xp, oldp, dec, scale=float(scale), rows=rows, d_in=d_in,
        has_bias=has_bias, corner=float(corner), block_rows=block_rows,
        mult_bf16=mult_bf16, interpret=interpret)
    return F.unpack_symmetric(packed, d_pad)[:n, :n]


@functools.lru_cache(maxsize=1)
def fused_factor_ema_supported() -> bool:
    """Once-per-process gate for the fused contraction+EMA kernel.

    Same contract as :func:`fused_patch_cov_supported`: Mosaic failures
    surface at compile/run time, so the dispatchers (KFAC.update_factors
    / accumulate_factors, parallel.distributed.local_factor_contribs)
    call this once and fall back to the stock XLA factor path for good
    if it fails — recorded via :func:`record_fallback`, never silent.
    On non-TPU backends the kernel runs in interpret mode (the parity
    tests and the CI smoke exercise the real kernel body on CPU), so
    the probe passes trivially there; KFAC_PALLAS_FALLBACK=1 forces a
    recorded failure everywhere.
    """
    if _forced_fallback():
        record_fallback('factor_ema', 'forced by KFAC_PALLAS_FALLBACK')
        return False
    if jax.default_backend() != 'tpu':
        return True
    try:
        import numpy as np

        from distributed_kfac_pytorch_tpu.ops import factors as F
        x = jnp.asarray(np.linspace(-1.0, 1.0, 16 * 12, dtype='float32')
                        .reshape(16, 12))
        old = jnp.eye(13, dtype=jnp.float32) * 0.5
        ref = F.update_running_avg(
            F.linear_a_factor(x, True), old, 0.9)
        got = fused_factor_ema(x, old, 0.9, has_bias=True)
        got_h, ref_h = np.asarray(got), np.asarray(ref)
        rel = (np.abs(got_h - ref_h).max()
               / max(float(np.abs(ref_h).max()), 1e-30))
        ok = bool(np.isfinite(got_h).all()) and rel < 5e-2
        if not ok:
            record_fallback('factor_ema',
                            f'parity probe rel error {rel:.3g} >= 5e-2')
        return ok
    except Exception as e:
        record_fallback('factor_ema',
                        f'probe failed: {type(e).__name__}: {e}')
        return False


# ---------------------------------------------------------------------------
# Fused bucketed precondition kernel with KL-clip epilogue (r21)
# ---------------------------------------------------------------------------
#
# The bucketed precondition path stacks same-shape layer grads and
# vmaps the two-sided inverse application; the r6 KL-clip then pays a
# separate full-tensor pass re-reading every preconditioned matrix to
# reduce sum(v * g). This kernel keeps one bucket slice resident in
# VMEM for the whole chain — eigen (QG^T g QA rescale) or baked
# (G_inv g A_inv) — and reduces the slice's v·g partial in the
# epilogue while v is still on-chip, so the clip pass costs zero extra
# HBM reads. Truncated r19 eigen bases are not eligible (static
# ``_truncated_side`` check at the dispatch sites).

def _bucket_precond_kernel(g_ref, right_ref, left_ref, da_ref, dg_ref,
                           damp_ref, v_ref, vg_ref, *, eigen: bool,
                           mult_dtype):
    """One bucket slice per grid cell.

    ``right_ref``/``left_ref``: QA/QG (eigen) or A_inv/G_inv (baked).
    ``da_ref``: (1, 8, a_pad) eigenvalue row (row 0 meaningful, padded
    with ones); ``dg_ref``: (1, g_pad, 128) eigenvalue column (lane 0
    meaningful, padded with ones) — both ignored on the baked branch.
    ``damp_ref``: (1, 1) SMEM damping. ``v_ref``: the preconditioned
    slice; ``vg_ref``: (1, 8, 128) sublane/lane-replicated
    ``sum(v * g)`` KL-clip partial (caller reads [0, 0]).
    """
    prec = (None if mult_dtype == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)
    dot = functools.partial(jnp.dot,
                            preferred_element_type=jnp.float32,
                            precision=prec)
    g32 = g_ref[0].astype(jnp.float32)
    g = g32.astype(mult_dtype)
    if eigen:
        qa = right_ref[0].astype(mult_dtype)
        qg = left_ref[0].astype(mult_dtype)
        v1 = dot(dot(qg.T, g), qa)
        da = da_ref[0][0:1, :]                    # (1, a_pad)
        dg = dg_ref[0][:, 0:1]                    # (g_pad, 1)
        v2 = v1 / (dg * da + damp_ref[0, 0])
        v = dot(dot(qg, v2.astype(mult_dtype)), qa.T)
    else:
        a_inv = right_ref[0].astype(mult_dtype)
        g_inv = left_ref[0].astype(mult_dtype)
        v = dot(dot(g_inv, g), a_inv)
    v_ref[0] = v
    # Zero feature padding keeps the padded entries of v exactly zero
    # (zero rows/cols of Q and the inverses), so the full-block
    # reduction equals the unpadded v.g partial.
    vg_ref[0] = jnp.broadcast_to(jnp.sum(v * g32), (8, 128))


@functools.partial(jax.jit,
                   static_argnames=('eigen', 'mult_bf16', 'interpret'))
def _pallas_bucket_precond(gstack, left, right, dg, da, damping, *,
                           eigen: bool, mult_bf16: bool,
                           interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, gp, ap = gstack.shape
    mult_dtype = jnp.bfloat16 if mult_bf16 else jnp.float32
    kernel = functools.partial(_bucket_precond_kernel, eigen=eigen,
                               mult_dtype=mult_dtype)
    v, vg = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((s, gp, ap), jnp.float32),
                   jax.ShapeDtypeStruct((s, 8, 128), jnp.float32)),
        grid=(s,),
        in_specs=[
            pl.BlockSpec((1, gp, ap), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ap, ap), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, gp, gp), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, ap), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, gp, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=(pl.BlockSpec((1, gp, ap), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, 8, 128), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(gstack, right, left, da, dg, damping)
    return v, vg[:, 0, 0]


def fused_bucket_precondition(gstack: jax.Array, entry: dict, damping,
                              *, compute_dtype=None,
                              interpret: bool = False):
    """Bucketed precondition with the KL-clip v·g partial fused in.

    ``gstack`` is the (S, g_dim, a_dim) same-shape gradient stack;
    ``entry`` the stacked inverse slots — baked ``{'A_inv', 'G_inv'}``
    or full-rank eigen ``{'QA', 'dA', 'QG', 'dG'}`` (truncated r19
    bases are NOT eligible; dispatch them to the stock XLA path).
    Returns ``(vstack, vg)``: the (S, g_dim, a_dim) fp32 preconditioned
    stack and the (S,) fp32 per-slice ``sum(v * grad)`` partials — the
    KL-clip term before the caller's lr^2 factor.
    """
    s, g_dim, a_dim = gstack.shape
    gp = _round_up(max(g_dim, 8), _LANE)
    ap = _round_up(max(a_dim, 8), _LANE)
    eigen = 'QA' in entry
    gpad = jnp.pad(gstack.astype(jnp.float32),
                   ((0, 0), (0, gp - g_dim), (0, ap - a_dim)))
    if eigen:
        right = jnp.pad(entry['QA'].astype(jnp.float32),
                        ((0, 0), (0, ap - a_dim), (0, ap - a_dim)))
        left = jnp.pad(entry['QG'].astype(jnp.float32),
                       ((0, 0), (0, gp - g_dim), (0, gp - g_dim)))
        # Eigenvalue padding is ONES so the padded denominators are
        # 1 + damping (never 0/0); the padded v1 entries are zero, so
        # the padded v2/v stay exactly zero.
        da = jnp.pad(entry['dA'].astype(jnp.float32),
                     ((0, 0), (0, ap - a_dim)), constant_values=1.0)
        dg = jnp.pad(entry['dG'].astype(jnp.float32),
                     ((0, 0), (0, gp - g_dim)), constant_values=1.0)
        da = jnp.broadcast_to(da[:, None, :], (s, 8, ap))
        dg = jnp.broadcast_to(dg[:, :, None], (s, gp, 128))
    else:
        right = jnp.pad(entry['A_inv'].astype(jnp.float32),
                        ((0, 0), (0, ap - a_dim), (0, ap - a_dim)))
        left = jnp.pad(entry['G_inv'].astype(jnp.float32),
                       ((0, 0), (0, gp - g_dim), (0, gp - g_dim)))
        da = jnp.zeros((s, 8, ap), jnp.float32)
        dg = jnp.zeros((s, gp, 128), jnp.float32)
    damp = jnp.asarray(damping, jnp.float32).reshape(1, 1)
    mult_bf16 = (compute_dtype is not None
                 and jnp.dtype(compute_dtype) == jnp.bfloat16)
    v, vg = _pallas_bucket_precond(gpad, left, right, dg, da, damp,
                                   eigen=eigen, mult_bf16=mult_bf16,
                                   interpret=interpret)
    return v[:, :g_dim, :a_dim], vg


@functools.lru_cache(maxsize=1)
def fused_precondition_supported() -> bool:
    """Once-per-process gate for the fused bucket-precondition kernel
    (same contract as :func:`fused_factor_ema_supported`)."""
    if _forced_fallback():
        record_fallback('bucket_precond',
                        'forced by KFAC_PALLAS_FALLBACK')
        return False
    if jax.default_backend() != 'tpu':
        return True
    try:
        import numpy as np

        from distributed_kfac_pytorch_tpu.ops import linalg
        rng = np.random.default_rng(0)
        g = jnp.asarray(rng.normal(size=(2, 8, 12)).astype('float32'))
        qa = jnp.asarray(np.linalg.qr(
            rng.normal(size=(2, 12, 12)))[0].astype('float32'))
        qg = jnp.asarray(np.linalg.qr(
            rng.normal(size=(2, 8, 8)))[0].astype('float32'))
        da = jnp.asarray(
            rng.uniform(0.5, 2.0, (2, 12)).astype('float32'))
        dg = jnp.asarray(
            rng.uniform(0.5, 2.0, (2, 8)).astype('float32'))
        entry = {'QA': qa, 'dA': da, 'QG': qg, 'dG': dg}
        ref = jax.vmap(lambda gm, e: linalg.precondition_dispatch(
            gm, e, 0.003))(g, entry)
        got, vg = fused_bucket_precondition(g, entry, 0.003)
        vg_ref = jnp.sum(ref * g, axis=(1, 2))
        got_h, ref_h = np.asarray(got), np.asarray(ref)
        vg_h, vg_ref_h = np.asarray(vg), np.asarray(vg_ref)
        rel = (np.abs(got_h - ref_h).max()
               / max(float(np.abs(ref_h).max()), 1e-30))
        rel_vg = (np.abs(vg_h - vg_ref_h).max()
                  / max(float(np.abs(vg_ref_h).max()), 1e-30))
        ok = (bool(np.isfinite(got_h).all()) and rel < 5e-2
              and rel_vg < 5e-2)
        if not ok:
            record_fallback(
                'bucket_precond',
                f'parity probe rel error v={rel:.3g} vg={rel_vg:.3g}')
        return ok
    except Exception as e:
        record_fallback('bucket_precond',
                        f'probe failed: {type(e).__name__}: {e}')
        return False
