"""Kronecker-factor statistics ops (pure jnp; jit/vmap/shard_map friendly).

Numerics parity with the reference formulas in kfac/layers/utils.py:13-178 and
kfac/layers/{linear.py,conv.py}, re-expressed functionally: no in-place
mutation, NHWC conv layout, and patch extraction via XLA's
``conv_general_dilated_patches`` instead of torch ``unfold`` (im2col).
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_kfac_pytorch_tpu.observability import profiling


def append_bias_ones(x: jax.Array) -> jax.Array:
    """Append a column of ones to the last dim (homogeneous coordinates).

    Reference parity: kfac/layers/utils.py:4-11.
    """
    ones = jnp.ones((*x.shape[:-1], 1), dtype=x.dtype)
    return jnp.concatenate([x, ones], axis=-1)


def get_cov(a: jax.Array, b: jax.Array | None = None,
            scale: float | None = None,
            compute_dtype=None) -> jax.Array:
    """Empirical second moment ``a^T @ b / scale`` of 2-D tensors.

    When ``b`` is None the result is explicitly symmetrized,
    ``(C + C^T) / 2``, to suppress float round-off asymmetry.

    ``compute_dtype`` casts the matmul *inputs* (e.g. to bfloat16 for the
    MXU fast path) while always accumulating in float32 — the TPU
    analogue of the reference's keep-autocast-dtype factor policy
    (README.md:150-160); the returned covariance is float32.

    Precision semantics (decided on measured v5e behavior):

      - ``compute_dtype=None`` (default): the backend's native matmul
        precision. On TPU that rounds fp32 inputs to bf16 before the
        MXU with fp32 accumulation (``preferred_element_type`` pins the
        accumulator only) — ~4e-3 relative covariance error, measured.
        This is the fast path and the production default: the factor
        EWMA runs every ``factor_update_freq`` steps on batch-sized
        tensors, and forcing 6-pass fp32 emulation here costs more than
        the whole amortized decomposition pipeline (+15 ms/iter on the
        tracked CIFAR config).
      - ``compute_dtype=jnp.float32``: *strict* fp32 — inputs cast to
        fp32 and the contraction runs at ``Precision.HIGHEST``
        (numerics parity with the reference's fp32 factors,
        kfac/layers/utils.py:40-43).
      - ``compute_dtype=jnp.bfloat16``: explicit bf16 inputs (the
        reference's ``--fp16`` factor mode analogue) — same MXU cost as
        the default on TPU, and makes the choice visible in configs.

    Reference parity: kfac/layers/utils.py:13-43.
    """
    if a.ndim != 2:
        raise ValueError(f'get_cov expects a 2-D tensor, got shape {a.shape}')
    if b is not None and a.shape != b.shape:
        raise ValueError(f'shape mismatch: {a.shape} vs {b.shape}')
    if scale is None:
        scale = a.shape[0]
    precision = None
    if compute_dtype is not None:
        a = a.astype(compute_dtype)
        b = b if b is None else b.astype(compute_dtype)
        if jnp.dtype(compute_dtype) == jnp.float32:
            precision = jax.lax.Precision.HIGHEST
    # Scale the (small) covariance output, not the (batch-sized) input:
    # an elementwise divide of the input materializes a full copy of a
    # tensor that is ~300 MB per conv layer at production batch sizes —
    # profiled on v5e, those copies dominated the whole K-FAC step.
    if b is None:
        cov = jnp.matmul(a.T, a, preferred_element_type=jnp.float32,
                         precision=precision)
        return (cov + cov.T) * (0.5 / scale)
    return jnp.matmul(a.T, b, preferred_element_type=jnp.float32,
                      precision=precision) * (1.0 / scale)


def update_running_avg(new: jax.Array, current: jax.Array,
                       alpha: float) -> jax.Array:
    """EWMA ``alpha * current + (1 - alpha) * new`` (functional, not in-place).

    Reference parity: kfac/layers/utils.py:164-178 (there, ``alpha`` is the
    ``factor_decay`` hyperparameter, default 0.95).
    """
    return alpha * current + (1.0 - alpha) * new


def collapse_batch_dims(x: jax.Array) -> jax.Array:
    """Collapse all but the last dim: (..., d) -> (prod(...), d).

    Functional analogue of the reference's accumulate-then-reshape
    (kfac/layers/utils.py:107-124): in JAX the captures arrive as one array,
    so concatenation over the accumulation list collapses into this reshape.
    """
    return x.reshape(-1, x.shape[-1])


# ---------------------------------------------------------------------------
# Per-layer-kind factor statistics
# ---------------------------------------------------------------------------

def _column_mean(x: jax.Array) -> jax.Array:
    """Column mean of a 2-D tensor as a ones-row matmul (fp32 accumulate).

    Expressed as a matmul rather than ``jnp.sum(x, axis=0)``: the batched
    column reduction rides the MXU on TPU, and the reduction form
    segfaults XLA:CPU inside large shard_map programs (bisected on the
    distributed embedding-parity test; same fragility class as the
    gather note in :func:`pack_symmetric`).
    """
    ones = jnp.ones((1, x.shape[0]), jnp.float32)
    # HIGHEST: the TPU-default matmul precision would round the fp32
    # inputs to bf16 on the MXU (see get_cov's precision note).
    return jnp.matmul(ones, x.astype(jnp.float32),
                      precision=jax.lax.Precision.HIGHEST)[0] / x.shape[0]


def _assemble_bias_factor(cov: jax.Array, bias_col: jax.Array,
                          corner) -> jax.Array:
    """[[cov, b], [b^T, corner]] — the covariance of rows with an appended
    ones column, built without ever materializing the (batch, dim + 1)
    concatenation (a full copy of the activation/patch tensor).

    Assembled as pad + two rank-1 outer products rather than block
    concatenation (keeps every op elementwise/pad — the most portable
    fusion-friendly form on both TPU and XLA:CPU).
    """
    d = cov.shape[0]
    padded = jnp.pad(cov, ((0, 1), (0, 1)))
    onehot = (jnp.arange(d + 1) == d).astype(cov.dtype)
    b_ext = jnp.pad(bias_col, (0, 1)) + (corner / 2.0) * onehot
    return padded + jnp.outer(onehot, b_ext) + jnp.outer(b_ext, onehot)


@profiling.scope('kfac/factors/linear_a')
def linear_a_factor(a: jax.Array, has_bias: bool,
                    compute_dtype=None) -> jax.Array:
    """A = cov(inputs (+ ones column)) for a dense layer.

    ``a`` may have arbitrary leading dims (batch, time, ...); they are
    collapsed. Reference parity: kfac/layers/linear.py:12-18; the bias
    row/column ``[sum(a)/n, 1]`` is assembled analytically instead of
    concatenating a ones column onto the batch tensor.
    """
    a = collapse_batch_dims(a)
    cov = get_cov(a, compute_dtype=compute_dtype)
    if not has_bias:
        return cov
    bias_col = _column_mean(a).astype(cov.dtype)
    return _assemble_bias_factor(cov, bias_col, 1.0)


@profiling.scope('kfac/factors/linear_g')
def linear_g_factor(g: jax.Array, compute_dtype=None) -> jax.Array:
    """G = cov(grad wrt layer outputs) for a dense layer.

    Reference parity: kfac/layers/linear.py:20-24.
    """
    return get_cov(collapse_batch_dims(g), compute_dtype=compute_dtype)


# ---------------------------------------------------------------------------
# KFAC-reduce: sum/mean over the shared (sequence/patch) axis BEFORE the
# covariance (arXiv:2311.00636; see sharing.approx for the policy layer)
# ---------------------------------------------------------------------------

def _reduce_shared_axes(x: jax.Array, mean: bool) -> jax.Array:
    """Reduce ``(B, *, d)`` over the middle (shared) axes -> ``(B, d)``.

    Expressed as a batched ones-row matmul rather than ``jnp.sum/mean``
    over the axis — the same portability rule as :func:`_column_mean`
    (axis reductions segfault XLA:CPU inside large shard_map programs,
    and the batched column reduction rides the MXU on TPU). Accumulates
    fp32 (``preferred_element_type``) and returns fp32 rows; the
    downstream covariance's ``compute_dtype`` governs the contraction
    inputs exactly as on the expand path.
    """
    if x.ndim <= 2:
        return x.astype(jnp.float32)
    b, d = x.shape[0], x.shape[-1]
    t = int(np.prod(x.shape[1:-1]))
    x3 = x.reshape(b, t, d)
    ones = jnp.ones((1, t), x3.dtype)
    out = jnp.matmul(ones, x3, preferred_element_type=jnp.float32)[:, 0]
    return out / t if mean else out


@profiling.scope('kfac/factors/linear_a_reduced')
def linear_a_factor_reduced(a: jax.Array, has_bias: bool,
                            compute_dtype=None) -> jax.Array:
    """KFAC-reduce A for a weight-shared dense layer.

    ``a`` is ``(B, T..., d)``; the shared axes are MEAN-reduced before
    the covariance — the paper's Eq. 22 convention, under which the
    appended bias column reduces to exactly 1 (an average of ones), so
    the bias row/column assembly is the ordinary
    :func:`linear_a_factor` over the ``(B, d)`` reduced rows. Scale is
    the reduced row count ``B`` (vs expand's ``B*T``): the factor
    contraction — the dominant factor-phase cost on transformer
    workloads — is a factor ``T`` cheaper. Degenerates bit-identically
    to expand at T=1 (test-pinned).
    """
    return linear_a_factor(_reduce_shared_axes(a, mean=True), has_bias,
                           compute_dtype=compute_dtype)


@profiling.scope('kfac/factors/linear_g_reduced')
def linear_g_factor_reduced(g: jax.Array,
                            compute_dtype=None) -> jax.Array:
    """KFAC-reduce G for a weight-shared dense layer.

    Output-grads are SUMMED over the shared axes (the weight gradient
    is the sum over positions, so the summed probe grad keeps the
    per-sample gradient scale exact — Eq. 22's counterpart to the
    activation mean), then the covariance runs over the ``B`` rows.
    """
    return linear_g_factor(_reduce_shared_axes(g, mean=False),
                           compute_dtype=compute_dtype)


@profiling.scope('kfac/factors/conv2d_a_reduced')
def conv2d_a_factor_reduced(a: jax.Array, kernel_size, strides, padding,
                            has_bias: bool,
                            compute_dtype=None) -> jax.Array:
    """KFAC-reduce A for a patch-embedding conv (NHWC input).

    The shared axis is the conv's output-position grid: patch vectors
    are MEAN-reduced over ``(OH, OW)`` and the covariance runs over the
    ``B`` reduced rows — the paper's ViT patch-embed treatment, with
    the bias column exactly 1 (Eq. 22). Intended for non-overlapping
    patch convs (``sharing.is_patch_conv``), where the patches tile the
    image disjointly; the math is well-defined for any conv geometry.

    NOTE the scaling convention deliberately differs from the expand
    path's reference-parity ``1/(rows * spatial^2)`` folding
    (:func:`conv2d_a_factor`): reduce is a different approximation with
    its own normalization (plain covariance over reduced rows, matching
    :func:`linear_a_factor_reduced`). At OH*OW = 1 the two coincide
    bit-identically (spatial = 1 folds to nothing; test-pinned).
    """
    if (compute_dtype is None and a.dtype == jnp.float32
            and jax.default_backend() == 'tpu'):
        # Same pre-im2col bf16 contract as conv2d_a_factor: under the
        # default precision the covariance rounds to bf16 on the MXU
        # anyway; casting first halves the patch-tensor HBM traffic.
        a = a.astype(jnp.bfloat16)
    patches = extract_conv2d_patches_slices(a, kernel_size, strides,
                                            padding)
    b = patches.shape[0]
    d = patches.shape[-1]
    reduced = _reduce_shared_axes(patches.reshape(b, -1, d), mean=True)
    return linear_a_factor(reduced, has_bias,
                           compute_dtype=compute_dtype)


@profiling.scope('kfac/factors/conv2d_g_reduced')
def conv2d_g_factor_reduced(g: jax.Array,
                            compute_dtype=None) -> jax.Array:
    """KFAC-reduce G for a patch-embedding conv: output-grads summed
    over the ``(OH, OW)`` grid, covariance over the ``B`` rows (the
    counterpart of :func:`conv2d_a_factor_reduced`; same convention
    note applies)."""
    b, c = g.shape[0], g.shape[-1]
    return linear_g_factor(
        _reduce_shared_axes(g.reshape(b, -1, c), mean=False),
        compute_dtype=compute_dtype)


@profiling.scope('kfac/factors/embedding_tied_a')
def embedding_tied_a_diag(g: jax.Array) -> jax.Array:
    """Diagonal vocab-side contribution of a tied ``Embed.attend`` site.

    The attend call site's exact vocab-side factor is the dense
    ``cov(dL/dlogits)`` — ``(vocab, vocab)``, which at LM vocabularies
    would dwarf every other factor in the model. Its DIAGONAL
    (``E[g_v^2]`` per vocab entry) is the projection that preserves the
    embedding layer's diagonal-A structure, so the in/out-tied pair
    keeps ONE factor pair and ONE inverse entry: the combined A is
    ``onehot-frequency (lookup) + diag cov(attend output-grads)``.
    Matmul-form mean (see :func:`_column_mean`'s portability note).
    """
    g2 = collapse_batch_dims(g)
    return _column_mean(g2.astype(jnp.float32) ** 2)


def extract_conv2d_patches(x: jax.Array,
                           kernel_size: Sequence[int],
                           strides: Sequence[int],
                           padding) -> jax.Array:
    """im2col: (B, H, W, C) NHWC -> (B, OH, OW, KH*KW*C) patches.

    The feature dim is ordered (kh, kw, cin) with ``kh`` slowest, matching
    the row order of a flax ``nn.Conv`` kernel of shape (KH, KW, Cin, Cout)
    flattened to (KH*KW*Cin, Cout) — so the A factor and the reshaped
    gradient live in the same basis. (The reference orders (cin, kh, kw)
    to match torch's (Cout, Cin, KH, KW) kernels — conv.py:50-70; same math,
    permuted basis.)

    TPU note: ``conv_general_dilated_patches`` lowers to a convolution with
    an identity kernel, which XLA maps onto the MXU — no gather/scatter.
    """
    kh, kw = kernel_size
    c = x.shape[-1]
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=tuple(strides),
        padding=padding,
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
    # conv_general_dilated_patches emits features ordered (c, kh, kw) with
    # channel slowest; reorder to (kh, kw, c) to match the flax kernel.
    b, oh, ow = patches.shape[:3]
    patches = patches.reshape(b, oh, ow, c, kh * kw)
    patches = jnp.swapaxes(patches, -1, -2)
    return patches.reshape(b, oh, ow, kh * kw * c)


def extract_conv2d_patches_slices(x: jax.Array,
                                  kernel_size: Sequence[int],
                                  strides: Sequence[int],
                                  padding) -> jax.Array:
    """im2col via explicit pad + KH*KW static strided slices + concat.

    Same value and (kh, kw, c) feature order as
    ``extract_conv2d_patches`` but assembled from shifted views instead
    of the identity-kernel convolution that
    ``conv_general_dilated_patches`` lowers to — the conv lowering costs
    ``rows * d * d`` MXU FLOPs (as many as the covariance contraction
    itself), while slicing is pure data movement. The natural piece
    order here is (kh, kw, c), so no basis permutation is needed
    downstream.
    """
    from distributed_kfac_pytorch_tpu.ops.pallas_kernels import _canonical_pad

    kh, kw = kernel_size
    sh, sw = strides
    b, h, w, c = x.shape
    (ph_lo, ph_hi), (pw_lo, pw_hi) = _canonical_pad(
        padding, (kh, kw), (h, w), (sh, sw))
    xp = jnp.pad(x, ((0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi), (0, 0)))
    oh = (h + ph_lo + ph_hi - kh) // sh + 1
    ow = (w + pw_lo + pw_hi - kw) // sw + 1
    pieces = [
        jax.lax.slice(xp, (0, ki, kj, 0),
                      (b, ki + sh * (oh - 1) + 1, kj + sw * (ow - 1) + 1, c),
                      (1, sh, sw, 1))
        for ki in range(kh) for kj in range(kw)]
    return jnp.concatenate(pieces, axis=-1)


def _conv_a_cov_pairs(a: jax.Array, kernel_size, strides, padding,
                      compute_dtype) -> jax.Array:
    """Blocked pairwise shifted-view contraction (round-4 third angle).

    The A-factor weight block decomposes over kernel offsets:
    ``A[(i, c), (j, c')] = Σ_rows view_i[r, c] · view_j[r, c']`` where
    ``view_i`` is the i-th strided *view* of the padded input (the same
    shifted slices the ``slices`` path concatenates into the patch
    tensor). Each of the ``n(n+1)/2`` upper block pairs
    (``n = kh·kw``) is ONE ``dot_general`` contracting the
    ``(b, oh, ow)`` dims of two views directly; lower blocks are
    transposes. vs the materialized-patch path:

      - ~half the MACs — the block symmetry ``B(j,i) = B(i,j)^T`` is
        exploitable here, while the patch-Gram ``P^T P`` matmul cannot
        skip its lower triangle;
      - no ``(rows, kh·kw·c)`` patch concat is ever written — operands
        are slices of the one padded input buffer (whether XLA fuses
        the slice into the contraction or materializes per-view copies
        is the measured question; see PERF.md round 4);
      - distinct from the failed crosscov band-trace (KFAC_CONV_PATCH_
        IMPL=crosscov, the round-2 3.3x regression): rows are
        contracted directly — the (W_p·C)^2 spatial Gram never exists
        and nothing is gather-assembled.

    Returns the (d, d) fp32 Gram (sum over rows, unscaled), in the
    (kh, kw, c) feature basis.
    """
    from distributed_kfac_pytorch_tpu.ops.pallas_kernels import (
        _canonical_pad,
    )

    kh, kw = kernel_size
    sh, sw = strides
    b, h, w, c = a.shape
    (ph_lo, ph_hi), (pw_lo, pw_hi) = _canonical_pad(
        padding, (kh, kw), (h, w), (sh, sw))
    xp = jnp.pad(a, ((0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi), (0, 0)))
    oh = (h + ph_lo + ph_hi - kh) // sh + 1
    ow = (w + pw_lo + pw_hi - kw) // sw + 1
    precision = None
    if compute_dtype is not None:
        xp = xp.astype(compute_dtype)
        if jnp.dtype(compute_dtype) == jnp.float32:
            precision = jax.lax.Precision.HIGHEST
    views = [
        jax.lax.slice(xp, (0, ki, kj, 0),
                      (b, ki + sh * (oh - 1) + 1,
                       kj + sw * (ow - 1) + 1, c),
                      (1, sh, sw, 1))
        for ki in range(kh) for kj in range(kw)]
    n = kh * kw
    blocks: dict[tuple[int, int], jax.Array] = {}
    for i in range(n):
        for j in range(i, n):
            blocks[(i, j)] = jax.lax.dot_general(
                views[i], views[j],
                dimension_numbers=(((0, 1, 2), (0, 1, 2)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=precision)
    gram = jnp.concatenate(
        [jnp.concatenate(
            [blocks[(i, j)] if i <= j else blocks[(j, i)].T
             for j in range(n)], axis=1)
         for i in range(n)], axis=0)
    # Diagonal blocks are v^T v (symmetric up to fp round-off); one
    # cheap (d, d) symmetrization matches get_cov's contract.
    return 0.5 * (gram + gram.T)


def _conv_out_geometry(a: jax.Array, kernel_size, strides, padding):
    """(oh, ow, rows, spatial) of the conv output for NHWC input ``a``."""
    from distributed_kfac_pytorch_tpu.ops.pallas_kernels import _canonical_pad

    kh, kw = kernel_size
    sh, sw = strides
    b, h, w, _ = a.shape
    (ph_lo, ph_hi), (pw_lo, pw_hi) = _canonical_pad(
        padding, (kh, kw), (h, w), (sh, sw))
    oh = (h + ph_lo + ph_hi - kh) // sh + 1
    ow = (w + pw_lo + pw_hi - kw) // sw + 1
    spatial = oh * ow
    return oh, ow, b * spatial, spatial


def _conv_bias_col(a: jax.Array, kernel_size, strides, padding,
                   rows: int, spatial: int) -> jax.Array:
    """Per-feature patch-row mean in (kh, kw, c) order, from the padded
    input's batch-sum instead of a second full read of the ~KH*KW x
    blown-up patch tensor (the covariance dot and a column reduce cannot
    be fused into one pass by XLA)."""
    from distributed_kfac_pytorch_tpu.ops.pallas_kernels import _canonical_pad

    kh, kw = kernel_size
    sh, sw = strides
    b, h, w, c = a.shape
    (ph_lo, ph_hi), (pw_lo, pw_hi) = _canonical_pad(
        padding, (kh, kw), (h, w), (sh, sw))
    oh = (h + ph_lo + ph_hi - kh) // sh + 1
    ow = (w + pw_lo + pw_hi - kw) // sw + 1
    xp_sum = jnp.pad(a, ((0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi),
                         (0, 0))).sum(0, dtype=jnp.float32)
    piece_means = [
        jax.lax.slice(
            xp_sum, (ki, kj, 0),
            (ki + sh * (oh - 1) + 1, kj + sw * (ow - 1) + 1, c),
            (sh, sw, 1)).sum((0, 1)) / rows
        for ki in range(kh) for kj in range(kw)]
    return jnp.concatenate(piece_means) / (spatial * spatial)


def _conv_a_cov_crosscov(a: jax.Array, kernel_size, strides, padding,
                         compute_dtype) -> jax.Array | None:
    """Patch-Gram ``P^T P`` without materializing the im2col tensor.

    Exact reordering of the covariance sum: with ``U_ki`` the h-shifted
    strided view of the padded input flattened to ``(B*OH, Wp*C)``,

        M(ki, ki')[(w, c), (w', c')] = U_ki^T U_ki'
        A[(ki, kj, c), (ki', kj', c')] = sum_q M(ki, ki')
                                           [(kj + sw*q, c), (kj' + sw*q, c')]

    i.e. one full-lane-width matmul per unique (ki <= ki') pair followed
    by a band-trace (diagonal gather + einsum) on the (Wp*C)^2 output.
    The hope was to skip the KH*KW x patch-tensor HBM write+read and the
    lane-starved (rows, KH*KW*C) contraction.

    MEASURED NEGATIVE (round 2 → 3): as the default this regressed the
    tracked-config whole step from 24.3 to 80.2 ms/iter on v5e
    (BENCH_r02.json; VERDICT round 2 bisection). Analytically the
    (Wp*C)^2 pair matmuls do ~2.6x the MACs of the patch contraction,
    and the band trace is built from ``jnp.take``/diagonal-einsum — the
    gather class :func:`pack_symmetric`'s note calls out as slow on
    TPU. Kept as an opt-in study path (KFAC_CONV_PATCH_IMPL=crosscov);
    the production default is the slices path. See PERF.md.

    Returns the unscaled Gram sum in (kh, kw, c) feature order, or None
    when the shape is out of the VMEM-safe regime (Wp*C > 1024 — e.g.
    ImageNet-resolution convs — or 1x1 kernels, where there is no patch
    blowup to avoid); callers fall back to the slices path.
    """
    from distributed_kfac_pytorch_tpu.ops.pallas_kernels import _canonical_pad

    kh, kw = kernel_size
    sh, sw = strides
    b, h, w, c = a.shape
    (ph_lo, ph_hi), (pw_lo, pw_hi) = _canonical_pad(
        padding, (kh, kw), (h, w), (sh, sw))
    wp = w + pw_lo + pw_hi
    if kh * kw == 1 or wp * c > 1024:
        return None
    xp = jnp.pad(a, ((0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi), (0, 0)))
    oh = (h + ph_lo + ph_hi - kh) // sh + 1
    ow = (w + pw_lo + pw_hi - kw) // sw + 1
    precision = None
    if compute_dtype is not None and jnp.dtype(compute_dtype) == jnp.float32:
        precision = jax.lax.Precision.HIGHEST

    u = [jax.lax.slice(xp, (0, ki, 0, 0),
                       (b, ki + sh * (oh - 1) + 1, wp, c),
                       (1, sh, 1, 1)).reshape(b * oh, wp * c)
         for ki in range(kh)]
    # q-window index grid: row q of the band for w-offset kj
    qidx = (jnp.arange(kw)[:, None] + sw * jnp.arange(ow)[None, :])  # (kw, ow)
    blocks: dict[tuple[int, int], jax.Array] = {}
    for ki in range(kh):
        for ki2 in range(ki, kh):
            m = jnp.matmul(u[ki].T, u[ki2],
                           preferred_element_type=jnp.float32,
                           precision=precision).reshape(wp, c, wp, c)
            g1 = jnp.take(m, qidx, axis=0)           # (kw, ow, c, wp, c)
            g2 = jnp.take(g1, qidx, axis=3)          # (kw, ow, c, kw, ow, c)
            # diagonal over the two q axes + sum: the band trace
            blocks[(ki, ki2)] = jnp.einsum('kqcmqd->kcmd', g2)
    rows_out = []
    for ki in range(kh):
        row = []
        for ki2 in range(kh):
            blk = (blocks[(ki, ki2)] if ki <= ki2
                   else jnp.transpose(blocks[(ki2, ki)], (2, 3, 0, 1)))
            row.append(blk.reshape(kw * c, kw * c))
        rows_out.append(jnp.concatenate(row, axis=1))
    gram = jnp.concatenate(rows_out, axis=0)
    # Explicit symmetrization for consistency with get_cov: the diagonal
    # (ki == ki') blocks rely on u^T u being exactly symmetric otherwise.
    return (gram + gram.T) * 0.5


@profiling.scope('kfac/factors/conv2d_a')
def conv2d_a_factor(a: jax.Array, kernel_size, strides, padding,
                    has_bias: bool, compute_dtype=None) -> jax.Array:
    """A factor for conv2d from NHWC inputs via im2col patches.

    Same value as the reference formula (kfac/layers/conv.py:24-34:
    ``a / spatial_size`` after ``append_bias_ones``, then cov over all
    B*OH*OW rows), restructured so nothing batch-sized is ever copied:
    the 1/spatial scaling folds into the covariance output scale and the
    bias row/column is assembled analytically (profiled on v5e: relayout
    copies, the ones-column concat, and the spatial-size divide were
    ~95% of the whole K-FAC step time in a naive translation).

    Patch-extraction dispatch (``KFAC_CONV_PATCH_IMPL``):

      - ``auto`` (default): measured per-shape rule — ``dilated`` in
        the large-spatial/small-d regime (output spatial >= 2048 and
        d <= 640, e.g. ResNet-50 stem/conv2_x at ImageNet resolution),
        ``slices`` everywhere else (every CIFAR class). Basis:
        benchmarks/conv_a_microbench.py on v5e.
      - ``slices``: pad + KH*KW strided slices + concat in (kh, kw, c)
        order — the measured-fastest path on the tracked CIFAR config
        (24.3 ms/iter whole-step).
      - ``crosscov``: band-trace Gram that never materializes the patch
        tensor — measured 3.3x whole-step regression, opt-in study path
        only (see _conv_a_cov_crosscov).
      - ``dilated``: legacy ``conv_general_dilated_patches`` path with
        the (c, kh, kw) -> (kh, kw, c) permutation applied to the small
        (D, D) covariance; ~38 ms/iter whole-step (BENCH_r01).
      - ``KFAC_FUSED_PATCH_COV=1``: opt-in fused Pallas study kernel
        (measured 18x slower than XLA per layer; kept for study).
    """
    kh, kw = kernel_size
    c = a.shape[-1]
    d = kh * kw * c
    if os.environ.get('KFAC_FUSED_PATCH_COV', '') == '1' and (
            jax.default_backend() == 'tpu' and d <= 640):
        # Opt-in fused VMEM patch-covariance Pallas kernel. Measured on
        # v5e (chained, cache-proof methodology): ~11 ms per stage-1
        # CIFAR layer vs ~0.6 ms for the XLA path below — Mosaic lowers
        # the in-kernel patch assembly (strided sublane slices + lane
        # concat of 16-lane pieces) as VPU shuffles that dwarf the
        # matmul, so the HBM-traffic saving never materializes. Kept as
        # an opt-in study kernel (like the Jacobi eigh); see PERF.md §2.
        from distributed_kfac_pytorch_tpu.ops import pallas_kernels
        try:
            if not pallas_kernels.fused_patch_cov_supported():
                raise ValueError('fused kernel unsupported here')
            mult_bf16 = (compute_dtype is None
                         or jnp.dtype(compute_dtype) == jnp.bfloat16)
            return pallas_kernels.conv_a_factor_fused(
                a, kernel_size, strides, padding, has_bias,
                mult_bf16=mult_bf16)
        except ValueError:
            pass  # unsupported padding config: XLA path
    if (compute_dtype is None and a.dtype == jnp.float32
            and jax.default_backend() == 'tpu'):
        # Under the default precision contract the covariance matmul
        # rounds fp32 inputs to bf16 on the MXU anyway (see get_cov);
        # casting BEFORE the im2col materialization makes the ~KH*KW x
        # blown-up patch tensor bf16, halving the HBM write+read that
        # dominates conv factor updates. Strict fp32
        # (compute_dtype=float32) keeps fp32 patches.
        a = a.astype(jnp.bfloat16)
    impl = os.environ.get('KFAC_CONV_PATCH_IMPL', 'auto')
    if impl not in ('auto', 'slices', 'crosscov', 'dilated', 'pairs'):
        raise ValueError(
            f'KFAC_CONV_PATCH_IMPL={impl!r}: expected one of '
            "'auto', 'slices', 'crosscov', 'dilated', 'pairs'")
    if impl == 'auto':
        # Measured per-shape dispatch (benchmarks/conv_a_microbench.py
        # on v5e — re-run it for current numbers; PERF.md rounds 3-4
        # record the deciding measurements):
        #   - dilated wins the large-spatial small-d regime (c64@56x56
        #     ~1.3x, and the 7x7/s2 ImageNet stem ~60x, where the
        #     49-slice concat relayouts are catastrophic while the
        #     identity-kernel conv tiles well);
        #   - pairs (round 4: blocked pairwise view contraction, ~half
        #     the MACs via block symmetry) wins every measured d > 640
        #     multi-tap class — ImageNet c128/c256/c512 3x3 at 1.2-2.2x
        #     over slices, incl. stride 2;
        #   - slices wins the remaining (CIFAR-class) shapes: at c<=64
        #     the pairs path's c-wide blocks underfeed the MXU lanes
        #     (stage2/3 measured 1.6-2.5x worse) while the 9c-wide
        #     patch matmul tiles fine.
        oh, ow, _, spatial = _conv_out_geometry(a, kernel_size, strides,
                                                padding)
        # kh*kw == 1 stays on slices: a 1x1 "patch extraction" is a
        # single strided slice with no concat relayout, and both other
        # paths' extra work is pure waste there.
        if kh * kw == 1:
            impl = 'slices'
        elif spatial >= 2048 and d <= 640:
            impl = 'dilated'
        elif d > 640:
            impl = 'pairs'
        else:
            impl = 'slices'
    if impl == 'pairs' and kh * kw > 1:
        # Round-4 third angle: blocked pairwise view contraction —
        # ~half the patch path's MACs (block symmetry), no patch
        # concat. Per-shape numbers: benchmarks/conv_a_microbench.py;
        # dispatched from 'auto' only where measured to win (PERF.md
        # round 4). kh*kw == 1 is a plain covariance — slices path.
        gram = _conv_a_cov_pairs(a, kernel_size, strides, padding,
                                 compute_dtype)
        oh, ow, rows, spatial = _conv_out_geometry(
            a, kernel_size, strides, padding)
        cov = gram * (1.0 / (rows * spatial * spatial))
        if not has_bias:
            return cov
        bias_col = _conv_bias_col(a, kernel_size, strides, padding,
                                  rows, spatial).astype(cov.dtype)
        return _assemble_bias_factor(cov, bias_col,
                                     1.0 / (spatial * spatial))
    if impl == 'crosscov':
        # Opt-in ONLY: measured 3.3x whole-step regression as the
        # default on v5e (BENCH_r02.json) — see _conv_a_cov_crosscov's
        # MEASURED NEGATIVE note. Falls through to the slices path
        # outside its shape regime.
        a_cc = a if compute_dtype is None else a.astype(compute_dtype)
        gram = _conv_a_cov_crosscov(a_cc, kernel_size, strides, padding,
                                    compute_dtype)
        if gram is not None:
            oh, ow, rows, spatial = _conv_out_geometry(
                a, kernel_size, strides, padding)
            cov = gram * (1.0 / (rows * spatial * spatial))
            if not has_bias:
                return cov
            bias_col = _conv_bias_col(a, kernel_size, strides, padding,
                                      rows, spatial).astype(cov.dtype)
            return _assemble_bias_factor(cov, bias_col,
                                         1.0 / (spatial * spatial))
    if impl in ('auto', 'slices', 'crosscov', 'pairs'):
        # DEFAULT: pad+slice+concat assembly — measured 24.3 ms/iter
        # whole-step on the tracked v5e config vs 80.2 for crosscov and
        # ~38 for dilated (BENCH_r01/r02 + round-2 verdict bisection).
        # The dilated-patches op lowers to an identity-kernel conv whose
        # MXU FLOPs equal the covariance contraction itself; slicing is
        # pure data movement and emits (kh, kw, c) feature order
        # directly (no (D, D) basis permutation afterwards).
        patches = extract_conv2d_patches_slices(a, kernel_size, strides,
                                                padding)
        b, oh, ow, d = patches.shape
        spatial = oh * ow
        rows = b * spatial
        p2 = patches.reshape(rows, d)
        cov = get_cov(p2, scale=rows * spatial * spatial,
                      compute_dtype=compute_dtype)
        if not has_bias:
            return cov
        bias_col = _conv_bias_col(a, kernel_size, strides, padding,
                                  rows, spatial).astype(cov.dtype)
        return _assemble_bias_factor(cov, bias_col,
                                     1.0 / (spatial * spatial))
    # impl == 'dilated': legacy identity-kernel-conv im2col.
    patches = jax.lax.conv_general_dilated_patches(
        a, filter_shape=(kh, kw), window_strides=tuple(strides),
        padding=padding, dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
    b, oh, ow, d = patches.shape
    spatial = oh * ow
    rows = b * spatial
    p2 = patches.reshape(rows, d)
    cov = get_cov(p2, scale=rows * spatial * spatial,
                  compute_dtype=compute_dtype)
    # Native feature order is (c, kh*kw) with c slowest; the factor basis
    # is (kh, kw, c) to match the flattened flax kernel. Permuting the
    # (D, D) covariance is ~1 MB of gather vs two relayouts of patches.
    perm = jnp.arange(d).reshape(c, kh * kw).T.reshape(-1)
    cov = cov[perm][:, perm]
    if not has_bias:
        return cov
    bias_col = (_column_mean(p2) / (spatial * spatial)
                ).astype(cov.dtype)[perm]
    return _assemble_bias_factor(cov, bias_col, 1.0 / (spatial * spatial))


@profiling.scope('kfac/factors/conv2d_grouped_a')
def conv2d_grouped_a_factor(a: jax.Array, kernel_size, strides, padding,
                            groups: int, has_bias: bool,
                            compute_dtype=None) -> jax.Array:
    """Per-group A factors for a grouped/depthwise conv: (G, da, da).

    Grouped convolution's Fisher block is block-diagonal over groups
    (group g's outputs see only its ``cin/G`` input channels), so the
    K-FAC approximation factorizes per group: ``A_g`` is the patch
    covariance restricted to group g's channels, with the same
    normalization as :func:`conv2d_a_factor` (cov over ``B*OH*OW`` rows
    of patches pre-divided by the spatial size). ``da = kh*kw*(cin/G)
    [+1]``. For depthwise convs (G = cin) each block is a tiny
    ``(kh*kw [+1])``-dim matrix — the standard K-FAC depthwise
    treatment, batched into one stacked einsum + (downstream) one
    batched damped inverse.

    No reference analogue: the reference's layer registry has no conv
    variant for ``feature_group_count != 1``
    (kfac/layers/__init__.py:13-36).
    """
    kh, kw = kernel_size
    c = a.shape[-1]
    if c % groups:
        raise ValueError(f'{c=} channels not divisible by {groups=}')
    cpg = c // groups
    if (compute_dtype is None and a.dtype == jnp.float32
            and jax.default_backend() == 'tpu'):
        a = a.astype(jnp.bfloat16)  # same contract as conv2d_a_factor
    patches = extract_conv2d_patches_slices(a, kernel_size, strides,
                                            padding)
    b, oh, ow, d = patches.shape
    spatial = oh * ow
    rows = b * spatial
    # (rows, kh*kw, G, cpg) -> (G, rows, kh*kw, cpg): per-group feature
    # order (kh, kw, cpg) matches the flattened flax kernel slice.
    p = patches.reshape(rows, kh * kw, groups, cpg)
    p = p.transpose(2, 0, 1, 3).reshape(groups, rows, kh * kw * cpg)
    precision = None
    if compute_dtype is not None:
        p = p.astype(compute_dtype)
        if jnp.dtype(compute_dtype) == jnp.float32:
            precision = jax.lax.Precision.HIGHEST
    cov = jnp.einsum('gri,grj->gij', p, p,
                     preferred_element_type=jnp.float32,
                     precision=precision)
    cov = (cov + cov.transpose(0, 2, 1)) * (
        0.5 / (rows * spatial * spatial))
    if not has_bias:
        return cov
    ones = jnp.ones((1, rows), jnp.float32)
    bias_cols = jnp.matmul(
        ones[None], p.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST)[:, 0, :] / (
        rows * spatial * spatial)
    corner = 1.0 / (spatial * spatial)
    return jax.vmap(
        lambda cv, bc: _assemble_bias_factor(cv, bc, corner))(
        cov, bias_cols.astype(cov.dtype))


@profiling.scope('kfac/factors/conv2d_grouped_g')
def conv2d_grouped_g_factor(g: jax.Array, groups: int,
                            compute_dtype=None) -> jax.Array:
    """Per-group G factors from NHWC output grads: (G, dg, dg).

    Output channels of a grouped conv are contiguous per group (XLA
    grouped-convolution layout), so group g's G factor is the covariance
    of its ``cout/G`` channel block, normalized like
    :func:`conv2d_g_factor`.
    """
    cout = g.shape[-1]
    if cout % groups:
        raise ValueError(f'{cout=} outputs not divisible by {groups=}')
    spatial = g.shape[1] * g.shape[2]
    g2 = g.reshape(-1, groups, cout // groups)
    rows = g2.shape[0]
    precision = None
    if compute_dtype is not None:
        g2 = g2.astype(compute_dtype)
        if jnp.dtype(compute_dtype) == jnp.float32:
            precision = jax.lax.Precision.HIGHEST
    cov = jnp.einsum('rgi,rgj->gij', g2, g2,
                     preferred_element_type=jnp.float32,
                     precision=precision)
    return (cov + cov.transpose(0, 2, 1)) * (
        0.5 / (rows * spatial * spatial))


@profiling.scope('kfac/factors/conv2d_g')
def conv2d_g_factor(g: jax.Array, compute_dtype=None) -> jax.Array:
    """G factor for conv2d from NHWC output grads.

    Reference parity: kfac/layers/conv.py:36-48 (there NCHW is transposed
    to channels-last first; NHWC already is). The 1/spatial scaling folds
    into the covariance output scale (no batch-sized elementwise copy).
    """
    spatial_size = g.shape[1] * g.shape[2]
    g2 = g.reshape(-1, g.shape[-1])
    return get_cov(g2, scale=g2.shape[0] * spatial_size * spatial_size,
                   compute_dtype=compute_dtype)


@profiling.scope('kfac/factors/embedding_a')
def embedding_a_factor(ids: jax.Array, vocab_size: int) -> jax.Array:
    """Diagonal A factor for an embedding layer: mean one-hot frequency.

    For one-hot input rows, A = E[a a^T] is diagonal with entry v equal to
    the empirical frequency of vocab id v. Returned as a vector (the
    diagonal). The reference's EmbeddingLayer computes ``mean(onehot^2)``
    (kfac/layers/embedding.py:32-63) but is hard-disabled
    (embedding.py:20); this implementation is live.
    """
    ids = ids.reshape(-1)
    counts = jnp.zeros((vocab_size,), jnp.float32).at[ids].add(1.0)
    return counts / ids.shape[0]


def pack_symmetric(m: jax.Array) -> jax.Array:
    """Pack a symmetric (n, n) matrix into ~half the elements, gather-free.

    Rectangular-full-packed-style layout built purely from
    triu/tril/slice/concat (no gather/scatter — XLA:CPU miscompiles
    gathers inside large shard_map programs, and on TPU masked dense ops
    vectorize better anyway): with ``k = ceil(n/2)`` (n padded to even),
    the strictly-lower zeros of the top ``k x n`` band of ``triu(m)``
    are filled with the transposed strict-lower content of the bottom
    ``k x k`` triangle, and the bottom block's diagonal rides in one
    extra row. Output shape ``(k + 1, n_pad)`` — about ``n^2/2 + n``
    elements on the wire instead of ``n^2``.
    """
    n = m.shape[-1]
    n_pad = n + (n % 2)
    if n_pad != n:
        m = jnp.pad(m, ((0, 1), (0, 1)))
    k = n_pad // 2
    u = jnp.triu(m)
    top = u[:k, :]                        # (k, n_pad)
    low = u[k:, k:]                       # (k, k) upper triangular
    # The strictly-lower slots of top[:, :k] are zero in triu(m); adding
    # the bottom triangle's strict-lower transpose fills them losslessly.
    top = top + jnp.concatenate(
        [jnp.tril(low.T, -1), jnp.zeros((k, n_pad - k), m.dtype)], axis=1)
    diag_low = jnp.sum(low * jnp.eye(k, dtype=m.dtype), axis=1)
    extra = jnp.concatenate(
        [diag_low, jnp.zeros((n_pad - k,), m.dtype)])[None, :]
    return jnp.concatenate([top, extra], axis=0)


def unpack_symmetric(packed: jax.Array, n: int) -> jax.Array:
    """Inverse of :func:`pack_symmetric` (gather-free)."""
    n_pad = packed.shape[-1]
    k = n_pad // 2
    top = packed[:k]
    diag_low = packed[k, :k]
    fill = jnp.tril(top[:, :k], -1)       # strict-lower of bottom block^T
    low = fill.T + diag_low[:, None] * jnp.eye(k, dtype=packed.dtype)
    u_top = jnp.concatenate([jnp.triu(top[:, :k]), top[:, k:]], axis=1)
    u_bot = jnp.concatenate([jnp.zeros((k, k), packed.dtype), low],
                            axis=1)
    u = jnp.concatenate([u_top, u_bot], axis=0)
    diag = jnp.sum(u * jnp.eye(n_pad, dtype=packed.dtype), axis=1)
    full = u + u.T - diag[:, None] * jnp.eye(n_pad, dtype=packed.dtype)
    return full[:n, :n]


def get_triu(x: jax.Array) -> jax.Array:
    """Flatten the upper triangle of a symmetric 2-D tensor.

    Reference-parity utility only (kfac/layers/utils.py:126-136): the
    production ``symmetry_aware_comm`` path uses the gather-free
    :func:`pack_symmetric` instead (gathers are slow on TPU and
    miscompile on XLA:CPU inside large shard_map programs). Kept because
    it is the reference's exact wire format (n(n+1)/2 flat elements),
    useful for interop/conversion.
    """
    if x.ndim != 2:
        raise ValueError('get_triu expects a 2-D tensor')
    n, m = x.shape
    if n > m:
        raise ValueError('tensor cannot have more rows than columns')
    rows, cols = jnp.triu_indices(n, k=0, m=m)
    return x[rows, cols]


def fill_triu(shape: Sequence[int], triu: jax.Array) -> jax.Array:
    """Rebuild a symmetric 2-D tensor from its flattened upper triangle.

    Reference parity: kfac/layers/utils.py:138-162.
    """
    if len(shape) != 2:
        raise ValueError('shape must be 2 dimensional')
    n, m = shape
    if n > m:
        raise ValueError('shape cannot have more rows than columns')
    rows, cols = jnp.triu_indices(n, k=0, m=m)
    out = jnp.zeros((n, m), dtype=triu.dtype).at[rows, cols].set(triu)
    # Mirror the strictly-lower triangle from the leading (n, n) square block
    # (all sub-diagonal entries of an n<=m matrix live there).
    sq = out[:, :n]
    strict = jnp.tril(jnp.ones((n, n), dtype=bool), k=-1)
    sym_sq = jnp.where(strict, sq.T, sq)
    return jnp.concatenate([sym_sq, out[:, n:]], axis=1)
