"""Pure-jnp math ops: factor statistics and dense linear algebra."""

from distributed_kfac_pytorch_tpu.ops import factors
from distributed_kfac_pytorch_tpu.ops import linalg
from distributed_kfac_pytorch_tpu.ops.factors import (
    append_bias_ones,
    collapse_batch_dims,
    conv2d_a_factor,
    conv2d_g_factor,
    embedding_a_factor,
    extract_conv2d_patches,
    fill_triu,
    get_cov,
    get_triu,
    linear_a_factor,
    linear_g_factor,
    update_running_avg,
)
from distributed_kfac_pytorch_tpu.ops.linalg import (
    get_eigendecomp,
    get_elementwise_inverse,
    get_inverse,
    newton_schulz_inverse,
    precondition_diag_a,
    precondition_eigen,
    precondition_inv,
)
from distributed_kfac_pytorch_tpu.ops import pallas_kernels
from distributed_kfac_pytorch_tpu.ops.pallas_kernels import (
    batched_inverse,
    batched_jacobi_eigh,
)
