"""Dense linear algebra for K-FAC factor inversion, jit/vmap friendly.

TPU-native replacements for the reference's cuSOLVER-backed ops
(kfac/layers/utils.py:45-105): ``torch.symeig`` -> ``jnp.linalg.eigh``,
``torch.cholesky`` + ``cholesky_inverse`` -> XLA Cholesky + triangular solves.
Decompositions always run in float32 regardless of the factor storage dtype,
matching the reference's policy (kfac/layers/base.py:432-441).

All functions are shape-polymorphic over leading batch dims via ``vmap`` at
the call site; the preconditioner batches same-size factors so XLA can run
the O(n^3) decompositions as one batched kernel spread across the mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def get_eigendecomp(x: jax.Array, clip: float | None = 0.0
                    ) -> tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition in fp32 with eigenvalue clipping.

    Returns ``(Q, d)`` with eigenvalues ascending. ``clip`` floors the
    eigenvalues (``max(d, clip)``), like the reference's
    ``get_eigendecomp(clip=0.0)`` (kfac/layers/utils.py:45-74), which
    guards against tiny negative eigenvalues from round-off.
    """
    d, q = jnp.linalg.eigh(x.astype(jnp.float32))
    if clip is not None:
        d = jnp.maximum(d, clip)
    return q, d


def get_inverse(x: jax.Array, damping: float | jax.Array | None = None
                ) -> jax.Array:
    """Damped SPD inverse via Cholesky: ``(x + damping*I)^-1`` in fp32.

    Implemented as a Cholesky factorization followed by two triangular
    solves against the identity — the XLA analogue of torch's
    ``cholesky_inverse(cholesky(x))`` (kfac/layers/utils.py:76-96).
    """
    x = x.astype(jnp.float32)
    if damping is not None:
        x = x + damping * jnp.eye(x.shape[-1], dtype=x.dtype)
    chol = jnp.linalg.cholesky(x)
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    inv_l = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    return inv_l.T @ inv_l


def get_elementwise_inverse(v: jax.Array,
                            damping: float | jax.Array | None = None
                            ) -> jax.Array:
    """Reciprocal of each non-zero element (zeros stay zero).

    Used for diagonal factors (embedding A). Reference parity:
    kfac/layers/utils.py:98-105.
    """
    if damping is not None:
        v = v + damping
    return jnp.where(v != 0.0, 1.0 / jnp.where(v != 0.0, v, 1.0), 0.0)


def precondition_eigen(grad: jax.Array, qa: jax.Array, qg: jax.Array,
                       da: jax.Array, dg: jax.Array,
                       damping: float | jax.Array) -> jax.Array:
    """Eigenbasis preconditioning: ``QG ((QG^T grad QA) / (dG dA^T + λ)) QA^T``.

    ``grad`` is the (out_dim, in_dim[+1]) gradient matrix. Matches the
    reference's eigen path (kfac/layers/base.py:459-470), returning fp32.
    """
    grad = grad.astype(jnp.float32)
    v1 = qg.T @ grad @ qa
    v2 = v1 / (dg[:, None] * da[None, :] + damping)
    return qg @ v2 @ qa.T


def precondition_inv(grad: jax.Array, a_inv: jax.Array,
                     g_inv: jax.Array) -> jax.Array:
    """Inverse-method preconditioning: ``G_inv @ grad @ A_inv``.

    Reference parity: kfac/layers/base.py:472-475.
    """
    return g_inv @ grad.astype(jnp.float32) @ a_inv


def precondition_diag_a(grad: jax.Array, a_inv_diag: jax.Array,
                        g_inv: jax.Array) -> jax.Array:
    """Preconditioning with a diagonal A inverse (embedding layers).

    ``(A_inv[:, None] * grad) @ G_inv`` for a (vocab, dim) gradient.
    Reference analogue: kfac/layers/embedding.py:87-99 (disabled there).
    """
    return (a_inv_diag[:, None] * grad.astype(jnp.float32)) @ g_inv
