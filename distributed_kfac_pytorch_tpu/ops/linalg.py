"""Dense linear algebra for K-FAC factor inversion, jit/vmap friendly.

TPU-native replacements for the reference's cuSOLVER-backed ops
(kfac/layers/utils.py:45-105): ``torch.symeig`` -> ``jnp.linalg.eigh``,
``torch.cholesky`` + ``cholesky_inverse`` -> XLA Cholesky + triangular solves.
Decompositions always run in float32 regardless of the factor storage dtype,
matching the reference's policy (kfac/layers/base.py:432-441).

All functions are shape-polymorphic over leading batch dims via ``vmap`` at
the call site; the preconditioner batches same-size factors so XLA can run
the O(n^3) decompositions as one batched kernel spread across the mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from distributed_kfac_pytorch_tpu.observability import profiling


def decomposition_cost(dim: int, count: int = 1,
                       rank: int | None = None) -> float:
    """Cost proxy for decomposing ``count`` SPD matrices of ``dim``.

    The classic ``dim^3`` FLOP scaling every dense factorization here
    shares (Cholesky, Newton–Schulz, the warm-polish matmuls, eigh) —
    the same proxy the KAISA work balancer uses
    (``assignment_strategy='compute'``, reference
    preconditioner.py:625-628). Used by the pipelined-firing chunk
    planner (``KFAC.inverse_chunk_plan``) to bin-pack same-dim bucket
    stacks into cost-balanced chunks; per-dim *measured* firing costs
    (the ``bucket_parts`` ms of a flagship firing leg) refine it via
    ``KFAC(inv_pipeline_costs={dim: ms})``.

    ``rank``: when the dim's dispatch resolves to the randomized
    low-rank path (r19, ``inv_lowrank_rank``), the firing is
    matmul-dominated at ``rank * dim^2`` FLOPs (sketch/subspace-refresh
    products of a (dim, rank) basis against the (dim, dim) factor)
    instead of ``dim^3`` — without this the r9/r14 LPT chunk planners
    would weight a low-rank bucket ``dim/rank``x too heavy and
    un-balance every pipelined window that mixes exact and low-rank
    buckets. ``None``/0 keeps the dense proxy.
    """
    if rank:
        return float(count) * float(rank) * float(dim) ** 2
    return float(count) * float(dim) ** 3


def get_eigendecomp(x: jax.Array, clip: float | None = 0.0
                    ) -> tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition in fp32 with eigenvalue clipping.

    Returns ``(Q, d)`` with eigenvalues ascending. ``clip`` floors the
    eigenvalues (``max(d, clip)``), like the reference's
    ``get_eigendecomp(clip=0.0)`` (kfac/layers/utils.py:45-74), which
    guards against tiny negative eigenvalues from round-off.
    """
    d, q = jnp.linalg.eigh(x.astype(jnp.float32))
    if clip is not None:
        d = jnp.maximum(d, clip)
    return q, d


def default_jacobi_sweeps(n: int) -> int:
    """Sweep count reaching fp32 roundoff: 12 up to n=512, +log2 beyond."""
    return 12 if n <= 512 else 12 + max(0, (n - 1).bit_length() - 9)


def jacobi_slot_iteration(a: jax.Array, v: jax.Array, sweeps: int
                          ) -> tuple[jax.Array, jax.Array]:
    """The Brent–Luk Jacobi inner loop over an even-dim slot-basis pair.

    Runs ``sweeps * (n - 1)`` rounds: rotate the paired half-blocks of
    ``a`` (rows then columns) and of ``v`` (columns), then move to the
    next tournament pairing with the systolic slice/concat exchange.
    Every op is elementwise/slice/concat — usable verbatim inside a
    Pallas kernel (ops.pallas_kernels) and under vmap.

    Returns (a, v) with ``a`` ~diagonal in the final slot order and
    ``v``'s columns the matching eigenvector candidates (original row
    basis). Callers sort by the diagonal afterwards.
    """
    n_pad = a.shape[-1]
    p = n_pad // 2
    eye_p = jnp.eye(p, dtype=jnp.float32)

    def halves(m, axis):
        return (jax.lax.slice_in_dim(m, 0, p, axis=axis),
                jax.lax.slice_in_dim(m, p, n_pad, axis=axis))

    def rotate(m, c, s, axis):
        """Mix the two halves along ``axis`` with per-pair (c, s)."""
        lo, hi = halves(m, axis)
        shape = (-1, 1) if axis == 0 else (1, -1)
        c = c.reshape(shape)
        s = s.reshape(shape)
        return jnp.concatenate([c * lo - s * hi, s * lo + c * hi],
                               axis=axis)

    def exchange(m, axis):
        """Brent–Luk systolic move to the next pairing (slice/concat).

        tops' = [t0, b0, t1..t_{p-2}]; bots' = [b1..b_{p-1}, t_{p-1}].
        """
        t, b = halves(m, axis)
        sl = lambda h, lo, hi: jax.lax.slice_in_dim(h, lo, hi, axis=axis)
        t_new = jnp.concatenate(
            [sl(t, 0, 1), sl(b, 0, 1), sl(t, 1, p - 1)], axis=axis)
        b_new = jnp.concatenate(
            [sl(b, 1, p), sl(t, p - 1, p)], axis=axis)
        return jnp.concatenate([t_new, b_new], axis=axis)

    def round_step(_, carry):
        a, v = carry
        # Pair i = (slot i, slot p+i): diagonals of the three p x p
        # blocks, extracted by mask-sum (no gathers).
        tl, tr = halves(halves(a, 0)[0], 1)     # a[:p,:p], a[:p,p:]
        br = halves(halves(a, 0)[1], 1)[1]      # a[p:,p:]
        app = jnp.sum(tl * eye_p, axis=1)
        aqq = jnp.sum(br * eye_p, axis=1)
        apq = jnp.sum(tr * eye_p, axis=1)
        small = jnp.abs(apq) <= 1e-30
        tau = (aqq - app) / jnp.where(small, 1.0, 2.0 * apq)
        # sign(0) must be +1: tau=0 (equal diagonal) needs the full
        # 45-degree rotation, not the identity.
        sgn = jnp.where(tau >= 0, 1.0, -1.0)
        t = sgn / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
        t = jnp.where(small, 0.0, t)
        c = 1.0 / jnp.sqrt(1.0 + t * t)
        s = t * c
        a = rotate(a, c, s, axis=0)             # J^T A
        a = rotate(a, c, s, axis=1)             # (J^T A) J
        v = rotate(v, c, s, axis=1)             # accumulate Q = J_1 J_2 ..
        if p > 1:
            a = exchange(a, axis=0)
            a = exchange(a, axis=1)
            v = exchange(v, axis=1)
        return a, v

    # fori_loop, not scan: identical semantics with no per-round outputs,
    # and it is the loop form the Mosaic (Pallas TPU) compiler can lower,
    # so the same code runs inside the VMEM kernel.
    rounds = sweeps * (n_pad - 1)
    a, v = jax.lax.fori_loop(0, rounds, round_step, (a, v))
    return a, v


def jacobi_eigh(x: jax.Array, sweeps: int | None = None
                ) -> tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition by Brent–Luk parallel Jacobi.

    The matrix lives in a *slot* basis where round ``r`` always pairs
    slot ``i`` with slot ``p + i`` (``p = n/2``): each round applies all
    ``p`` disjoint Givens rotations as two half-matrix elementwise
    combines (rows, then columns), then moves pairs to the next
    tournament arrangement with the Brent–Luk systolic exchange — a
    fixed slice/concat shuffle. One sweep = ``n - 1`` rounds covering
    every index pair once. The entire inner loop is elementwise ops,
    slices and concats — no gather/scatter — so it vectorizes cleanly on
    wide vector units and ports directly to a VMEM-resident Pallas
    kernel. Accuracy: off-diagonal mass contracts quadratically once
    small; 12 sweeps reach fp32 roundoff for n <= ~512, and the default
    scales the count with log2(n) beyond that.

    Returns ``(Q, d)`` with eigenvalues ascending (same convention as
    :func:`get_eigendecomp`). Pure JAX, vmap-friendly.
    """
    n = x.shape[-1]
    x = x.astype(jnp.float32)
    if sweeps is None:
        sweeps = default_jacobi_sweeps(n)
    if n == 1:
        return jnp.ones((1, 1), jnp.float32), x.reshape(1)
    n_pad = n + (n % 2)
    a = x
    if n_pad != n:
        # Pad with a decoupled unit eigenvalue; stripped after sorting.
        a = jnp.pad(x, ((0, 1), (0, 1)))
        a = a.at[n, n].set(1.0)
    v0 = jnp.eye(n_pad, dtype=jnp.float32)
    a, v = jacobi_slot_iteration(a, v0, sweeps)
    d = jnp.diagonal(a)
    order = jnp.argsort(d)
    d = d[order]
    v = v[:, order]
    if n_pad != n:
        # Drop the padding eigenpair: its eigenvector is exactly e_n.
        keep = v[n, :] < 0.5
        # Static-shape removal: positions of kept columns among first n.
        idx = jnp.nonzero(keep, size=n)[0]
        v = jnp.take(v[:n, :], idx, axis=1)
        d = jnp.take(d, idx)
    return v, d


def eigh_polish(a: jax.Array, q_prev: jax.Array, iters: int = 16,
                theta: float = 0.8, t_max: float = 0.2,
                ns_steps: int = 3,
                precision=None) -> tuple[jax.Array, jax.Array]:
    """Warm-start symmetric eigendecomposition by basis polishing.

    Given an SPD matrix ``a`` and an orthonormal matrix ``q_prev`` whose
    columns approximately diagonalize it, refine the basis with a fixed
    number of matmul-only iterations. Per iteration:

      1. rotate into the current basis: ``B = Q^T a Q`` (symmetrized);
      2. simultaneous Jacobi correction: for each off-diagonal pair the
         *exact* two-sided Jacobi rotation tangent
         ``t = sign(τ)/(|τ| + sqrt(1 + τ^2))``, ``τ = (d_j - d_i)/2E_ij``
         (``|t| <= 1``, so exactly-degenerate pairs rotate instead of
         dividing by ~0), clipped elementwise to ``t_max`` and assembled
         into a skew-symmetric ``X``. The clip is what keeps the
         *well-separated* pairs converging fast: without it, eigenvalue
         clusters contribute |t|~1 entries that keep ``|X|_2`` large and
         the global rescale (next) would keep damping every pair's
         correction (measured: tail convergence rate 0.65/iter unclipped
         vs 0.4 clipped). Cluster-internal rotations proceed at the
         capped pace — harmless, their basis choice doesn't affect the
         preconditioner. The whole update is then rescaled to spectral
         norm ``theta`` (power iteration on ``-X^2`` estimates
         ``|X|_2``; data-dependent in *value*, never in runtime);
      3. ``Q <- Q (I + X)``, then ``ns_steps`` Newton–Schulz
         orthogonalization steps ``Q <- Q (3I - Q^T Q) / 2`` (for skew
         ``X`` the orthogonality defect of ``I + X`` is exactly
         ``X^T X``; each NS step squares the defect).

    16 iterations reach ~1e-4 preconditioning accuracy from a 0.2-rad
    basis rotation and ~1e-5 steady-state accuracy tracking the
    per-firing factor drift of an EWMA K-FAC run (validated on synthetic
    drifting-spectrum suites; see tests/test_warm_eigh.py).

    Why this beats a cold eigh for K-FAC: factors drift slowly (EWMA
    with decay ~0.95) and the state already carries the previous basis,
    so per inverse update the basis is nearly right already. Every op
    is a dense fp32 matmul or elementwise map — data-independent
    runtime on the MXU, batchable over a factor stack — versus the
    XLA/backend eigh whose iterative while-loops run longer as
    conditioning worsens (observed 45 -> 240+ ms on trained ResNet-32
    factor sets on v5e, PERF.md §6). The reference pays a sequential
    cuSOLVER ``symeig`` per layer per update instead
    (kfac/layers/base.py:432-441).

    Accuracy note: within tight eigenvalue *clusters* the returned
    basis may briefly mix cluster members (rotations there are capped
    per iteration) — harmless for K-FAC preconditioning, where the
    damping quotient ``1/(dG dA + λ)`` is flat across near-equal
    eigenvalues, and self-correcting across firings.

    ``q_prev`` may be RECTANGULAR ``(n, r)`` with orthonormal columns
    (the r19 randomized low-rank path): every step then operates on
    the ``r x r`` projected matrix ``B = Q^T a Q`` — the polish
    diagonalizes *within* ``span(Q)`` (a Rayleigh–Ritz refinement;
    the span itself is rotated toward the dominant subspace by the
    caller's subspace-iteration refresh, :func:`lowrank_eigh`). For a
    square ``q_prev`` the ops are identical to the historical path
    (``r == n``), bit-for-bit.

    Returns ``(Q, d)`` with eigenvalues in *tracked* order (continuity
    with ``q_prev``'s columns), NOT sorted.
    """
    a = a.astype(jnp.float32)
    q = q_prev.astype(jnp.float32)
    n = q.shape[-1]  # basis rank: == a dim for the classic square case
    eye = jnp.eye(n, dtype=jnp.float32)
    if precision is None:
        # HIGHEST: measured on v5e (benchmarks/eigh_methods.py), HIGH
        # (3-pass bf16 emulation) saves only ~7% wall clock — the
        # firing is not MXU-bound at these sizes — while its absolute
        # rounding floor costs 300x accuracy on spread spectra
        # (9e-6 -> 3e-3 worst preconditioning error).
        precision = jax.lax.Precision.HIGHEST
    mm = functools.partial(jnp.matmul, precision=precision)

    def body(_, q):
        b = mm(q.T, mm(a, q))
        b = 0.5 * (b + b.T)
        d = jnp.sum(b * eye, axis=1)
        e = b - d[:, None] * eye
        delta = d[None, :] - d[:, None]          # Δ_ij = d_j - d_i
        sgn_e = jnp.where(e >= 0, 1.0, -1.0)
        abs_e = jnp.abs(e)
        tau = delta / jnp.maximum(2.0 * abs_e, 1e-30)
        # sign(0) -> +1 so exactly-degenerate pairs still rotate.
        t = (jnp.where(tau >= 0, 1.0, -1.0)
             / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau)))
        t = jnp.clip(t, -t_max, t_max)
        x = sgn_e * t * (abs_e > 1e-30)
        x = jnp.triu(x, 1)
        x = x - x.T                              # skew by construction
        # Spectral-norm estimate via power iteration on X^T X = -X^2
        # (matvecs only, O(n^2)); scale X into the NS-orthogonalization
        # basin. The shrink engages only while strongly-coupled pairs
        # overlap (early tracking transients); near convergence it is
        # the identity and quadratic convergence takes over.
        v0 = jnp.full((n,), 1.0 / n, jnp.float32)

        def pw(_, v):
            w = x @ (x @ v)
            return -w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

        v = jax.lax.fori_loop(0, 10, pw, v0)
        nrm = jnp.sqrt(jnp.linalg.norm(x @ (x @ v)))
        x = x * jnp.minimum(1.0, theta / jnp.maximum(nrm, 1e-30))
        q = q + mm(q, x)
        for _ in range(ns_steps):
            q = 0.5 * mm(q, 3.0 * eye - mm(q.T, q))
        return q

    q = jax.lax.fori_loop(0, iters, body, q)
    d = jnp.sum(mm(q.T, mm(a, q)) * eye, axis=1)
    return q, d


def batched_eigh(stack: jax.Array, method: str = 'xla',
                 clip: float | None = 0.0,
                 sweeps: int | None = None,
                 q_prev: jax.Array | None = None,
                 polish_iters: int = 16
                 ) -> tuple[jax.Array, jax.Array]:
    """Eigendecompose a (B, n, n) SPD stack: ``(Q, d)``.

    ``method='xla'`` vmaps the backend eigh (eigenvalues ascending);
    ``'jacobi'`` dispatches through
    ``ops.pallas_kernels.batched_jacobi_eigh`` (Brent–Luk parallel
    Jacobi — vmapped pure JAX by default; the VMEM Pallas kernel is
    opt-in, hardware-validated but VMEM-bound at n >= 128 — see its
    dispatch comment); ``'warm'`` requires ``q_prev`` (a (B, n, n)
    stack of previous bases) and runs the matmul-only
    :func:`eigh_polish` (eigenvalues in tracked, not sorted, order);
    ``'auto'`` picks 'warm' when ``q_prev`` is given, else 'xla'.
    Single dispatch point for the bucketed eigen paths in
    ``preconditioner`` and ``parallel.distributed``.
    """
    if method == 'auto':
        method = 'warm' if q_prev is not None else 'xla'
    if method == 'warm':
        if q_prev is None:
            raise ValueError("eigh method 'warm' requires q_prev")
        with profiling.annotate('kfac/eigh/warm'):
            qs, ds = jax.vmap(
                lambda m, q0: eigh_polish(m, q0, iters=polish_iters))(
                    stack, q_prev)
            if clip is not None:
                ds = jnp.maximum(ds, clip)
            return qs, ds
    if method == 'jacobi':
        from distributed_kfac_pytorch_tpu.ops import pallas_kernels
        with profiling.annotate('kfac/eigh/jacobi'):
            qs, ds = pallas_kernels.batched_jacobi_eigh(stack, sweeps)
            if clip is not None:
                ds = jnp.maximum(ds, clip)
            return qs, ds
    if method != 'xla':
        raise ValueError(
            "eigh method must be 'auto', 'xla', 'jacobi' or 'warm', "
            f'got {method!r}')
    with profiling.annotate('kfac/eigh/xla'):
        return jax.vmap(lambda m: get_eigendecomp(m, clip=clip))(stack)


def lowrank_eigh(a: jax.Array, rank: int,
                 q_prev: jax.Array | None = None,
                 power_iters: int = 2,
                 polish_iters: int = 8,
                 seed: int = 0) -> tuple[jax.Array, jax.Array]:
    """Rank-``r`` truncated eigendecomposition of an SPD matrix.

    Randomized NLA (Halko-Martinsson-Tropp range finder, the
    *Randomized K-FACs* recipe, arXiv:2206.15397) turns the O(d^3)
    eigh wall into O(r d^2) matmul work:

      - **cold** (``q_prev=None`` — checkpoint rebuilds, factor-only
        restores): a Gaussian test matrix ``Ω (d, r)`` sketches the
        range, ``power_iters`` subspace iterations
        ``Y <- A orth(Y)`` sharpen it against slow spectral decay,
        and an exact ``r x r`` Rayleigh–Ritz (``eigh`` of
        ``Q^T A Q`` — r^3, negligible) extracts the eigenpairs. The
        test matrix is a fixed-seed deterministic draw, so rebuilds
        are reproducible run to run.
      - **warm** (the in-run firing path): one subspace-iteration
        refresh ``orth(A q_prev)`` rotates the carried basis toward
        the factor's current dominant subspace (EWMA factors drift
        slowly, so one step per firing tracks it — the same argument
        as the full-rank warm polish), then :func:`eigh_polish`
        re-diagonalizes within the span with the proven matmul-only
        iteration — run in the PROJECTED ``r x r`` space: the polish
        never leaves ``span(Q)``, so ``Q_k = Q_0 Z_k`` and
        ``B_k = Z_k^T (Q_0^T A Q_0) Z_k`` — project once (two thin
        A-products, the whole O(r d^2) cost), polish ``Z`` against
        the small ``B_0`` at O(r^3)/iter, recombine ``Q = Q_0 Z``.
        Identical math to polishing the rectangular basis directly
        (``Q_0`` has orthonormal columns, so ``Q^T Q = Z^T Z`` and
        the Newton–Schulz orthogonalization maps 1:1), at 2·r·d^2
        instead of 2·iters·r·d^2 — the constant that makes the
        firing beat a d^3/3 Cholesky from d ~ 1.5k upward. The
        carried basis CONVERGES across firing windows instead of
        re-randomizing each time.

    Every sketch product is an fp32-pinned matmul
    (``preferred_element_type=jnp.float32`` — the r6 dtype-discipline
    contract, enforced by kfaclint's dtype family on these call
    sites), so bf16-stored factors cannot silently degrade the basis.

    Returns ``(Q, d)`` with ``Q (d, r)`` orthonormal columns and ``d``
    the ``r`` Rayleigh eigenvalues (ascending on the cold path,
    tracked order on the warm path — consumers are order-invariant).
    The discarded tail is treated as 0 by every consumer: the damped
    operator is ``Q diag(1/(d+λ)) Q^T + (I - Q Q^T)/λ`` — full-rank
    correct, with tail curvature regularized to the damping floor
    (see :func:`eigen_side_inverse` / :func:`precondition_eigen`).
    """
    a = a.astype(jnp.float32)
    n = a.shape[-1]
    if not 0 < rank < n:
        raise ValueError(
            f'lowrank_eigh needs 0 < rank < dim, got {rank=} dim={n}')
    if q_prev is not None:
        lowrank_sketch = q_prev.astype(jnp.float32)
        refreshed = jnp.matmul(a, lowrank_sketch,
                               preferred_element_type=jnp.float32,
                               precision=jax.lax.Precision.HIGHEST)
        q0, _ = jnp.linalg.qr(refreshed)
        # Project once (the only other O(r d^2) product), polish the
        # r x r rotation Z in the projected space, recombine. See the
        # docstring for why this is identical to polishing the
        # rectangular basis directly.
        aq0 = jnp.matmul(a, q0, preferred_element_type=jnp.float32,
                         precision=jax.lax.Precision.HIGHEST)
        b0 = jnp.matmul(q0.T, aq0,
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)
        b0 = 0.5 * (b0 + b0.T)
        z, d = eigh_polish(b0, jnp.eye(rank, dtype=jnp.float32),
                           iters=polish_iters)
        q = jnp.matmul(q0, z, preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)
        return q, d
    # Cold start: Gaussian range-finder sketch + power iterations.
    lowrank_sketch = jax.random.normal(jax.random.PRNGKey(seed),
                                       (n, rank), jnp.float32)
    y = jnp.matmul(a, lowrank_sketch,
                   preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)
    for _ in range(max(0, power_iters)):
        q0, _ = jnp.linalg.qr(y)
        y = jnp.matmul(a, q0, preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)
    q0, _ = jnp.linalg.qr(y)
    # Rayleigh–Ritz on the r x r projection: exact within the sketched
    # subspace, and r^3 is noise next to the r d^2 sketch products.
    b = jnp.matmul(q0.T, jnp.matmul(a, q0,
                                    preferred_element_type=jnp.float32,
                                    precision=jax.lax.Precision.HIGHEST),
                   preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)
    d, u = jnp.linalg.eigh(0.5 * (b + b.T))
    q = jnp.matmul(q0, u, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)
    return q, d


def batched_lowrank_eigh(stack: jax.Array, rank: int,
                         q_prev: jax.Array | None = None,
                         power_iters: int = 2,
                         polish_iters: int = 8,
                         clip: float | None = 0.0,
                         seed: int = 0
                         ) -> tuple[jax.Array, jax.Array]:
    """Truncated-eigendecompose a (B, n, n) SPD stack: ``(Q, d)`` with
    ``Q (B, n, rank)`` / ``d (B, rank)``.

    The low-rank analogue of :func:`batched_eigh` — one vmapped
    :func:`lowrank_eigh` per same-dim bucket; ``q_prev`` is a
    ``(B, n, rank)`` stack of carried truncated bases (the warm
    subspace-refresh + polish path). ``clip`` floors the Rayleigh
    eigenvalues like the exact path (tiny negatives from round-off on
    a PSD factor). Single dispatch point for the single-chip and SPMD
    bucketed firing paths.
    """
    with profiling.annotate('kfac/eigh/lowrank'):
        if q_prev is None:
            qs, ds = jax.vmap(
                lambda m: lowrank_eigh(m, rank,
                                       power_iters=power_iters,
                                       seed=seed))(stack)
        else:
            qs, ds = jax.vmap(
                lambda m, q0: lowrank_eigh(
                    m, rank, q_prev=q0,
                    polish_iters=polish_iters))(stack, q_prev)
        if clip is not None:
            ds = jnp.maximum(ds, clip)
        return qs, ds


@profiling.scope('kfac/inverse/cholesky')
def get_inverse(x: jax.Array, damping: float | jax.Array | None = None
                ) -> jax.Array:
    """Damped SPD inverse via Cholesky: ``(x + damping*I)^-1`` in fp32.

    Implemented as a Cholesky factorization followed by two triangular
    solves against the identity — the XLA analogue of torch's
    ``cholesky_inverse(cholesky(x))`` (kfac/layers/utils.py:76-96).
    """
    x = x.astype(jnp.float32)
    if damping is not None:
        x = x + damping * jnp.eye(x.shape[-1], dtype=x.dtype)
    chol = jnp.linalg.cholesky(x)
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    inv_l = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    return inv_l.T @ inv_l


@profiling.scope('kfac/inverse/newton')
def newton_schulz_inverse(x: jax.Array,
                          damping: float | jax.Array | None = None,
                          iters: int = 100,
                          tol: float = 1e-5) -> jax.Array:
    """Damped SPD inverse via Newton–Schulz (Hotelling–Bodewig) iteration.

    ``X_{k+1} = X_k (2I - M X_k)`` with ``M = x + damping*I`` and
    ``X_0 = I / ||M||_inf``. Matmul-only — every FLOP lands on the MXU,
    unlike the partly-sequential Cholesky/eigh factorizations. The error
    squares each step, so ``~log2(cond(M)) + 6`` iterations suffice
    (cond <= ||M||_inf/damping); the loop exits early once the residual
    ``max|M X - I|`` drops below ``tol``, with ``iters`` as the hard cap
    for pathologically-conditioned inputs.

    The same trick production TPU second-order optimizers use for inverse
    matrix roots (distributed Shampoo's coupled Newton iteration); for
    K-FAC only the plain inverse is needed. Semantically interchangeable
    with :func:`get_inverse` (the reference's damped Cholesky inverse,
    kfac/layers/utils.py:76-96) — same operator, different algorithm.
    """
    x = x.astype(jnp.float32)
    n = x.shape[-1]
    eye = jnp.eye(n, dtype=jnp.float32)
    m = x if damping is None else x + damping * eye
    bound = jnp.maximum(jnp.max(jnp.sum(jnp.abs(m), axis=-1)), 1e-30)
    x0 = eye / bound
    # Full fp32 matmul precision: with the TPU default (bf16 passes) the
    # iteration stalls at a ~1e-1 residual floor once ||X|| ~ 1/damping.
    mm = functools.partial(jnp.matmul, precision=jax.lax.Precision.HIGHEST)

    def cond_fn(state):
        k, _, res = state
        return jnp.logical_and(k < iters, res > tol)

    def body(state):
        k, xk, _ = state
        y = mm(m, xk)
        res = jnp.max(jnp.abs(y - eye))  # residual of xk, costs O(n^2)
        return k + 1, 2.0 * xk - mm(xk, y), res

    _, out, _ = jax.lax.while_loop(
        cond_fn, body, (jnp.zeros((), jnp.int32), x0,
                        jnp.full((), jnp.inf, jnp.float32)))
    return out


def get_elementwise_inverse(v: jax.Array,
                            damping: float | jax.Array | None = None
                            ) -> jax.Array:
    """Reciprocal of each non-zero element (zeros stay zero).

    Used for diagonal factors (embedding A). Reference parity:
    kfac/layers/utils.py:98-105.
    """
    if damping is not None:
        v = v + damping
    return jnp.where(v != 0.0, 1.0 / jnp.where(v != 0.0, v, 1.0), 0.0)


def _precond_mm(compute_dtype):
    """(operand dtype, matmul) for a non-default precondition compute dtype.

    Mirrors the ``ops.factors.get_cov`` contract: operands are cast to
    ``compute_dtype`` while every contraction accumulates in fp32
    (``preferred_element_type``); ``float32`` additionally requests
    ``Precision.HIGHEST`` (strict fp32 — no TPU bf16 rounding of the
    inputs). Callers keep the legacy upcast-to-fp32 path for
    ``compute_dtype=None`` so the default is bit-identical to the
    pre-knob behavior.
    """
    cdt = jnp.dtype(compute_dtype)
    precision = (jax.lax.Precision.HIGHEST if cdt == jnp.float32
                 else None)
    mm = functools.partial(jnp.matmul,
                           preferred_element_type=jnp.float32,
                           precision=precision)
    return cdt, mm


def _truncated_side(q: jax.Array) -> bool:
    """Static: is this eigenbasis truncated (rectangular (n, r), r < n —
    the r19 randomized low-rank representation)?"""
    return q.shape[-1] < q.shape[-2]


@profiling.scope('kfac/precond/eigen')
def precondition_eigen(grad: jax.Array, qa: jax.Array, qg: jax.Array,
                       da: jax.Array, dg: jax.Array,
                       damping: float | jax.Array,
                       compute_dtype=None) -> jax.Array:
    """Eigenbasis preconditioning: ``QG ((QG^T grad QA) / (dG dA^T + λ)) QA^T``.

    ``grad`` is the (out_dim, in_dim[+1]) gradient matrix. Matches the
    reference's eigen path (kfac/layers/base.py:459-470), returning fp32.

    ``compute_dtype``: input dtype for the four contractions (fp32
    accumulation; see :func:`_precond_mm`). The eigenvalue quotient —
    the damping-sensitive part — always runs in fp32; only the matmul
    *operands* drop precision. ``None`` (default) keeps the legacy
    upcast-everything-to-fp32 path bit-for-bit.

    **Truncated sides** (r19): either basis may be rectangular
    ``(n, r)`` with ``r`` matching its eigenvalue vector — the
    randomized low-rank representation, whose discarded tail
    eigenvalues are 0 by convention. The joint quotient then splits
    into the captured block plus a damping-only complement:

        ``P = grad/λ + QG (C/(dG dA^T + λ) - C/λ) QA^T``,
        ``C = QG^T grad QA``

    — algebraically exact for the operator whose tail eigenvalues are
    0 (the three complement blocks all carry denominator λ), and
    full-rank correct: no gradient direction is dropped, tail
    curvature is regularized to the damping floor. All products are
    ``r``-thin (O(r d^2) per step instead of O(d^3)). A square/square
    pair keeps the historical formula bit-for-bit (the static shape
    check selects at trace time).
    """
    truncated = _truncated_side(qa) or _truncated_side(qg)
    if compute_dtype is None:
        grad = grad.astype(jnp.float32)
        v1 = qg.T @ grad @ qa
        v2 = v1 / (dg[:, None] * da[None, :] + damping)
        if not truncated:
            return qg @ v2 @ qa.T
        return grad / damping + qg @ (v2 - v1 / damping) @ qa.T
    cdt, mm = _precond_mm(compute_dtype)
    qa = qa.astype(cdt)
    qg = qg.astype(cdt)
    v1 = mm(qg.T, mm(grad.astype(cdt), qa))
    denom = (dg.astype(jnp.float32)[:, None]
             * da.astype(jnp.float32)[None, :] + damping)
    if not truncated:
        v2 = (v1 / denom).astype(cdt)
        return mm(qg, mm(v2, qa.T))
    # Complement term in fp32 (damping-sensitive), thin products in cdt.
    mid = (v1 / denom - v1 / damping).astype(cdt)
    return (grad.astype(jnp.float32) / damping
            + mm(qg, mm(mid, qa.T)))


@profiling.scope('kfac/precond/inv')
def precondition_inv(grad: jax.Array, a_inv: jax.Array,
                     g_inv: jax.Array, compute_dtype=None) -> jax.Array:
    """Inverse-method preconditioning: ``G_inv @ grad @ A_inv``.

    Reference parity: kfac/layers/base.py:472-475. With
    ``compute_dtype=jnp.bfloat16`` and bf16-stored inverses
    (``inv_dtype=jnp.bfloat16``) the casts are no-ops: the inverses are
    consumed *resident* — no fp32 upcast copy of the (dim, dim) operand
    is ever materialized, which is the bandwidth lever at LM scale
    (4096² inverse reads every step; PERF.md r6).
    """
    if compute_dtype is None:
        return g_inv @ grad.astype(jnp.float32) @ a_inv
    cdt, mm = _precond_mm(compute_dtype)
    return mm(g_inv.astype(cdt), mm(grad.astype(cdt),
                                    a_inv.astype(cdt)))


@profiling.scope('kfac/precond/diag_a')
def precondition_diag_a(grad: jax.Array, a_inv_diag: jax.Array,
                        g_inv: jax.Array, compute_dtype=None) -> jax.Array:
    """Preconditioning with a diagonal A inverse (embedding layers).

    ``(A_inv[:, None] * grad) @ G_inv`` for a (vocab, dim) gradient.
    Reference analogue: kfac/layers/embedding.py:87-99 (disabled there).
    The diagonal scale (elementwise, VPU-bound) always runs in fp32;
    ``compute_dtype`` governs the G-side contraction only.
    """
    if compute_dtype is None:
        return (a_inv_diag[:, None] * grad.astype(jnp.float32)) @ g_inv
    cdt, mm = _precond_mm(compute_dtype)
    scaled = a_inv_diag.astype(jnp.float32)[:, None] * grad.astype(
        jnp.float32)
    return mm(scaled.astype(cdt), g_inv.astype(cdt))


def eigen_side_inverse(q: jax.Array, d: jax.Array,
                       damping: float | jax.Array) -> jax.Array:
    """Per-side damped inverse from an eigendecomposition:
    ``Q diag(1/(d+λ)) Q^T`` = ``(F + λI)^{-1}`` (exact when (Q, d) is).

    Used at inverse-*firing* time to bake a mixed-method layer's eigen
    side into a dense damped inverse, so both sides of a split layer
    carry the same firing-time λ (the reference non-eigen timing
    semantics, kfac/layers/base.py:439: damping is baked at
    compute-inverses time, not read at precondition time).

    A TRUNCATED ``(n, r)`` basis (r19 low-rank) bakes the full-rank-
    correct damped inverse of the tail-zero operator:
    ``I/λ + Q diag(1/(d+λ) - 1/λ) Q^T`` — the same complement
    convention as :func:`precondition_eigen`, assembled in O(r n^2).
    """
    q = q.astype(jnp.float32)
    d = d.astype(jnp.float32)
    if _truncated_side(q):
        eye = jnp.eye(q.shape[-2], dtype=jnp.float32)
        scale = 1.0 / (d + damping) - 1.0 / damping
        return eye / damping + (q * scale[None, :]) @ q.T
    return (q * (1.0 / (d + damping))[None, :]) @ q.T


def precondition_dispatch(grad: jax.Array, entry: dict,
                          damping: float | jax.Array,
                          diag_a: jax.Array | None = None,
                          compute_dtype=None) -> jax.Array:
    """Per-layer preconditioning, dispatched on the inverse slots present.

    Single point of truth for the single-chip and SPMD preconditioners
    under per-dim inverse dispatch (``inverse_method='auto'``):

      - both sides eigen (``QA``/``dA``/``QG``/``dG``, no baked
        inverses): the reference eigen path with *joint* damping
        ``1/(dG dA^T + λ)`` read at precondition time
        (kfac/layers/base.py:459-470 — λ is the live scheduled value,
        like the reference's);
      - any baked inverse present: ``G_inv @ grad @ A_inv``
        (kfac/layers/base.py:472-475). Mixed-method layers carry a
        firing-time-baked dense inverse for their eigen side too
        (:func:`eigen_side_inverse`, computed in the inverse update),
        so BOTH sides of a split layer use the same firing-time λ —
        the reference non-eigen timing semantics — and the per-step
        eigen-side reconstruction cost is gone. Damping-semantics
        note: PARITY.md.

    ``diag_a``: diagonal A inverse for embedding layers (elementwise,
    damping already baked) — then ``entry`` carries only the G side.

    ``compute_dtype``: operand dtype for the precondition contractions
    (``KFAC.precond_compute_dtype``), threaded through every branch so
    ``auto`` mixed-method layers cannot drift: ``None`` = the legacy
    fp32-upcast path (bit-identical default), ``jnp.bfloat16`` = bf16
    operands with fp32 accumulation (the MXU fast path; bf16-stored
    inverses are consumed resident, no upcast copy), ``jnp.float32`` =
    strict fp32 (``Precision.HIGHEST``).
    """
    if diag_a is not None:
        if 'G_inv' in entry:
            return precondition_diag_a(grad, diag_a, entry['G_inv'],
                                       compute_dtype=compute_dtype)
        with profiling.annotate('kfac/precond/diag_a_eigen'):
            # Truncated QG (r19): the G side serves the tail-zero
            # damped operator grad/λ + grad QG (1/(dG+λ) - 1/λ) QG^T —
            # same complement convention as precondition_eigen.
            truncated = _truncated_side(entry['QG'])
            if compute_dtype is None:
                v1 = grad.astype(jnp.float32) @ entry['QG']
                v2 = v1 / (entry['dG'][None, :] + damping)
                if truncated:
                    return diag_a[:, None] * (
                        grad.astype(jnp.float32) / damping
                        + (v2 - v1 / damping) @ entry['QG'].T)
                return diag_a[:, None] * (v2 @ entry['QG'].T)
            cdt, mm = _precond_mm(compute_dtype)
            qg = entry['QG'].astype(cdt)
            v1 = mm(grad.astype(cdt), qg)
            v2 = v1 / (entry['dG'].astype(jnp.float32)[None, :] + damping)
            if truncated:
                mid = (v2 - v1 / damping).astype(cdt)
                return diag_a.astype(jnp.float32)[:, None] * (
                    grad.astype(jnp.float32) / damping + mm(mid, qg.T))
            return diag_a.astype(jnp.float32)[:, None] * mm(
                v2.astype(cdt), qg.T)
    a_baked = 'A_inv' in entry
    g_baked = 'G_inv' in entry
    if not a_baked and not g_baked:
        return precondition_eigen(grad, entry['QA'], entry['QG'],
                                  entry['dA'], entry['dG'], damping,
                                  compute_dtype=compute_dtype)
    return precondition_inv(grad, entry['A_inv'], entry['G_inv'],
                            compute_dtype=compute_dtype)
