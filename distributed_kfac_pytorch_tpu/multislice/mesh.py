"""Nested multi-slice mesh construction + slice/rank arithmetic.

The device order contract: slices are CONTIGUOUS runs of the global
device (and rank) list — on a real multi-slice pod the runtime
enumerates each slice's devices together, and on the CPU test backend
(``--xla_force_host_platform_device_count=N``) contiguity is what the
supervisor's slice-failure classifier and the fleet's gang placement
key off. :func:`slice_rank_groups` is the single source of that
arithmetic, shared by the r17 supervisor (all-ranks-of-one-slice-stale
classification) and the observability report's per-slice rows.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from distributed_kfac_pytorch_tpu.parallel.distributed import (
    KFAC_AXES,
    SLICE_AXIS,
    make_kfac_mesh,
    resolve_grad_workers,
)
from distributed_kfac_pytorch_tpu.parallel.sequence import SEQ_AXIS
from distributed_kfac_pytorch_tpu.preconditioner import CommMethod


def make_multislice_mesh(devices: Sequence[jax.Device] | None = None, *,
                         num_slices: int = 1,
                         comm_method: CommMethod = CommMethod.COMM_OPT,
                         grad_worker_fraction: float = 0.25,
                         seq_parallel: int = 1) -> Mesh:
    """Build the ``(slices, inv_groups, grad_workers[, seq])`` mesh.

    ``num_slices == 1`` returns the flat ``make_kfac_mesh`` mesh (no
    slice axis) — the bit-identity guarantee of ``--num-slices 1``.
    Otherwise each contiguous ``world/num_slices`` run of devices is
    one slice (one ICI domain); within a slice the KAISA grid is built
    exactly like the flat mesh's (``placement.WorkerAllocator`` per
    slice), so the in-slice topology — and therefore every ICI
    collective's participant set — is unchanged from a
    ``world/num_slices``-device flat run.
    """
    if num_slices < 1:
        raise ValueError(f'{num_slices=} must be >= 1')
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if num_slices == 1:
        return make_kfac_mesh(devices, comm_method=comm_method,
                              grad_worker_fraction=grad_worker_fraction,
                              seq_parallel=seq_parallel)
    if devices.size % num_slices:
        raise ValueError(f'{num_slices=} does not divide '
                         f'{devices.size} devices')
    per_slice = devices.size // num_slices
    if per_slice % seq_parallel:
        raise ValueError(f'{seq_parallel=} does not divide the '
                         f'{per_slice} devices of each slice')
    from distributed_kfac_pytorch_tpu.parallel.placement import (
        WorkerAllocator,
    )
    dp = per_slice // seq_parallel
    gw = resolve_grad_workers(dp, comm_method, grad_worker_fraction)
    alloc = WorkerAllocator(dp, gw / dp)
    assert alloc.grad_workers == gw
    grid = alloc.grid
    slabs = devices.reshape(num_slices, per_slice)
    if seq_parallel > 1:
        devs = np.stack([slab.reshape(dp, seq_parallel)[grid]
                         for slab in slabs])
        return Mesh(devs, (SLICE_AXIS,) + KFAC_AXES + (SEQ_AXIS,))
    devs = np.stack([slab[grid] for slab in slabs])
    return Mesh(devs, (SLICE_AXIS,) + KFAC_AXES)


def slice_count(mesh: Mesh) -> int:
    """Number of slices of a mesh (1 for a flat mesh)."""
    return (int(mesh.shape[SLICE_AXIS])
            if SLICE_AXIS in mesh.axis_names else 1)


def batch_axes(mesh: Mesh) -> tuple[str, ...]:
    """Batch-dim sharding axes for a (possibly sliced) K-FAC mesh.

    The slice axis (when present) plus both K-FAC axes — NOT the
    sequence axis, which shards the sequence dim. Mirrors
    ``DistributedKFAC.batch_axes`` for callers that build batch
    PartitionSpecs before (or without) a ``DistributedKFAC``.
    """
    return (((SLICE_AXIS,) if SLICE_AXIS in mesh.axis_names else ())
            + KFAC_AXES)


def slice_rank_groups(world: int, num_slices: int
                      ) -> tuple[tuple[int, ...], ...]:
    """Per-slice contiguous rank groups: slice ``s`` owns ranks
    ``[s * world/num_slices, (s+1) * world/num_slices)``.

    The single source of the slice<->rank arithmetic (module
    docstring); raises when ``num_slices`` does not divide ``world``
    so a drifted world size fails loudly instead of misattributing
    ranks.
    """
    if num_slices < 1:
        raise ValueError(f'{num_slices=} must be >= 1')
    if world % num_slices:
        raise ValueError(f'{num_slices=} does not divide world size '
                         f'{world}')
    per = world // num_slices
    return tuple(tuple(range(s * per, (s + 1) * per))
                 for s in range(num_slices))


def slice_of_rank(rank: int, world: int, num_slices: int) -> int:
    """The slice id owning ``rank`` (contiguous-run arithmetic)."""
    if not 0 <= rank < world:
        raise ValueError(f'{rank=} out of range for world {world}')
    if num_slices <= 1:
        return 0
    if world % num_slices:
        raise ValueError(f'{num_slices=} does not divide world size '
                         f'{world}')
    return rank // (world // num_slices)
