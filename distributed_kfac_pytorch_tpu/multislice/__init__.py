"""Multi-slice scale-out: two-level collective topology (r20).

Production TPU pods are many ICI-connected slices joined by slow DCN.
This package makes the K-FAC collective topology hierarchy-aware:

  - :func:`make_multislice_mesh` builds a nested mesh with an OUTER
    ``SLICE_AXIS`` (``parallel.distributed.SLICE_AXIS``) whose index
    is the slice id; within a slice the KAISA
    ``(inv_groups, grad_workers[, seq])`` grid is unchanged, so every
    latency-critical collective (the in-group inverse ``all_gather``,
    the intra-slice factor ``pmean``) rides ICI only.
  - Inverse groups are slice-confined: ``DistributedKFAC`` places work
    over the GLOBAL row space (``num_slices * rows_per_slice``), each
    slice holding a contiguous run of rows — decompositions and
    inverse state never cross the DCN; only preconditioned gradients
    do (the delivery ``psum`` over both row axes), following the
    comm/compute placement analysis of arXiv:2206.15143 /
    arXiv:2107.06533.
  - Factor reduction can go hierarchical (``KFAC(hierarchical_reduce=
    True)``): intra-slice ``pmean`` on ICI every factor step, ONE
    bucketed inter-slice DCN reduce per r14 cadence window — exact by
    the same EMA-linearity argument as the r14 deferred reduction,
    parity-pinned against the flat reduce.

``num_slices=1`` degenerates to the flat ``make_kfac_mesh`` mesh and
is bit-identical to the single-slice path. Everything is CPU-testable
with ``--xla_force_host_platform_device_count`` nested meshes, like
every SPMD feature so far.
"""

from distributed_kfac_pytorch_tpu.multislice.mesh import (  # noqa: F401
    batch_axes,
    make_multislice_mesh,
    slice_count,
    slice_of_rank,
    slice_rank_groups,
)
from distributed_kfac_pytorch_tpu.parallel.distributed import (  # noqa: F401
    SLICE_AXIS,
)
