"""LSTM built from Dense submodules so K-FAC sees every gate.

The reference reimplements LSTM out of ``nn.Linear`` because cuDNN's
fused kernel hides per-timestep activations from hooks
(reference kfac/modules/lstm.py:1-225, README.md:200-201). In JAX nothing
is hidden, but the same decomposition is still what *defines* the K-FAC
blocks: each gate (or fused gate stack) is a Dense module that
``KFACCapture`` registers, with one capture per timestep — the analogue of
the reference's per-timestep factor summation
(``LinearMultiLayer``, kfac/layers/linear.py:27-59).

The timestep loop is a Python unroll (not ``lax.scan``): each call sows
its own activation/probe pair, exactly the ``accumulate_data`` contract
(reference kfac/layers/base.py:364-379). Sequence lengths are static per
training setup (BPTT truncation, reference torch_language_model.py:52),
so the unroll compiles once.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class LSTMCellKFAC(nn.Module):
    """LSTM cell with 8 per-gate Dense modules (one K-FAC block per gate).

    Reference parity: LSTMCellKFAC (kfac/modules/lstm.py:41-68). Gate
    order (i, f, g, o); biases live on the input-side projections like
    torch's ``bias_ih``/``bias_hh`` pair collapsed to one.
    """
    hidden_size: int
    dtype: Any = None    # compute dtype (params stay fp32); None = infer

    @nn.compact
    def __call__(self, x, state):
        h, c = state
        gates = {}
        for name in ('i', 'f', 'g', 'o'):
            wx = nn.Dense(self.hidden_size, use_bias=True,
                          dtype=self.dtype, name=f'w_{name}x')(x)
            wh = nn.Dense(self.hidden_size, use_bias=True,
                          dtype=self.dtype, name=f'w_{name}h')(h)
            gates[name] = wx + wh
        i = nn.sigmoid(gates['i'])
        f = nn.sigmoid(gates['f'])
        g = nn.tanh(gates['g'])
        o = nn.sigmoid(gates['o'])
        new_c = f * c + i * g
        new_h = o * nn.tanh(new_c)
        return new_h, (new_h, new_c)


class LSTMCell(nn.Module):
    """LSTM cell with 2 fused 4H Dense modules (input and recurrent).

    Reference parity: LSTMCell (kfac/modules/lstm.py:71-88) — the standard
    torch parameterization; two big MXU-friendly matmuls per step and two
    K-FAC blocks per cell.
    """
    hidden_size: int
    dtype: Any = None    # compute dtype (params stay fp32); None = infer

    @nn.compact
    def __call__(self, x, state):
        h, c = state
        zx = nn.Dense(4 * self.hidden_size, use_bias=True,
                      dtype=self.dtype, name='w_ih')(x)
        zh = nn.Dense(4 * self.hidden_size, use_bias=True,
                      dtype=self.dtype, name='w_hh')(h)
        z = zx + zh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        new_c = nn.sigmoid(f) * c + nn.sigmoid(i) * nn.tanh(g)
        new_h = nn.sigmoid(o) * nn.tanh(new_c)
        return new_h, (new_h, new_c)


class LSTMLayer(nn.Module):
    """One direction of one layer: Python-unrolled timestep loop.

    Reference parity: LSTMLayer (kfac/modules/lstm.py:91-118). Input is
    batch-major ``(batch, time, features)``; returns the full output
    sequence and final state.
    """
    hidden_size: int
    kfac_cell: bool = True    # 8 per-gate blocks vs 2 fused blocks
    reverse: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, xs, state=None, lengths=None):
        """``lengths``: optional (batch,) int array of valid sequence
        lengths — the jit-friendly ``PackedSequence`` analogue (reference
        kfac/modules/lstm.py:120-225). Every timestep still executes
        (static shapes), but for rows past their length:

          - the cell *inputs* (x_t and the recurrent h) are zeroed, so
            the K-FAC ``a`` captures of those rows are zero and
            contribute nothing to the factor covariance;
          - the state is carried through unchanged (forward: the final
            state is the state at the last valid step; reverse: the
            run effectively starts at each row's last valid token);
          - outputs at padded positions are zero (packed-unpack
            convention), so a loss that masks padded targets sends zero
            gradient into those cell calls — the ``g`` captures of
            padded rows are zero too.

        Note on factor normalization: covariance averages divide by the
        full padded ``batch * time`` row count (a static shape), not the
        valid-token count. Relative to a truly packed implementation
        (the reference divides by the shrinking packed batch) this
        scales the weight blocks of A and G by ``valid / (B * T)``; for
        *biased* layers the homogeneous bias coordinate of A is NOT
        scaled (every row's implicit 1 still counts — a zeroed row
        contributes ``e_bias e_bias^T``), so the bias coordinate's
        relative curvature is overestimated by up to ``B*T/valid`` and
        its preconditioned update correspondingly damped. Exact packed
        statistics would need the capture pipeline to carry per-row
        masks into the factor math; with typical padding fractions the
        distortion is modest and affects bias updates only.
        """
        cell_cls = LSTMCellKFAC if self.kfac_cell else LSTMCell
        cell = cell_cls(self.hidden_size, dtype=self.dtype, name='cell')
        batch = xs.shape[0]
        if state is None:
            h = jnp.zeros((batch, self.hidden_size), xs.dtype)
            state = (h, h)
        steps = range(xs.shape[1])
        if self.reverse:
            steps = reversed(list(steps))
        outs = []
        for t in steps:
            if lengths is None:
                y, state = cell(xs[:, t], state)
            else:
                mask = (t < lengths).astype(xs.dtype)[:, None]
                h_old, c_old = state
                y_new, (h_new, c_new) = cell(
                    xs[:, t] * mask, (h_old * mask, c_old * mask))
                state = (jnp.where(mask > 0, h_new, h_old),
                         jnp.where(mask > 0, c_new, c_old))
                y = y_new * mask
            outs.append(y)
        if self.reverse:
            outs = outs[::-1]
        return jnp.stack(outs, axis=1), state


class LSTM(nn.Module):
    """Multi-layer (optionally bidirectional) K-FAC-friendly LSTM.

    Reference parity: LSTM (kfac/modules/lstm.py:120-225): per-layer
    dropout between stacked layers, batch-major IO, and concatenated
    directions. State is a list (one (h, c) per layer-direction).
    """
    hidden_size: int
    num_layers: int = 1
    dropout: float = 0.0
    bidirectional: bool = False
    kfac_cell: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, xs, states=None, *, lengths=None,
                 train: bool = True):
        n_dirs = 2 if self.bidirectional else 1
        if states is None:
            states = [None] * (self.num_layers * n_dirs)
        new_states = []
        out = xs
        for layer in range(self.num_layers):
            dirs = []
            for d in range(n_dirs):
                idx = layer * n_dirs + d
                seq, st = LSTMLayer(
                    self.hidden_size, kfac_cell=self.kfac_cell,
                    reverse=(d == 1), dtype=self.dtype,
                    name=f'layer{layer}_d{d}')(
                        out, states[idx], lengths=lengths)
                dirs.append(seq)
                new_states.append(st)
            out = dirs[0] if n_dirs == 1 else jnp.concatenate(dirs, -1)
            if self.dropout > 0 and layer < self.num_layers - 1:
                out = nn.Dropout(self.dropout, deterministic=not train)(out)
        return out, new_states
