"""K-FAC-friendly recurrent modules (reference kfac/modules)."""

from distributed_kfac_pytorch_tpu.modules.lstm import (
    LSTM,
    LSTMCell,
    LSTMCellKFAC,
    LSTMLayer,
)
