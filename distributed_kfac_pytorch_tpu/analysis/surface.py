"""Cross-file surface-consistency checks (the ``surface`` family).

The knob surface spans five places that must agree:

  1. ``training.optimizers.OptimConfig`` — the field registry;
  2. ``training.optimizers.TUNABLE_FIELDS`` — the subset a tuned
     artifact may override (must be ⊆ the OptimConfig fields);
  3. the three example CLIs — every tunable needs its flag in each
     (``--kfac-update-freq`` style, see :data:`FLAG_ALIASES`);
  4. ``autotune.space.default_space()`` knobs and
     ``autotune.driver.kfac_overrides`` special-cases — both must
     reference real tunable fields;
  5. ``observability.sink.EVENT_KINDS`` — every literal event name
     emitted anywhere in the package must be registered there.

Everything here is *static* (AST over the source tree, no imports) so
the lint CLI stays fast and jax-free; ``tests/test_surface.py`` is
the semantic double-check that imports the real modules, so tier-1
catches drift even when lint is skipped.
"""

from __future__ import annotations

import ast
import pathlib

from distributed_kfac_pytorch_tpu.analysis.rules import Finding

#: OptimConfig field -> CLI flag, where the mechanical
#: underscores->dashes mapping does not hold.
FLAG_ALIASES = {
    'kfac_inv_update_freq': '--kfac-update-freq',
    'factor_decay': '--stat-decay',
    'weight_decay': '--wd',
}

EXAMPLE_CLIS = ('train_cifar10_resnet.py', 'train_imagenet_resnet.py',
                'train_language_model.py')


def flag_for(field: str) -> str:
    return FLAG_ALIASES.get(field, '--' + field.replace('_', '-'))


# ---------------------------------------------------------------------------
# AST extraction helpers
# ---------------------------------------------------------------------------

def _parse(path: pathlib.Path) -> ast.AST | None:
    try:
        return ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return None


def _tuple_literal(tree: ast.AST, name: str
                   ) -> tuple[list[str], int] | None:
    """Top-level ``NAME = ('a', 'b', ...)`` -> (values, lineno)."""
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            vals = [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
            return vals, node.lineno
    return None


def _dataclass_fields(tree: ast.AST, classname: str) -> list[str]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == classname:
            return [s.target.id for s in node.body
                    if isinstance(s, ast.AnnAssign)
                    and isinstance(s.target, ast.Name)]
    return []


def _knob_names(tree: ast.AST, func: str) -> list[tuple[str, int]]:
    """First-arg string literals of ``Knob(...)`` calls inside
    ``func``."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func:
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == 'Knob' and sub.args
                        and isinstance(sub.args[0], ast.Constant)):
                    out.append((sub.args[0].value, sub.lineno))
    return out


def _name_compare_literals(tree: ast.AST, func: str, var: str
                           ) -> list[tuple[str, int]]:
    """String literals ``var`` is compared against inside ``func``
    (``name == 'x'`` / ``name in ('x', 'y')``)."""
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef) and node.name == func):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Compare):
                continue
            if not (isinstance(sub.left, ast.Name)
                    and sub.left.id == var):
                continue
            for comp in sub.comparators:
                if (isinstance(comp, ast.Constant)
                        and isinstance(comp.value, str)):
                    out.append((comp.value, sub.lineno))
                elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    out.extend(
                        (e.value, sub.lineno) for e in comp.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str))
    return out


def _cli_flags(tree: ast.AST) -> set[str]:
    """Every ``add_argument('--flag', ...)`` literal in the file."""
    flags = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == 'add_argument' and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            flags.add(node.args[0].value)
    return flags


#: Callable names whose first string argument is an event name. Both
#: attribute calls (``sink.event_record('x')``, ``self._event('x')``)
#: and bare-name calls (``emit_event(sink, 'x')`` — helper functions a
#: module defines over its sink, the r17 supervisor/heartbeat shape)
#: are scanned: an event literal laundered through a local helper must
#: still be registered in ``sink.EVENT_KINDS``.
_EVENT_EMITTERS = ('event_record', '_event', 'emit_event')


def _event_literals(tree: ast.AST) -> list[tuple[str, int]]:
    """Literal event names this module emits: the first string argument
    of any :data:`_EVENT_EMITTERS` call (attribute or bare name) plus
    ``{'event': 'x', ...}`` dict literals."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
            elif isinstance(node.func, ast.Name):
                name = node.func.id
            else:
                name = None
            if name in _EVENT_EMITTERS:
                # First STRING positional arg: helpers often take the
                # sink first (``emit_event(sink, 'x', ...)``).
                for arg in node.args[:2]:
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        out.append((arg.value, node.lineno))
                        break
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (isinstance(k, ast.Constant) and k.value == 'event'
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    out.append((v.value, node.lineno))
    return out


# ---------------------------------------------------------------------------
# The checks
# ---------------------------------------------------------------------------

def check_surface(package_dir: str | pathlib.Path,
                  examples_dir: str | pathlib.Path | None = None
                  ) -> tuple[list[Finding], list[str]]:
    """Run every cross-file check; returns ``(findings, skipped)``.

    ``skipped`` lists checks that could not run (e.g. no examples/
    directory in an installed-package tree) — reported, never silent.
    """
    pkg = pathlib.Path(package_dir)
    findings: list[Finding] = []
    skipped: list[str] = []

    def emit(path: pathlib.Path, line: int, message: str):
        findings.append(Finding(str(path), line, 0, 'surface-drift',
                                'surface', message))

    opt_path = pkg / 'training' / 'optimizers.py'
    opt_tree = _parse(opt_path)
    fields: list[str] = []
    tunables: list[str] = []
    if opt_tree is None:
        skipped.append('optimizers.py unreadable: TUNABLE_FIELDS/'
                       'OptimConfig checks skipped')
    else:
        fields = _dataclass_fields(opt_tree, 'OptimConfig')
        tup = _tuple_literal(opt_tree, 'TUNABLE_FIELDS')
        if not fields or tup is None:
            skipped.append('OptimConfig/TUNABLE_FIELDS not found: '
                           'surface checks degraded')
        else:
            tunables, tline = tup
            for t in tunables:
                if t not in fields:
                    emit(opt_path, tline,
                         f'TUNABLE_FIELDS entry {t!r} is not an '
                         'OptimConfig field — a tuned artifact '
                         'naming it would be rejected at apply time')
            if len(set(tunables)) != len(tunables):
                emit(opt_path, tline,
                     'TUNABLE_FIELDS contains duplicates')

    # autotune space knobs reference tunable fields
    space_path = pkg / 'autotune' / 'space.py'
    space_tree = _parse(space_path)
    if space_tree is None:
        skipped.append('autotune/space.py unreadable: knob check '
                       'skipped')
    elif tunables:
        for knob, line in _knob_names(space_tree, 'default_space'):
            if knob not in tunables:
                emit(space_path, line,
                     f'autotune space knob {knob!r} is not in '
                     'TUNABLE_FIELDS — the driver could commit an '
                     'artifact apply_tuned must reject')

    # kfac_overrides special-cases reference tunable fields
    driver_path = pkg / 'autotune' / 'driver.py'
    driver_tree = _parse(driver_path)
    if driver_tree is None:
        skipped.append('autotune/driver.py unreadable: '
                       'kfac_overrides check skipped')
    elif tunables:
        for name, line in _name_compare_literals(
                driver_tree, 'kfac_overrides', 'name'):
            if name not in tunables:
                emit(driver_path, line,
                     f'kfac_overrides special-cases {name!r}, which '
                     'is not a TUNABLE_FIELDS entry (dead or stale '
                     'mapping)')

    # every tunable has its CLI flag in all three examples
    if examples_dir is None:
        examples_dir = pkg.parent / 'examples'
    examples_dir = pathlib.Path(examples_dir)
    if not examples_dir.is_dir():
        skipped.append(f'{examples_dir}: no examples directory — '
                       'CLI-flag coverage check skipped')
    elif tunables:
        for cli in EXAMPLE_CLIS:
            cli_path = examples_dir / cli
            cli_tree = _parse(cli_path)
            if cli_tree is None:
                skipped.append(f'{cli}: unreadable — CLI-flag '
                               'coverage check skipped for it')
                continue
            flags = _cli_flags(cli_tree)
            for field in tunables:
                want = flag_for(field)
                if want not in flags:
                    emit(cli_path, 1,
                         f'tunable {field!r} has no {want} flag in '
                         f'{cli} — the knob surface must stay '
                         'consistent across all three example CLIs')

    # every literal event name is registered in sink.EVENT_KINDS
    sink_path = pkg / 'observability' / 'sink.py'
    sink_tree = _parse(sink_path)
    kinds = _tuple_literal(sink_tree, 'EVENT_KINDS') \
        if sink_tree is not None else None
    if kinds is None:
        skipped.append('observability/sink.py has no EVENT_KINDS '
                       'registry: event-name check skipped')
    else:
        registry = set(kinds[0])
        for py in sorted(pkg.rglob('*.py')):
            if '__pycache__' in py.parts:
                continue
            tree = _parse(py)
            if tree is None:
                continue
            for name, line in _event_literals(tree):
                if name not in registry:
                    emit(py, line,
                         f'event name {name!r} is not in '
                         'observability.sink.EVENT_KINDS — register '
                         'it so report/gate consumers can rely on '
                         'one registry')

    return findings, skipped
