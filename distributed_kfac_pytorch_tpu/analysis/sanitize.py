"""Runtime sanitizer gates: the dynamic oracle for the static rules.

    KFAC_SANITIZE=transfer,nan,retrace python examples/train_...

Three independent modes, comma-separated in the ``KFAC_SANITIZE``
environment variable (read once per epoch by
``training.engine.train_epoch``; unset = the unsanitized engine
path). The static linter (``analysis.lint``) under-approximates by
design — it flags only syntactically certain violations — so these
gates are what proves the invariants hold end-to-end on a real
training loop (``scripts/lint_smoke.sh`` runs a representative
fast-tier module under ``KFAC_SANITIZE=transfer,nan`` in CI).

``transfer``
    Wraps every *warm* step dispatch in
    ``jax.transfer_guard_device_to_host('disallow')`` plus a
    Python-level ``jax.device_get`` interposer. On accelerator
    backends the XLA guard catches every device->host transfer the
    step provokes (a stray ``.item()``, an implicit ``__bool__``,
    ``np.asarray`` of a traced value); on the CPU backend arrays are
    host-resident and the XLA guard never trips (zero-copy reads),
    so the interposer — which raises on any ``jax.device_get``
    inside the guarded region — is what keeps the mode load-bearing
    in CPU CI. The first dispatch of each cadence-flag combination
    is exempt — trace + XLA compile legitimately reads device
    constants, and those steps are already labeled
    ``fired='compile'`` in the metrics stream. The documented
    per-step blocking points (the r10 barrier probe, the
    epoch-boundary metric drain) sit OUTSIDE the guarded region by
    construction, mirroring their lint waivers.

``nan``
    Runs every step dispatch under ``jax.debug_nans``: a NaN/Inf
    produced by the step fails loudly at the producing primitive
    instead of poisoning the factor EMAs. Applied uniformly to every
    dispatch (compile steps included) so the debug flag cannot fork
    the jit trace cache mid-run. This is the eager cousin of the
    on-device ``nonfinite_guard`` (which protects factor statistics
    only and is collective-safe).

``retrace``
    After every step, checks the step builder's host-side
    ``trace_counts`` tally (``DistributedKFAC.build_train_step``)
    and raises on any variant traced more than once — the online
    form of the zero-retrace contract the offline gate regresses
    (``observability.gate``: ``retraces`` metric).

The sanitizer costs dispatch-pipelining (context-manager toggles per
step; debug_nans blocks on every step's outputs) and must stay off
in production runs.
"""

from __future__ import annotations

import contextlib
import os

ENV_VAR = 'KFAC_SANITIZE'
MODES = ('transfer', 'nan', 'retrace')


class SanitizerError(RuntimeError):
    """A sanitizer gate tripped (transfer/retrace violation)."""


@contextlib.contextmanager
def _device_get_interposer():
    """Raise on any ``jax.device_get`` within the region.

    The CPU-backend arm of the transfer gate (see module docs): XLA's
    transfer guard is a no-op when arrays are host-resident, but an
    explicit ``device_get`` on the hot path is a violation on every
    backend — it blocks the host on device completion. Patches the
    public binding for the region's duration (the engine loop is
    single-threaded; restored on exit even on error)."""
    import jax

    def _blocked(*args, **kwargs):
        raise SanitizerError(
            'KFAC_SANITIZE=transfer: jax.device_get inside a warm '
            'step dispatch — a host sync on the hot path. Drain the '
            'value asynchronously (metrics sink) or move the read '
            'to a documented blocking point (and waive it in lint)')

    orig = jax.device_get
    jax.device_get = _blocked
    try:
        yield
    finally:
        jax.device_get = orig


def parse_modes(value: str | None) -> frozenset:
    """Parse a ``KFAC_SANITIZE`` value; raises on unknown modes so a
    typo ('KFAC_SANITIZE=transfers') cannot silently sanitize
    nothing."""
    if not value:
        return frozenset()
    modes = frozenset(s.strip() for s in value.split(',') if s.strip())
    unknown = sorted(modes - set(MODES))
    if unknown:
        raise ValueError(
            f'{ENV_VAR}={value!r}: unknown sanitizer mode(s) '
            f'{unknown} (choose from {list(MODES)})')
    return modes


class Sanitizer:
    """Per-epoch sanitizer (engine-owned; see module docs).

    A Sanitizer with no modes is inert: ``step_guard`` degrades to a
    null context and ``after_step`` returns immediately, so the
    engine wires it unconditionally without forking its step loop.
    """

    def __init__(self, modes=()):
        self.modes = frozenset(modes)
        self._warm_variants: set = set()

    def __bool__(self) -> bool:
        return bool(self.modes)

    @classmethod
    def from_env(cls, environ=None) -> 'Sanitizer':
        return cls(parse_modes((environ or os.environ).get(ENV_VAR)))

    def _warm_set(self, step_fn) -> set:
        """The per-step-fn warm-variant set, attached to the step
        callable itself so it lives exactly as long as the compiled
        variant cache does — a Sanitizer is rebuilt every epoch (the
        env is re-read), and a per-epoch set would re-exempt the
        first dispatch of every flag combination in every epoch
        (e.g. the once-per-window inverse firing would NEVER be
        guarded on a one-window epoch). Falls back to the
        sanitizer-local set for callables that refuse attributes."""
        warm = getattr(step_fn, '_kfac_sanitize_warm', None)
        if warm is None:
            warm = set()
            try:
                step_fn._kfac_sanitize_warm = warm
            except (AttributeError, TypeError):
                warm = self._warm_variants
        return warm

    def step_guard(self, step_fn, flags: dict):
        """Context manager wrapping ONE dispatch of ``step_fn``.

        ``flags`` is the step's static cadence-flag dict — the first
        dispatch of each distinct combination is the compile step
        and runs without the transfer guard (see module docs); every
        later dispatch of that combination is steady-state hot path
        and must not transfer device->host. The nan gate applies to
        every dispatch uniformly (a per-step flip of ``debug_nans``
        would fork the jit trace cache).
        """
        if not self.modes:
            return contextlib.nullcontext()
        import jax
        stack = contextlib.ExitStack()
        if 'nan' in self.modes:
            stack.enter_context(jax.debug_nans(True))
        if 'transfer' in self.modes:
            warm = self._warm_set(step_fn)
            key = tuple(sorted(flags.items()))
            if key in warm:
                stack.enter_context(
                    jax.transfer_guard_device_to_host('disallow'))
                stack.enter_context(_device_get_interposer())
            else:
                warm.add(key)
        return stack

    def after_step(self, step_fn, step: int) -> None:
        """Post-dispatch checks (currently: the retrace tally)."""
        if 'retrace' not in self.modes:
            return
        counts = getattr(step_fn, 'trace_counts', None)
        if not counts:
            return
        retraced = {k: n for k, n in counts.items() if n > 1}
        if retraced:
            raise SanitizerError(
                f'KFAC_SANITIZE=retrace: step {step} left program '
                f'variant(s) traced more than once: {retraced} — '
                'the one-compile-per-variant contract is broken '
                '(PERF.md pitfalls 2-3; see the retrace events in '
                'the metrics stream for the variant labels)')
