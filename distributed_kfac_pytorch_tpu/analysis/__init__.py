"""kfaclint: repo-invariant static analysis + runtime sanitizers.

The r6-r14 subsystems rest on invariants that, until r15, were only
enforced by example-specific runtime tests:

  - **host-sync hygiene** — no ``.item()`` / ``jax.device_get`` /
    device-value ``float()``/``int()`` casts / implicit ``__bool__``
    on the hot-path modules (``preconditioner``,
    ``parallel/distributed``, ``ops/*``, ``layers/*``,
    ``training/engine``). A single stray host read serializes the
    async dispatch pipeline (arXiv:2107.06533's "smart parallelism"
    wins evaporate exactly this way).
  - **retrace hazards** — the static-cadence contract (one compile
    per program variant, ever; PERF.md pitfalls 2-3) requires
    hashable canonical variant-key flags and no ``jax.jit`` /
    ``shard_map`` construction inside per-step loops.
  - **collective axis discipline** — every ``psum``/``pmean``/
    ``all_gather`` names its axes via the canonical constants
    (``parallel.distributed.INV_GROUP_AXIS`` & friends), never
    string literals, so a mesh-axis rename cannot silently split the
    collective surface.
  - **dtype discipline** — bf16-pipeline matmuls carry fp32
    accumulation (``preferred_element_type``), the r6 contract.
  - **surface consistency** — ``TUNABLE_FIELDS`` ⊆ ``OptimConfig``,
    every tunable has its CLI flag in all three examples, autotune
    space knobs / ``kfac_overrides`` reference real fields, and
    event names are drawn from ``observability.sink.EVENT_KINDS``.

Static entry point (exit 1 on violation, ``--json`` machine output
like ``observability.gate``):

    python -m distributed_kfac_pytorch_tpu.analysis.lint

Runtime counterpart (the dynamic oracle for the static rules), wired
into ``training.engine.train_epoch``:

    KFAC_SANITIZE=transfer,nan,retrace python examples/...

See :mod:`analysis.rules` for the rule families and the inline waiver
syntax (``# kfaclint: waive[rule-id] reason``), :mod:`analysis.surface`
for the cross-file checks, and :mod:`analysis.sanitize` for the
runtime mode.
"""

from distributed_kfac_pytorch_tpu.analysis.rules import (  # noqa: F401
    Finding,
    RULES,
    lint_source,
)
from distributed_kfac_pytorch_tpu.analysis.sanitize import (  # noqa: F401
    Sanitizer,
)
