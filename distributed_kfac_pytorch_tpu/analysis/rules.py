"""AST rule families for kfaclint (single-file checks).

Five rule families; the first four live here (pure AST, one file at a
time), the fifth (``surface``) is cross-file and lives in
:mod:`analysis.surface`:

==================  =====================================================
family              rules
==================  =====================================================
``host-sync``       ``host-item``, ``host-device-get``,
                    ``host-scalar-cast``, ``host-implicit-bool``,
                    ``host-np-asarray`` — device->host transfers on the
                    hot-path modules. Static under-approximation by
                    design: only *syntactically certain* device values
                    (a ``jnp.*``/``jax.lax.*`` call in the expression)
                    are flagged; ``KFAC_SANITIZE=transfer`` is the
                    dynamic oracle for what the AST cannot see.
``retrace``         ``retrace-jit-in-loop``,
                    ``retrace-traced-mutation``,
                    ``retrace-variant-flag`` — hazards to the
                    one-compile-per-variant contract (PERF.md
                    pitfalls 2-3; the ``trace_counts`` guard and
                    ``KFAC_SANITIZE=retrace`` are the runtime form).
``axis``            ``axis-literal`` — collectives must name axes via
                    the canonical constants
                    (``parallel.distributed.INV_GROUP_AXIS``,
                    ``GRAD_WORKER_AXIS``, ``KFAC_AXES``,
                    ``parallel.sequence.SEQ_AXIS``), never string
                    literals.
``dtype``           ``dtype-matmul-accum`` — a matmul whose operands
                    are syntactically bf16-flavored (``bfloat16`` /
                    ``*compute_dtype*`` / ``*bf16*`` names) or part
                    of the r19 randomized low-rank sketch pipeline
                    (``*sketch*`` / ``*lowrank*`` names — the basis
                    products that must not silently accumulate in a
                    reduced-precision backend default) must pin fp32
                    accumulation via ``preferred_element_type``
                    (the r6 bf16-pipeline contract).
                    ``dtype-pallas-matmul-accum`` — EVERY matmul
                    inside a Pallas kernel body (a function passed to
                    ``pl.pallas_call``, directly or through
                    ``functools.partial``, or whose signature takes
                    two or more ``*_ref`` parameters) must pin
                    ``preferred_element_type=jnp.float32``: Mosaic
                    lowers an unpinned MXU matmul at the operand
                    dtype, so a bf16 block accumulates in bf16 with
                    no backend-default safety net (r21; the fused
                    factor/precondition kernels are the production
                    call sites).
==================  =====================================================

Waiver syntax (for the documented blocking points — the barrier
probe, metric drains, checkpoint-restore paths):

    kstep = int(jax.device_get(s['step']))  # kfaclint: waive[host-sync] one sync per epoch, documented

A waiver names a rule id or a family, must carry a non-empty reason,
and covers its own line plus the following line (so it can sit on its
own line above a multi-line call). A malformed waiver is itself a
finding (``waiver-unknown-rule`` / ``waiver-missing-reason``) so a
typo cannot silently disable a rule.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize

# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

#: rule id -> (family, one-line doc). The single point of truth the
#: CLI's --list-rules, the waiver validator and the tests read.
RULES = {
    'host-item': (
        'host-sync', '.item() is a device->host sync'),
    'host-device-get': (
        'host-sync', 'jax.device_get blocks on device values'),
    'host-scalar-cast': (
        'host-sync', 'float()/int()/bool() of a traced expression '
        'forces a host sync'),
    'host-implicit-bool': (
        'host-sync', 'branching on a jnp/lax expression calls '
        '__bool__ -> host sync'),
    'host-np-asarray': (
        'host-sync', 'np.asarray/np.array of a jnp/lax expression '
        'pulls it to host'),
    'retrace-jit-in-loop': (
        'retrace', 'jax.jit/shard_map built inside a loop body '
        'retraces per iteration'),
    'retrace-traced-mutation': (
        'retrace', 'assigning self.<attr> inside a jitted function '
        'mutates module state at trace time'),
    'retrace-variant-flag': (
        'retrace', 'variant-key cadence flag given a non-canonical '
        '(unhashable or float/str) value'),
    'axis-literal': (
        'axis', 'collective names an axis with a string literal '
        'instead of the canonical axis constants'),
    'dtype-matmul-accum': (
        'dtype', 'bf16-flavored matmul without fp32 '
        'preferred_element_type accumulation'),
    'dtype-pallas-matmul-accum': (
        'dtype', 'matmul inside a Pallas kernel body without fp32 '
        'preferred_element_type accumulation'),
    'surface-drift': (
        'surface', 'cross-file knob/event surface drift '
        '(see analysis.surface)'),
    # meta rules (waiver hygiene; never waivable themselves)
    'waiver-unknown-rule': (
        'waiver', 'waiver names a rule id/family that does not exist'),
    'waiver-missing-reason': (
        'waiver', 'waiver carries no reason'),
}

FAMILIES = ('host-sync', 'retrace', 'axis', 'dtype', 'surface')

#: the variant-key cadence flags build_train_step statically keys on.
VARIANT_FLAGS = ('factor_update', 'inv_update', 'inv_chunk',
                 'factor_reduce', 'factor_snapshot')

#: jax.lax collectives whose axis argument the axis rule inspects,
#: mapped to the positional index of that argument.
COLLECTIVE_AXIS_ARG = {
    'psum': 1, 'pmean': 1, 'pmax': 1, 'pmin': 1,
    'all_gather': 1, 'all_to_all': 1, 'ppermute': 1,
    'psum_scatter': 1, 'pshuffle': 1,
    'axis_index': 0, 'axis_size': 0,
}

#: jnp/lax functions that LOOK like device calls but return host
#: values (static dtype predicates) — exempt from host-implicit-bool.
_STATIC_PREDICATES = frozenset({
    'issubdtype', 'isdtype', 'dtype', 'result_type', 'can_cast',
    'shape', 'ndim', 'size'})

_MATMUL_FUNCS = frozenset({
    'matmul', 'dot', 'einsum', 'tensordot', 'dot_general'})

_BF16_NAME = re.compile(r'bfloat16|bf16|compute_dtype|sketch|lowrank')

#: hot-path module patterns (package-relative posix paths) the
#: host-sync and dtype families are scoped to.
HOT_PATH_PATTERNS = (
    'preconditioner.py',
    'parallel/distributed.py',
    'parallel/sequence.py',
    'training/engine.py',
    'ops/',
    'layers/',
)


def is_hot_path(package_rel_path: str) -> bool:
    """True when ``package_rel_path`` (posix, relative to the package
    root) is one of the hot-path modules."""
    p = package_rel_path.replace('\\', '/')
    return any(p == pat or (pat.endswith('/') and p.startswith(pat))
               for pat in HOT_PATH_PATTERNS)


@dataclasses.dataclass
class Finding:
    """One rule violation (or waiver-hygiene problem)."""
    path: str
    line: int
    col: int
    rule: str
    family: str
    message: str
    waived: bool = False

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Waivers
# ---------------------------------------------------------------------------

_WAIVER_RE = re.compile(
    r'#\s*kfaclint:\s*waive\[([^\]]*)\]\s*(.*)$')


@dataclasses.dataclass
class Waiver:
    line: int
    rules: tuple          # rule ids and/or family names
    reason: str
    used: bool = False

    def covers(self, rule: str, family: str, line: int) -> bool:
        if line not in (self.line, self.line + 1):
            return False
        return rule in self.rules or family in self.rules


def parse_waivers(source: str, path: str
                  ) -> tuple[list[Waiver], list[Finding]]:
    """Scan ``source`` for waiver comments; malformed ones become
    findings (a typo must not silently disable a rule).

    Real COMMENT tokens only (via ``tokenize``) — waiver syntax
    quoted in a docstring or string literal is documentation, not a
    waiver."""
    waivers, findings = [], []
    comments: list[tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # unparseable files already get a syntax-error finding
    for lineno, text in comments:
        m = _WAIVER_RE.search(text)
        if not m:
            continue
        names = tuple(s.strip() for s in m.group(1).split(',')
                      if s.strip())
        reason = m.group(2).strip()
        bad = [n for n in names
               if n not in RULES and n not in FAMILIES]
        if bad or not names:
            findings.append(Finding(
                path, lineno, 0, 'waiver-unknown-rule', 'waiver',
                f'waiver names unknown rule(s)/family(ies) '
                f'{bad or ["<empty>"]} — one of {sorted(RULES)} or '
                f'{list(FAMILIES)}'))
            continue
        if not reason:
            findings.append(Finding(
                path, lineno, 0, 'waiver-missing-reason', 'waiver',
                'waiver must carry a reason '
                '(# kfaclint: waive[rule] why this blocking point '
                'is legitimate)'))
            continue
        waivers.append(Waiver(lineno, names, reason))
    return waivers, findings


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------

def _chain(node) -> list[str] | None:
    """`jax.lax.psum` -> ['jax', 'lax', 'psum']; None if not a plain
    dotted name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


class _Aliases:
    """Import aliases for the jax / jax.numpy / jax.lax / numpy roots."""

    def __init__(self, tree: ast.AST):
        self.jnp = {'jnp'}      # jax.numpy aliases
        self.lax = {'lax'}      # jax.lax aliases
        self.jax = {'jax'}
        self.np = {'np', 'onp', 'numpy'}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name
                    if a.name == 'jax.numpy':
                        self.jnp.add(name)
                    elif a.name == 'jax.lax':
                        self.lax.add(name)
                    elif a.name == 'jax':
                        self.jax.add(name)
                    elif a.name == 'numpy':
                        self.np.add(name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == 'jax':
                    for a in node.names:
                        if a.name == 'numpy':
                            self.jnp.add(a.asname or 'numpy')
                        elif a.name == 'lax':
                            self.lax.add(a.asname or 'lax')

    def is_device_chain(self, chain: list[str] | None) -> bool:
        """True when the dotted chain roots in jnp / lax / jax.lax —
        an expression that produces (or is) a traced/device value."""
        if not chain or len(chain) < 2:
            return False
        if chain[0] in self.jnp or chain[0] in self.lax:
            return True
        return (chain[0] in self.jax and len(chain) >= 3
                and chain[1] in ('lax', 'numpy'))

    def device_func_name(self, chain: list[str] | None) -> str | None:
        """Final attribute of a device-rooted chain (else None)."""
        return chain[-1] if self.is_device_chain(chain) else None


def _contains_device_expr(node: ast.AST, aliases: _Aliases) -> bool:
    """True when the expression syntactically CONTAINS a device value:
    a jnp/lax call, an ``.item()`` call, or ``jax.device_get``."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        chain = _chain(sub.func)
        if aliases.is_device_chain(chain):
            return True
        if chain and chain[-1] == 'device_get':
            return True
        if (isinstance(sub.func, ast.Attribute)
                and sub.func.attr == 'item' and not sub.args):
            return True
    return False


def _has_string_literal(node: ast.AST) -> bool:
    """Str constant, or a tuple/list containing one."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_has_string_literal(e) for e in node.elts)
    return False


# ---------------------------------------------------------------------------
# The visitor
# ---------------------------------------------------------------------------

class _RuleVisitor(ast.NodeVisitor):
    def __init__(self, path: str, aliases: _Aliases, *, hot: bool,
                 jit_wrapped_names: frozenset,
                 pallas_kernel_names: frozenset = frozenset()):
        self.path = path
        self.aliases = aliases
        self.hot = hot
        self.jit_wrapped_names = jit_wrapped_names
        self.pallas_kernel_names = pallas_kernel_names
        self.findings: list[Finding] = []
        self._loop_depth = 0
        self._jitted_depth = 0
        self._pallas_depth = 0

    def _emit(self, node, rule: str, message: str):
        family = RULES[rule][0]
        self.findings.append(Finding(
            self.path, node.lineno, node.col_offset, rule, family,
            message))

    # -- loops (for retrace-jit-in-loop scope) --------------------------
    def visit_For(self, node):
        # target/iter evaluate ONCE, before the loop — only the body
        # re-executes per iteration (orelse runs once, after)
        self.visit(node.target)
        self.visit(node.iter)
        self._loop_body(node)

    def visit_While(self, node):
        if self.hot:
            self._check_bool_context(node.test)
        # the test DOES re-evaluate per iteration
        self._loop_depth += 1
        self.visit(node.test)
        self._loop_depth -= 1
        self._loop_body(node)

    def _loop_body(self, node):
        self._loop_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        self._loop_depth -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    # -- function defs (traced-mutation scope) --------------------------
    def visit_FunctionDef(self, node):
        self._function(node)

    def visit_AsyncFunctionDef(self, node):
        self._function(node)

    def _is_jit_decorator(self, dec) -> bool:
        chain = _chain(dec) or (
            _chain(dec.func) if isinstance(dec, ast.Call) else None)
        if chain and chain[-1] == 'jit':
            return True
        # functools.partial(jax.jit, ...)
        if isinstance(dec, ast.Call) and dec.args:
            inner = _chain(dec.args[0])
            if inner and inner[-1] == 'jit':
                return True
        return False

    def _is_pallas_kernel(self, node) -> bool:
        """A def is a Pallas kernel body when it is passed to
        ``pallas_call`` somewhere in the module, or (structural
        fallback for kernels handed over through wrappers the name
        scan cannot see) when two or more of its parameters follow
        the ``*_ref`` Ref-argument naming convention."""
        if node.name in self.pallas_kernel_names:
            return True
        params = node.args.posonlyargs + node.args.args
        return sum(p.arg.endswith('_ref') for p in params) >= 2

    def _function(self, node):
        jitted = (any(self._is_jit_decorator(d)
                      for d in node.decorator_list)
                  or node.name in self.jit_wrapped_names)
        in_pallas = self._is_pallas_kernel(node)
        if jitted:
            self._jitted_depth += 1
        if in_pallas:
            self._pallas_depth += 1
        # a nested def is a fresh loop scope: jit built once inside a
        # helper that a loop merely CALLS is not a per-iteration build
        saved_loops, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = saved_loops
        if in_pallas:
            self._pallas_depth -= 1
        if jitted:
            self._jitted_depth -= 1

    def _check_self_mutation(self, node, targets):
        if self._jitted_depth == 0:
            return
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == 'self'):
                self._emit(
                    node, 'retrace-traced-mutation',
                    f'self.{t.attr} assigned inside a jitted '
                    'function: module state mutated at trace time '
                    'is frozen into the compiled program and '
                    'desyncs on retrace — thread it through the '
                    'state pytree instead')

    def visit_Assign(self, node):
        self._check_self_mutation(node, node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_self_mutation(node, [node.target])
        self.generic_visit(node)

    # -- branch tests (implicit __bool__) -------------------------------
    def _check_bool_context(self, test):
        """A jnp/lax call ANYWHERE in a boolean test means the test
        value is traced: ``if jnp.any(x)``, ``if jnp.max(x) > t``,
        ``while jnp.linalg.norm(g) > eps and i < n`` all force
        ``__bool__`` on a device value. Static dtype/shape predicates
        (``jnp.issubdtype`` & co) are exempt."""
        def outermost(node):
            """Outermost device calls only (one finding per traced
            subexpression, not one per nested jnp call)."""
            if isinstance(node, ast.Call):
                name = self.aliases.device_func_name(
                    _chain(node.func))
                if name and name not in _STATIC_PREDICATES:
                    yield node
                    return
            for child in ast.iter_child_nodes(node):
                yield from outermost(child)

        for e in outermost(test):
            self._emit(
                e, 'host-implicit-bool',
                f'branching on {ast.unparse(e)[:60]!r} calls '
                '__bool__ on a traced value (host sync; '
                'ConcretizationTypeError under jit) — use '
                'jnp.where/lax.cond or hoist the decision to '
                'the host')

    def visit_If(self, node):
        if self.hot:
            self._check_bool_context(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node):
        if self.hot:
            self._check_bool_context(node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node):
        if self.hot:
            self._check_bool_context(node.test)
        self.generic_visit(node)

    # -- calls ----------------------------------------------------------
    def visit_Call(self, node):
        chain = _chain(node.func)
        tail = chain[-1] if chain else None

        # retrace-jit-in-loop: applies everywhere (not just hot files)
        if (self._loop_depth > 0
                and tail in ('jit', 'shard_map', 'pmap')
                and (self.aliases.is_device_chain(chain)
                     or (chain and chain[0] in self.aliases.jax)
                     or chain == ['jit'] or chain == ['shard_map'])):
            self._emit(
                node, 'retrace-jit-in-loop',
                f'{".".join(chain)} constructed inside a loop body: '
                'each iteration builds a fresh traced callable '
                '(compile per iteration) — hoist the jit/shard_map '
                'out of the loop and reuse it')

        # retrace-variant-flag: canonical variant-key values only
        for kw in node.keywords:
            if kw.arg in VARIANT_FLAGS:
                bad = None
                if isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                    bad = 'an unhashable literal'
                elif (isinstance(kw.value, ast.Constant)
                      and not isinstance(kw.value.value,
                                         (bool, int, type(None)))):
                    bad = f'a {type(kw.value.value).__name__} literal'
                if bad:
                    self._emit(
                        node, 'retrace-variant-flag',
                        f'cadence flag {kw.arg}={ast.unparse(kw.value)}'
                        f' is {bad}: variant-cache keys must be '
                        'bool/int/None (hashable, canonical) or every '
                        'step compiles its own program variant')

        # axis-literal: canonical axis constants only
        axis_idx = COLLECTIVE_AXIS_ARG.get(tail)
        if axis_idx is not None and (
                self.aliases.is_device_chain(chain)
                or chain == [tail]):
            exprs = [kw.value for kw in node.keywords
                     if kw.arg in ('axis_name', 'axis', 'axis_names')]
            if not exprs and len(node.args) > axis_idx:
                exprs = [node.args[axis_idx]]
            for e in exprs:
                if _has_string_literal(e):
                    self._emit(
                        node, 'axis-literal',
                        f'{tail} names axis {ast.unparse(e)} as a '
                        'string literal — use the canonical axis '
                        'constants (parallel.distributed.'
                        'INV_GROUP_AXIS / GRAD_WORKER_AXIS / '
                        'KFAC_AXES / SLICE_AXIS, '
                        'parallel.sequence.SEQ_AXIS) so '
                        'a mesh rename cannot split the collective '
                        'surface')

        if self.hot:
            self._hot_call_rules(node, chain, tail)
        self.generic_visit(node)

    def _hot_call_rules(self, node, chain, tail):
        aliases = self.aliases
        # host-item
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == 'item' and not node.args):
            self._emit(
                node, 'host-item',
                f'{ast.unparse(node)[:60]!r}: .item() blocks the '
                'host on device completion — keep the value on '
                'device (metrics pytree) or drain it at the epoch '
                'boundary')
        # host-device-get
        if tail == 'device_get' and chain and (
                chain[0] in aliases.jax or chain == ['device_get']):
            self._emit(
                node, 'host-device-get',
                'jax.device_get on the hot path blocks the host — '
                'drain asynchronously (sink) or waive the '
                'documented blocking point')
        # host-scalar-cast
        if (isinstance(node.func, ast.Name)
                and node.func.id in ('float', 'int', 'bool')
                and len(node.args) == 1
                and _contains_device_expr(node.args[0], aliases)):
            self._emit(
                node, 'host-scalar-cast',
                f'{node.func.id}() of a device expression forces a '
                'host sync — keep it traced or drain it off the '
                'step path')
        # host-np-asarray
        if (tail in ('asarray', 'array') and chain
                and chain[0] in aliases.np and node.args
                and _contains_device_expr(node.args[0], aliases)):
            self._emit(
                node, 'host-np-asarray',
                f'np.{tail}() of a jnp/lax expression pulls it to '
                'host — keep the computation in jnp or waive the '
                'documented blocking point')
        # dtype-matmul-accum / dtype-pallas-matmul-accum
        if (tail in _MATMUL_FUNCS
                and aliases.is_device_chain(chain)
                and not any(kw.arg == 'preferred_element_type'
                            for kw in node.keywords)):
            if self._pallas_depth > 0:
                # Inside a Pallas kernel body the requirement is
                # unconditional — Mosaic accumulates an unpinned MXU
                # matmul at the operand dtype, so even an fp32-looking
                # Ref load can be a bf16 block under a compute_dtype
                # knob. The generic bf16-flavor rule is subsumed.
                self._emit(
                    node, 'dtype-pallas-matmul-accum',
                    f'{tail} inside a Pallas kernel body must pin '
                    'fp32 accumulation: pass preferred_element_type='
                    'jnp.float32 (Mosaic lowers the MXU accumulate '
                    'at the operand dtype with no backend-default '
                    'safety net)')
            else:
                flavored = any(
                    isinstance(sub, (ast.Name, ast.Attribute))
                    and _BF16_NAME.search(
                        sub.id if isinstance(sub, ast.Name)
                        else sub.attr)
                    for a in node.args for sub in ast.walk(a))
                if flavored:
                    self._emit(
                        node, 'dtype-matmul-accum',
                        f'{tail} with bf16-flavored operands must '
                        'pin fp32 accumulation: pass '
                        'preferred_element_type=jnp.float32 (the r6 '
                        'bf16-pipeline contract — bf16 operands, '
                        'fp32 accumulate)')


def _jit_wrapped_names(tree: ast.AST) -> frozenset:
    """Names of functions passed (by name) to jax.jit in this module —
    their defs count as jitted for retrace-traced-mutation."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = _chain(node.func)
            if chain and chain[-1] == 'jit' and node.args:
                inner = node.args[0]
                if isinstance(inner, ast.Name):
                    names.add(inner.id)
    return frozenset(names)


def _pallas_kernel_names(tree: ast.AST) -> frozenset:
    """Names of functions handed to ``pallas_call`` in this module —
    their defs count as Pallas kernel bodies for
    dtype-pallas-matmul-accum. Covers the bare form
    (``pl.pallas_call(kernel, ...)``) and the partial-bound form
    (``pl.pallas_call(functools.partial(kernel, decay=d), ...)``)
    the in-tree kernels use to close over scalars."""
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _chain(node.func)
        if not (chain and chain[-1] == 'pallas_call' and node.args):
            continue
        inner = node.args[0]
        if isinstance(inner, ast.Name):
            names.add(inner.id)
        elif isinstance(inner, ast.Call) and inner.args:
            head = _chain(inner.func)
            if head and head[-1] == 'partial':
                bound = inner.args[0]
                if isinstance(bound, ast.Name):
                    names.add(bound.id)
    return frozenset(names)


def lint_file(path: str, source: str, *, hot: bool | None = None,
              package_rel: str | None = None
              ) -> tuple[list[Finding], list[Waiver]]:
    """Lint one file's source; returns ``(findings, waivers)``.

    ``hot`` forces hot-path scoping (None: derived from
    ``package_rel`` via :func:`is_hot_path`). Waived findings are
    returned with ``waived=True`` (the CLI reports but does not fail
    on them); each returned waiver carries its authoritative
    ``used`` flag — the single coverage predicate is
    :meth:`Waiver.covers`.
    """
    if hot is None:
        hot = bool(package_rel) and is_hot_path(package_rel)
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, e.offset or 0,
                        'syntax-error', 'waiver',
                        f'file does not parse: {e.msg}')], []
    waivers, findings = parse_waivers(source, path)
    aliases = _Aliases(tree)
    visitor = _RuleVisitor(
        path, aliases, hot=hot,
        jit_wrapped_names=_jit_wrapped_names(tree),
        pallas_kernel_names=_pallas_kernel_names(tree))
    visitor.visit(tree)
    for f in visitor.findings:
        for w in waivers:
            if w.covers(f.rule, f.family, f.line):
                f.waived = True
                w.used = True
                break
    findings.extend(visitor.findings)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings, waivers


def lint_source(path: str, source: str, *, hot: bool | None = None,
                package_rel: str | None = None) -> list[Finding]:
    """:func:`lint_file`, findings only (the single-file API)."""
    return lint_file(path, source, hot=hot,
                     package_rel=package_rel)[0]
