"""kfaclint CLI: repo-invariant static analysis.

    python -m distributed_kfac_pytorch_tpu.analysis.lint [PATH ...]

Exit 0 = clean, 1 = violations, 2 = usage error — the same contract
as ``observability.gate``, so CI wires both the same way
(``scripts/lint_smoke.sh``). ``--json`` emits the machine verdict.

With no PATH arguments the default scan set is the package tree plus
the sibling ``examples/`` and ``benchmarks/`` directories (when
present); ``tests/`` is deliberately NOT scanned — tests host-sync
on purpose (oracles, fixtures) — but an explicit PATH argument lints
anything, which is how the fixture matrix under
``tests/fixtures/lint/`` pins each rule.

The single-file rule families (host-sync / retrace / axis / dtype)
come from :mod:`analysis.rules`; the cross-file ``surface`` family
from :mod:`analysis.surface` (skipped when ``--no-surface`` or when
PATH arguments are given that exclude the package).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from distributed_kfac_pytorch_tpu.analysis import rules as rules_mod
from distributed_kfac_pytorch_tpu.analysis import surface as surface_mod
from distributed_kfac_pytorch_tpu.analysis.rules import (
    FAMILIES,
    RULES,
    lint_file,
)

_SKIP_PARTS = frozenset({'__pycache__', '.git', 'csrc'})


def package_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent.parent


def default_paths() -> list[pathlib.Path]:
    pkg = package_root()
    out = [pkg]
    for sibling in ('examples', 'benchmarks'):
        d = pkg.parent / sibling
        if d.is_dir():
            out.append(d)
    return out


def iter_py_files(paths) -> list[pathlib.Path]:
    files: list[pathlib.Path] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            files.extend(
                f for f in sorted(p.rglob('*.py'))
                if not _SKIP_PARTS.intersection(f.parts))
        elif p.suffix == '.py':
            files.append(p)
        else:
            raise ValueError(f'{p}: not a .py file or directory')
    return files


def package_rel(path: pathlib.Path) -> str | None:
    """Path relative to the package root (posix), or None if outside
    the package (examples/benchmarks are never hot-path)."""
    try:
        return path.resolve().relative_to(package_root()).as_posix()
    except ValueError:
        return None


def lint_paths(paths, *, families=None,
               with_surface: 'bool | str' = True,
               assume_hot: bool = False) -> dict:
    """Lint ``paths``; returns the verdict object the CLI prints.

    ``families``: restrict to these rule families (None = all).
    ``with_surface``: True runs the cross-file surface checks; a
    string skips them and is reported verbatim as the skip reason
    (never a silent drop).
    ``assume_hot``: treat every file as hot-path (the fixture-matrix
    escape hatch — files outside the package are otherwise never
    hot, so the host-sync/dtype families would not fire on them).
    """
    files = iter_py_files(paths)
    findings = []
    n_waived = 0
    unused_waivers = []
    for f in files:
        file_findings, waivers = lint_file(
            str(f), f.read_text(),
            hot=True if assume_hot else None,
            package_rel=package_rel(f))
        for w in waivers:
            if not w.used:
                unused_waivers.append(
                    {'path': str(f), 'line': w.line,
                     'rules': list(w.rules), 'reason': w.reason})
        findings.extend(file_findings)
    if with_surface is True and families is not None \
            and 'surface' not in families:
        # don't pay the package-wide re-parse for findings the
        # family filter would immediately discard
        with_surface = ("surface checks skipped: --family filter "
                        "excludes 'surface'")
    if with_surface is True:
        pkg = package_root()
        surface_findings, skipped = surface_mod.check_surface(pkg)
        findings.extend(surface_findings)
    else:
        skipped = [str(with_surface)]
    if families:
        findings = [fi for fi in findings if fi.family in families
                    or fi.family == 'waiver']
    n_waived = sum(1 for fi in findings if fi.waived)
    active = [fi for fi in findings if not fi.waived]
    return {
        'pass': not active,
        'n_files': len(files),
        'n_findings': len(active),
        'n_waived': n_waived,
        'findings': [fi.to_dict() for fi in findings],
        'unused_waivers': unused_waivers,
        'skipped': skipped,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog='python -m distributed_kfac_pytorch_tpu.analysis.lint',
        description='kfaclint: host-sync / retrace / axis / dtype / '
                    'surface invariant checks over the source tree. '
                    'Exit 0 = clean, 1 = violations, 2 = usage '
                    'error.')
    p.add_argument('paths', nargs='*',
                   help='files or directories to lint (default: the '
                        'package + examples/ + benchmarks/)')
    p.add_argument('--json', action='store_true',
                   help='machine-readable verdict on stdout')
    p.add_argument('--family', action='append', default=[],
                   choices=list(FAMILIES),
                   help='restrict to a rule family (repeatable)')
    p.add_argument('--no-surface', action='store_true',
                   help='skip the cross-file surface checks')
    p.add_argument('--assume-hot', action='store_true',
                   help='treat every linted file as a hot-path '
                        'module (arms the host-sync/dtype families '
                        'outside the package — the fixture-matrix '
                        'mode)')
    p.add_argument('--list-rules', action='store_true',
                   help='print the rule registry and exit')
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, (family, doc) in sorted(RULES.items()):
            print(f'{rule:26s} [{family}] {doc}')
        return 0

    try:
        paths = ([pathlib.Path(s) for s in args.paths]
                 or default_paths())
        missing = [str(s) for s in paths if not s.exists()]
        if missing:
            raise ValueError(f'no such path(s): {missing}')
        # Surface checks are anchored to the package: run them on the
        # default (whole-tree) invocation and whenever an explicit
        # PATH covers the package root; otherwise report the skip
        # with its real reason (never a silent drop).
        if args.no_surface:
            with_surface = 'surface checks disabled (--no-surface)'
        elif not args.paths:
            with_surface = True
        else:
            pkg = package_root()
            resolved = [p.resolve() for p in paths]
            # a path "covers" the package when it IS the package root
            # or an ancestor of it (e.g. the repo root / '.') — a
            # single file inside the package does not.
            if any(r == pkg or r in pkg.parents for r in resolved):
                with_surface = True
            else:
                with_surface = ('surface checks skipped: explicit '
                                'PATH arguments do not cover the '
                                'package root')
        verdict = lint_paths(
            paths, families=set(args.family) or None,
            assume_hot=args.assume_hot,
            with_surface=with_surface)
    except (OSError, ValueError) as e:
        print(f'error: {e}', file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(verdict, sort_keys=True))
        return 0 if verdict['pass'] else 1

    print('== kfaclint ==')
    print(f"{verdict['n_files']} file(s); "
          f"{verdict['n_findings']} violation(s), "
          f"{verdict['n_waived']} waived")
    for fi in verdict['findings']:
        tag = 'waived ' if fi['waived'] else 'FAIL   '
        print(f"  {tag}{fi['path']}:{fi['line']}:{fi['col']} "
              f"[{fi['rule']}] {fi['message']}")
    for w in verdict['unused_waivers']:
        print(f"  note   {w['path']}:{w['line']} unused waiver "
              f"for {w['rules']}")
    for s in verdict['skipped']:
        print(f'  skip   {s}')
    print('PASS' if verdict['pass'] else 'FAIL')
    return 0 if verdict['pass'] else 1


if __name__ == '__main__':
    sys.exit(main())
