"""TPU-native distributed K-FAC: a JAX/XLA/Pallas rebuild of the
capabilities of MLHPC/Distributed_KFAC_Pytorch (kfac-pytorch 0.3.1).

Current public surface: the ``ops`` (factor statistics, dense linalg) and
``parallel`` (mesh placement) subpackages. The top-level ``KFAC`` /
``CommMethod`` / ``KFACParamScheduler`` API (parity with reference
kfac/__init__.py:1-5) lands as the preconditioner core is built out.
"""

__version__ = '0.1.0'

from distributed_kfac_pytorch_tpu import ops
from distributed_kfac_pytorch_tpu import parallel
