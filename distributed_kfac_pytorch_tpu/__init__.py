"""TPU-native distributed K-FAC: a JAX/XLA/Pallas rebuild of the
capabilities of MLHPC/Distributed_KFAC_Pytorch (kfac-pytorch 0.3.1).

Public API (parity with reference kfac/__init__.py:1-5):
  - ``KFAC``: the K-FAC gradient preconditioner (functional state pytree).
  - ``CommMethod``: COMM_OPT / MEM_OPT / HYBRID_OPT strategies.
  - ``KFACParamScheduler``: epoch-schedule decay of damping / update freqs.
  - ``KFACCapture``: hook-free activation/output-grad capture for flax.
plus the ``ops``, ``parallel`` and ``layers`` subpackages.
"""

__version__ = '0.1.0'

from distributed_kfac_pytorch_tpu import compat

compat.install()

from distributed_kfac_pytorch_tpu import fp16
from distributed_kfac_pytorch_tpu import observability
from distributed_kfac_pytorch_tpu import ops
from distributed_kfac_pytorch_tpu import parallel
from distributed_kfac_pytorch_tpu import utils
from distributed_kfac_pytorch_tpu.capture import KFACCapture
from distributed_kfac_pytorch_tpu.optim import kfac_transform
from distributed_kfac_pytorch_tpu.parallel.distributed import (
    DistributedKFAC,
    make_kfac_mesh,
)
from distributed_kfac_pytorch_tpu.preconditioner import CommMethod, KFAC
from distributed_kfac_pytorch_tpu.scheduler import KFACParamScheduler
