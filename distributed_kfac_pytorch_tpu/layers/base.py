"""Layer-kind dispatch: the functional KFACLayer contract.

The reference expresses per-module math as KFACLayer subclasses holding
mutable state (kfac/layers/{base,linear,conv,embedding}.py); here each kind
is a set of pure functions over a ``LayerSpec`` and that layer's captures:

  - ``compute_a_factor(spec, a_calls)`` / ``compute_g_factor(spec, g_calls)``
    (reference contract: kfac/layers/base.py:443-449);
  - ``grads_to_matrix`` / ``matrix_to_grads`` mapping a flax param subtree
    to the 2-D ``(out_dim, in_dim[+1])`` form the preconditioner works in
    (reference: kfac/layers/base.py:310-319, conv override conv.py:17-22).

Multi-call layers (LSTM cells etc.) sum per-call factors like the
reference's LinearMultiLayer (kfac/layers/linear.py:27-59).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from distributed_kfac_pytorch_tpu.capture import (
    CONV2D,
    CONV2D_GROUPED,
    EMBEDDING,
    KFAC_REDUCE,
    LINEAR,
    LayerSpec,
)
from distributed_kfac_pytorch_tpu.ops import factors as F

KNOWN_KINDS = (LINEAR, CONV2D, CONV2D_GROUPED, EMBEDDING)


def compute_a_factor(spec: LayerSpec, a_calls: Sequence[jax.Array],
                     compute_dtype=None) -> jax.Array:
    """Input-covariance factor A from per-call activations.

    ``compute_dtype`` selects the covariance matmul input dtype (fp32
    accumulation always) — see ops.factors.get_cov.

    ``spec.kfac_approx`` dispatches the weight-sharing approximation
    for dense/patch-conv layers: 'expand' (default) flattens the
    shared axis into covariance rows (the historical path, untouched);
    'reduce' averages activations over it first (sharing.approx,
    arXiv:2311.00636 Eq. 22). Static per-spec dispatch — the choice is
    program structure, not data.
    """
    reduced = spec.kfac_approx == KFAC_REDUCE
    if spec.kind == LINEAR:
        fn = (F.linear_a_factor_reduced if reduced
              else F.linear_a_factor)
        out = None
        for a in a_calls:
            cur = fn(a, spec.has_bias, compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == CONV2D:
        fn = (F.conv2d_a_factor_reduced if reduced
              else F.conv2d_a_factor)
        out = None
        for a in a_calls:
            cur = fn(a, spec.kernel_size, spec.strides,
                     spec.padding, spec.has_bias,
                     compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == CONV2D_GROUPED:
        out = None
        for a in a_calls:
            cur = F.conv2d_grouped_a_factor(
                a, spec.kernel_size, spec.strides, spec.padding,
                spec.feature_group_count, spec.has_bias,
                compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == EMBEDDING:
        out = None
        for ids in a_calls:
            cur = F.embedding_a_factor(ids, spec.vocab_size)
            out = cur if out is None else out + cur
        return out
    raise ValueError(f'unknown layer kind {spec.kind!r}')


def compute_g_factor(spec: LayerSpec, g_calls: Sequence[jax.Array],
                     compute_dtype=None) -> jax.Array:
    """Output-gradient covariance factor G from per-call probe grads.

    Under ``spec.kfac_approx == 'reduce'`` the grads are summed over
    the shared axis before the covariance (the Eq. 22 counterpart of
    the activation mean — see :func:`compute_a_factor`).
    """
    reduced = spec.kfac_approx == KFAC_REDUCE
    if spec.kind in (LINEAR, EMBEDDING):
        fn = (F.linear_g_factor_reduced
              if reduced and spec.kind == LINEAR else F.linear_g_factor)
        out = None
        for g in g_calls:
            cur = fn(g, compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == CONV2D:
        fn = (F.conv2d_g_factor_reduced if reduced
              else F.conv2d_g_factor)
        out = None
        for g in g_calls:
            cur = fn(g, compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == CONV2D_GROUPED:
        out = None
        for g in g_calls:
            cur = F.conv2d_grouped_g_factor(
                g, spec.feature_group_count, compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    raise ValueError(f'unknown layer kind {spec.kind!r}')


#: capture-entry keys that are QUADRATIC in the output-gradients —
#: under SPMD (local-mean loss) and gradient accumulation these need
#: the ``1/world**2`` / ``1/accum**2`` rescale the primary 'G' gets;
#: everything else ('A', 'G_a') is activation-derived and needs none.
#: Single point of truth for parallel.distributed's contrib scaling.
GRAD_QUADRATIC_KEYS = ('G', 'A_g2')


def compute_tied_factor_extras(spec: LayerSpec, entry: dict,
                               compute_dtype=None):
    """Tied-embedding attend-site factor contributions, or None.

    For an in/out-tied embedding (``spec.tied_calls > 0``, captures
    carrying the ``a_tied``/``g_tied`` attend streams), the attend call
    site's Fisher block folds into the SAME factor pair as the lookup
    (sum of per-site Kronecker approximations — the multi-call /
    LinearMultiLayer semantics applied across the tie):

      - ``A_g2``: diagonal vocab-side term ``diag cov(dL/dlogits)``
        (ops.factors.embedding_tied_a_diag) — added to the lookup's
        one-hot-frequency diagonal. QUADRATIC in the output grads
        (see GRAD_QUADRATIC_KEYS).
      - ``G_a``: d-side term ``cov(attend inputs)`` — added to the
        lookup's output-grad covariance. Activation-derived.

    Returns ``{'A_g2': vec, 'G_a': mat}`` (per-call sums) or None for
    layers without tied captures. One factor pair, one inverse entry:
    the state layout is untouched — only the statistics change.
    """
    if spec.kind != EMBEDDING or not entry.get('g_tied'):
        return None
    a_diag = None
    for g in entry['g_tied']:
        cur = F.embedding_tied_a_diag(g)
        a_diag = cur if a_diag is None else a_diag + cur
    g_cov = None
    for x in entry['a_tied']:
        cur = F.get_cov(F.collapse_batch_dims(x),
                        compute_dtype=compute_dtype)
        g_cov = cur if g_cov is None else g_cov + cur
    return {'A_g2': a_diag, 'G_a': g_cov}


def grads_to_matrix(spec: LayerSpec, grads: dict) -> jax.Array:
    """Flax param-grad subtree -> 2-D (out_dim, in_dim[+1]) matrix.

    Layouts: flax Dense kernels are (in, out) [torch is (out, in)], conv
    kernels (kh, kw, cin, cout) [torch (cout, cin, kh, kw)], embeddings
    (vocab, dim). The matrix form matches the factor bases produced by
    compute_a_factor/compute_g_factor.
    """
    if spec.kind == LINEAR:
        mat = grads['kernel'].T
        if spec.has_bias:
            mat = jnp.concatenate([mat, grads['bias'][:, None]], axis=1)
        return mat
    if spec.kind == CONV2D:
        k = grads['kernel']
        mat = k.reshape(-1, k.shape[-1]).T  # (cout, kh*kw*cin)
        if spec.has_bias:
            mat = jnp.concatenate([mat, grads['bias'][:, None]], axis=1)
        return mat
    if spec.kind == CONV2D_GROUPED:
        # (kh, kw, cpg, cout) -> (G, cout/G, kh*kw*cpg [+1]): output
        # channels are contiguous per group (XLA grouped-conv layout).
        k = grads['kernel']
        groups = spec.feature_group_count
        d = k.shape[0] * k.shape[1] * k.shape[2]
        cout = k.shape[-1]
        mat = k.reshape(d, groups, cout // groups).transpose(1, 2, 0)
        if spec.has_bias:
            b = grads['bias'].reshape(groups, cout // groups, 1)
            mat = jnp.concatenate([mat, b], axis=-1)
        return mat
    if spec.kind == EMBEDDING:
        # (vocab, dim): A is diagonal over vocab, G is (dim, dim).
        return grads['embedding']
    raise ValueError(f'unknown layer kind {spec.kind!r}')


def matrix_to_grads(spec: LayerSpec, mat: jax.Array,
                    like: dict) -> dict:
    """Inverse of grads_to_matrix, shaped like the param subtree ``like``."""
    out = dict(like)
    if spec.kind == LINEAR:
        if spec.has_bias:
            out['bias'] = mat[:, -1].reshape(like['bias'].shape)
            mat = mat[:, :-1]
        out['kernel'] = mat.T.reshape(like['kernel'].shape)
        return out
    if spec.kind == CONV2D:
        if spec.has_bias:
            out['bias'] = mat[:, -1].reshape(like['bias'].shape)
            mat = mat[:, :-1]
        out['kernel'] = mat.T.reshape(like['kernel'].shape)
        return out
    if spec.kind == CONV2D_GROUPED:
        if spec.has_bias:
            out['bias'] = mat[..., -1].reshape(like['bias'].shape)
            mat = mat[..., :-1]
        # (G, cout/G, d) -> (d, G, cout/G) -> (kh, kw, cpg, cout)
        out['kernel'] = mat.transpose(2, 0, 1).reshape(
            like['kernel'].shape)
        return out
    if spec.kind == EMBEDDING:
        out['embedding'] = mat.reshape(like['embedding'].shape)
        return out
    raise ValueError(f'unknown layer kind {spec.kind!r}')


def factor_shapes(spec: LayerSpec, params: dict) -> tuple[int, int]:
    """(A_dim, G_dim) for this layer, from its param subtree shapes.

    Used by worker assignment before any data has flowed — unlike the
    reference, which must defer assignment until first factors exist
    (preconditioner.py:499-504), factor dims are static functions of the
    param shapes.
    """
    if spec.kind == LINEAR:
        in_dim, out_dim = params['kernel'].shape
        return in_dim + int(spec.has_bias), out_dim
    if spec.kind == CONV2D:
        kh, kw, cin, cout = params['kernel'].shape
        return kh * kw * cin + int(spec.has_bias), cout
    if spec.kind == CONV2D_GROUPED:
        # PER-GROUP dims; the layer carries feature_group_count stacked
        # (da, da)/(dg, dg) blocks rather than one dense factor pair.
        kh, kw, cpg, cout = params['kernel'].shape
        return (kh * kw * cpg + int(spec.has_bias),
                cout // spec.feature_group_count)
    if spec.kind == EMBEDDING:
        vocab, dim = params['embedding'].shape
        return vocab, dim  # A is diagonal (vector of length vocab)
    raise ValueError(f'unknown layer kind {spec.kind!r}')
