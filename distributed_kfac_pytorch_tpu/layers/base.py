"""Layer-kind dispatch: the functional KFACLayer contract.

The reference expresses per-module math as KFACLayer subclasses holding
mutable state (kfac/layers/{base,linear,conv,embedding}.py); here each kind
is a set of pure functions over a ``LayerSpec`` and that layer's captures:

  - ``compute_a_factor(spec, a_calls)`` / ``compute_g_factor(spec, g_calls)``
    (reference contract: kfac/layers/base.py:443-449);
  - ``grads_to_matrix`` / ``matrix_to_grads`` mapping a flax param subtree
    to the 2-D ``(out_dim, in_dim[+1])`` form the preconditioner works in
    (reference: kfac/layers/base.py:310-319, conv override conv.py:17-22).

Multi-call layers (LSTM cells etc.) sum per-call factors like the
reference's LinearMultiLayer (kfac/layers/linear.py:27-59).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from distributed_kfac_pytorch_tpu.capture import (
    CONV2D,
    CONV2D_GROUPED,
    EMBEDDING,
    LINEAR,
    LayerSpec,
)
from distributed_kfac_pytorch_tpu.ops import factors as F

KNOWN_KINDS = (LINEAR, CONV2D, CONV2D_GROUPED, EMBEDDING)


def compute_a_factor(spec: LayerSpec, a_calls: Sequence[jax.Array],
                     compute_dtype=None) -> jax.Array:
    """Input-covariance factor A from per-call activations.

    ``compute_dtype`` selects the covariance matmul input dtype (fp32
    accumulation always) — see ops.factors.get_cov.
    """
    if spec.kind == LINEAR:
        out = None
        for a in a_calls:
            cur = F.linear_a_factor(a, spec.has_bias,
                                    compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == CONV2D:
        out = None
        for a in a_calls:
            cur = F.conv2d_a_factor(a, spec.kernel_size, spec.strides,
                                    spec.padding, spec.has_bias,
                                    compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == CONV2D_GROUPED:
        out = None
        for a in a_calls:
            cur = F.conv2d_grouped_a_factor(
                a, spec.kernel_size, spec.strides, spec.padding,
                spec.feature_group_count, spec.has_bias,
                compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == EMBEDDING:
        out = None
        for ids in a_calls:
            cur = F.embedding_a_factor(ids, spec.vocab_size)
            out = cur if out is None else out + cur
        return out
    raise ValueError(f'unknown layer kind {spec.kind!r}')


def compute_g_factor(spec: LayerSpec, g_calls: Sequence[jax.Array],
                     compute_dtype=None) -> jax.Array:
    """Output-gradient covariance factor G from per-call probe grads."""
    if spec.kind in (LINEAR, EMBEDDING):
        out = None
        for g in g_calls:
            cur = F.linear_g_factor(g, compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == CONV2D:
        out = None
        for g in g_calls:
            cur = F.conv2d_g_factor(g, compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    if spec.kind == CONV2D_GROUPED:
        out = None
        for g in g_calls:
            cur = F.conv2d_grouped_g_factor(
                g, spec.feature_group_count, compute_dtype=compute_dtype)
            out = cur if out is None else out + cur
        return out
    raise ValueError(f'unknown layer kind {spec.kind!r}')


def grads_to_matrix(spec: LayerSpec, grads: dict) -> jax.Array:
    """Flax param-grad subtree -> 2-D (out_dim, in_dim[+1]) matrix.

    Layouts: flax Dense kernels are (in, out) [torch is (out, in)], conv
    kernels (kh, kw, cin, cout) [torch (cout, cin, kh, kw)], embeddings
    (vocab, dim). The matrix form matches the factor bases produced by
    compute_a_factor/compute_g_factor.
    """
    if spec.kind == LINEAR:
        mat = grads['kernel'].T
        if spec.has_bias:
            mat = jnp.concatenate([mat, grads['bias'][:, None]], axis=1)
        return mat
    if spec.kind == CONV2D:
        k = grads['kernel']
        mat = k.reshape(-1, k.shape[-1]).T  # (cout, kh*kw*cin)
        if spec.has_bias:
            mat = jnp.concatenate([mat, grads['bias'][:, None]], axis=1)
        return mat
    if spec.kind == CONV2D_GROUPED:
        # (kh, kw, cpg, cout) -> (G, cout/G, kh*kw*cpg [+1]): output
        # channels are contiguous per group (XLA grouped-conv layout).
        k = grads['kernel']
        groups = spec.feature_group_count
        d = k.shape[0] * k.shape[1] * k.shape[2]
        cout = k.shape[-1]
        mat = k.reshape(d, groups, cout // groups).transpose(1, 2, 0)
        if spec.has_bias:
            b = grads['bias'].reshape(groups, cout // groups, 1)
            mat = jnp.concatenate([mat, b], axis=-1)
        return mat
    if spec.kind == EMBEDDING:
        # (vocab, dim): A is diagonal over vocab, G is (dim, dim).
        return grads['embedding']
    raise ValueError(f'unknown layer kind {spec.kind!r}')


def matrix_to_grads(spec: LayerSpec, mat: jax.Array,
                    like: dict) -> dict:
    """Inverse of grads_to_matrix, shaped like the param subtree ``like``."""
    out = dict(like)
    if spec.kind == LINEAR:
        if spec.has_bias:
            out['bias'] = mat[:, -1].reshape(like['bias'].shape)
            mat = mat[:, :-1]
        out['kernel'] = mat.T.reshape(like['kernel'].shape)
        return out
    if spec.kind == CONV2D:
        if spec.has_bias:
            out['bias'] = mat[:, -1].reshape(like['bias'].shape)
            mat = mat[:, :-1]
        out['kernel'] = mat.T.reshape(like['kernel'].shape)
        return out
    if spec.kind == CONV2D_GROUPED:
        if spec.has_bias:
            out['bias'] = mat[..., -1].reshape(like['bias'].shape)
            mat = mat[..., :-1]
        # (G, cout/G, d) -> (d, G, cout/G) -> (kh, kw, cpg, cout)
        out['kernel'] = mat.transpose(2, 0, 1).reshape(
            like['kernel'].shape)
        return out
    if spec.kind == EMBEDDING:
        out['embedding'] = mat.reshape(like['embedding'].shape)
        return out
    raise ValueError(f'unknown layer kind {spec.kind!r}')


def factor_shapes(spec: LayerSpec, params: dict) -> tuple[int, int]:
    """(A_dim, G_dim) for this layer, from its param subtree shapes.

    Used by worker assignment before any data has flowed — unlike the
    reference, which must defer assignment until first factors exist
    (preconditioner.py:499-504), factor dims are static functions of the
    param shapes.
    """
    if spec.kind == LINEAR:
        in_dim, out_dim = params['kernel'].shape
        return in_dim + int(spec.has_bias), out_dim
    if spec.kind == CONV2D:
        kh, kw, cin, cout = params['kernel'].shape
        return kh * kw * cin + int(spec.has_bias), cout
    if spec.kind == CONV2D_GROUPED:
        # PER-GROUP dims; the layer carries feature_group_count stacked
        # (da, da)/(dg, dg) blocks rather than one dense factor pair.
        kh, kw, cpg, cout = params['kernel'].shape
        return (kh * kw * cpg + int(spec.has_bias),
                cout // spec.feature_group_count)
    if spec.kind == EMBEDDING:
        vocab, dim = params['embedding'].shape
        return vocab, dim  # A is diagonal (vector of length vocab)
    raise ValueError(f'unknown layer kind {spec.kind!r}')
