"""Per-layer-kind K-FAC math: captures -> factors, grads <-> matrices."""

from distributed_kfac_pytorch_tpu.layers.base import (
    GRAD_QUADRATIC_KEYS,
    KNOWN_KINDS,
    compute_a_factor,
    compute_g_factor,
    compute_tied_factor_extras,
    factor_shapes,
    grads_to_matrix,
    matrix_to_grads,
)
