"""Activation / output-gradient capture for K-FAC, without hooks.

The reference relies on torch forward/backward hooks to snapshot each
module's inputs and output-gradients (kfac/preconditioner.py:701-727,
kfac/layers/base.py:364-379) because autograd hides intermediates. In JAX
nothing is hidden: this module captures both quantities *functionally* from
any flax model, unmodified:

  - activations ``a``: a method interceptor (``nn.intercept_methods``) wraps
    every registered module call and ``sow``s its input into the
    ``kfac_in`` collection;
  - output gradients ``g``: the interceptor adds a zero-valued probe to the
    module output (``Module.perturb``); differentiating the loss wrt the
    ``kfac_probes`` collection yields exactly dL/dy per module call.

Both arrive as pure outputs of one ``value_and_grad`` — no mutation, no
graph introspection, jit/vmap/shard_map-safe. Modules called multiple times
per step (e.g. LSTM cells unrolled over time) get one capture and one probe
per call, the analogue of the reference's ``accumulate_data`` path
(kfac/layers/base.py:364-379).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

CAPTURE_COL = 'kfac_in'
PROBE_COL = 'kfac_probes'


def extra_vars_of(variables) -> dict:
    """The collections a caller should carry as train-state extra_vars:
    everything except 'params' and the capture-internal collections
    (``KFAC.init`` returns ``kfac_probes`` shaped for the *init* batch —
    stale for any other batch, and dead weight in checkpoints). The one
    place the internal-collection names are spelled outside this module.
    """
    return {k: v for k, v in variables.items()
            if k not in ('params', PROBE_COL, CAPTURE_COL)}

# Module kinds, mirroring the reference's KNOWN_MODULES
# (kfac/layers/__init__.py:11) plus the embedding layer the reference
# disabled (kfac/layers/embedding.py:20).
LINEAR = 'linear'
CONV2D = 'conv2d'
EMBEDDING = 'embedding'
# Grouped/depthwise conv: per-group block-diagonal Fisher (round 5 —
# BEYOND the reference, whose registry has no conv variant at all for
# feature_group_count != 1, kfac/layers/__init__.py:13-36; this
# framework preconditions MobileNet/EfficientNet-class models).
CONV2D_GROUPED = 'conv2d_grouped'

# Weight-sharing Kronecker approximations (arXiv:2311.00636, "K-FAC for
# Modern Neural Network Architectures"). A layer whose weight is shared
# across a sequence/patch axis (every Dense in a transformer block, the
# ViT patch-embed conv) admits two principled factorizations:
#   - KFAC_EXPAND: per-position independence — flatten (batch, T, d)
#     into B*T covariance rows (the historical default of this repo's
#     collapse_batch_dims path; bit-identical to pre-sharing behavior);
#   - KFAC_REDUCE: reduce over the shared axis BEFORE the covariance —
#     activations are averaged and output-grads summed over T (the
#     paper's Eq. 22 convention keeps the bias column exactly 1), so
#     the factor contraction sees B rows instead of B*T: a factor-T
#     cheaper statistic that is exact whenever activations are constant
#     across the shared axis and empirically matches expand on
#     transformer/ViT workloads.
# The per-layer choice is carried here, in the registry
# (LayerSpec.kfac_approx), resolved by sharing.approx.
KFAC_EXPAND = 'expand'
KFAC_REDUCE = 'reduce'
KFAC_APPROXES = (KFAC_EXPAND, KFAC_REDUCE)


@dataclasses.dataclass(frozen=True)
class LayerSpec:
    """Static description of one registered layer (hashable, trace-safe).

    The functional analogue of a registered ``KFACLayer``'s identity/config
    (reference kfac/layers/base.py:10-45): everything the factor math needs
    to interpret this layer's captures and map its gradient to/from the
    2-D ``(out_dim, in_dim[+1])`` matrix form.
    """
    path: tuple[str, ...]          # module path == params subtree path
    kind: str                      # LINEAR | CONV2D | CONV2D_GROUPED | EMBEDDING
    has_bias: bool
    num_calls: int = 1             # calls per training step (e.g. timesteps)
    # conv2d / conv2d_grouped only:
    kernel_size: tuple[int, ...] | None = None
    strides: tuple[int, ...] | None = None
    padding: Any = None
    feature_group_count: int = 1   # conv2d_grouped: number of groups
    # embedding only:
    vocab_size: int | None = None
    # Weight-sharing approximation for this layer's factor statistics
    # (KFAC_EXPAND | KFAC_REDUCE). Registration records 'expand' (the
    # exact-parity default); sharing.annotate_specs resolves the
    # per-layer setting from KFAC(kfac_approx=...). Static program
    # structure: the choice is baked into the trace (zero retraces).
    kfac_approx: str = KFAC_EXPAND
    # Shared-axis positions seen at registration (prod of the input
    # dims between batch and features for a Dense; 1 when the input is
    # 2-D). The sharing policy's "is this Dense sequence/patch-shared"
    # signal; informational for other kinds.
    shared_positions: int = 1
    # Tied-embedding support: number of ``Embed.attend`` call sites
    # captured for this embedding (0 = lookup-only registration). The
    # in/out-tied pair contributes BOTH call sites' statistics to one
    # factor pair with one inverse entry (the reference's
    # register_shared_module intent, kfac/preconditioner.py:404-470 —
    # which it then disabled wholesale, embedding.py:20).
    tied_calls: int = 0

    @property
    def name(self) -> str:
        return '/'.join(self.path) if self.path else '<root>'


def _canonical_padding(padding, n_spatial: int):
    if isinstance(padding, str):
        return padding
    if isinstance(padding, int):
        return [(padding, padding)] * n_spatial
    out = []
    for p in padding:
        out.append((p, p) if isinstance(p, int) else tuple(p))
    return out


def _conv_decline_reason(mod: nn.Conv) -> str | None:
    """Why a conv-family module cannot be K-FAC-preconditioned, or None.

    These are the configurations the factor math does not model (the
    reference's registry simply has no layer class for them either,
    kfac/layers/__init__.py:13-36 — but it *errors* on the module kinds
    it refuses, :31-33, where silence here would hide a partially
    preconditioned model). Grouped/depthwise convs are SUPPORTED since
    round 5 (per-group block-diagonal factors, kind CONV2D_GROUPED).
    """
    dilation = mod.kernel_dilation
    if dilation is not None and any(
            d != 1 for d in (dilation if isinstance(dilation, Sequence)
                             else (dilation,))):
        return f'dilated conv (kernel_dilation={dilation})'
    if len(tuple(mod.kernel_size)) != 2:
        return f'non-2D conv (kernel_size={tuple(mod.kernel_size)})'
    return None


def _decline_reason(mod: nn.Module) -> str | None:
    """Why a capturable-family module is NOT preconditioned, or None.

    One policy for every registered family (round 4 — the round-3
    review found Conv declined subclasses loudly while a Dense subclass
    with overridden call semantics was silently captured as plain
    Dense, so its factor math could mis-model it): the exact type and
    flax's own lifted-transform wrappers (nn.remat / nn.scan — base
    call semantics, wrapped execution) are accepted; any USER subclass
    is declined loudly, plus the conv-configuration checks. A user
    subclass that genuinely behaves like its base can be registered by
    converting it to composition over the exact type.
    """
    for base in (nn.Dense, nn.Conv, nn.Embed):
        if isinstance(mod, base) and type(mod) is not base:
            # flax's lifted transforms (nn.remat / nn.scan / ...)
            # generate subclasses in flax.linen.transforms whose call
            # SEMANTICS are the base's (only execution is wrapped) —
            # capture them like the base; decline user subclasses.
            if type(mod).__module__.startswith('flax.linen.'):
                break
            return (f'{base.__name__} subclass {type(mod).__name__} '
                    f'(capture only matches exact {base.__name__}; its '
                    'call semantics may differ from the factor math)')
    if isinstance(mod, nn.Conv):
        return _conv_decline_reason(mod)
    return None


def _spec_for_module(mod: nn.Module, path: tuple[str, ...],
                     num_calls: int, a_in=None) -> LayerSpec | None:
    """Build a LayerSpec for a supported flax module, else None.

    Mirrors the registry dispatch in reference kfac/layers/__init__.py:13-36
    (module type -> KFACLayer class), with unsupported configurations
    (grouped/dilated convs, subclasses of the registered families)
    skipped rather than mis-modelled (declines are recorded and
    reported — see KFACCapture.skipped_modules).

    ``a_in`` is the module input at registration time — only its static
    SHAPE is read (the Dense shared-axis position count for the
    sharing policy); None leaves the default.
    """
    if _decline_reason(mod) is not None:
        return None
    # isinstance AFTER the decline gate: what reaches here is the exact
    # type or a flax lifted-transform wrapper (accepted above).
    if isinstance(mod, nn.Dense):
        shared = (int(np.prod(a_in.shape[1:-1]))
                  if a_in is not None and a_in.ndim > 2 else 1)
        return LayerSpec(path=path, kind=LINEAR, has_bias=mod.use_bias,
                         num_calls=num_calls, shared_positions=shared)
    if isinstance(mod, nn.Conv):
        strides = mod.strides
        if strides is None:
            strides = (1, 1)
        elif isinstance(strides, int):
            strides = (strides, strides)
        else:
            strides = tuple(strides)
        groups = mod.feature_group_count
        return LayerSpec(path=path,
                         kind=CONV2D if groups == 1 else CONV2D_GROUPED,
                         has_bias=mod.use_bias,
                         num_calls=num_calls,
                         kernel_size=tuple(mod.kernel_size),
                         strides=strides,
                         padding=_canonical_padding(mod.padding, 2),
                         feature_group_count=groups)
    if isinstance(mod, nn.Embed):
        return LayerSpec(path=path, kind=EMBEDDING, has_bias=False,
                         num_calls=num_calls, vocab_size=mod.num_embeddings)
    return None


class KFACCapture:
    """Registers supported modules of a flax model and captures (a, g).

    The functional counterpart of ``KFAC.register_model``
    (reference kfac/preconditioner.py:355-402): walks the model by
    *intercepting* calls rather than attaching hooks, prunes subtrees whose
    path component or class name matches ``skip_layers`` (case-insensitive,
    like reference preconditioner.py:191-200), and exposes

      ``loss_and_grads(loss_fn, params, *args)``
        -> (loss, aux, param_grads, captures, updated_vars)

    where ``captures`` maps layer name -> {'a': tuple, 'g': tuple} with one
    entry per module call.
    """

    def __init__(self, model: nn.Module,
                 skip_layers: str | Sequence[str] | None = None,
                 capture_dtype: Any = 'auto',
                 trainable: Callable[[str], bool] | None = None,
                 tied_embeddings: bool = False):
        self.model = model
        # Capture ``Embed.attend`` call sites (the tied in/out decoder,
        # flax's form of the reference register_shared_module pair) so
        # both uses of a tied embedding weight feed one factor pair.
        # Off by default: the lookup-only capture is the historical
        # bit-identical path (KFAC resolves the default from its
        # sharing setting).
        self.tied_embeddings = tied_embeddings
        self._tied_counts: dict[tuple[str, ...], int] = {}
        if skip_layers is None:
            skip_layers = []
        elif isinstance(skip_layers, str):
            skip_layers = [skip_layers]
        self.skip_layers = frozenset(s.lower() for s in skip_layers)
        # Frozen-parameter support (reference module_requires_grad,
        # kfac/layers/__init__.py:38-40: modules whose params don't
        # require grad are never registered). JAX has no requires_grad;
        # fine-tuning freezes params via the optimizer (optax.masked /
        # zero updates), so the caller states the same intent here:
        # ``trainable('/'.join(module_path)) -> bool``. Frozen layers
        # get no capture, no factor statistics, and no preconditioning
        # — their (unused) gradients pass through untouched.
        self.trainable = trainable
        # Dtype for captured activations ('a'). The captures feed ONLY
        # the factor statistics, whose covariance matmuls round fp32
        # inputs to bf16 on the TPU MXU anyway (ops.factors.get_cov
        # precision contract) — so storing them bf16 loses nothing the
        # matmul keeps, while halving the capture write and (for convs)
        # the im2col patch materialization traffic that dominates the
        # factor phase (PERF.md round 3). This is also production
        # reference behavior: under --fp16/AMP its hooks capture the
        # autocast half-precision activations (kfac/layers/base.py:385,
        # README.md:150-160). 'auto' = bf16 on TPU for float inputs,
        # passthrough elsewhere; None = always passthrough (strict-fp32
        # parity); an explicit dtype forces the cast. Output-grad
        # captures ('g') are never cast here — they are read once, so a
        # cast would add traffic, not save it.
        self.capture_dtype = capture_dtype
        self._specs: dict[str, LayerSpec] | None = None
        self._skipped: dict[str, str] = {}

    def _cast_capture(self, x):
        cd = self.capture_dtype
        if cd is None:
            return x
        if cd == 'auto':
            if (jax.default_backend() == 'tpu'
                    and x.dtype == jnp.float32):
                cd = jnp.bfloat16
            else:
                return x
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != cd:
            return x.astype(cd)
        return x

    # -- registration ------------------------------------------------------

    def _module_path(self, mod: nn.Module) -> tuple[str, ...]:
        return tuple(mod.path)

    def _is_skipped(self, mod: nn.Module, path: tuple[str, ...]) -> bool:
        if type(mod).__name__.lower() in self.skip_layers:
            return True
        return any(part.lower() in self.skip_layers for part in path)

    def _make_interceptor(self, record_specs: bool):
        call_counts: dict[tuple[str, ...], int] = {}
        tied_counts: dict[tuple[str, ...], int] = {}
        self._tied_counts = tied_counts

        def tied_attend(mod, path, args, kwargs, next_fun):
            """Capture an ``Embed.attend`` call site (the output-tied
            use of a tied in/out embedding weight: ``logits = x E^T``).
            The attend input rides in the ``a_tied`` capture slot and
            the output probe in ``tied_probe<i>`` — paired by
            :meth:`collect` into the same layer's captures so both call
            sites' statistics feed ONE factor pair (the reference's
            register_shared_module intent, preconditioner.py:404-470).
            """
            if args:
                x_in = args[0]
            elif 'query' in kwargs:
                x_in = kwargs['query']
            else:
                return next_fun(*args, **kwargs)
            idx = tied_counts.get(path, 0)
            tied_counts[path] = idx + 1
            mod.sow(CAPTURE_COL, 'a_tied', self._cast_capture(x_in),
                    init_fn=tuple, reduce_fn=lambda p, x: p + (x,))
            y = next_fun(*args, **kwargs)
            return mod.perturb(f'tied_probe{idx}', y,
                               collection=PROBE_COL)

        def interceptor(next_fun, args, kwargs, context):
            mod = context.module
            if mod is None:
                return next_fun(*args, **kwargs)
            is_attend = (self.tied_embeddings
                         and context.method_name == 'attend'
                         and isinstance(mod, nn.Embed))
            if context.method_name != '__call__' and not is_attend:
                return next_fun(*args, **kwargs)
            path = self._module_path(mod)
            if self._is_skipped(mod, path):
                if record_specs and path:
                    self._skipped['/'.join(path)] = 'skip_layers match'
                return next_fun(*args, **kwargs)
            if self.trainable is not None and \
                    not self.trainable('/'.join(path)):
                if record_specs and path:
                    self._skipped['/'.join(path)] = (
                        'frozen (trainable predicate): plain gradients, '
                        'no factor work')
                return next_fun(*args, **kwargs)
            reason = _decline_reason(mod)
            if reason or _spec_for_module(mod, path, 1) is None:
                if record_specs and reason:
                    self._skipped['/'.join(path)] = reason
                return next_fun(*args, **kwargs)
            if is_attend:
                return tied_attend(mod, path, args, kwargs, next_fun)
            # Dense/Conv/Embed all name their input 'inputs'; support both
            # positional and keyword call styles.
            if args:
                a_in = args[0]
            elif 'inputs' in kwargs:
                a_in = kwargs['inputs']
            else:
                return next_fun(*args, **kwargs)

            idx = call_counts.get(path, 0)
            call_counts[path] = idx + 1
            mod.sow(CAPTURE_COL, 'a', self._cast_capture(a_in),
                    init_fn=tuple, reduce_fn=lambda p, x: p + (x,))
            y = next_fun(*args, **kwargs)
            y = mod.perturb(f'probe{idx}', y, collection=PROBE_COL)
            if record_specs:
                spec = _spec_for_module(mod, path, call_counts[path],
                                        a_in)
                self._specs['/'.join(path)] = spec
            return y

        return interceptor

    def init(self, rng, *args, init_model: nn.Module | None = None,
             **kwargs) -> tuple[dict, dict]:
        """Init model variables under interception; records layer specs.

        Returns ``(variables, specs)`` (plain dicts). ``variables`` contains 'params' and
        'kfac_probes' (zeros, shaped for the init batch).

        ``init_model`` optionally substitutes a structurally-identical
        single-device twin for the trace — needed when ``self.model``
        contains collectives that only trace inside ``shard_map`` (e.g. a
        ring-attention sequence-parallel model): params and layer specs
        depend only on structure, so the twin's registration is exact.
        """
        self._specs = {}
        self._skipped = {}
        model = self.model if init_model is None else init_model
        with nn.intercept_methods(self._make_interceptor(record_specs=True)):
            variables = model.init(rng, *args, **kwargs)
        variables = dict(variables)
        variables.pop(CAPTURE_COL, None)
        # Tied attend call sites seen during the trace: merge the count
        # into the owning embedding's spec (the attend branch never
        # records specs itself — registration is the lookup's job; an
        # attend on a NEVER-looked-up Embed stays unregistered, like
        # any other un-called module).
        for path, n in self._tied_counts.items():
            name = '/'.join(path)
            if name in self._specs:
                self._specs[name] = dataclasses.replace(
                    self._specs[name], tied_calls=n)
        self._record_unregistered_params(variables.get('params', {}))
        declined = {n: r for n, r in self._skipped.items()
                    if 'conv' in r.lower() or 'subclass' in r}
        if declined:
            # The reference hard-errors on module kinds it refuses
            # (kfac/layers/__init__.py:31-33); silence here would hide a
            # partially preconditioned model, so be loud about the convs
            # K-FAC *should* cover but cannot.
            import warnings
            lines = ', '.join(f'{n} ({r})' for n, r in declined.items())
            warnings.warn(
                f'K-FAC cannot precondition {len(declined)} '
                f'module(s); their params get plain gradients: {lines}. '
                'See KFACCapture.skipped_modules for the full report.')
        return variables, dict(self._specs)

    def _record_unregistered_params(self, params) -> None:
        """Record parameterized modules that registration never covered.

        Walks the params tree for leaf-parent paths (modules holding
        arrays directly). Anything not a registered layer and not already
        recorded gets a generic 'unsupported module type' entry — e.g.
        BatchNorm scale/bias (benign: the reference never preconditions
        normalization layers either) or custom modules with params.
        """
        def walk(node, path):
            if not isinstance(node, dict):
                return
            if any(not isinstance(v, dict) for v in node.values()):
                # Direct array leaves: this path is a parameterized
                # module. Do NOT return — a module may hold its own
                # params AND nested parameterized submodules.
                name = '/'.join(path)
                if name not in self._specs and name not in self._skipped:
                    self._skipped[name] = (
                        'unsupported module type (params receive plain '
                        'gradients)')
            for key, child in node.items():
                walk(child, path + (key,))

        walk(params, ())

    @property
    def skipped_modules(self) -> dict[str, str]:
        """{module path: reason} for every parameterized module K-FAC does
        not precondition — skip_layers matches, declined conv configs
        (grouped/dilated/non-2D/subclass), and unsupported kinds. The
        loud-report answer to the reference's silent partial coverage
        (it errors only on RNNCellBase, kfac/layers/__init__.py:31-33).
        """
        return dict(self._skipped)

    @property
    def specs(self) -> dict[str, LayerSpec]:
        if self._specs is None:
            raise ValueError('no layers registered: call init() first')
        return dict(self._specs)

    # -- capture-time application -----------------------------------------

    @staticmethod
    def _clean_extra(extra_vars) -> dict:
        """Caller-supplied collections minus capture internals.

        ``KFAC.init`` returns a ``kfac_probes`` collection shaped for the
        *init* batch; a caller that forwards every non-param collection
        (the natural spelling — bench.py, the CLIs) must not pre-seat
        those stale shapes here, where fresh probes are built per batch.
        """
        extra_vars = dict(extra_vars or {})
        extra_vars.pop(PROBE_COL, None)
        extra_vars.pop(CAPTURE_COL, None)
        return extra_vars

    def zero_probes(self, params, *args, extra_vars=None, mutable_cols=(),
                    **kwargs):
        """Zero probe pytree shaped for the given batch (via eval_shape).

        Everything is closed over rather than passed through ``eval_shape``
        so non-array arguments (e.g. ``train=True`` flags) stay Python
        values instead of becoming tracers; ``eval_shape`` never executes
        compute either way.
        """
        extra_vars = self._clean_extra(extra_vars)

        def shapes():
            with nn.intercept_methods(
                    self._make_interceptor(record_specs=False)):
                _, state = self.model.apply(
                    {'params': params, **extra_vars}, *args,
                    mutable=[CAPTURE_COL, PROBE_COL, *mutable_cols],
                    **kwargs)
            return state.get(PROBE_COL, {})
        tree = jax.eval_shape(shapes)
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), tree)

    def apply(self, params, probes, *args, extra_vars=None,
              mutable_cols=(), **kwargs):
        """Forward pass with capture.

        ``extra_vars`` supplies additional variable collections (e.g.
        ``{'batch_stats': ...}``); ``mutable_cols`` names the ones the
        model updates in-pass. Returns
        ``(out, activations_tree, updated_vars)``.
        """
        extra_vars = self._clean_extra(extra_vars)
        with nn.intercept_methods(self._make_interceptor(record_specs=False)):
            out, state = self.model.apply(
                {'params': params, PROBE_COL: probes, **extra_vars}, *args,
                mutable=[CAPTURE_COL, *mutable_cols], **kwargs)
        updated = {c: state[c] for c in mutable_cols if c in state}
        return out, state.get(CAPTURE_COL, {}), updated

    def loss_and_grads(self, loss_fn: Callable, params, *args,
                       probes=None, extra_vars=None, mutable_cols=(),
                       has_aux=False, loss_scale=None, intercept=True,
                       **kwargs):
        """One backward pass yielding param grads AND per-layer captures.

        ``loss_fn`` receives the model output only — close over labels and
        any other data: ``lambda out: cross_entropy(out, labels)``. With
        ``has_aux=True`` it returns ``(loss, aux)``.

        ``loss_scale`` multiplies the loss before differentiation and
        divides the gradients and output-grad captures after — the fp16
        loss-scaling hook (the analogue of the reference's GradScaler
        unscaling at hook time, kfac/layers/base.py:374-375,397-407).
        Identity in fp32/bf16; on TPU bf16 needs no scaling, so the
        default is None.

        ``extra_vars`` are non-differentiated collections passed to apply
        (e.g. ``{'batch_stats': ...}``); collections listed in
        ``mutable_cols`` are updated during the pass and returned.

        ``intercept=False`` skips the capture machinery entirely — a plain
        ``value_and_grad`` over ``model.apply``, returning ``captures={}``.
        This is the static-cadence fast path for non-factor-update steps:
        the reference's hooks are gated off exactly the same way on those
        steps (``_periodic_hook``, kfac/preconditioner.py:684-699), and
        measurement shows XLA does NOT dead-code-eliminate the probe/sow
        machinery when captures go unused (+2.7 ms/iter on ResNet-50
        @224px b64 — PERF.md round 4).

        Returns ``(loss, aux, grads, captures, updated_vars)`` where
        ``captures`` maps layer name -> {'a': (per-call activations...),
        'g': (per-call output grads...)} and ``updated_vars`` holds the
        new values of ``mutable_cols`` ({} if none).
        """
        # Loss-scaling is shared by both paths: scale the loss before
        # differentiation, unscale loss/grad outputs after (the
        # reference's GradScaler hook semantics) — one definition so the
        # intercepting and plain paths cannot drift.
        def scale_loss(loss):
            return loss if loss_scale is None else loss * loss_scale

        def unscale(*trees):
            if loss_scale is None:
                return trees
            inv = 1.0 / loss_scale
            return tuple(jax.tree.map(lambda g: g * inv, t) for t in trees)

        if not intercept:
            if probes is not None:
                raise ValueError(
                    'probes were passed with intercept=False — the capture '
                    'machinery is skipped entirely on non-intercepting '
                    'steps, so precomputed probes indicate caller '
                    'confusion; drop probes or set intercept=True')
            extra = self._clean_extra(extra_vars)

            def plain(params):
                out, state = self.model.apply(
                    {'params': params, **extra}, *args,
                    mutable=list(mutable_cols), **kwargs)
                res = loss_fn(out)
                loss, aux = res if has_aux else (res, None)
                updated = {c: state[c] for c in mutable_cols if c in state}
                return scale_loss(loss), (aux, updated)

            (loss, (aux, updated)), grads = jax.value_and_grad(
                plain, has_aux=True)(params)
            loss, grads = unscale(loss, grads)
            return loss, aux, grads, {}, updated

        if probes is None:
            probes = self.zero_probes(params, *args, extra_vars=extra_vars,
                                      mutable_cols=mutable_cols, **kwargs)

        def wrapped(params, probes):
            out, acts, updated = self.apply(
                params, probes, *args, extra_vars=extra_vars,
                mutable_cols=mutable_cols, **kwargs)
            res = loss_fn(out)
            loss, aux = res if has_aux else (res, None)
            return scale_loss(loss), (aux, acts, updated)

        (loss, (aux, acts, updated)), (grads, probe_grads) = (
            jax.value_and_grad(wrapped, argnums=(0, 1), has_aux=True)(
                params, probes))
        loss, grads, probe_grads = unscale(loss, grads, probe_grads)
        captures = self.collect(acts, probe_grads)
        return loss, aux, grads, captures, updated

    def collect(self, acts_tree, probe_grads_tree) -> dict[str, dict]:
        """Pair sown activations with probe gradients, per layer name.

        Call counts are derived from the trees themselves, not the
        init-time ``spec.num_calls`` — a weight-shared module may be called
        a different number of times at step time (e.g. a cell unrolled to a
        different sequence length) and a/g must stay paired per call.
        """
        captures = {}
        for name, spec in self.specs.items():
            acts_node = _get_path(acts_tree, spec.path)
            a_node = tuple(acts_node['a'])
            g_node = _get_path(probe_grads_tree, spec.path)
            n_tied = len(acts_node.get('a_tied', ()))
            gs = tuple(g_node[f'probe{i}']
                       for i in range(len(g_node) - n_tied))
            if len(a_node) != len(gs):
                raise ValueError(
                    f'layer {name}: {len(a_node)} captured activations vs '
                    f'{len(gs)} probe gradients — activation and probe '
                    'call counts must match')
            captures[name] = {'a': a_node, 'g': gs}
            if n_tied:
                # Tied-embedding attend sites: inputs + output-grad
                # probes, paired per call like the primary stream.
                captures[name]['a_tied'] = tuple(acts_node['a_tied'])
                captures[name]['g_tied'] = tuple(
                    g_node[f'tied_probe{i}'] for i in range(n_tied))
        return captures


def _get_path(tree, path: tuple[str, ...]):
    node = tree
    for part in path:
        node = node[part]
    return node


def subsample_captures(captures: dict, fraction: float) -> dict:
    """Keep ``ceil(B * fraction)`` evenly-strided batch rows per capture.

    Within-step thinning of the factor statistics: every covariance in
    this package normalizes by its own row count (ops.factors.get_cov),
    so a leading-dim subsample estimates the same expectations — the
    same statistical axis as the reference's production cadence
    (factors from one batch in 50, launch_node_torch_imagenet.sh:73-87),
    applied within the batch instead of across steps. Rows are taken
    *strided* across the whole batch (not a head slice) so pipelines
    that order rows within a batch (class-grouped samplers,
    length-bucketed LM batches) still contribute across the batch; the
    estimator is unbiased when batch composition doesn't correlate with
    position, which strided sampling preserves far more robustly than a
    prefix. The factor phase's cost (patch materialization + covariance
    contraction) scales with the kept rows. Slices are static (shapes
    are Python ints under jit).

    Not applied to gradients or preconditioning — only the A/G factor
    statistics see the subset.
    """
    if fraction >= 1.0:
        return captures

    def keep(t):
        b = t.shape[0]
        k = max(1, int(math.ceil(b * fraction)))
        if k >= b:
            return t
        # Evenly spread positions (i * b) // k cover the whole batch at
        # every fraction; a `[::b//k][:k]` stride degenerates to a head
        # slice whenever b // k == 1 (any fraction > 0.5) and always
        # orphans the tail when b % k != 0. Static numpy index -> one
        # constant gather under jit.
        return t[np.arange(k) * b // k]

    # All capture streams thin identically — including the tied
    # 'a_tied'/'g_tied' attend-site streams, which feed the same factor
    # statistics (dropping them here would silently bias the tied
    # factor pair toward the lookup site at fraction < 1).
    return {name: {key: tuple(keep(t) for t in calls)
                   for key, calls in c.items()}
            for name, c in captures.items()}
