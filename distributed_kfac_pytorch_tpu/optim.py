"""optax adapter: K-FAC as a GradientTransformation.

The reference's KFAC subclasses ``torch.optim.Optimizer``
(kfac/preconditioner.py:39,203-214) so it slots into torch training
loops; the JAX-native equivalent is an
``optax.GradientTransformationExtraArgs`` that preconditions incoming
gradients, so K-FAC chains with any optax optimizer:

    tx = optax.chain(
        kfac_transform(kfac),
        optax.sgd(lr, momentum=0.9),
    )
    updates, state = tx.update(grads, state, params,
                               captures=captures, lr=lr)

``captures`` (from ``KFACCapture.loss_and_grads``) ride through optax's
extra-args mechanism; cadence/strength hyperparameters are dynamic.
"""

from __future__ import annotations

from typing import NamedTuple

import optax

from distributed_kfac_pytorch_tpu.preconditioner import KFAC


class KFACTransformState(NamedTuple):
    kfac_state: dict


def kfac_transform(kfac: KFAC) -> optax.GradientTransformationExtraArgs:
    """Wrap a (post-``init``) KFAC preconditioner as an optax transform.

    ``update`` requires ``captures=`` and accepts the same dynamic
    hyperparameters as :meth:`KFAC.step` (``lr``, ``damping``,
    ``factor_decay``, ``factor_update_freq``, ``inv_update_freq``).
    """

    def init_fn(params):
        return KFACTransformState(kfac_state=kfac.init_state(params))

    def update_fn(updates, state, params=None, *, captures, lr=None,
                  damping=None, factor_decay=None, factor_update_freq=None,
                  inv_update_freq=None, **extra):
        del params, extra
        precond, new_state = kfac.step(
            state.kfac_state, updates, captures, lr=lr, damping=damping,
            factor_decay=factor_decay,
            factor_update_freq=factor_update_freq,
            inv_update_freq=inv_update_freq)
        return precond, KFACTransformState(kfac_state=new_state)

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)
