"""Epoch-schedule decay of K-FAC hyperparameters.

Parity with reference kfac/scheduler.py:1-94 (KFACParamScheduler), adapted
to the functional core: instead of mutating an optimizer's param group, the
scheduler *returns* the current hyperparameter values; the training loop
passes them into ``KFAC.step(...)``, whose cadence/strength arguments are
dynamic (no recompilation when they change).
"""

from __future__ import annotations

from typing import Sequence


def _factor_func(schedule: Sequence[int] | None, alpha: float):
    """Multiplicative decay factor as a function of the step/epoch count.

    Reference parity: kfac/scheduler.py:65-79 (note: the reference sorts
    the schedule in reverse but still multiplies once per passed
    threshold; behavior is order-independent, kept simple here).
    """
    sched = sorted(schedule) if schedule else []

    def factor(step: int) -> float:
        f = 1.0
        for t in sched:
            if step >= t:
                f *= alpha
        return f

    return factor


class KFACParamScheduler:
    """StepLR-style scheduler for damping and update frequencies.

    Args:
      kfac: the KFAC preconditioner whose base hyperparameters to scale.
      damping_alpha: multiplicative damping factor (default 1).
      damping_schedule: epochs at which to multiply damping by
        ``damping_alpha``.
      update_freq_alpha: multiplicative update-frequency factor (default 1).
      update_freq_schedule: epochs at which to multiply both
        ``factor_update_freq`` and ``inv_update_freq``.
      start_step: starting epoch counter (for checkpoint resume).

    Call ``step()`` once per epoch, then read ``params()`` (or the
    individual properties) and feed them to ``KFAC.step``.
    """

    def __init__(self, kfac, *,
                 damping_alpha: float = 1.0,
                 damping_schedule: Sequence[int] | None = None,
                 update_freq_alpha: float = 1.0,
                 update_freq_schedule: Sequence[int] | None = None,
                 start_step: int = 0):
        self.damping_base = kfac.damping
        self.factor_update_freq_base = kfac.factor_update_freq
        self.inv_update_freq_base = kfac.inv_update_freq
        self.damping_alpha = damping_alpha
        self.damping_schedule = (list(damping_schedule)
                                 if damping_schedule else None)
        self.update_freq_alpha = update_freq_alpha
        self.update_freq_schedule = (list(update_freq_schedule)
                                     if update_freq_schedule else None)
        self._damping_factor = _factor_func(damping_schedule, damping_alpha)
        self._freq_factor = _factor_func(update_freq_schedule,
                                         update_freq_alpha)
        self._step = start_step

    @property
    def damping(self) -> float:
        return self.damping_base * self._damping_factor(self._step)

    @property
    def factor_update_freq(self) -> int:
        return max(1, int(self.factor_update_freq_base *
                          self._freq_factor(self._step)))

    @property
    def inv_update_freq(self) -> int:
        return max(1, int(self.inv_update_freq_base *
                          self._freq_factor(self._step)))

    def params(self) -> dict:
        """Current kwargs for ``KFAC.step``."""
        return {'damping': self.damping,
                'factor_update_freq': self.factor_update_freq,
                'inv_update_freq': self.inv_update_freq}

    def step(self, step: int | None = None) -> dict:
        """Advance (or jump) the epoch counter; returns current params.

        Reference parity: kfac/scheduler.py:81-94.
        """
        self._step = self._step + 1 if step is None else step
        return self.params()

    def state_dict(self) -> dict:
        return {'step': self._step,
                'damping_base': self.damping_base,
                'damping_alpha': self.damping_alpha,
                'damping_schedule': self.damping_schedule,
                'factor_update_freq_base': self.factor_update_freq_base,
                'inv_update_freq_base': self.inv_update_freq_base,
                'update_freq_alpha': self.update_freq_alpha,
                'update_freq_schedule': self.update_freq_schedule}

    def load_state_dict(self, sd: dict) -> None:
        self._step = sd['step']
        self.damping_base = sd['damping_base']
        self.damping_alpha = sd['damping_alpha']
        self.damping_schedule = sd['damping_schedule']
        self.factor_update_freq_base = sd['factor_update_freq_base']
        self.inv_update_freq_base = sd['inv_update_freq_base']
        self.update_freq_alpha = sd['update_freq_alpha']
        self.update_freq_schedule = sd['update_freq_schedule']
        self._damping_factor = _factor_func(self.damping_schedule,
                                            self.damping_alpha)
        self._freq_factor = _factor_func(self.update_freq_schedule,
                                         self.update_freq_alpha)
