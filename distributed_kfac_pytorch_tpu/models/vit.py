"""Vision Transformer (Dosovitskiy et al. 2021), flax NHWC — the
encoder-attention workload.

BEYOND the reference: its layer registry has no attention-bearing
module kinds (Linear / Conv2d / Embedding / LSTMCell only,
``kfac/layers/__init__.py:13-36``), and its attention-bearing example
(``torch_language_model.py``) ships broken — it has no transformer
workload at all. Here every ViT weight layer is
K-FAC-visible: the patch embedding is a stride-P ``nn.Conv`` (a
``conv2d`` factor whose A covariance is over non-overlapping patches),
and each encoder block reuses ``transformer_lm.TransformerBlock`` with
``causal=False`` — the same four q/k/v/o Denses + two MLP Denses the LM
flagship preconditions, now under bidirectional attention
(``parallel.sequence`` ops take ``causal``; exactness at both settings
is pinned in ``tests/test_sequence_parallel.py``). The cls token and
position table are plain (non-layer) params, exactly like the LM's
``pos_embed`` — SGD-updated, outside K-FAC's blocks, matching how the
reference leaves non-module params alone.

Weight-sharing preconditioning (r13): under
``KFAC(kfac_approx='reduce')`` the patch-embed conv registers under
the KFAC-reduce approximation (its stride==kernel VALID geometry is
the ``sharing.is_patch_conv`` signature — patch vectors mean-reduced
over the grid before the covariance, the paper's ViT treatment,
arXiv:2311.00636) and every encoder Dense reduces over the patch
sequence; ``'expand'`` (the default) keeps the reference conv2d/flatten
factor math bit-identically.

For high-resolution inputs, ``attn_block_size`` folds the patch
sequence blockwise on one device (the chunked-attention knob inherited
from the shared block; the cls token's ragged ``num_patches + 1``
length is handled by the fold's masked padding). Ring attention over a
mesh (``seq_axis``) is deliberately not exposed here: image
classification shards over batch, not sequence — the LM is the
sequence-parallel workload.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributed_kfac_pytorch_tpu.models.transformer_lm import (
    TransformerBlock,
)


class VisionTransformer(nn.Module):
    """Patch-embed conv -> cls token + learned positions -> encoder
    blocks (bidirectional) -> final LN -> Dense head on the cls token
    (``pool='mean'`` switches to global average pooling, the paper's
    appendix-D variant — identical K-FAC coverage either way).
    """
    num_classes: int
    patch_size: int = 16
    d_model: int = 384
    num_layers: int = 12
    num_heads: int = 6
    mlp_ratio: int = 4
    dropout: float = 0.0
    pool: str = 'cls'            # 'cls' | 'mean'
    attn_block_size: int | None = None
    dtype: Any = None            # compute dtype (params stay fp32)

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        if self.pool not in ('cls', 'mean'):
            raise ValueError(f"pool must be 'cls' or 'mean', "
                             f'got {self.pool!r}')
        p = self.patch_size
        if x.shape[1] % p or x.shape[2] % p:
            raise ValueError(f'input {x.shape[1]}x{x.shape[2]} not '
                             f'divisible by patch_size={p}')
        y = nn.Conv(self.d_model, (p, p), strides=(p, p), padding='VALID',
                    dtype=self.dtype, name='patch_embed')(x)
        b = y.shape[0]
        y = y.reshape(b, -1, self.d_model)          # (B, HW/P^2, D)
        if self.pool == 'cls':
            cls = self.param('cls_token', nn.initializers.zeros,
                             (1, 1, self.d_model))
            y = jnp.concatenate(
                [jnp.broadcast_to(cls, (b, 1, self.d_model)).astype(y.dtype),
                 y], axis=1)
        pos = self.param('pos_embed', nn.initializers.normal(0.02),
                         (y.shape[1], self.d_model))
        y = y + pos.astype(y.dtype)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        for i in range(self.num_layers):
            y = TransformerBlock(self.num_heads, mlp_ratio=self.mlp_ratio,
                                 dropout=self.dropout, causal=False,
                                 attn_block_size=self.attn_block_size,
                                 dtype=self.dtype,
                                 name=f'block{i}')(y, train=train)
        y = nn.LayerNorm(dtype=self.dtype, name='ln_f')(y)
        y = y[:, 0] if self.pool == 'cls' else jnp.mean(y, axis=1)
        return nn.Dense(self.num_classes, dtype=self.dtype, name='head')(y)


def get_model(num_classes: int, size: str = 'small',
              **overrides) -> VisionTransformer:
    """Named configs following the ViT paper's Ti/S/B ladder, plus a
    CIFAR-scale variant (patch 4 on 32x32 inputs -> 64 patches)."""
    configs = {
        'cifar': dict(patch_size=4, d_model=192, num_layers=6,
                      num_heads=3),
        'tiny': dict(patch_size=16, d_model=192, num_layers=12,
                     num_heads=3),
        'small': dict(patch_size=16, d_model=384, num_layers=12,
                      num_heads=6),
        # ViT-B/16: q/k/v/o A factors 769, MLP A factors 769/3073 —
        # straddles the 640 eigen/cholesky auto-dispatch cutoff like
        # both existing flagships.
        'base': dict(patch_size=16, d_model=768, num_layers=12,
                     num_heads=12),
    }
    if size not in configs:
        raise ValueError(f'unknown size {size!r}; have {sorted(configs)}')
    cfg = {**configs[size], **overrides}
    return VisionTransformer(num_classes=num_classes, **cfg)
