"""CIFAR-10 ResNet family (He et al. arXiv:1512.03385), flax NHWC.

TPU-native counterpart of the reference's CIFAR model zoo
(examples/cnn_utils/cifar_resnet.py: ResNet-20/32/44/56/110/1202 with
option-A parameter-free shortcuts). Parameter counts match the paper
(ResNet-20 0.27M ... ResNet-1202 19.4M). Convs are `nn.Conv` and the head
is `nn.Dense`, so every FLOP-carrying layer is K-FAC-registrable by
`KFACCapture`; BatchNorm runs through the `batch_stats` collection.

Layout is NHWC (TPU-native; torch reference is NCHW) and option-A
downsampling is a strided slice + channel zero-pad, identical math to the
reference's `LambdaLayer` shortcut (cifar_resnet.py:85-86).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


def _norm_layer(norm: str, train: bool, dtype, name: str,
                bn_momentum: float = 0.9):
    """BatchNorm (reference parity) or GroupNorm (stateless control).

    The 'group' variant exists for the convergence methodology: BN's
    running statistics lag large preconditioned weight movement on
    small synthetic sets (the recorded round-3 val-oscillation
    negative); GroupNorm has no cross-step state, so a GN run isolates
    whether BN statistics — not the preconditioner — drive the
    oscillation. 8 groups (standard; >= 2 channels/group at planes=16).

    ``bn_momentum`` is the running-statistics EWMA coefficient (flax
    convention: new = m*old + (1-m)*batch; 0.9 here matches the torch
    reference's momentum=0.1 default). Tunable because under K-FAC's
    large preconditioned steps the stats-lag timescale 1/(1-m) is a
    convergence knob (round-5 BN study).
    """
    if norm == 'group':
        return nn.GroupNorm(num_groups=8, dtype=dtype, name=name)
    return nn.BatchNorm(use_running_average=not train,
                        momentum=bn_momentum, dtype=dtype, name=name)


class BasicBlock(nn.Module):
    """3x3 conv -> BN -> relu -> 3x3 conv -> BN + shortcut -> relu.

    Reference parity: cifar_resnet.py:69-98 (option-A shortcut: strided
    subsample + zero-pad channels, no parameters).
    """

    planes: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32
    norm: str = 'batch'
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = True):
        in_planes = x.shape[-1]
        y = nn.Conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                    padding=1, use_bias=False, dtype=self.dtype,
                    kernel_init=nn.initializers.kaiming_normal(),
                    name='conv1')(x)
        y = _norm_layer(self.norm, train, self.dtype, 'bn1',
                        self.bn_momentum)(y)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype,
                    kernel_init=nn.initializers.kaiming_normal(),
                    name='conv2')(y)
        y = _norm_layer(self.norm, train, self.dtype, 'bn2',
                        self.bn_momentum)(y)
        if self.stride != 1 or in_planes != self.planes:
            # Option A: subsample spatially, zero-pad channels (NHWC).
            sc = x[:, ::2, ::2, :]
            pad = self.planes // 4
            sc = jnp.pad(sc, ((0, 0), (0, 0), (0, 0), (pad, pad)))
        else:
            sc = x
        return nn.relu(y + sc)


class CifarResNet(nn.Module):
    """Stacked BasicBlocks over 16/32/64 planes + global-pool Dense head.

    Reference parity: cifar_resnet.py:101-132.
    """

    num_blocks: Sequence[int]
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    norm: str = 'batch'
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = nn.Conv(16, (3, 3), padding=1, use_bias=False, dtype=self.dtype,
                    kernel_init=nn.initializers.kaiming_normal(),
                    name='conv1')(x)
        y = _norm_layer(self.norm, train, self.dtype, 'bn1',
                        self.bn_momentum)(y)
        y = nn.relu(y)
        for stage, (planes, stride) in enumerate(
                zip((16, 32, 64), (1, 2, 2)), start=1):
            for i in range(self.num_blocks[stage - 1]):
                y = BasicBlock(planes, stride if i == 0 else 1,
                               dtype=self.dtype, norm=self.norm,
                               bn_momentum=self.bn_momentum,
                               name=f'layer{stage}_block{i}')(y, train=train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        kernel_init=nn.initializers.kaiming_normal(),
                        name='linear')(y)


_DEPTHS = {20: (3, 3, 3), 32: (5, 5, 5), 44: (7, 7, 7), 56: (9, 9, 9),
           110: (18, 18, 18), 1202: (200, 200, 200)}


def resnet(depth: int, num_classes: int = 10,
           dtype: jnp.dtype = jnp.float32,
           norm: str = 'batch',
           bn_momentum: float = 0.9) -> CifarResNet:
    """CIFAR ResNet by depth (20/32/44/56/110/1202)."""
    if depth not in _DEPTHS:
        raise ValueError(f'unsupported CIFAR ResNet depth {depth}; '
                         f'choose from {sorted(_DEPTHS)}')
    return CifarResNet(num_blocks=_DEPTHS[depth], num_classes=num_classes,
                       dtype=dtype, norm=norm, bn_momentum=bn_momentum)


def get_model(name: str, num_classes: int = 10,
              dtype: jnp.dtype = jnp.float32,
              bn_momentum: float = 0.9) -> CifarResNet:
    """Model by name, e.g. 'resnet32' (reference cifar_resnet.py:40-51);
    a 'gn' suffix ('resnet20gn') swaps BatchNorm for GroupNorm (the
    stateless-normalization control used by the convergence study)."""
    name = name.lower()
    if not name.startswith('resnet'):
        raise ValueError(f'unknown CIFAR model {name!r}')
    norm = 'batch'
    if name.endswith('gn'):
        norm, name = 'group', name[:-2]
    return resnet(int(name[len('resnet'):]), num_classes, dtype, norm,
                  bn_momentum)
