"""ImageNet ResNet-18/34/50/101/152 (He et al.), flax NHWC.

The reference trains torchvision's `resnet50`/`resnet152` (imported in
examples/torch_imagenet_resnet.py — models come from torchvision, not the
repo); this is the TPU-native equivalent with the same architecture:
7x7/2 stem, max-pool, [Basic|Bottleneck] stages, global average pool,
Dense head. Option-B (projection) shortcuts, as torchvision uses.

All convs are `nn.Conv` and the head `nn.Dense`, so K-FAC registers every
weight layer; bf16 activations are supported via `dtype` while BatchNorm
statistics stay fp32 (flax default param dtype).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

_KAIMING = nn.initializers.kaiming_normal()


def _bn(train: bool, dtype, name: str, momentum: float = 0.9):
    return nn.BatchNorm(use_running_average=not train, momentum=momentum,
                        epsilon=1e-5, dtype=dtype, name=name)


class BasicBlockV1(nn.Module):
    """Two 3x3 convs (ResNet-18/34)."""

    planes: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = True):
        sc = x
        y = nn.Conv(self.planes, (3, 3), (self.stride, self.stride),
                    padding=1, use_bias=False, dtype=self.dtype,
                    kernel_init=_KAIMING, name='conv1')(x)
        y = _bn(train, self.dtype, 'bn1', self.bn_momentum)(y)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype, kernel_init=_KAIMING, name='conv2')(y)
        y = _bn(train, self.dtype, 'bn2', self.bn_momentum)(y)
        if self.stride != 1 or x.shape[-1] != self.planes:
            sc = nn.Conv(self.planes, (1, 1), (self.stride, self.stride),
                         use_bias=False, dtype=self.dtype,
                         kernel_init=_KAIMING, name='downsample_conv')(x)
            sc = _bn(train, self.dtype, 'downsample_bn',
                     self.bn_momentum)(sc)
        return nn.relu(y + sc)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck, expansion 4 (ResNet-50/101/152)."""

    planes: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32
    expansion: int = 4
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = True):
        out_planes = self.planes * self.expansion
        sc = x
        y = nn.Conv(self.planes, (1, 1), use_bias=False, dtype=self.dtype,
                    kernel_init=_KAIMING, name='conv1')(x)
        y = nn.relu(_bn(train, self.dtype, 'bn1', self.bn_momentum)(y))
        y = nn.Conv(self.planes, (3, 3), (self.stride, self.stride),
                    padding=1, use_bias=False, dtype=self.dtype,
                    kernel_init=_KAIMING, name='conv2')(y)
        y = nn.relu(_bn(train, self.dtype, 'bn2', self.bn_momentum)(y))
        y = nn.Conv(out_planes, (1, 1), use_bias=False, dtype=self.dtype,
                    kernel_init=_KAIMING, name='conv3')(y)
        y = _bn(train, self.dtype, 'bn3', self.bn_momentum)(y)
        if self.stride != 1 or x.shape[-1] != out_planes:
            sc = nn.Conv(out_planes, (1, 1), (self.stride, self.stride),
                         use_bias=False, dtype=self.dtype,
                         kernel_init=_KAIMING, name='downsample_conv')(x)
            sc = _bn(train, self.dtype, 'downsample_bn',
                     self.bn_momentum)(sc)
        return nn.relu(y + sc)


class ImageNetResNet(nn.Module):
    """Standard ImageNet ResNet: stem + 4 stages + pooled Dense head."""

    stage_sizes: Sequence[int]
    bottleneck: bool = True
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    # Stem width; stage s uses width * 2^(s-1) planes. 64 is the paper
    # network. Narrow widths (e.g. 8) keep the exact 54-layer flagship
    # topology — bottlenecks, strided shortcut convs, depth — at
    # single-core-compilable program sizes (tests/test_flagship.py's
    # narrow variant).
    width: int = 64
    bn_momentum: float = 0.9
    # Block-granularity gradient checkpointing: each residual block's
    # activations are rematerialized in the backward pass, trading
    # ~1/3 extra forward FLOPs for O(depth) activation memory — the
    # standard TPU recipe for fitting larger monolithic batches (the
    # bf16 K-FAC capture path OOMs at b128@224 without it; round 5).
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = nn.Conv(self.width, (7, 7), (2, 2), padding=3, use_bias=False,
                    dtype=self.dtype, kernel_init=_KAIMING, name='conv1')(x)
        y = nn.relu(_bn(train, self.dtype, 'bn1', self.bn_momentum)(y))
        y = nn.max_pool(y, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        block = Bottleneck if self.bottleneck else BasicBlockV1
        if self.remat:
            # static_argnums: `train` is a Python bool, not a tracer
            # (flax counts the module itself as arg 0, x as 1, train 2).
            block = nn.remat(block, static_argnums=(2,))
        for stage, n_blocks in enumerate(self.stage_sizes, start=1):
            planes = self.width * 2 ** (stage - 1)
            for i in range(n_blocks):
                stride = 2 if (stage > 1 and i == 0) else 1
                y = block(planes, stride, dtype=self.dtype,
                          bn_momentum=self.bn_momentum,
                          name=f'layer{stage}_block{i}')(y, train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        kernel_init=_KAIMING, name='fc')(y)


_CONFIGS = {
    18: ((2, 2, 2, 2), False),
    34: ((3, 4, 6, 3), False),
    50: ((3, 4, 6, 3), True),
    101: ((3, 4, 23, 3), True),
    152: ((3, 8, 36, 3), True),
}


def resnet(depth: int, num_classes: int = 1000,
           dtype: jnp.dtype = jnp.float32,
           bn_momentum: float = 0.9,
           remat: bool = False) -> ImageNetResNet:
    """ImageNet ResNet by depth (18/34/50/101/152)."""
    if depth not in _CONFIGS:
        raise ValueError(f'unsupported ImageNet ResNet depth {depth}; '
                         f'choose from {sorted(_CONFIGS)}')
    sizes, bottleneck = _CONFIGS[depth]
    return ImageNetResNet(stage_sizes=sizes, bottleneck=bottleneck,
                          num_classes=num_classes, dtype=dtype,
                          bn_momentum=bn_momentum, remat=remat)


def get_model(name: str, num_classes: int = 1000,
              dtype: jnp.dtype = jnp.float32,
              bn_momentum: float = 0.9,
              remat: bool = False) -> ImageNetResNet:
    """Model by name, e.g. 'resnet50' (reference uses torchvision names)."""
    name = name.lower()
    if not name.startswith('resnet'):
        raise ValueError(f'unknown ImageNet model {name!r}')
    return resnet(int(name[len('resnet'):]), num_classes, dtype,
                  bn_momentum, remat)
