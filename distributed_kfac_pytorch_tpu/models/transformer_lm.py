"""Transformer decoder language model with Linear-layer K-FAC support.

The BASELINE tracked config 4 ("Transformer-XL-style LM with Linear-layer
K-FAC") workload. Attention is built from plain ``nn.Dense`` projections —
not flax's fused ``MultiHeadDotProductAttention`` (whose ``DenseGeneral``
params are invisible to the K-FAC layer registry, capture.py) — so every
projection (q/k/v/o) and MLP matmul is preconditioned exactly like the
reference preconditions LSTM-cell Linears (kfac/layers/linear.py:27-59).

Long contexts: pass ``seq_axis`` to shard the sequence over a mesh axis —
attention runs as a ring (``parallel.sequence.ring_self_attention``), the
rest of the network is token-local, and K-FAC factor statistics average
over the extra axis like any other batch sharding. The reference has no
analogue (SURVEY.md §5: sequence handling = BPTT truncation only).

Weight-sharing preconditioning (r13): every Dense here shares its
weight across the sequence axis, so ``KFAC(kfac_approx='reduce')``
switches their factor statistics to the KFAC-reduce approximation
(sum/mean over the sequence before the covariance, arXiv:2311.00636 —
a factor-seq cheaper factor update; ``sharing.approx``). With
``tie_weights`` the ``Embed.attend`` decoder call site then also feeds
the embedding's single factor pair (one inverse for the tied in/out
weight) instead of contributing gradient with no statistics.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributed_kfac_pytorch_tpu.parallel.sequence import (
    chunked_causal_attention,
    local_causal_attention,
    ring_self_attention,
)


class CausalSelfAttention(nn.Module):
    """Multi-head causal self-attention from four K-FAC-visible Denses.

    ``attn_block_size`` (single-device only) switches to the
    memory-efficient chunked fold — O(seq * block) live logits instead
    of O(seq^2) — for long contexts that fit one chip's compute but not
    monolithic attention's score tensor.
    """
    num_heads: int
    seq_axis: str | None = None
    attn_block_size: int | None = None
    causal: bool = True  # False = bidirectional (encoder/ViT use)
    dtype: Any = None    # compute dtype (params stay fp32); None = infer

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(f'{d_model=} not divisible by '
                             f'{self.num_heads=}')
        head_dim = d_model // self.num_heads

        def heads(y):
            return y.reshape(*y.shape[:-1], self.num_heads, head_dim)

        q = heads(nn.Dense(d_model, dtype=self.dtype, name='q_proj')(x))
        k = heads(nn.Dense(d_model, dtype=self.dtype, name='k_proj')(x))
        v = heads(nn.Dense(d_model, dtype=self.dtype, name='v_proj')(x))
        if self.seq_axis is not None and self.attn_block_size is not None:
            raise ValueError(
                'seq_axis and attn_block_size are mutually exclusive: '
                'the ring already folds blockwise per device (set '
                'attn_block_size=None under sequence parallelism)')
        if self.seq_axis is not None:
            o = ring_self_attention(q, k, v, axis_name=self.seq_axis,
                                    causal=self.causal)
        elif self.attn_block_size is not None:
            o = chunked_causal_attention(q, k, v,
                                         block_size=self.attn_block_size,
                                         causal=self.causal)
        else:
            o = local_causal_attention(q, k, v, causal=self.causal)
        o = o.reshape(*x.shape[:-1], d_model).astype(x.dtype)
        return nn.Dense(d_model, dtype=self.dtype, name='out_proj')(o)


class TransformerBlock(nn.Module):
    """Pre-LN block: LN -> attention -> LN -> GELU MLP.

    ``causal=True`` is the decoder (LM) form; ``causal=False`` the
    bidirectional encoder form (ViT, ``models/vit.py``).
    """
    num_heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    seq_axis: str | None = None
    attn_block_size: int | None = None
    causal: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        d_model = x.shape[-1]
        h = CausalSelfAttention(self.num_heads, seq_axis=self.seq_axis,
                                attn_block_size=self.attn_block_size,
                                causal=self.causal,
                                dtype=self.dtype, name='attn')(
            nn.LayerNorm(dtype=self.dtype, name='ln1')(x))
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = x + h
        y = nn.LayerNorm(dtype=self.dtype, name='ln2')(x)
        y = nn.Dense(self.mlp_ratio * d_model, dtype=self.dtype,
                     name='mlp_in')(y)
        y = nn.gelu(y)
        y = nn.Dense(d_model, dtype=self.dtype, name='mlp_out')(y)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        return x + y


class TransformerLM(nn.Module):
    """Decoder-only LM: embed + learned positions -> blocks -> logits.

    With ``seq_axis`` set, ``ids`` is the device-local contiguous sequence
    block and ``pos_offset`` must give its global start (device index *
    local length) so position embeddings line up across the ring.
    ``tie_weights`` reuses the embedding matrix as the decoder
    (``Embed.attend``), the flax-native form of the reference's
    ``register_shared_module`` tied-embedding path
    (kfac/preconditioner.py:404-470, torch_language_model.py:284-286).
    """
    vocab_size: int
    d_model: int = 512
    num_layers: int = 6
    num_heads: int = 8
    max_len: int = 2048
    dropout: float = 0.1
    tie_weights: bool = True
    seq_axis: str | None = None
    attn_block_size: int | None = None
    dtype: Any = None    # compute dtype (params stay fp32); None = infer

    @nn.compact
    def __call__(self, ids, *, train: bool = True, pos_offset=0):
        embed = nn.Embed(self.vocab_size, self.d_model,
                         dtype=self.dtype, name='embed')
        x = embed(ids)
        pos_table = self.param(
            'pos_embed', nn.initializers.normal(0.02),
            (self.max_len, self.d_model))
        pos = pos_offset + jnp.arange(ids.shape[-1])
        x = x + pos_table[pos].astype(x.dtype)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        for i in range(self.num_layers):
            x = TransformerBlock(self.num_heads, dropout=self.dropout,
                                 seq_axis=self.seq_axis,
                                 attn_block_size=self.attn_block_size,
                                 dtype=self.dtype,
                                 name=f'block{i}')(x, train=train)
        x = nn.LayerNorm(dtype=self.dtype, name='ln_f')(x)
        if self.tie_weights:
            return embed.attend(x)
        return nn.Dense(self.vocab_size, dtype=self.dtype,
                        name='decoder')(x)


def get_model(vocab_size: int, size: str = 'small',
              **overrides) -> TransformerLM:
    """Named configs akin to the reference's model zoo entry points."""
    configs = {
        'tiny': dict(d_model=128, num_layers=2, num_heads=4),
        'small': dict(d_model=512, num_layers=6, num_heads=8),
        'base': dict(d_model=768, num_layers=12, num_heads=12),
        # Transformer-XL large shape (d1024, 18 layers, FFN 4096 —
        # BASELINE config 4's "Transformer-XL-style"): the factor set
        # straddles the 640 eigen/cholesky dispatch cutoff (q/k/v/o
        # A factors 1025, MLP A factors 1025/4097, G 1024/4096).
        'xl': dict(d_model=1024, num_layers=18, num_heads=16),
        # d2048 — the top rung of the r13 expand/reduce scaling ladder
        # (flagship_lm.py --approx-ab): MLP factors 8192/8193, where
        # KFAC-reduce's sum-over-sequence factor statistics are ~seq x
        # cheaper than the expand flatten (sharing.approx).
        'xxl': dict(d_model=2048, num_layers=24, num_heads=16),
    }
    if size not in configs:
        raise ValueError(f'unknown size {size!r}; have {sorted(configs)}')
    cfg = {**configs[size], **overrides}
    return TransformerLM(vocab_size=vocab_size, **cfg)
