"""Flax model zoo: the workloads the reference framework trains.

- ``cifar_resnet``: ResNet-20..1202 for CIFAR-10 (reference
  examples/cnn_utils/cifar_resnet.py).
- ``imagenet_resnet``: ResNet-18..152 for ImageNet-1k (reference uses
  torchvision models in examples/torch_imagenet_resnet.py).
- ``lstm_lm``: LSTM language model (reference examples/rnn_utils/lstm.py).
- ``transformer_lm``: Transformer decoder LM with Linear-layer K-FAC and
  optional ring-attention sequence parallelism (BASELINE config 4).
- ``mobilenet``: MobileNetV1 — the depthwise workload the reference
  cannot precondition (no grouped-conv layer kind there); exercises
  this framework's ``conv2d_grouped`` path end to end.
- ``vit``: Vision Transformer — conv patch embed + bidirectional
  encoder blocks (shared with ``transformer_lm``), another family the
  reference has no working analogue of.
"""

from distributed_kfac_pytorch_tpu.models import cifar_resnet
from distributed_kfac_pytorch_tpu.models import imagenet_resnet
from distributed_kfac_pytorch_tpu.models import lstm_lm
from distributed_kfac_pytorch_tpu.models import mobilenet
from distributed_kfac_pytorch_tpu.models import transformer_lm
from distributed_kfac_pytorch_tpu.models import vit
