"""MobileNetV1 (Howard et al. 2017), flax NHWC — the depthwise workload.

BEYOND the reference: its layer registry has no conv variant for
``feature_group_count != 1`` (``kfac/layers/__init__.py:13-36``), so
on MobileNet-class models it silently loses preconditioning on every
depthwise layer (13 of the 27 weight layers here). This framework's
``conv2d_grouped`` kind (per-group block-diagonal factors, see
``layers/base.py`` / ``ops/factors.py``) preconditions all of them,
making MobileNetV1 the natural measured workload for that path
(``benchmarks/depthwise_bench.py``).

Architecture: 3x3/2 stem conv, then 13 depthwise-separable blocks
(3x3 depthwise + 1x1 pointwise, each BN+ReLU), global average pool,
Dense head — widths scaled by ``width_mult`` as in the paper. All
weight layers are `nn.Conv`/`nn.Dense`, so K-FAC registers everything;
bf16 activations via ``dtype`` with fp32 BatchNorm statistics.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

_KAIMING = nn.initializers.kaiming_normal()

# (pointwise out-planes, depthwise stride) per separable block — the
# paper's 13-block body (Table 1): 64, 128x2, 256x2, 512x6, 1024x2.
_BODY = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
         (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
         (1024, 1)]


def _bn(train: bool, dtype, name: str, momentum: float = 0.9):
    return nn.BatchNorm(use_running_average=not train, momentum=momentum,
                        epsilon=1e-5, dtype=dtype, name=name)


class SeparableBlock(nn.Module):
    """3x3 depthwise conv + 1x1 pointwise conv, each BN+ReLU."""

    planes: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = True):
        in_ch = x.shape[-1]
        y = nn.Conv(in_ch, (3, 3), (self.stride, self.stride), padding=1,
                    feature_group_count=in_ch, use_bias=False,
                    dtype=self.dtype, kernel_init=_KAIMING, name='dw')(x)
        y = nn.relu(_bn(train, self.dtype, 'bn_dw', self.bn_momentum)(y))
        y = nn.Conv(self.planes, (1, 1), use_bias=False, dtype=self.dtype,
                    kernel_init=_KAIMING, name='pw')(y)
        return nn.relu(_bn(train, self.dtype, 'bn_pw', self.bn_momentum)(y))


class MobileNetV1(nn.Module):
    """Stem + 13 separable blocks + pooled Dense head."""

    num_classes: int = 1000
    width_mult: float = 1.0
    dtype: jnp.dtype = jnp.float32
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = True):
        def w(planes):
            return max(8, int(planes * self.width_mult))

        y = nn.Conv(w(32), (3, 3), (2, 2), padding=1, use_bias=False,
                    dtype=self.dtype, kernel_init=_KAIMING, name='conv1')(x)
        y = nn.relu(_bn(train, self.dtype, 'bn1', self.bn_momentum)(y))
        for i, (planes, stride) in enumerate(_BODY):
            y = SeparableBlock(w(planes), stride, dtype=self.dtype,
                               bn_momentum=self.bn_momentum,
                               name=f'block{i}')(y, train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        kernel_init=_KAIMING, name='fc')(y)


def get_model(num_classes: int = 1000, width_mult: float = 1.0,
              dtype=jnp.float32, bn_momentum: float = 0.9) -> MobileNetV1:
    return MobileNetV1(num_classes=num_classes, width_mult=width_mult,
                       dtype=dtype, bn_momentum=bn_momentum)
