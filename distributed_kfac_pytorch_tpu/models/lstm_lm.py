"""LSTM language model (reference examples/rnn_utils/lstm.py).

Embedding -> K-FAC-friendly LSTM stack -> Dense decoder, with optional
tied embedding/decoder weights (reference lstm.py:38-41). With
``tie_weights`` the decoder uses ``Embed.attend`` — one shared parameter,
the flax-native form of the reference's ``register_shared_module``
(kfac/preconditioner.py:404-470); K-FAC then preconditions the shared
weight through its embedding registration only.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributed_kfac_pytorch_tpu.modules.lstm import LSTM


class LSTMLanguageModel(nn.Module):
    vocab_size: int
    embedding_dim: int = 650
    hidden_dim: int = 650
    num_layers: int = 2
    dropout: float = 0.5
    tie_weights: bool = False
    kfac_cell: bool = True
    dtype: Any = None    # compute dtype (params stay fp32); None = infer

    @nn.compact
    def __call__(self, ids, states=None, *, train: bool = True):
        if self.tie_weights and self.embedding_dim != self.hidden_dim:
            raise ValueError('tie_weights requires embedding_dim == '
                             'hidden_dim (reference rnn lstm.py:38-41)')
        embed = nn.Embed(self.vocab_size, self.embedding_dim,
                         dtype=self.dtype, name='embed')
        x = embed(ids)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x, states = LSTM(self.hidden_dim, num_layers=self.num_layers,
                         dropout=self.dropout, kfac_cell=self.kfac_cell,
                         dtype=self.dtype,
                         name='lstm')(x, states, train=train)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        if self.tie_weights:
            logits = embed.attend(x)
        else:
            logits = nn.Dense(self.vocab_size, dtype=self.dtype,
                              name='decoder')(x)
        return logits, states
