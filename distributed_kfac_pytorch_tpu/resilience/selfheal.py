"""Self-healing training: the fault-response escalation ladder (r16).

A production run cannot treat every numeric fault as fatal: before this
module, a persistent non-finite window either silently skipped factor
updates forever (the on-device guard protects the EWMA but nothing
re-seeds it) or killed the run, and recovery always meant
die-and-relaunch (r8). The :class:`SelfHealController` makes faults
survivable *in-process* — detect, degrade, recover — with
die-and-relaunch demoted to the last rung:

  1. **Skip-window** (rung 1, pre-existing): the on-device
     ``nonfinite_guard`` drops a non-finite candidate factor window and
     counts it in ``metrics['nonfinite_skips']``. The ladder *reads*
     this; it does not change it.
  2. **Damping escalation** (rung 2): on repeated bad windows
     (non-finite events or a loss-spike divergence) the controller
     multiplies the step's damping by ``damping_factor`` — a pure
     host-side scale on the traced ``hyper['damping']`` scalar, so the
     cadence stays ZERO-retrace — and decays it back one notch per
     clean window.
  3. **Per-bucket quarantine** (rung 3): when bad windows persist and a
     factor scan attributes them to specific layers, those layers'
     precondition shape-buckets are gated to the raw-gradient (plain
     SGD) direction via the on-device ``hyper['bucket_gate']`` mask
     (``KFAC.precondition(gates=)``), their factor EWMAs are reset to
     the init seeds and re-accumulate from clean statistics, and after
     a parity probe (re-accumulated factors finite + at least one
     inverse refresh) the bucket is re-admitted.
  4. **In-process rollback** (rung 4): when the fault cannot be
     attributed or quarantine does not clear it, :class:`Rollback`
     propagates out of ``engine.train_epoch``; the CLI restores the
     newest *verified* step checkpoint older than the fault onset
     (:func:`rollback_restore` — checksum-verified AND finite, walking
     past corrupt bundles with ``ckpt_quarantine`` events) and
     continues training in the same process.
  5. Only past ``max_rollbacks`` (or with no restorable bundle) does
     the process die — the r8 relaunch loop is the final rung, not the
     first response.

Cost discipline: per step the controller does host arithmetic only; the
one deliberate device sync is the window-boundary metric read (every
``check_every`` steps, like the straggler probe's documented cost), and
the factor finiteness scan runs only while a window is already bad. The
ladder is OFF by default; with it off, ``train_epoch`` is byte-for-byte
the pre-r16 engine (bit-identity pinned in tests/test_selfheal.py).
Armed, every adjustment is a traced-scalar VALUE change — zero
retraces, pinned by the same tests.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np


class Rollback(RuntimeError):
    """Raised by the controller when the ladder escalates to rung 4.

    Propagates out of ``engine.train_epoch`` (sinks are flushed first);
    the CLI catches it, restores via :func:`rollback_restore` and
    continues the training loop in-process.
    """

    def __init__(self, global_step: int, onset_step: int, reason: str):
        super().__init__(
            f'self-heal rollback requested at step {global_step} '
            f'(fault onset ~step {onset_step}): {reason}')
        self.global_step = int(global_step)
        self.onset_step = int(onset_step)
        self.reason = reason


class SelfHealExhausted(RuntimeError):
    """The ladder is out of rungs (rollback budget spent); the process
    should die and let the r8 relaunch loop take over."""


@dataclasses.dataclass
class SelfHealConfig:
    """Knobs of the escalation ladder (README "Self-healing").

    ``check_every`` is the window length in optimizer steps — the one
    host sync the armed ladder adds runs at this cadence (the CLIs
    default it to the inverse-update frequency, so the ladder observes
    once per K-FAC cadence window).
    """
    check_every: int = 10
    # Rung 2: damping escalation.
    escalate_after: int = 1        # consecutive bad windows to escalate
    damping_factor: float = 10.0   # per-escalation multiplier
    damping_max_mult: float = 1e4  # multiplier ceiling
    diverge_ratio: float = 10.0    # boundary loss > ratio * EMA -> bad
    loss_ema_alpha: float = 0.5    # boundary-loss reference tracking
    # How fast a DIVERGED reference re-legitimizes: on a diverged
    # window the loss reference grows by at most this factor (the
    # normal EMA update is suspended — feeding the spiked loss into
    # its own reference at full alpha would declare any plateau
    # healthy within one window and make the rollback rung
    # unreachable for pure-divergence faults). A divergence deeper
    # than ~ratio * adapt^rollback_after therefore escalates to
    # rollback instead of being absorbed; a moderate transient is
    # re-accepted within a few windows (escalate -> decay back).
    diverge_adapt: float = 1.2
    # Rung 3: per-bucket quarantine.
    quarantine: bool = True
    quarantine_after: int = 2      # consecutive bad windows to gate
    readmit_windows: int = 2       # min windows gated before the probe
    # Rung 4: in-process rollback.
    rollback_after: int = 5        # consecutive bad windows to roll back
    max_rollbacks: int = 1

    def __post_init__(self):
        if self.check_every < 1:
            raise ValueError(f'{self.check_every=} must be >= 1')
        if self.damping_factor <= 1.0:
            raise ValueError(f'{self.damping_factor=} must be > 1')
        if self.diverge_adapt <= 1.0:
            raise ValueError(f'{self.diverge_adapt=} must be > 1')
        if not (self.escalate_after >= 1
                and self.quarantine_after >= 1
                and self.rollback_after >= 1):
            raise ValueError('escalate_after/quarantine_after/'
                             'rollback_after must be >= 1')
        if self.rollback_after <= self.quarantine_after and \
                self.quarantine:
            raise ValueError(
                f'{self.rollback_after=} must exceed '
                f'{self.quarantine_after=} — quarantine needs at least '
                'one window to act before the ladder skips past it')


def bucket_layer_map(kfac, params) -> dict[str, list[str]]:
    """Precondition shape-bucket key -> the registered layers in it.

    Same ``eval_shape``-over-``grads_to_matrix`` derivation as
    ``KFAC.metric_bucket_keys`` (one source of shape truth), extended
    with the membership the quarantine reset needs.
    """
    from distributed_kfac_pytorch_tpu import layers as L
    from distributed_kfac_pytorch_tpu.observability import (
        metrics as obs_metrics,
    )

    def _get(tree, path):
        for part in path:
            tree = tree[part]
        return tree

    out: dict[str, list[str]] = {}
    for name, spec in kfac.specs.items():
        sh = jax.eval_shape(
            lambda p, s=spec: L.grads_to_matrix(s, p),
            _get(params, spec.path)).shape
        out.setdefault(obs_metrics.shape_key(sh), []).append(name)
    return out


def _recommit(value, leaf):
    """Place a freshly-built reset array on the original leaf's
    committed sharding: the jitted step's executable expects global
    mesh-placed inputs, and a host-local replacement would fail the
    dispatch on a multi-process mesh (single-process it would merely
    pay a silent re-commit)."""
    sharding = getattr(leaf, 'sharding', None)
    if isinstance(leaf, jax.Array) and sharding is not None:
        return jax.device_put(value, sharding)
    return value


def _seed_like(leaf):
    """The ``init_state`` factor seed for one factor leaf: identity for
    square (stacked) matrices, ones for diagonal vectors — shape,
    dtype and committed sharding preserved (see :func:`_recommit`)."""
    import jax.numpy as jnp

    shape = leaf.shape
    if len(shape) >= 2 and shape[-1] == shape[-2]:
        eye = jnp.eye(shape[-1], dtype=leaf.dtype)
        seed = jnp.broadcast_to(eye, shape)
    else:
        seed = jnp.ones(shape, leaf.dtype)
    return _recommit(seed, leaf)


class SelfHealController:
    """Host-side ladder state machine driven by the metrics stream.

    Wire through ``engine.train_epoch(selfheal=...)``; construct via
    ``resilience.cli.make_selfheal`` (the CLIs) or directly in tests.

    ``bucket_layers``: :func:`bucket_layer_map` output; None disables
    the quarantine rung (the ladder then goes skip -> damping ->
    rollback). When present, :meth:`adjust_hyper` carries a
    ``bucket_gate`` entry (one traced scalar per bucket, 1.0 = normal)
    in EVERY step's hyper — constant structure, so arming the ladder
    costs one compile per program variant and zero retraces after.
    With ``config.quarantine=False`` but ``bucket_layers`` given, the
    gate STRUCTURE still rides (all ones, never flipped) — the rung is
    inert but the traced program is identical, so a step builder can
    be shared across both controller shapes.
    """

    def __init__(self, config: SelfHealConfig | None = None, *,
                 bucket_layers: dict[str, list[str]] | None = None,
                 sink=None):
        self.config = config or SelfHealConfig()
        self.bucket_layers = bucket_layers
        self.sink = sink
        self.damping_mult = 1.0
        self.gates: dict[str, float] = {
            k: 1.0 for k in (bucket_layers or {})}
        self.pending_events: list[dict] = []
        self.rollbacks = 0
        # Window bookkeeping.
        self._consec_bad = 0
        self._onset_step: int | None = None
        self._last_skips = 0.0
        self._loss_ema: float | None = None
        self._last_inv_work = 0.0
        # bucket -> {'since': windows gated, 'inv_work_at': firing
        # count when gated} for the parity probe.
        self._quarantined: dict[str, dict] = {}

    # -- the per-step hooks (engine.train_epoch) -----------------------

    def adjust_hyper(self, hyper: dict) -> dict:
        """This step's effective hyperparameters: escalated damping
        (value-only change on the traced scalar) plus the per-bucket
        quarantine gates. Called every step; pure host dict work."""
        out = dict(hyper)
        if self.damping_mult != 1.0:
            out['damping'] = hyper['damping'] * self.damping_mult
        if self.bucket_layers is not None:
            out['bucket_gate'] = dict(self.gates)
        return out

    def observe(self, state, metrics: dict) -> None:
        """Consume one completed step (called with ``state.step`` still
        at the step just executed). Host arithmetic except at window
        boundaries; may reset quarantined layers' factor EWMAs in
        ``state.kfac_state`` and may raise :class:`Rollback`."""
        step = int(state.step)
        if (step + 1) % self.config.check_every:
            return
        self._boundary(step, state, metrics)

    def drain_events(self) -> list[dict]:
        out, self.pending_events = self.pending_events, []
        return out

    # -- window-boundary logic -----------------------------------------

    @staticmethod
    def _read(metrics: dict, key: str) -> float:
        v = metrics.get(key)
        if v is None:
            return float('nan')
        try:
            return float(np.asarray(jax.device_get(v)))
        except (TypeError, ValueError):
            return float('nan')

    def _boundary(self, step: int, state, metrics: dict) -> None:
        cfg = self.config
        # The one deliberate sync: a handful of device scalars from the
        # step just executed, every check_every steps.
        loss = self._read(metrics, 'loss')
        skips = self._read(metrics, 'kfac/nonfinite_skips')
        grad_norm = self._read(metrics, 'kfac/grad_norm')
        precond_norm = self._read(metrics, 'kfac/precond_norm')
        # Total inverse-refresh work = monolithic firings + pipelined
        # chunk firings; either key may be absent (a k=1 run records
        # no chunk counter) — only both-missing means "no signal".
        inv_u = self._read(metrics, 'kfac/inv_updates')
        inv_c = self._read(metrics, 'kfac/inv_chunk_firings')
        if math.isnan(inv_u) and math.isnan(inv_c):
            inv_work = float('nan')
        else:
            inv_work = ((0.0 if math.isnan(inv_u) else inv_u)
                        + (0.0 if math.isnan(inv_c) else inv_c))

        nonfinite = (
            (not math.isnan(skips) and skips > self._last_skips)
            or not math.isfinite(loss)
            or (not math.isnan(grad_norm)
                and not math.isfinite(grad_norm))
            or (not math.isnan(precond_norm)
                and not math.isfinite(precond_norm)))
        if not math.isnan(skips):
            self._last_skips = skips
        diverged = (not nonfinite and math.isfinite(loss)
                    and self._loss_ema is not None
                    and loss > cfg.diverge_ratio * self._loss_ema)
        if diverged:
            # Suspend the normal EMA: the spiked loss must not vouch
            # for itself. The reference re-legitimizes by at most
            # ×diverge_adapt per window, so a sustained plateau keeps
            # flagging (and can reach the rollback rung) while a
            # moderate transient is re-accepted within a few windows.
            self._loss_ema *= cfg.diverge_adapt
        elif math.isfinite(loss):
            a = cfg.loss_ema_alpha
            self._loss_ema = (loss if self._loss_ema is None
                              else (1 - a) * self._loss_ema + a * loss)

        if not math.isnan(inv_work):
            self._last_inv_work = inv_work
        if nonfinite or diverged:
            self._bad_window(step, state,
                             'nonfinite' if nonfinite else 'diverge',
                             loss)
        else:
            self._clean_window(step)
        self._probe_quarantined(step, state, inv_work)

    def _bad_window(self, step: int, state, kind: str,
                    loss: float) -> None:
        cfg = self.config
        self._consec_bad += 1
        if self._onset_step is None:
            # The fault began somewhere inside this window; the rollback
            # walk must not restore a bundle saved after its start.
            self._onset_step = max(0, step - cfg.check_every)
        if self._consec_bad >= cfg.escalate_after and \
                self.damping_mult < cfg.damping_max_mult:
            self.damping_mult = min(
                self.damping_mult * cfg.damping_factor,
                cfg.damping_max_mult)
            self._event('selfheal_escalate', global_step=step,
                        kind=kind, damping_mult=self.damping_mult,
                        bad_windows=self._consec_bad)
        if self.config.quarantine and self.bucket_layers is not None \
                and self._consec_bad >= cfg.quarantine_after:
            self._quarantine_bad_buckets(step, state)
        if self._consec_bad >= cfg.rollback_after:
            self._request_rollback(step, kind, loss)

    def _clean_window(self, step: int) -> None:
        cfg = self.config
        self._consec_bad = 0
        if not self._quarantined:
            self._onset_step = None
        if self.damping_mult > 1.0:
            self.damping_mult = max(
                1.0, self.damping_mult / cfg.damping_factor)
            self._event('selfheal_deescalate', global_step=step,
                        damping_mult=self.damping_mult)

    # -- rung 3: quarantine --------------------------------------------

    def _scan_factors(self, kfac_state: dict) -> dict[str, bool]:
        """layer -> factors-all-finite (host scan; only runs while a
        window is already bad or a quarantined bucket is up for its
        readmission probe)."""
        from distributed_kfac_pytorch_tpu.resilience import (
            integrity as integrity_lib,
        )
        factors = kfac_state.get('factors', {})
        return {name: integrity_lib.finite_ok(entry)
                for name, entry in factors.items()}

    def _quarantine_bad_buckets(self, step: int, state) -> None:
        finite = self._scan_factors(state.kfac_state)
        for bucket, layers in self.bucket_layers.items():
            if bucket in self._quarantined or \
                    self.gates.get(bucket, 1.0) == 0.0:
                continue
            bad = [n for n in layers if not finite.get(n, True)]
            if not bad:
                continue
            self.gates[bucket] = 0.0
            self._quarantined[bucket] = {
                'since': 0, 'inv_work_at': self._last_inv_work}
            state.kfac_state = self._reset_layers(state.kfac_state,
                                                 layers)
            self._event('selfheal_quarantine', global_step=step,
                        bucket=bucket, layers=','.join(sorted(layers)),
                        nonfinite_layers=','.join(sorted(bad)))

    def _reset_layers(self, kfac_state: dict, layers) -> dict:
        """Reset the named layers' factor EWMAs (and any overlap-state
        mirrors) to the init seeds: quarantined layers re-accumulate
        statistics from scratch instead of EMA-ing poison forever."""
        out = dict(kfac_state)
        for group in ('factors', 'frozen_factors'):
            if group not in out:
                continue
            entries = dict(out[group])
            for name in layers:
                if name in entries:
                    entries[name] = jax.tree.map(_seed_like,
                                                 entries[name])
            out[group] = entries
        if 'factor_accum' in out:
            import jax.numpy as jnp
            acc = dict(out['factor_accum'])
            for name in layers:
                if name in acc:
                    acc[name] = jax.tree.map(
                        lambda x: _recommit(jnp.zeros_like(x), x),
                        acc[name])
            out['factor_accum'] = acc
        return out

    def _probe_quarantined(self, step: int, state,
                           inv_work: float) -> None:
        """Rung-3 exit: the parity probe. A bucket re-admits once its
        re-accumulated factors are finite AND at least one inverse
        refresh (monolithic or chunk firing) consumed them — the
        rebuilt preconditioner then serves clean directions."""
        if not self._quarantined:
            return
        cfg = self.config
        finite = None
        for bucket in list(self._quarantined):
            q = self._quarantined[bucket]
            q['since'] += 1
            if q['since'] < cfg.readmit_windows:
                continue
            refired = (not math.isnan(inv_work)
                       and inv_work > q['inv_work_at'])
            if not refired:
                continue
            if finite is None:
                finite = self._scan_factors(state.kfac_state)
            layers = self.bucket_layers[bucket]
            if all(finite.get(n, True) for n in layers):
                self.gates[bucket] = 1.0
                windows = q['since']
                del self._quarantined[bucket]
                self._event('selfheal_readmit', global_step=step,
                            bucket=bucket, windows=windows)
        if not self._quarantined and self._consec_bad == 0:
            self._onset_step = None

    # -- rung 4: rollback ----------------------------------------------

    def _request_rollback(self, step: int, kind: str,
                          loss: float) -> None:
        cfg = self.config
        reason = (f'{self._consec_bad} consecutive bad windows '
                  f'(last: {kind}, loss={loss:.4g}, '
                  f'damping_mult={self.damping_mult:g})')
        if self.rollbacks >= cfg.max_rollbacks:
            raise SelfHealExhausted(
                f'self-heal ladder exhausted at step {step}: {reason} '
                f'after {self.rollbacks} rollback(s) — dying for the '
                'relaunch loop (r8), the ladder\'s last rung')
        self.rollbacks += 1
        onset = self._onset_step if self._onset_step is not None else step
        raise Rollback(step, onset, reason)

    def after_rollback(self, restored_step: int) -> None:
        """Re-arm the ladder on the restored (pre-fault) state: gates
        lift, damping resets, window counters clear. The rollback
        budget (``rollbacks``) is NOT reset — a recurring fault must
        eventually fall through to relaunch, keeping the ladder
        bounded."""
        self._consec_bad = 0
        self._onset_step = None
        self._last_skips = 0.0
        self._last_inv_work = 0.0
        self._loss_ema = None
        self.damping_mult = 1.0
        self._quarantined.clear()
        for k in self.gates:
            self.gates[k] = 1.0

    def _event(self, name: str, **data) -> None:
        self.pending_events.append({'event': name, **data})


# ---------------------------------------------------------------------------
# Rollback restore (the CLI half of rung 4)
# ---------------------------------------------------------------------------

def rollback_restore(step_mgr, like: dict, *, from_step: int,
                     onset_step: int | None = None, reason: str = '',
                     sink=None):
    """Restore the newest VERIFIED step bundle for an in-process
    rollback; returns ``(label, tree)``.

    Candidates are the step tree's bundles at or before ``onset_step``
    (a bundle saved after the fault began would roll back INTO the
    fault); each must pass the content-checksum verification AND a
    finiteness scan of its K-FAC group (``integrity.finite_ok`` — a
    poisoned state checksums perfectly). Failing bundles emit
    ``ckpt_quarantine`` events and the walk continues. Raises
    :class:`SelfHealExhausted` when nothing restorable remains — the
    process then dies into the r8 relaunch loop.
    """
    from distributed_kfac_pytorch_tpu.resilience import (
        cli as cli_lib,
        integrity as integrity_lib,
    )
    labels = sorted(step_mgr.all_steps(), reverse=True)
    if onset_step is not None:
        labels = [l for l in labels if l <= onset_step]
    quarantined: list[str] = []
    for label in labels:
        found = cli_lib._walk_restore(step_mgr, like, None, kind='step',
                                      sink=sink, labels=[label],
                                      quarantined=quarantined)
        if found is None:
            continue
        label, tree, _relaid = found
        if not integrity_lib.finite_ok(tree.get('kfac', {})):
            # mgr= moves the bundle aside on disk: it checksums clean,
            # so the r8 relaunch resume (checksum-only) would
            # otherwise restore this poisoned bundle right back after
            # the ladder exhausts.
            cli_lib._quarantine(sink, 'step', label,
                                'restored K-FAC state contains '
                                'non-finite values (saved after the '
                                'fault?)', quarantined, mgr=step_mgr)
            continue
        if sink is not None:
            sink.event_record('selfheal_rollback',
                              from_step=int(from_step),
                              to_step=int(tree['scalars']['step']),
                              label=int(label),
                              reason=str(reason)[:300])
        return label, tree
    raise SelfHealExhausted(
        f'rollback requested at step {from_step} but no verified '
        f'step checkpoint at or before step {onset_step} exists '
        f'({len(quarantined)} quarantined: {quarantined[:3]}...) — '
        'dying for the relaunch loop (r8)')


def handle_rollback(rb: Rollback, *, args, step_mgr, like: dict, state,
                    dkfac, sink=None, controller=None, kfac_sched=None,
                    checkpointer=None,
                    verbose: bool = False) -> tuple[int, int]:
    """The CLIs' shared rung-4 recovery: restore the newest verified
    pre-fault bundle into the LIVE ``TrainState`` and return the
    ``(start_epoch, start_offset)`` to continue the epoch loop from —
    all without exiting the process.

    The preconditioner state is rebuilt from the bundle through
    ``DistributedKFAC.load_state_dict`` (inverses recomputed when
    absent), discarding every poisoned live tensor; ``controller``
    (when given) is re-armed via :meth:`SelfHealController
    .after_rollback`.
    """
    label, tree = rollback_restore(
        step_mgr, like, from_step=rb.global_step,
        onset_step=rb.onset_step, reason=rb.reason, sink=sink)
    state.params = tree['params']
    state.opt_state = tree['opt_state']
    if dkfac is not None:
        state.kfac_state = dkfac.load_state_dict(tree['kfac'],
                                                 state.params)
    state.extra_vars = tree['extra_vars']
    sc = tree['scalars']
    state.epoch = int(sc['epoch'])
    state.step = int(sc['step'])
    if kfac_sched is not None:
        kfac_sched.step(state.epoch)
    if controller is not None:
        controller.after_rollback(state.step)
    if checkpointer is not None and checkpointer.policy is not None:
        # Re-key the interval policy to the restored position: its
        # last-save step is still the pre-rollback value, and
        # "steps since last save" would stay negative for the whole
        # replay — zero step checkpoints while replaying is exactly
        # when a second fault would be unrecoverable.
        checkpointer.policy.note_saved(state.step)
    if verbose:
        print(f'self-heal: rolled back in-process to verified step '
              f'checkpoint {label} (global step {state.step}, epoch '
              f'{state.epoch}, offset {int(sc["step_in_epoch"])}) — '
              f'{rb.reason}')
    return int(sc['epoch']), int(sc['step_in_epoch'])
