"""Resilience: preemption-safe checkpointing, mid-epoch resume, chaos.

The subsystem that makes interrupted training a normal, tested path
(ISSUE r8). Four parts, one discipline — *a preempted run loses at most
the in-flight step, and a resumed run replays the exact remaining batch
sequence*:

  - :mod:`preemption` — SIGTERM/SIGINT (and pluggable, e.g. TPU
    maintenance-event) grace-period handling the train loop polls once
    per step; on trigger the loop forces a *blocking* checkpoint save
    and exits with :data:`preemption.RELAUNCH_EXIT_CODE` so a
    supervisor relaunch-loop restarts the job.
  - :mod:`policy` — global-step-indexed checkpoints on top of
    ``training.checkpoint.CheckpointManager``: step-interval and
    wall-clock-interval knobs plus on-preemption forcing
    (:class:`policy.StepCheckpointer`).
  - :mod:`dataiter` — the data-stream state (seed, epoch, step offset)
    captured in every checkpoint bundle; with the seeded pipelines in
    ``training.datasets`` (``skip_batches=``) a resumed run replays the
    remaining batches bit-identically.
  - :mod:`faults` + :mod:`chaos` — fault injectors (simulated
    preemption at step *k*, NaN batches, hard crashes, crash during
    checkpoint write, live-factor corruption, checkpoint bit rot,
    loss-spike divergence) driven by the ``KFAC_CHAOS`` env var, and
    the ``python -m ...resilience.chaos`` harness that runs a training
    command under them with an optional relaunch loop.
  - :mod:`integrity` — content checksums recorded in every bundle's
    scalars at save and verified at restore (r16); the resume walk
    quarantines bundles that fail (``ckpt_quarantine``) and lands on
    the newest verifiable one.
  - :mod:`selfheal` — the r16 fault-response escalation ladder
    (skip-window -> damping escalation -> per-bucket quarantine ->
    in-process last-good-checkpoint rollback), driven from
    ``engine.train_epoch`` by the on-device metrics stream; see
    README "Self-healing".
  - :mod:`heartbeat` + :mod:`supervisor` — the r17 failure
    supervision layer: per-rank liveness leases (atomic JSON files
    written from the train loop) and the
    ``python -m ...resilience.supervisor`` process that launches the
    training command, classifies failures (crash / hang / dead
    worker / lost capacity / persistent straggler / crash loop) from
    exit codes, lease expiry and the r10 rank shards, and recovers —
    relaunch with backoff under a budget, survivor-mesh failover and
    grow-back via the r11 elastic resume; see README "Supervision &
    failover".
  - :mod:`cli` — the shared flag surface (``--checkpoint-steps``,
    ``--checkpoint-secs``, ``--preemption-grace``, ``--resume-step``)
    and the unified newest-of-step-or-epoch resume helper used by all
    three example CLIs (mirrors ``observability.cli``).

Resilience events (preemption, forced/interval saves with latency,
restores) ride in the schema-versioned observability metrics JSONL
(``kind='event'``) and are summarized by ``observability.report``.

Everything loads lazily so importing the package costs nothing on the
hot path (same pattern as ``observability``).
"""

from __future__ import annotations

import importlib

_LAZY = ('preemption', 'policy', 'dataiter', 'faults', 'chaos', 'cli',
         'integrity', 'selfheal', 'heartbeat', 'supervisor')

__all__ = list(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        mod = importlib.import_module(
            f'distributed_kfac_pytorch_tpu.resilience.{name}')
        globals()[name] = mod
        return mod
    raise AttributeError(name)
