"""Grace-period preemption handling for the train loop.

TPU pods (and most preemptible fleets) announce eviction with a signal
— SIGTERM from the GCE preemption notice, SIGINT from an operator — a
short grace window before the kill. The handler here converts that
asynchronous notice into a flag the train loop polls **once per step**
(`PreemptionHandler.triggered`); on trigger the loop forces a *blocking*
checkpoint save (`policy.StepCheckpointer`) and the CLI exits with
:data:`RELAUNCH_EXIT_CODE`, which a supervisor relaunch-loop treats as
"restart me" (see scripts/tpu_pod_setup.md §5) while any other exit
code means done/failed.

Beyond signals the handler is pluggable: ``add_source(fn)`` registers a
zero-argument callable polled alongside the flag — the hook for a TPU
maintenance-event watcher (GCE metadata server
``instance/maintenance-event``) or an orchestration sidecar. A
file-based source ships built in (``file_source``): touching the
sentinel path requests a graceful drain, which is also how the
``KFAC_CHAOS`` fault injector and ops runbooks drive it without
signals.

Multihost note: the flag is LOCAL; acting on it independently would
let a signal that lands between different hosts' polls force the
collective save at different steps and wedge the pod. The
``StepCheckpointer`` therefore treats rank 0's flag as the single
decision authority and broadcasts its verdict each step
(``policy.StepCheckpointer._agree``) — pod preemption reaches every
worker within the same step, so this costs at most one step of grace.
A *single* failing host (signal never reaches rank 0) is the other
failure mode — handled by the relaunch loop restarting all workers
from the last durable checkpoint (tests/test_multihost.py kill test),
not by this handler.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Callable

def _relaunch_exit_code() -> int:
    """The "preempted with checkpoint saved — relaunch me" exit code.

    Default 75 = EX_TEMPFAIL from sysexits.h (temporary failure,
    retry) — deliberate, but it COLLIDES with anything else on the box
    that speaks sysexits (sendmail-family tooling most famously; see
    MIGRATION.md "Relaunch exit code"). ``KFAC_RELAUNCH_EXIT``
    overrides it; both the training CLIs (which exit with it) and the
    relaunch-loop side (``resilience.chaos``, ``resilience.supervisor``
    — which compare against it) read THIS constant, so setting the env
    var for the whole process tree keeps the two sides agreeing.
    """
    raw = os.environ.get('KFAC_RELAUNCH_EXIT')
    if raw is None:
        return 75
    try:
        code = int(raw)
    except ValueError:
        raise ValueError(
            f'KFAC_RELAUNCH_EXIT={raw!r} is not an integer exit code'
        ) from None
    if not 1 <= code <= 255:
        # 0 means success to every supervisor; >255 wraps mod 256 on
        # POSIX and would silently alias another code.
        raise ValueError(
            f'KFAC_RELAUNCH_EXIT={code} must be in 1..255 (0 is '
            'success; values past 255 wrap on POSIX exit)')
    return code


# Supervisors loop `while rc == RELAUNCH_EXIT_CODE`; anything else is
# success or a real failure. Env-configurable (KFAC_RELAUNCH_EXIT),
# read once at import — children re-read it at their own import, so an
# env var set on the supervisor propagates consistently.
RELAUNCH_EXIT_CODE = _relaunch_exit_code()


class Preempted(Exception):
    """Raised out of the train loop after the forced preemption save.

    Carries where training stopped so the CLI can log it; the
    checkpoint is already durable when this propagates
    (``StepCheckpointer`` saves *blocking* before raising).
    """

    def __init__(self, global_step: int, reason: str = 'preempted'):
        super().__init__(f'{reason} at global step {global_step}')
        self.global_step = global_step
        self.reason = reason


def file_source(path: str) -> Callable[[], str | None]:
    """A trigger source that fires when ``path`` exists.

    Ops (or the chaos harness) request a graceful drain with
    ``touch <path>``; wired from the ``KFAC_PREEMPT_FILE`` env var by
    ``resilience.cli.install_preemption``.
    """

    def check():
        return f'sentinel file {path}' if os.path.exists(path) else None

    return check


class PreemptionHandler:
    """Signal-driven (and pluggable) preemption flag with a grace budget.

    Usage::

        handler = PreemptionHandler(grace_secs=30.0).install()
        ...
        if handler.triggered():          # polled once per step
            <blocking checkpoint save>
            raise Preempted(step, handler.reason)

    Semantics:

    - First SIGTERM/SIGINT: set the flag and start the grace clock;
      training finishes the in-flight step, saves, exits 75.
    - Second signal of the same kind: escalate — the previous handler
      (usually the default, i.e. terminate) is restored and the signal
      re-raised, so a save wedged past the operator's patience can
      still be killed.
    - ``add_source``: extra zero-arg callables polled by
      ``triggered()``; returning a truthy value (used as the reason)
      triggers exactly like a signal.
    """

    def __init__(self, grace_secs: float = 30.0,
                 signals=(signal.SIGTERM, signal.SIGINT)):
        self.grace_secs = float(grace_secs)
        self.signals = tuple(signals)
        self.reason: str | None = None
        self._triggered = False
        self._deadline: float | None = None
        self._prev: dict[int, object] = {}
        self._sources: list[Callable[[], str | None]] = []

    # -- installation ---------------------------------------------------

    def install(self) -> 'PreemptionHandler':
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def _on_signal(self, signum, frame) -> None:
        if self._triggered:
            self._escalate(signum)
            return
        self.trigger(f'signal {signal.Signals(signum).name}')

    def _escalate(self, signum) -> None:
        """Second signal: restore the prior disposition and re-raise."""
        signal.signal(signum, self._prev.get(signum, signal.SIG_DFL))
        os.kill(os.getpid(), signum)

    # -- triggering / polling ------------------------------------------

    def add_source(self, fn: Callable[[], str | None]) -> None:
        """Register an extra trigger source (e.g. a TPU
        maintenance-event poller); polled by :meth:`triggered`."""
        self._sources.append(fn)

    def trigger(self, reason: str = 'preempted') -> None:
        """Request a graceful drain (signal handler, source, or chaos)."""
        if not self._triggered:
            self._triggered = True
            self.reason = reason
            self._deadline = time.monotonic() + self.grace_secs

    def triggered(self) -> bool:
        """Poll point for the train loop — cheap (no syscalls unless
        sources are registered)."""
        if not self._triggered:
            for src in self._sources:
                why = src()
                if why:
                    self.trigger(str(why))
                    break
        return self._triggered

    def remaining_grace(self) -> float:
        """Seconds left in the grace budget (inf before triggering)."""
        if self._deadline is None:
            return float('inf')
        return self._deadline - time.monotonic()
