"""Chaos harness: run a training command under injected faults.

    python -m distributed_kfac_pytorch_tpu.resilience.chaos \\
        'preempt@2' -- python examples/train_cifar10_resnet.py ...

Sets ``KFAC_CHAOS`` to the (validated) fault spec and execs the
command. With ``--relaunch N`` it also plays supervisor: while the
child exits with :data:`preemption.RELAUNCH_EXIT_CODE` (preempted,
checkpoint saved) it relaunches — up to N times — with the fault spec
CLEARED for relaunches (faults are one-shot; pass ``--keep-faults`` to
re-inject every launch). This is the one-command form of the
kill-and-resume smoke (scripts/resilience_smoke.sh) and doubles as the
documented relaunch-loop shape for real supervisors
(scripts/tpu_pod_setup.md §5). It only handles the COOPERATIVE failure
(a graceful drain that exits the relaunch code); crashes, hangs and
dead workers need the full supervisor —
``python -m distributed_kfac_pytorch_tpu.resilience.supervisor`` —
which adds heartbeat-lease liveness, kill-and-relaunch, survivor-mesh
failover and crash-loop escalation (README "Supervision & failover").

A ``resize@K->N`` fault makes the relaunch a TOPOLOGY change: the
relaunched command runs with an N-device world
(``--xla_force_host_platform_device_count=N`` injected into
``XLA_FLAGS`` — the CPU-backend world-size knob, which is how the
grow/shrink loop is testable with no pod; on real TPU fleets the
re-provisioning supervisor owns the device count and this harness only
models its relaunch step). The resumed run then reshards its K-FAC
state through the elastic path instead of cold restarting.

A ``slice-loss@K->S`` fault (r20 multi-slice) drains the same way but
the relaunch lands on the S SURVIVOR slices: the new world is
``S * per_slice`` devices (per-slice size derived from the prior
launch's forced device count and ``KFAC_NUM_SLICES`` — fail-closed
when either is missing), and ``KFAC_NUM_SLICES=S`` is exported so the
CLI's ``--num-slices`` default follows the shrink.

Exit status: the final child's exit code (so CI can gate on it).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from distributed_kfac_pytorch_tpu.resilience import faults
from distributed_kfac_pytorch_tpu.resilience.preemption import (
    RELAUNCH_EXIT_CODE,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog='python -m distributed_kfac_pytorch_tpu.resilience.chaos',
        description='Run a training command under injected faults '
                    '(sets KFAC_CHAOS), optionally relaunching while '
                    f'it exits {RELAUNCH_EXIT_CODE} (preempted).')
    p.add_argument('spec',
                   help="fault spec 'kind@step[,kind@step...]'; kinds: "
                        'preempt, crash, nan-batch, crash-in-save, '
                        'corrupt-factor (Inf into a live Kronecker '
                        'factor), corrupt-ckpt (bit-flip a saved '
                        'bundle), diverge (loss-spike injection), '
                        "resize@K->N (relaunch with an N-device world), "
                        'slice-loss@K->S (drop whole slices: relaunch '
                        'on the S survivor slices with '
                        'KFAC_NUM_SLICES=S), '
                        'hang (wedge without exit — needs the real '
                        'supervisor to detect), slowrank (persistent '
                        'per-step delay) '
                        "(use '-' for no faults: pure relaunch loop)")
    p.add_argument('--relaunch', type=int, default=0, metavar='N',
                   help='relaunch the command up to N times while it '
                        f'exits {RELAUNCH_EXIT_CODE}')
    p.add_argument('--keep-faults', action='store_true',
                   help='re-inject the fault spec on every relaunch '
                        '(default: faults fire on the first launch '
                        'only)')
    if argv is None:
        argv = sys.argv[1:]
    # Split at the first '--' ourselves: argparse REMAINDER would start
    # swallowing at the first positional and eat our own options.
    cmd: list[str] = []
    if '--' in argv:
        split = argv.index('--')
        argv, cmd = argv[:split], argv[split + 1:]
    args = p.parse_args(argv)
    if not cmd:
        p.error('no command given (append: -- python examples/...)')
    spec = None if args.spec == '-' else args.spec
    plan = faults.parse_spec(spec)  # validate before launching anything

    env = dict(os.environ)
    if plan is not None:
        env[faults.ENV_VAR] = spec
    else:
        env.pop(faults.ENV_VAR, None)

    launches = 0
    while True:
        rc = subprocess.run(cmd, env=env).returncode
        launches += 1
        if rc != RELAUNCH_EXIT_CODE or launches > args.relaunch:
            break
        note = ''
        if plan is not None and plan.resize_to is not None:
            env['XLA_FLAGS'] = faults.xla_flags_with_device_count(
                env.get('XLA_FLAGS', ''), plan.resize_to)
            note = f' with {plan.resize_to} devices'
        if plan is not None and plan.slice_loss_to is not None:
            # Relaunch onto the survivor slices: per-slice device
            # count recovered from the prior launch's forced device
            # count + KFAC_NUM_SLICES — both must be present and
            # consistent (fail closed; guessing a world would hide a
            # mis-set harness rather than test failover).
            prev = int(env.get('KFAC_NUM_SLICES', '0') or 0)
            world = faults.forced_device_count(env.get('XLA_FLAGS', ''))
            if prev < 1 or world is None or world % prev:
                raise SystemExit(
                    'chaos: slice-loss relaunch needs KFAC_NUM_SLICES '
                    'and --xla_force_host_platform_device_count (a '
                    'multiple of it) in the environment to derive the '
                    f'per-slice device count (got slices={prev}, '
                    f'forced world={world})')
            if plan.slice_loss_to >= prev:
                raise SystemExit(
                    f'chaos: slice-loss@K->{plan.slice_loss_to} must '
                    f'name FEWER than the {prev} launched slices '
                    '(it drops slices, not grows them)')
            new_world = (world // prev) * plan.slice_loss_to
            env['XLA_FLAGS'] = faults.xla_flags_with_device_count(
                env.get('XLA_FLAGS', ''), new_world)
            env['KFAC_NUM_SLICES'] = str(plan.slice_loss_to)
            note = (f' on {plan.slice_loss_to} survivor slice(s) '
                    f'({new_world} devices)')
        print(f'chaos: launch {launches} exited {rc} (preempted) — '
              f'relaunching{note} ({launches}/{args.relaunch})',
              file=sys.stderr)
        if not args.keep_faults:
            env.pop(faults.ENV_VAR, None)
    return rc


if __name__ == '__main__':
    sys.exit(main())
