"""Step-level checkpoint policy on top of ``CheckpointManager``.

Epoch checkpoints (the CLIs' ``--checkpoint-freq``) lose up to an epoch
of work on preemption — hours at ImageNet/LM scale. The
:class:`StepCheckpointer` adds **global-step-indexed** checkpoints in a
``steps/`` subdirectory of the run's checkpoint tree, driven by:

  - a step interval (``--checkpoint-steps N``),
  - a wall-clock interval (``--checkpoint-secs S``), and
  - on-preemption forcing: when the polled
    :class:`preemption.PreemptionHandler` has triggered, a *blocking*
    save runs regardless of the intervals and :class:`Preempted` is
    raised so the CLI can exit with the relaunch code.

Saves are async by default (orbax snapshots and writes behind the
loop); the forced preemption save blocks, because durability before
process exit is the whole point. Each bundle carries the resume point
(``epoch``, ``step_in_epoch``, ``data_seed`` scalars — see
:mod:`dataiter`) so a relaunch replays the exact remaining batches.

Multihost: saves are collective (every process calls ``save``; orbax
coordinates the shard writes), so decisions must agree across hosts —
rank 0 is the single decision authority and its verdict is broadcast
each step (see :meth:`StepCheckpointer._agree`; signals and wall
clocks can otherwise tip different hosts into one-sided collective
saves). Fault injection (:mod:`faults`) is polled here too: the
injectors fire at the same once-per-step point the real failures
would.
"""

from __future__ import annotations

import time

import numpy as np

from distributed_kfac_pytorch_tpu.resilience import faults as faults_lib
from distributed_kfac_pytorch_tpu.resilience.preemption import (
    Preempted,
    PreemptionHandler,
)


class CheckpointPolicy:
    """Pure decision logic: is a step checkpoint due?

    ``every_steps`` counts *global optimizer steps since the last step
    save* (robust across resumes, unlike modulo-of-global-step);
    ``every_secs`` is wall-clock since the last step save. Either knob
    at 0 disables it; both at 0 means only forced (preemption) saves.
    """

    def __init__(self, every_steps: int = 0, every_secs: float = 0.0,
                 *, start_step: int = 0, clock=time.monotonic):
        if every_steps < 0 or every_secs < 0:
            raise ValueError('checkpoint intervals must be >= 0, got '
                             f'{every_steps=} {every_secs=}')
        self.every_steps = int(every_steps)
        self.every_secs = float(every_secs)
        self._clock = clock
        self._last_step = int(start_step)
        self._last_time = clock()

    def should_save(self, global_step: int) -> bool:
        if self.every_steps and \
                global_step - self._last_step >= self.every_steps:
            return True
        if self.every_secs and \
                self._clock() - self._last_time >= self.every_secs:
            return True
        return False

    def note_saved(self, global_step: int) -> None:
        self._last_step = int(global_step)
        self._last_time = self._clock()


class StepCheckpointer:
    """Per-step checkpoint + preemption + fault-injection hook.

    ``train_epoch`` calls :meth:`after_step` once per completed step;
    the CLIs call :meth:`poll` between epochs (preemption can arrive
    during eval). ``bundle_fn(state, step_in_epoch) -> tree`` assembles
    the checkpoint bundle (the CLI closes over its model/optimizer
    specifics); ``sink`` (an ``observability.JsonlMetricsSink`` or
    None) receives ``kind='event'`` records for every save (with
    latency) and preemption.
    """

    def __init__(self, mgr, policy: CheckpointPolicy | None, bundle_fn,
                 *, preemption: PreemptionHandler | None = None,
                 sink=None, plan: faults_lib.FaultPlan | None = None,
                 always_block: bool = False):
        self.mgr = mgr
        self.policy = policy
        self.bundle_fn = bundle_fn
        self.preemption = preemption
        self.sink = sink
        self.plan = plan
        self.always_block = always_block
        # State-corruption injections fire ONCE per process: an
        # in-process self-heal rollback (r16) rewinds state.step below
        # the fault step, and re-firing on the replay would make every
        # rollback a guaranteed re-poisoning (the crash/drain faults
        # exit the process, so only these three need the latch).
        self._fired: set[str] = set()

    # -- the once-per-step hook ----------------------------------------

    def after_step(self, state, step_in_epoch: int) -> None:
        """Called by ``train_epoch`` after each completed step with the
        number of steps finished in the current epoch (skip offset
        included). May raise :class:`Preempted` — the checkpoint is
        durable before it propagates."""
        gstep = int(state.step)
        if self.plan is not None:
            # Persistent-straggler delay first: it models a SLOW host,
            # so it must tax every step (the other injectors fire at
            # one step).
            faults_lib.slow_step(self.plan, gstep)
            if self.plan.crash_at == gstep:
                faults_lib.hard_crash()
            if self.plan.hang_at == gstep:
                # Wedge without exit: the heartbeat for this step was
                # already published by the engine (beat runs before
                # this hook), so the supervisor sees a FRESH lease at
                # the hang step that then stops advancing — the exact
                # lease-expiry signature --hang-timeout detects.
                faults_lib.hang()
            if self.plan.corrupt_factor_at == gstep and \
                    state.kfac_state is not None and \
                    self._once('corrupt-factor'):
                # Silent in-memory corruption: an Inf lands in a live
                # Kronecker factor OUTSIDE the jitted step, past the
                # on-device EWMA guard — the r16 quarantine rung's
                # proof fault.
                state.kfac_state = faults_lib.poison_factors(
                    state.kfac_state)
            if self.plan.diverge_at == gstep and self._once('diverge'):
                # Loss-spike injection (finite values): the damping-
                # escalation rung's proof fault.
                state.params = faults_lib.poison_params(state.params)
            if self.plan.corrupt_ckpt_at == gstep and \
                    self._once('corrupt-ckpt'):
                # Bit-rot a FINALIZED bundle: force a blocking save so
                # the step dir exists, then flip a byte in its largest
                # file — the verified resume walk must quarantine it.
                self.save(state, step_in_epoch, blocking=True)
                faults_lib.corrupt_bundle_file(self.mgr.directory,
                                               gstep)
            if self.plan.preempt_at == gstep and \
                    self.preemption is not None:
                self.preemption.trigger('injected preemption')
            if self.plan.resize_at == gstep and \
                    self.preemption is not None:
                # A topology change drains exactly like a preemption
                # (forced blocking save, relaunch exit code); the NEW
                # world size lives in the spec the chaos harness
                # parsed — it relaunches with that many devices and
                # the resumed run reshards through the elastic path.
                self.preemption.trigger(
                    f'injected resize -> {self.plan.resize_to} devices')
            if self.plan.slice_loss_at == gstep and \
                    self.preemption is not None:
                # Whole-slice loss (r20) drains exactly like a
                # preemption too; the chaos harness relaunches onto
                # the surviving slices (shrunken world +
                # KFAC_NUM_SLICES) and the resumed run reshards
                # through the same elastic path as resize.
                self.preemption.trigger(
                    'injected slice loss -> '
                    f'{self.plan.slice_loss_to} survivor slice(s)')
        preempted = (self.preemption is not None
                     and self.preemption.triggered())
        due = self.policy is not None and self.policy.should_save(gstep)
        preempted, due = self._agree(preempted, due)
        if preempted:
            self.save(state, step_in_epoch, blocking=True, forced=True)
            reason = ((self.preemption.reason if self.preemption
                       else None) or 'preempted')
            self._event('preemption', global_step=gstep, reason=reason,
                        grace_remaining_s=round(
                            self.preemption.remaining_grace(), 3)
                        if self.preemption else None)
            raise Preempted(gstep, reason)
        if due:
            self.save(state, step_in_epoch)

    def _once(self, key: str) -> bool:
        """True exactly the first time ``key`` fires this process."""
        if key in self._fired:
            return False
        self._fired.add(key)
        return True

    @staticmethod
    def _agree(preempted: bool, due: bool) -> tuple[bool, bool]:
        """Make the save decision identical on every host.

        ``mgr.save`` is COLLECTIVE, so a decision any host takes alone
        wedges the pod: a SIGTERM can land between different hosts'
        polls (one forces the save at step k, another at k+1), and the
        wall-clock interval can tip over one step apart across hosts
        (``time.monotonic`` is process-relative — clock sync cannot
        fix it). Rank 0 is therefore the single decision authority:
        its (preempted, due) bits are broadcast each step and every
        host acts on those. Pod preemption reaches all workers within
        the same step, so deferring to rank 0's observation costs at
        most one step of grace; a signal that reaches only a non-zero
        rank is the killed-worker case (relaunch loop), not a drain.
        Single-process: the local bits, no collective.
        """
        import jax

        if jax.process_count() == 1:
            return preempted, due
        from jax.experimental import multihost_utils

        bits = (1 if preempted else 0) | (2 if due else 0)
        agreed = int(multihost_utils.broadcast_one_to_all(
            np.int32(bits if jax.process_index() == 0 else 0)))
        return bool(agreed & 1), bool(agreed & 2)

    def poll(self, state, step_in_epoch: int = 0) -> None:
        """Epoch-boundary preemption check (no interval logic): the CLI
        calls this between epochs so a signal that lands during eval or
        checkpointing still drains within one epoch turn."""
        if self.preemption is not None and self.preemption.triggered():
            self.after_step(state, step_in_epoch)

    # -- saving ---------------------------------------------------------

    def save(self, state, step_in_epoch: int, *, blocking: bool = False,
             forced: bool = False) -> None:
        """Save a global-step-indexed bundle (async unless blocking)."""
        blocking = blocking or self.always_block
        gstep = int(state.step)
        t0 = time.perf_counter()
        self.mgr.save(gstep, self.bundle_fn(state, int(step_in_epoch)),
                      force=True)
        if self.plan is not None and self.plan.crash_in_save_at == gstep:
            # Die between the snapshot (save() returned: arrays are
            # captured, the background write is in flight) and the
            # finalize rename — the torn-write window.
            faults_lib.hard_crash()
        if blocking:
            self.mgr.wait_until_finished()
        if self.policy is not None:
            self.policy.note_saved(gstep)
        self._event('checkpoint_save', global_step=gstep,
                    step_in_epoch=int(step_in_epoch),
                    latency_ms=round(
                        (time.perf_counter() - t0) * 1000.0, 3),
                    blocking=bool(blocking), forced=bool(forced))

    def _event(self, name: str, **data) -> None:
        if self.sink is not None:
            self.sink.event_record(name, **data)

    def close(self) -> None:
        self.mgr.close()
