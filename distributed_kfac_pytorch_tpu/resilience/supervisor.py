"""Failure supervision: launch, watch leases, classify, recover.

    python -m distributed_kfac_pytorch_tpu.resilience.supervisor \\
        --workdir ./sup --devices 4 -- \\
        python examples/train_cifar10_resnet.py ...

The chaos harness (``resilience.chaos``) plays supervisor only for the
*cooperative* failure (the child exits the relaunch code after a
graceful drain). This module retires the remaining "die and hope a
human relaunches" classes (ISSUE r17):

  ============  ============================  =========================
  failure       signal                        response
  ============  ============================  =========================
  crash         nonzero exit (not the          relaunch with exponential
                relaunch code)                 backoff, under
                                               ``--max-restarts``
  graceful      exit == RELAUNCH_EXIT_CODE     immediate relaunch (the
  drain         (checkpoint already durable)   checkpoint is fresh; no
                                               backoff, no budget)
  hang          every heartbeat lease stale    ``hang_detected``, kill
                past ``--hang-timeout`` (or    (TERM then KILL), then
                no lease within                relaunch like a crash
                ``--startup-grace``)
  dead worker   a SUBSET of rank leases        ``supervisor_failover``:
                stale past                     kill the wedged rest,
                ``--failover-grace`` while     relaunch on the survivor
                others stay fresh              mesh (shrunken world →
                                               r11 elastic resume)
  lost/         ``--capacity-file`` device     drain via the preemption
  returned      count differs from the         sentinel, relaunch at the
  capacity      running world                  new world
                                               (``supervisor_failover``
                                               on shrink,
                                               ``supervisor_growback``
                                               on grow)
  persistent    one rank slowest on ≥80% of    graceful drain + shrink,
  straggler     recent common steps with       like a dead worker
                mean skew ≥                    (opt-in:
                ``--straggler-skew-ms``        ``--straggler-skew-ms``)
                (r10 rank shards)
  crash loop    the SAME global step failing   ``crash_loop`` event +
                ``--crash-loop-after``         diagnostic bundle + exit
                consecutive relaunches         :data:`CRASH_LOOP_EXIT`
                (poison batch /                (deterministic bugs must
                deterministic bug)             not burn the budget)
  ============  ============================  =========================

Failover is *provably lossless*: checkpoints record their saving world
and ``elastic.reshard`` re-packs K-FAC state onto any mesh (N→M→N
bit-identity is pinned — README "Elastic training"), so shrinking to
survivors and growing back when capacity returns is a permutation, not
a hope. On the CPU backend the world size rides in ``XLA_FLAGS``
(``faults.xla_flags_with_device_count`` — the same knob the chaos
``resize`` fault uses); on a real fleet the resource manager owns
device counts and this supervisor models its relaunch step.

Exit codes (documented in README "Supervision & failover"): the final
child's code when training completes or the supervisor is told to
stop; :data:`EXHAUSTED_EXIT` (76) when the restart budget runs out;
:data:`CRASH_LOOP_EXIT` (77) on crash-loop detection. The relaunch
code itself is ``KFAC_RELAUNCH_EXIT``-configurable (default 75 —
``preemption.RELAUNCH_EXIT_CODE``, shared with the chaos loop).

Supervisor decisions are durable: every event
(``supervisor_restart`` / ``supervisor_failover`` /
``supervisor_growback`` / ``hang_detected`` / ``crash_loop`` — all
registered in ``sink.EVENT_KINDS``) is written to a sidecar JSONL
(default ``<metrics>.supervisor`` next to the child's ``--kfac-metrics``
stream when ``--metrics`` is given, else
``<workdir>/supervisor.<instance>.jsonl``) that
``observability.report`` merges into its supervision section and
``observability.gate`` reads for the ``supervisor_restarts`` metric.

Default artifact paths are namespaced per supervisor *instance* (a
pid-unique token, or ``--instance NAME``): the heartbeat lease
subdirectory, the workdir event stream and the drain sentinel all
carry the token, so several concurrent supervisors — the fleet
scheduler (``distributed_kfac_pytorch_tpu.fleet``) runs one per job —
can share one scratch directory without mixing leases or interleaving
streams (r18 satellite).
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import random
import signal
import subprocess
import sys
import time

from distributed_kfac_pytorch_tpu.resilience import faults as faults_lib
from distributed_kfac_pytorch_tpu.resilience import (
    heartbeat as hb_lib,
)
from distributed_kfac_pytorch_tpu.resilience.preemption import (
    RELAUNCH_EXIT_CODE,
)

#: Restart budget exhausted: the job keeps dying and the supervisor is
#: out of relaunches — a human (or a higher-level scheduler) must look.
#: 76 collides with sysexits EX_PROTOCOL; see MIGRATION.md.
EXHAUSTED_EXIT = 76
#: Crash-loop detected: the SAME global step failed --crash-loop-after
#: consecutive relaunches — relaunching again cannot help (poison
#: batch, deterministic bug). 77 collides with sysexits EX_NOPERM; see
#: MIGRATION.md. A diagnostic bundle is written next to the leases.
CRASH_LOOP_EXIT = 77

DIAGNOSTIC_NAME = 'crash_loop_diagnostic.json'

#: Per-process supervisor counter: combined with the pid it tokens the
#: default artifact namespace (heartbeat subdirectory, event-stream
#: and drain-sentinel names) so concurrent supervisors — separate
#: processes OR several in one fleet process — sharing a scratch
#: workdir cannot collide.
_INSTANCES = itertools.count(1)


class RestartBackoff:
    """Exponential relaunch backoff with a cap and decorrelation jitter.

    ``next_delay()`` returns 0, then the exponential schedule
    base, base*factor, ... capped at ``cap`` (the first restart after a
    healthy stretch is free — the checkpoint is fresh and most faults
    are transient); ``reset()`` re-arms after progress.

    Each nonzero delay is drawn uniformly from
    ``[d*(1-jitter), d]`` (``d`` = the deterministic schedule value):
    a pool-wide fault that kills many supervised jobs at once would
    otherwise relaunch them all on the SAME schedule and thundering-
    herd the pool every base*factor^n seconds forever (r18 satellite).
    ``jitter=0`` restores the deterministic schedule; ``seed`` makes
    the draw reproducible for tests (and lets a fleet give every job
    its own decorrelated stream).
    """

    def __init__(self, base: float = 1.0, factor: float = 2.0,
                 cap: float = 60.0, jitter: float = 0.5,
                 seed: int | None = None):
        if base < 0 or factor < 1.0 or cap < 0:
            raise ValueError(
                f'bad backoff ({base=}, {factor=}, {cap=}): need '
                'base >= 0, factor >= 1, cap >= 0')
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f'backoff jitter must be in [0, 1], '
                             f'got {jitter}')
        self.base = float(base)
        self.factor = float(factor)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        self._failures = 0

    def next_delay(self) -> float:
        n = self._failures
        self._failures += 1
        if n == 0:
            return 0.0
        d = min(self.cap, self.base * self.factor ** (n - 1))
        if self.jitter and d > 0:
            d *= 1.0 - self.jitter * self._rng.random()
        return d

    def reset(self) -> None:
        self._failures = 0


class CapacityFile:
    """Torn-read-tolerant poll of a resource manager's capacity file.

    The file is a plain overwrite (not an atomic rename), so a poll
    can catch it mid-write: empty, truncated, or non-integer.
    ``read()`` returns ``(value, error)`` — ``value`` is the newest
    good integer, or the LAST known one while degraded (None before
    any good read, and while the file simply does not exist yet);
    ``error`` is the exception string exactly ONCE at the start of
    each degradation episode (the caller emits one
    ``capacity_degraded`` event per episode, never per poll). Shared
    by the supervisor's per-job channel and the fleet scheduler's
    pool view so the degradation protocol cannot fork (r18).
    """

    def __init__(self, path: str):
        self.path = path
        self.last: int | None = None
        self._degraded = False

    def read(self) -> tuple[int | None, str | None]:
        try:
            with open(self.path) as f:
                value = int(f.read().strip())
        except FileNotFoundError:
            # Absence is not degradation: capacity tracking may not
            # have started yet (and must not trigger a resize).
            return self.last, None
        except (OSError, ValueError) as e:
            if self._degraded:
                return self.last, None
            self._degraded = True
            return self.last, str(e)
        self._degraded = False
        self.last = value
        return value, None


class CrashLoopDetector:
    """Consecutive-failures-at-the-same-step counter.

    ``observe(step)`` records one failure with the global step training
    had reached (from the newest lease; None when it died before any
    heartbeat — repeated None IS a loop: failing before the first step
    every time). Returns True when the same step has now failed
    ``after`` consecutive times. Any progress — a failure at a LATER
    step — resets the count to 1 (pinned by tests/test_supervisor.py):
    the job is moving, however painfully, and the budget is the right
    limiter for that.
    """

    def __init__(self, after: int = 3):
        if after < 1:
            raise ValueError(f'crash-loop threshold must be >= 1, '
                             f'got {after}')
        self.after = int(after)
        self._step: int | None = None
        self._count = 0

    @property
    def count(self) -> int:
        return self._count

    @property
    def step(self) -> int | None:
        return self._step

    def observe(self, step: int | None) -> bool:
        if self._count and step == self._step:
            self._count += 1
        else:
            self._step = step
            self._count = 1
        return self._count >= self.after

    def reset(self) -> None:
        self._step = None
        self._count = 0


def classify_stragglers(shards: dict[int, list[dict]], *,
                        skew_ms: float, min_steps: int = 8,
                        frac: float = 0.8
                        ) -> tuple[int, float] | None:
    """Persistent-straggler verdict over the r10 rank shards.

    A rank is a *persistent* straggler when, over the newest
    ``min_steps`` steps common to every LIVE shard, it is the slowest
    rank on at least ``frac`` of them AND the mean (slowest - fastest)
    dispatch skew on those steps is ``>= skew_ms``. One slow step is
    jitter; the supervisor only acts on sustained, attributable skew.
    Returns ``(rank, mean_skew_ms)`` or None.

    Shards whose newest recorded step trails the freshest shard by
    more than a sink-flush-sized margin are FROZEN — a rank removed by
    an earlier failover shrink, whose file stays on disk forever.
    They are dropped before the common-step intersection: keeping them
    would pin the intersection to the pre-shrink era and permanently
    blind the classifier for the rest of the session.
    """
    if len(shards) < 2 or skew_ms <= 0:
        return None
    per_rank: dict[int, dict[int, float]] = {}
    for rank, records in shards.items():
        steps = {r['step']: float(r['host_step_ms'])
                 for r in records
                 if r.get('kind') == 'step' and 'host_step_ms' in r}
        if steps:
            per_rank[rank] = steps
    if len(per_rank) < 2:
        return None
    head = max(max(m) for m in per_rank.values())
    # Live shards can trail by up to one flush window (drain_every=64
    # records) plus the comparison window itself; anything further
    # behind is a dead rank's frozen file.
    stale_before = head - (64 + 8 * min_steps)
    per_rank = {r: m for r, m in per_rank.items()
                if max(m) >= stale_before}
    if len(per_rank) < 2:
        return None
    common = set.intersection(*(set(m) for m in per_rank.values()))
    if len(common) < min_steps:
        return None
    window = sorted(common)[-min_steps:]
    slowest_counts: dict[int, int] = {}
    skews: dict[int, list[float]] = {}
    for step in window:
        times = {rank: per_rank[rank][step] for rank in per_rank}
        slowest = max(times, key=times.get)
        slowest_counts[slowest] = slowest_counts.get(slowest, 0) + 1
        skews.setdefault(slowest, []).append(
            times[slowest] - min(times.values()))
    rank, hits = max(slowest_counts.items(), key=lambda kv: kv[1])
    if hits < frac * len(window):
        return None
    mean_skew = sum(skews[rank]) / len(skews[rank])
    if mean_skew < skew_ms:
        return None
    return rank, mean_skew


class Supervisor:
    """One supervised training command: launch, watch, classify, recover.

    All timing knobs are in seconds; ``clock``/``sleep`` are injectable
    for the unit matrix. See the module docstring for the failure
    table and :func:`main` for the CLI surface.
    """

    def __init__(self, cmd: list[str], *, workdir: str,
                 instance: str | None = None,
                 heartbeat_dir: str | None = None,
                 events_path: str | None = None,
                 metrics_path: str | None = None,
                 extra_env: dict | None = None,
                 devices: int | None = None,
                 start_devices: int | None = None,
                 min_devices: int = 1,
                 slices: int = 1,
                 capacity_file: str | None = None,
                 hang_timeout: float = 300.0,
                 startup_grace: float = 900.0,
                 failover_grace: float = 0.0,
                 straggler_skew_ms: float = 0.0,
                 max_restarts: int = 5,
                 crash_loop_after: int = 3,
                 backoff: RestartBackoff | None = None,
                 poll_secs: float = 0.5,
                 drain_grace: float = 300.0,
                 term_grace: float = 10.0,
                 keep_faults: bool = False,
                 clock=time.time, sleep=time.sleep):
        if not cmd:
            raise ValueError('supervisor: no command to supervise')
        if hang_timeout <= 0:
            raise ValueError('--hang-timeout must be > 0 (hang '
                             'detection is the point of the leases)')
        if RELAUNCH_EXIT_CODE in (EXHAUSTED_EXIT, CRASH_LOOP_EXIT):
            raise ValueError(
                f'KFAC_RELAUNCH_EXIT={RELAUNCH_EXIT_CODE} collides '
                f'with a supervisor verdict code (budget-exhausted '
                f'{EXHAUSTED_EXIT} / crash-loop {CRASH_LOOP_EXIT}) — '
                'the exit statuses would be ambiguous')
        if devices is not None and not min_devices <= devices:
            raise ValueError(f'{devices=} below {min_devices=}')
        if slices < 1:
            raise ValueError(f'{slices=} must be >= 1')
        self.cmd = list(cmd)
        self.workdir = os.path.abspath(workdir)
        # Per-launch artifact namespace (r18 satellite): two concurrent
        # supervisors pointed at ONE scratch workdir (a fleet packing
        # several jobs onto a shared filesystem) must not mix heartbeat
        # leases — each other's ranks would read as a dead subset — or
        # clobber each other's event stream (the sink's atomic rewrite
        # is last-writer-wins on a shared path). Defaults therefore
        # land under a unique per-supervisor token; explicit
        # --heartbeat-dir / --events / --metrics paths are honored
        # verbatim (the <metrics>.supervisor sidecar convention the
        # report/gate readers rely on is unchanged).
        self.instance = (str(instance) if instance
                         else f'{os.getpid()}.{next(_INSTANCES)}')
        self.heartbeat_dir = (os.path.abspath(heartbeat_dir)
                              if heartbeat_dir
                              else os.path.join(self.workdir,
                                                'heartbeats',
                                                self.instance))
        from distributed_kfac_pytorch_tpu.observability.sink import (
            SUPERVISOR_SIDECAR_SUFFIX,
        )
        self.metrics_path = metrics_path
        if events_path is None:
            events_path = (metrics_path + SUPERVISOR_SIDECAR_SUFFIX
                           if metrics_path
                           else os.path.join(
                               self.workdir,
                               f'supervisor.{self.instance}.jsonl'))
        self.events_path = events_path
        self.extra_env = dict(extra_env or {})
        self.sentinel = os.path.join(
            self.workdir, f'drain.{self.instance}.sentinel')
        self.devices = devices
        self.world = (start_devices if start_devices is not None
                      else devices)
        self.min_devices = int(min_devices)
        # Live slice count (r20 multi-slice): the slice-failure
        # classifier keys rank groups off it, the child env exports it
        # (KFAC_NUM_SLICES -> the CLIs' --num-slices default), and a
        # committed slice failover decrements it.
        self.slices = int(slices)
        self.capacity_file = capacity_file
        self.hang_timeout = float(hang_timeout)
        self.startup_grace = float(startup_grace)
        self.failover_grace = float(failover_grace)
        self.straggler_skew_ms = float(straggler_skew_ms)
        self.max_restarts = int(max_restarts)
        self.crash_loop = CrashLoopDetector(crash_loop_after)
        self.backoff = backoff or RestartBackoff()
        self.poll_secs = float(poll_secs)
        self.drain_grace = float(drain_grace)
        self.term_grace = float(term_grace)
        self.keep_faults = bool(keep_faults)
        self._clock = clock
        self._sleep = sleep
        self.launches = 0
        self.restarts = 0          # failure-driven (budgeted)
        self.history: list[dict] = []
        self._stop: str | None = None
        self._straggler_handled: set[int] = set()
        self._next_straggler_check = 0.0
        self._capacity = (CapacityFile(capacity_file)
                          if capacity_file else None)
        os.makedirs(self.workdir, exist_ok=True)
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        from distributed_kfac_pytorch_tpu.observability.sink import (
            JsonlMetricsSink,
        )
        self.events = JsonlMetricsSink(
            events_path, process_index=0,
            meta={'supervisor': True, 'cmd': ' '.join(self.cmd),
                  'devices': devices, 'start_devices': self.world,
                  'max_restarts': max_restarts,
                  'hang_timeout_s': self.hang_timeout,
                  'relaunch_exit': RELAUNCH_EXIT_CODE})

    # -- event plumbing -------------------------------------------------

    def _event(self, name: str, **data) -> None:
        self.events.event_record(name, **data)
        detail = ' '.join(f'{k}={v}' for k, v in sorted(data.items()))
        print(f'supervisor: {name} {detail}', file=sys.stderr,
              flush=True)

    # -- child lifecycle ------------------------------------------------

    def _child_env(self) -> dict:
        env = dict(os.environ)
        # Per-job overrides (the fleet's KFAC_CHAOS / tuned paths ride
        # here): merged BEFORE the one-shot fault clearing below so an
        # injected fault spec obeys the same relaunch discipline.
        env.update({str(k): str(v)
                    for k, v in self.extra_env.items()})
        env[hb_lib.ENV_DIR] = self.heartbeat_dir
        env[hb_lib.ENV_INCARNATION] = str(self.launches)
        env['KFAC_PREEMPT_FILE'] = self.sentinel
        if self.world is not None:
            env['XLA_FLAGS'] = faults_lib.xla_flags_with_device_count(
                env.get('XLA_FLAGS', ''), self.world)
        if self.slices > 1:
            # The CLIs' --num-slices defaults from this, so a slice
            # failover's shrunken slice count propagates to the
            # relaunched child without editing its argv.
            env['KFAC_NUM_SLICES'] = str(self.slices)
        if self.launches > 0 and not self.keep_faults:
            # Faults are one-shot, exactly like the chaos harness: a
            # relaunch must not re-trip the injected failure (pass
            # --keep-faults to re-inject — the crash-loop legs do).
            env.pop(faults_lib.ENV_VAR, None)
        return env

    def _launch(self) -> subprocess.Popen:
        try:
            os.unlink(self.sentinel)
        except FileNotFoundError:
            pass
        hb_lib.clear_leases(self.heartbeat_dir)
        env = self._child_env()
        self.launches += 1
        print(f'supervisor: launch {self.launches} '
              f'(world={self.world if self.world is not None else "-"})'
              f': {" ".join(self.cmd)}', file=sys.stderr, flush=True)
        return subprocess.Popen(self.cmd, env=env)

    def _kill(self, proc: subprocess.Popen) -> None:
        """TERM, grace, KILL — the hang/dead-rank escalation (a wedged
        process may have a preemption handler that eats the first
        TERM, which is fine: the KILL is the backstop)."""
        if proc.poll() is not None:
            return
        proc.terminate()
        deadline = self._clock() + self.term_grace
        while proc.poll() is None and self._clock() < deadline:
            self._sleep(min(0.1, self.poll_secs))
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    def _drain(self, proc: subprocess.Popen) -> int:
        """Graceful drain: touch the preemption sentinel, wait for the
        child to save-and-exit (it polls once per step, so the wait
        budget must cover a full step INCLUDING a possible compile),
        escalate to kill past ``drain_grace``. Returns the exit code."""
        with open(self.sentinel, 'w') as f:
            f.write('supervisor drain\n')
        deadline = self._clock() + self.drain_grace
        while proc.poll() is None and self._clock() < deadline:
            self._sleep(self.poll_secs)
        if proc.poll() is None:
            self._kill(proc)
        return proc.returncode

    # -- watching -------------------------------------------------------

    def _capacity_target(self) -> int | None:
        """The world size the capacity file currently allows (clamped
        to [min_devices, devices]), or None when capacity tracking is
        off / the file has never been readable.

        A torn/empty/non-integer read keeps the LAST known target and
        emits exactly one ``capacity_degraded`` warning event per
        degradation episode (:class:`CapacityFile`): a momentarily
        unreadable resource view must neither crash the supervision
        loop nor trigger a spurious resize (r18 satellite;
        regression-pinned with a mid-write truncated file)."""
        if self._capacity is None or self.devices is None:
            return None
        cap, error = self._capacity.read()
        if error is not None:
            self._event('capacity_degraded', path=self.capacity_file,
                        error=error, last_target=cap)
        if cap is None:
            return None
        return max(self.min_devices, min(self.devices, cap))

    def _check_stragglers(self) -> tuple[int, float] | None:
        if self.straggler_skew_ms <= 0 or not self.metrics_path:
            return None
        # Throttled well below the lease poll: merge_shards re-reads
        # every rank shard in FULL (rotated segments included), an
        # O(stream length) parse — at the 0.5 s poll cadence a long
        # run would spend its supervisor re-parsing megabytes per
        # second to re-derive a verdict about the newest ~8 steps.
        # Persistence is the point of the classifier anyway; a
        # 10-second-class look rate loses nothing.
        now = self._clock()
        if now < self._next_straggler_check:
            return None
        self._next_straggler_check = now + max(10.0,
                                               20.0 * self.poll_secs)
        from distributed_kfac_pytorch_tpu.observability import (
            stragglers as straggler_mod,
        )
        try:
            shards, _torn, _errors = straggler_mod.merge_shards(
                self.metrics_path)
        except (OSError, ValueError):
            return None
        verdict = classify_stragglers(
            shards, skew_ms=self.straggler_skew_ms)
        if verdict is not None and verdict[0] in self._straggler_handled:
            return None
        return verdict

    def _watch(self, proc: subprocess.Popen, launch_time: float):
        """Block until something needs a decision. Returns one of
        ``('exit', rc)`` / ``('hang', detail)`` /
        ``('dead_rank', dead, live)`` / ``('resize', target)`` /
        ``('straggler', rank, skew_ms)`` / ``('stop', reason)`` —
        the child is still running for every kind except 'exit'."""
        while True:
            rc = proc.poll()
            if rc is not None:
                return ('exit', rc)
            if self._stop is not None:
                return ('stop', self._stop)
            now = self._clock()
            # Incarnation-filtered: a lease left behind by an earlier
            # incarnation (or a quarantined job that shared the dir)
            # is that run's last words, not a live rank — counting it
            # here would fire an instant false hang/dead-rank verdict
            # on its stale timestamp.
            leases, _errors = hb_lib.scan_leases(
                self.heartbeat_dir, incarnation=self.launches - 1)
            if leases:
                ages = {r: hb_lib.lease_age(lease, now)
                        for r, lease in leases.items()}
                if min(ages.values()) > self.hang_timeout:
                    return ('hang',
                            {'newest_age_s': round(min(ages.values()), 3),
                             'ranks': sorted(leases)})
                if self.failover_grace > 0 and len(ages) > 1:
                    dead = sorted(r for r, a in ages.items()
                                  if a > self.failover_grace)
                    live = sorted(r for r, a in ages.items()
                                  if a <= self.failover_grace)
                    if dead and live:
                        return ('dead_rank', dead, live)
            elif now - launch_time > self.startup_grace:
                return ('hang', {'newest_age_s': None,
                                 'ranks': [],
                                 'detail': 'no heartbeat lease within '
                                           'the startup grace'})
            target = self._capacity_target()
            if target is not None and self.world is not None \
                    and target != self.world:
                return ('resize', target)
            straggler = self._check_stragglers()
            if straggler is not None:
                return ('straggler', straggler[0],
                        round(straggler[1], 3))
            self._sleep(self.poll_secs)

    # -- failure bookkeeping --------------------------------------------

    def _last_step(self) -> int | None:
        """The newest global step any rank's lease recorded — the
        incarnation's last words, read BEFORE the next launch clears
        the lease dir. The crash-loop detector keys on it."""
        leases, _ = hb_lib.scan_leases(self.heartbeat_dir)
        if not leases:
            return None
        return max(int(lease.get('step', 0))
                   for lease in leases.values())

    def _note(self, outcome: str, rc, last_step,
              launch_time: float) -> None:
        self.history.append({
            'launch': self.launches, 'outcome': outcome,
            'rc': rc, 'last_step': last_step,
            'world': self.world,
            'duration_s': round(self._clock() - launch_time, 3)})

    def _budgeted_restart(self, reason: str, *, last_step,
                          rc=None, **extra) -> int | None:
        """One failure-driven relaunch: crash-loop check, budget check,
        backoff. Returns an exit code to stop with, or None to
        relaunch."""
        looping = self.crash_loop.observe(last_step)
        if looping:
            diag = self._write_diagnostic(last_step)
            self._event('crash_loop', failure_step=last_step,
                        consecutive=self.crash_loop.count,
                        reason=reason, diagnostic=diag)
            print(f'supervisor: crash loop — global step {last_step} '
                  f'failed {self.crash_loop.count} consecutive '
                  f'launches; relaunching cannot help. Diagnostic '
                  f'bundle: {diag}', file=sys.stderr, flush=True)
            return CRASH_LOOP_EXIT
        self.restarts += 1
        if self.restarts > self.max_restarts:
            print(f'supervisor: restart budget exhausted '
                  f'({self.max_restarts}) — giving up with exit '
                  f'{EXHAUSTED_EXIT}', file=sys.stderr, flush=True)
            return EXHAUSTED_EXIT
        delay = self.backoff.next_delay()
        self._event('supervisor_restart', reason=reason, rc=rc,
                    restart=self.restarts, budget=self.max_restarts,
                    backoff_s=round(delay, 3), last_step=last_step,
                    **extra)
        if delay > 0:
            self._sleep(delay)
        return None

    def _write_diagnostic(self, last_step) -> str:
        """The crash-loop post-mortem bundle: launch history, last
        leases, the fault spec — everything a human needs before
        touching the budget again."""
        leases, lease_errors = hb_lib.scan_leases(self.heartbeat_dir)
        path = os.path.join(self.workdir, DIAGNOSTIC_NAME)
        with open(path, 'w') as f:
            json.dump({
                'failure_step': last_step,
                'consecutive_failures': self.crash_loop.count,
                'cmd': self.cmd,
                'world': self.world,
                'chaos_spec': os.environ.get(faults_lib.ENV_VAR),
                'history': self.history[-20:],
                'leases': {str(r): lease
                           for r, lease in leases.items()},
                'lease_errors': lease_errors,
            }, f, indent=1, sort_keys=True)
            f.write('\n')
        return path

    def _resize(self, target: int, reason: str, **extra) -> None:
        """Commit a world change and emit the matching event (shrink =
        failover, grow = grow-back). The relaunch itself resumes
        through the r11 elastic path — lossless by the pinned N→M→N
        bit-identity."""
        name = ('supervisor_growback' if target > (self.world or 0)
                else 'supervisor_failover')
        self._event(name, reason=reason, from_devices=self.world,
                    to_devices=target, **extra)
        self.world = target
        # Rank indices renumber on the resized relaunch: a handled
        # straggler's old index may now name a healthy survivor, so
        # the suppression latch must not outlive the topology.
        self._straggler_handled.clear()

    # -- the loop -------------------------------------------------------

    def _install_signals(self) -> None:
        def handler(signum, frame):
            self._stop = f'signal {signal.Signals(signum).name}'

        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, handler)

    def run(self, install_signals: bool = True) -> int:
        """Supervise until the command succeeds, the budget runs out,
        a crash loop is detected, or the supervisor is told to stop.
        Returns the process exit code.

        ``install_signals=False`` skips the SIGTERM/SIGINT handlers —
        required when the supervisor runs off the main thread (the
        fleet scheduler runs one per job); the embedding process owns
        the signals and requests a stop by setting ``request_stop``.
        """
        if install_signals:
            self._install_signals()
        try:
            return self._run()
        finally:
            self.events.close()

    def request_stop(self, reason: str = 'stop requested') -> None:
        """Ask the supervision loop to drain the child and return
        (thread-safe: the watcher polls the flag every ``poll_secs``).
        The fleet's preempt-to-queue and shutdown paths use this."""
        self._stop = str(reason)

    def _run(self) -> int:
        while True:
            proc = self._launch()
            launch_time = self._clock()
            kind, *info = self._watch(proc, launch_time)
            last_step = self._last_step()
            if kind == 'exit':
                rc = info[0]
                self._note('exit', rc, last_step, launch_time)
                if rc == 0:
                    return 0
                if rc == RELAUNCH_EXIT_CODE:
                    # Cooperative drain: checkpoint durable, no budget.
                    # A capacity change lands at this boundary (the
                    # drain may even have been OUR sentinel).
                    self.crash_loop.reset()
                    self.backoff.reset()
                    target = self._capacity_target()
                    if target is not None and self.world is not None \
                            and target != self.world:
                        self._resize(target, 'capacity')
                    else:
                        self._event('supervisor_restart',
                                    reason='drain', rc=rc,
                                    restart=self.restarts,
                                    budget=self.max_restarts,
                                    backoff_s=0.0,
                                    last_step=last_step)
                    continue
                stop = self._budgeted_restart('crash', rc=rc,
                                              last_step=last_step)
                if stop is not None:
                    return stop
                continue
            if kind == 'stop':
                print(f'supervisor: {info[0]} — draining the child and '
                      'stopping', file=sys.stderr, flush=True)
                rc = self._drain(proc)
                self._note('stop', rc, self._last_step(), launch_time)
                if rc is None:
                    return 1
                # A drain that escalated to kill leaves a NEGATIVE
                # returncode (-signum); propagating it through
                # sys.exit would wrap mod 256 into an undocumented
                # status — report it the shell way (128 + signum).
                return 128 - rc if rc < 0 else rc
            if kind == 'hang':
                self._event('hang_detected', last_step=last_step,
                            **info[0])
                self._kill(proc)
                self._note('hang', proc.returncode, last_step,
                           launch_time)
                stop = self._budgeted_restart('hang', rc=proc.returncode,
                                              last_step=last_step)
                if stop is not None:
                    return stop
                continue
            if kind == 'dead_rank':
                dead, live = info
                # The survivors are wedged on collectives with the dead
                # rank — no graceful drain is possible; kill and resume
                # the whole job from the last durable checkpoint on the
                # survivor mesh.
                self._kill(proc)
                self._note('dead_rank', proc.returncode, last_step,
                           launch_time)
                n = len(dead) + len(live)
                # Slice-failure classification (r20): ALL ranks of
                # exactly one slice stale while every other slice's
                # ranks are live = that slice's ICI domain died (power
                # / DCN partition), not a sick host — fail over to the
                # survivor slices and shrink the slice count so the
                # relaunched child builds an (S-1)-slice mesh.
                slice_idx = None
                if self.slices > 1 and n % self.slices == 0:
                    from distributed_kfac_pytorch_tpu.multislice.mesh \
                        import slice_rank_groups
                    for i, group in enumerate(
                            slice_rank_groups(n, self.slices)):
                        if list(group) == dead:
                            slice_idx = i
                            break
                reason = ('slice_failure' if slice_idx is not None
                          else 'dead_rank')
                target = self.world
                if self.world is not None:
                    target = max(self.min_devices,
                                 self.world * len(live) // n)
                if target == self.world:
                    # No survivor mesh to shrink onto (launcher owns
                    # the topology, or already at --min-devices): the
                    # relaunch is a plain failure-recovery attempt and
                    # MUST stay bounded — a host that keeps wedging
                    # would otherwise drive an infinite free
                    # kill/relaunch loop outside the budget and the
                    # crash-loop detector.
                    stop = self._budgeted_restart(
                        reason, rc=proc.returncode,
                        last_step=last_step,
                        dead_ranks=','.join(map(str, dead)))
                    if stop is not None:
                        return stop
                    continue
                extra = ({'slice': slice_idx}
                         if slice_idx is not None else {})
                self._event('supervisor_failover', reason=reason,
                            dead_ranks=','.join(map(str, dead)),
                            live_ranks=','.join(map(str, live)),
                            from_devices=self.world, to_devices=target,
                            **extra)
                self.world = target
                if slice_idx is not None:
                    # Commit the shrink AFTER the event so the trail
                    # records the pre-failover slice count.
                    self.slices -= 1
                self._straggler_handled.clear()  # ranks renumber
                self.crash_loop.reset()
                continue
            if kind == 'resize':
                target = info[0]
                rc = self._drain(proc)
                self._note('resize', rc, self._last_step(), launch_time)
                self._resize(target, 'capacity')
                self.crash_loop.reset()
                continue
            if kind == 'straggler':
                rank, skew = info
                self._straggler_handled.add(rank)
                rc = self._drain(proc)
                self._note('straggler', rc, self._last_step(),
                           launch_time)
                target = self.world
                if self.world is not None:
                    leases, _ = hb_lib.scan_leases(self.heartbeat_dir)
                    n = max(2, len(leases))
                    target = max(self.min_devices,
                                 self.world * (n - 1) // n)
                self._event('supervisor_failover', reason='straggler',
                            rank=rank, mean_skew_ms=skew,
                            from_devices=self.world, to_devices=target)
                self.world = target
                continue
            raise AssertionError(f'unhandled watch outcome {kind!r}')


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog='python -m distributed_kfac_pytorch_tpu.resilience'
             '.supervisor',
        description='Launch a training command under failure '
                    'supervision: heartbeat-lease hang detection, '
                    'crash relaunch with backoff, survivor-mesh '
                    'failover and grow-back, crash-loop escalation. '
                    f'Exit: the final child code, {EXHAUSTED_EXIT} = '
                    f'restart budget exhausted, {CRASH_LOOP_EXIT} = '
                    'crash loop detected.')
    p.add_argument('--workdir', default='./supervisor',
                   help='supervisor state dir (heartbeat leases, drain '
                        'sentinel, event stream, crash-loop diagnostic)')
    p.add_argument('--instance', default=None, metavar='NAME',
                   help='artifact namespace token for the default '
                        'heartbeat subdirectory / event stream / drain '
                        'sentinel (default: a pid-unique token, so '
                        'concurrent supervisors sharing a workdir '
                        'cannot mix leases or clobber streams; set a '
                        'stable name for predictable paths)')
    p.add_argument('--heartbeat-dir', default=None,
                   help='lease directory (default <workdir>/heartbeats/'
                        '<instance>; exported to the child as '
                        'KFAC_HEARTBEAT_DIR)')
    p.add_argument('--events', default=None, metavar='PATH',
                   help='supervisor event JSONL (default '
                        '<metrics>.supervisor when --metrics is given, '
                        'else <workdir>/supervisor.<instance>.jsonl)')
    p.add_argument('--metrics', default=None, metavar='PATH',
                   help="the child's --kfac-metrics path: names the "
                        'event sidecar the report/gate merge, and '
                        'locates the rank shards the straggler '
                        'classifier reads')
    p.add_argument('--hang-timeout', type=float, default=300.0,
                   metavar='S',
                   help='every lease stale past S seconds = hang: '
                        'kill and relaunch. Budget ABOVE the worst '
                        'step + eval + checkpoint gap (leases are only '
                        'written from the train loop)')
    p.add_argument('--startup-grace', type=float, default=900.0,
                   metavar='S',
                   help='hang budget before the FIRST lease of an '
                        'incarnation (model build + compile happen '
                        'before any step runs)')
    p.add_argument('--failover-grace', type=float, default=0.0,
                   metavar='S',
                   help='a SUBSET of ranks stale past S seconds while '
                        'others stay fresh = dead worker: kill and '
                        'relaunch on the survivor mesh (0 = lease '
                        'failover off; needs >= 2 heartbeating ranks)')
    p.add_argument('--straggler-skew-ms', type=float, default=0.0,
                   help='treat a rank as a persistent straggler (drain '
                        '+ shrink) when it is slowest on >= 80%% of '
                        'recent common steps with mean skew above this '
                        '(reads the r10 rank shards next to --metrics; '
                        '0 = off)')
    p.add_argument('--max-restarts', type=int, default=5, metavar='N',
                   help='failure-driven (crash/hang) relaunch budget; '
                        f'past it exit {EXHAUSTED_EXIT}. Graceful '
                        'drains (preemption/resize) are free')
    p.add_argument('--crash-loop-after', type=int, default=3,
                   metavar='K',
                   help='the same global step failing K consecutive '
                        'relaunches = crash loop: write a diagnostic '
                        f'bundle and exit {CRASH_LOOP_EXIT} instead of '
                        'burning the budget')
    p.add_argument('--backoff', type=float, default=1.0, metavar='S',
                   help='exponential backoff base for crash/hang '
                        'relaunches (0, S, 2S, 4S, ... capped)')
    p.add_argument('--backoff-cap', type=float, default=60.0,
                   metavar='S')
    p.add_argument('--backoff-jitter', type=float, default=0.5,
                   metavar='F',
                   help='decorrelation jitter fraction in [0, 1]: each '
                        'nonzero delay is drawn uniformly from '
                        '[d*(1-F), d] so many jobs relaunching after a '
                        'pool-wide fault do not thundering-herd on the '
                        'same schedule (0 = deterministic)')
    p.add_argument('--poll', type=float, default=0.5, metavar='S',
                   help='lease/capacity poll interval')
    p.add_argument('--drain-grace', type=float, default=300.0,
                   metavar='S',
                   help='wait budget for a sentinel-requested graceful '
                        'drain before escalating to kill (the child '
                        'polls once per STEP — cover a compile)')
    p.add_argument('--term-grace', type=float, default=10.0,
                   metavar='S',
                   help='SIGTERM-to-SIGKILL escalation window')
    p.add_argument('--devices', type=int, default=None, metavar='N',
                   help='full/target world size, managed via the '
                        'XLA_FLAGS host-platform device count (the '
                        'CPU-backend model of re-provisioning; leave '
                        'unset when the launcher owns the topology)')
    p.add_argument('--start-devices', type=int, default=None,
                   metavar='M',
                   help='initial world size when resuming a previously '
                        'shrunken job (default: --devices); with '
                        'capacity at N the first relaunch grows back')
    p.add_argument('--min-devices', type=int, default=1, metavar='M',
                   help='never shrink below this world size')
    p.add_argument('--slices', type=int, default=1, metavar='S',
                   help='multi-slice job (r20): the child trains an '
                        'S-slice mesh (KFAC_NUM_SLICES is exported so '
                        '--num-slices follows). With --failover-grace, '
                        'all-ranks-of-one-slice-stale classifies as a '
                        'slice failure: fail over to the survivor '
                        'slices and relaunch with S-1')
    p.add_argument('--capacity-file', default=None, metavar='PATH',
                   help='file holding the currently-available device '
                        'count (the resource manager\'s live view); '
                        'polled — a drop below the running world '
                        'drains and relaunches shrunken '
                        '(supervisor_failover), a recovery grows back '
                        '(supervisor_growback)')
    p.add_argument('--keep-faults', action='store_true',
                   help='re-inject KFAC_CHAOS on every relaunch '
                        '(default: faults fire on the first launch '
                        'only, like the chaos harness)')
    if argv is None:
        argv = sys.argv[1:]
    cmd: list[str] = []
    if '--' in argv:
        split = argv.index('--')
        argv, cmd = argv[:split], argv[split + 1:]
    args = p.parse_args(argv)
    if not cmd:
        p.error('no command given (append: -- python examples/...)')
    sup = Supervisor(
        cmd, workdir=args.workdir, instance=args.instance,
        heartbeat_dir=args.heartbeat_dir,
        events_path=args.events, metrics_path=args.metrics,
        devices=args.devices, start_devices=args.start_devices,
        min_devices=args.min_devices, slices=args.slices,
        capacity_file=args.capacity_file,
        hang_timeout=args.hang_timeout,
        startup_grace=args.startup_grace,
        failover_grace=args.failover_grace,
        straggler_skew_ms=args.straggler_skew_ms,
        max_restarts=args.max_restarts,
        crash_loop_after=args.crash_loop_after,
        backoff=RestartBackoff(base=args.backoff,
                               cap=args.backoff_cap,
                               jitter=args.backoff_jitter),
        poll_secs=args.poll, drain_grace=args.drain_grace,
        term_grace=args.term_grace, keep_faults=args.keep_faults)
    return sup.run()


if __name__ == '__main__':
    sys.exit(main())
