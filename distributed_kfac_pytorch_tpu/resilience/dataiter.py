"""Data-stream state capture for deterministic mid-epoch resume.

The training pipelines in ``training.datasets`` are seeded and
epoch-indexed: ``epoch_batches(seed=s, epoch=e)`` derives its shuffle
permutation and augmentation RNG from ``SeedSequence([s, e])``, and
``bptt_batches`` draws its window offset once per epoch from
``SeedSequence([s, e])``. The full stream position is therefore three
integers — ``(seed, epoch, step_in_epoch)`` — and resuming is
*replay*: rebuild the epoch-``e`` iterator and skip the first ``k``
batches while consuming exactly the RNG draws the skipped batches
would have consumed (``skip_batches=`` in the dataset helpers;
``consume_augment_rng`` keeps the augmentation stream aligned). The
resumed run then yields bit-identical batches to the uninterrupted one
— pinned by tests/test_resilience.py and the kill-and-resume smoke.

:class:`DataStreamState` is the checkpoint-bundle representation:
plain int scalars (``data_seed`` / ``epoch`` / ``step_in_epoch`` in
``bundle_state(**scalars)``) so orbax round-trips them untouched.

Limits: the replay guarantee covers the numpy pipelines (CIFAR,
synthetic ImageNet, the LM corpus). The real-data ``tf.data`` ImageNet
path reshuffles per *iterator creation*, not per epoch index, so a
relaunch sees a different order there — resume still restores model
state exactly but the remaining-batch sequence is best-effort
(``train_imagenet_resnet`` skips at batch granularity via
``Dataset.skip``).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class DataStreamState:
    """Position of a seeded training stream (see module docstring)."""
    seed: int
    epoch: int
    step_in_epoch: int

    def scalars(self) -> dict:
        """The checkpoint-bundle scalar fields for this position."""
        return {'data_seed': int(self.seed), 'epoch': int(self.epoch),
                'step_in_epoch': int(self.step_in_epoch)}

    @classmethod
    def from_scalars(cls, scalars: dict, *,
                     default_seed: int = 0) -> 'DataStreamState':
        """Rebuild from a restored bundle's ``scalars`` tree (device or
        host scalars both coerce through int())."""
        return cls(seed=int(scalars.get('data_seed', default_seed)),
                   epoch=int(scalars.get('epoch', 0)),
                   step_in_epoch=int(scalars.get('step_in_epoch', 0)))


def resume_offset(state: DataStreamState | None, epoch: int) -> int:
    """Batches to skip when starting ``epoch``: the saved offset for
    the interrupted epoch, 0 for every later one."""
    if state is not None and epoch == state.epoch:
        return state.step_in_epoch
    return 0
