"""Checkpoint-bundle content integrity: checksums at save, verified at
restore (r16).

A corrupted step checkpoint used to poison resume with no fallback: a
bit-flipped array file either fails the orbax restore outright (best
case) or deserializes into silently-wrong weights (worst case — the run
"resumes" from garbage). This module closes that hole:

  - :func:`tree_checksum` reduces a bundle pytree to one 63-bit content
    digest (shape + dtype + raw bytes of every array leaf, value of
    every scalar leaf, keyed by tree path — deterministic for a fixed
    structure, and a fixed structure is exactly what orbax
    ``StandardRestore`` guarantees).
  - ``training.checkpoint.bundle_state`` stamps the digest into the
    bundle's ``scalars`` under :data:`CHECKSUM_KEY` at assembly time,
    so it rides inside the bundle with zero format machinery.
  - :func:`verify_tree` recomputes the digest over a RESTORED tree and
    compares: a flipped byte in any array payload produces different
    restored bytes, hence a mismatch. The unified resume path
    (``resilience.cli.resume``) and the in-process rollback
    (``resilience.selfheal.rollback_restore``) quarantine a failing
    bundle (``ckpt_quarantine`` event) and walk back to the newest
    bundle that verifies, instead of crashing (or worse, not
    crashing).
  - :func:`finite_ok` additionally scans the restored K-FAC group for
    non-finite values: a bundle saved AFTER an in-memory factor
    corruption checksums perfectly (the digest vouches for integrity,
    not health), so the rollback walk must also refuse to roll back
    INTO poison.

Scope and honesty: the digest is computed from fully-addressable
host-fetched values. On a multi-process pod, non-rank-local shards are
not addressable and the gather would serialize the pod through one
host; bundles saved there record :data:`UNVERIFIED` (0) and restore
with a warning — the same degraded-but-working behavior pre-r16
bundles get (MIGRATION.md "Checkpoint integrity"). Single-process runs
(every test tier, the chaos harness, single-host TPU boxes) get the
full end-to-end guarantee.
"""

from __future__ import annotations

import hashlib

import jax
import numpy as np

#: Key of the content digest inside ``bundle['scalars']``. An int (not
#: a string) so it round-trips orbax scalar handling like the other
#: resume-point scalars.
CHECKSUM_KEY = 'integrity_checksum'
#: Sentinel digest meaning "recorded as unverifiable at save time"
#: (multi-process save). Distinct from the field being ABSENT, which
#: means a pre-r16 bundle.
UNVERIFIED = 0


def _leaf_update(h, path: str, leaf) -> None:
    h.update(path.encode())
    arr = np.asarray(jax.device_get(leaf))
    if arr.ndim == 0:
        # Scalars hash by VALUE, not representation: a python int saved
        # through orbax can come back as a 0-d numpy scalar (and its
        # default width differs across platforms) — repr of .item() is
        # the stable cross-trip form. Non-finite floats repr fine.
        h.update(repr(arr.item()).encode())
        return
    h.update(str(arr.shape).encode())
    h.update(str(arr.dtype).encode())
    h.update(np.ascontiguousarray(arr).tobytes())


def tree_checksum(tree) -> int:
    """63-bit content digest of a bundle pytree.

    Walks every leaf in ``jax.tree_util`` flatten order with its path
    string; the ``scalars``' :data:`CHECKSUM_KEY` leaf is excluded (the
    digest cannot cover itself). Returns :data:`UNVERIFIED` when any
    leaf is not fully addressable (multi-process shards) — recorded,
    never raising, so pod saves keep working.
    """
    h = hashlib.sha256()
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    # Addressability pre-scan BEFORE any device fetch: on a pod, bailing
    # out mid-walk would already have paid host transfers for the
    # leaves in front of the first non-addressable one.
    if any(getattr(leaf, 'is_fully_addressable', True) is False
           for _path, leaf in leaves):
        return UNVERIFIED
    for path, leaf in leaves:
        pstr = jax.tree_util.keystr(path)
        if pstr.endswith(f"['{CHECKSUM_KEY}']"):
            continue
        _leaf_update(h, pstr, leaf)
    digest = int.from_bytes(h.digest()[:8], 'big') & ((1 << 63) - 1)
    # The real digest must never collide with the sentinel; remap the
    # 2^-63 case rather than letting it read as "unverified".
    return digest or 1


def stamp(tree: dict, compute: bool = True) -> dict:
    """Record the content digest into ``tree['scalars']`` (in place on
    the scalars dict the caller just built; returns the tree).

    ``compute=False`` records the :data:`UNVERIFIED` sentinel WITHOUT
    the host fetch + hash — for restore TEMPLATES, which must carry
    the field (orbax structures are exact) but whose digest nobody
    ever reads (``resilience.cli.resume`` / ``handle_rollback`` build
    one from live state on every launch; hashing the whole model for
    it was pure startup cost).
    """
    scalars = tree.get('scalars')
    if isinstance(scalars, dict):
        scalars[CHECKSUM_KEY] = (tree_checksum(tree) if compute
                                 else UNVERIFIED)
    return tree


def recorded_checksum(tree: dict):
    """The digest recorded in a restored bundle: an int, or None for a
    pre-r16 bundle (no field)."""
    scalars = tree.get('scalars', {})
    if CHECKSUM_KEY not in scalars:
        return None
    return int(np.asarray(scalars[CHECKSUM_KEY]).item())


def verify_tree(tree: dict) -> tuple[bool | None, int | None, int]:
    """Verify a restored bundle against its recorded digest.

    Returns ``(ok, recorded, actual)``: ``ok`` is None when the bundle
    carries no digest or recorded :data:`UNVERIFIED` (pre-r16 /
    multi-process save — restore proceeds with a warning, not a
    quarantine), else the comparison verdict.
    """
    recorded = recorded_checksum(tree)
    if recorded is None or recorded == UNVERIFIED:
        # Nothing to verify against — skip the (full host fetch +
        # hash) recompute entirely; pre-r16 and template/multi-process
        # bundles restore unverified either way.
        return None, recorded, UNVERIFIED
    actual = tree_checksum(tree)
    if actual == UNVERIFIED:
        return None, recorded, actual
    return recorded == actual, recorded, actual


def finite_ok(subtree) -> bool:
    """True when every float leaf of ``subtree`` is finite.

    The rollback walk applies this to the restored ``kfac`` group: a
    checkpoint written after the state was already poisoned is
    internally consistent (checksum passes) but rolling back into it
    would re-seed the very fault being healed.
    """
    for leaf in jax.tree_util.tree_leaves(subtree):
        arr = np.asarray(jax.device_get(leaf))
        if arr.dtype.kind == 'f' and arr.size:
            # ml_dtypes (bf16) support isfinite through float32.
            if not np.isfinite(
                    arr.astype(np.float32, copy=False)).all():
                return False
    return True


def strip_checksum(like: dict) -> dict:
    """A restore template for bundles that PREDATE the checksum field:
    same tree minus ``scalars[CHECKSUM_KEY]`` (orbax StandardRestore
    structures must match exactly, so the template must not demand a
    leaf the bundle never saved)."""
    if not isinstance(like, dict) or 'scalars' not in like:
        return like
    scalars = {k: v for k, v in like['scalars'].items()
               if k != CHECKSUM_KEY}
    return {**like, 'scalars': scalars}


def describe_mismatch(recorded: int | None, actual: int) -> str:
    if recorded is None:
        return 'bundle predates content checksums (pre-r16)'
    if recorded == UNVERIFIED:
        return 'bundle recorded no digest (multi-process save)'
    return (f'content digest mismatch: recorded {recorded:#x}, '
            f'restored data hashes to {actual:#x}')
