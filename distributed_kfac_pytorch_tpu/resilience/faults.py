"""Fault injectors: the chaos half of the resilience subsystem.

A :class:`FaultPlan` names *where* (global optimizer step) each fault
fires; the plan rides in the ``KFAC_CHAOS`` env var so the real CLIs
run unmodified under injected failure (the ``chaos`` harness sets it,
tests set it directly). Spec grammar — comma-separated ``kind@step``::

    preempt@K         trigger the preemption handler after step K
                      completes (graceful drain: forced blocking save,
                      exit RELAUNCH_EXIT_CODE) — the simulated
                      TPU-eviction path
    crash@K           os._exit(137) after step K: an UNCLEAN kill (no
                      save, no atexit) — the killed-worker path; resume
                      falls back to the last interval/epoch checkpoint
    nan-batch@K       poison the batch consumed at step K with a NaN —
                      exercises the on-device non-finite factor guard
                      (observability r7 ``nonfinite_guard``)
    crash-in-save@K   die between kicking off the (async) checkpoint
                      snapshot for step K and its finalize — the torn
                      checkpoint write; orbax's write-to-tmp-then-rename
                      atomicity must keep ``latest_epoch()`` from ever
                      surfacing the torn step
    corrupt-factor@K  after step K completes, plant an Inf in one LIVE
                      Kronecker factor (host-side state edit, bypassing
                      the on-device EWMA guard) — the silent-state-
                      corruption path the r16 self-healing ladder's
                      per-bucket quarantine exists for
    corrupt-ckpt@K    after the step-K checkpoint finalizes (forced
                      blocking save), flip one byte in its largest
                      on-disk file — the bit-rot path; the verified
                      resume walk must quarantine the bundle
                      (``ckpt_quarantine``) and land on an older
                      verifiable one
    diverge@K         after step K completes, scale every parameter by
                      a large factor (host-side) — a loss-spike
                      injection that exercises the ladder's damping
                      escalation + decay-back rung without any
                      non-finite value (so it runs under
                      ``KFAC_SANITIZE=nan``)
    resize@K->N       topology change after step K completes: drain
                      like a preemption (forced blocking save, exit
                      RELAUNCH_EXIT_CODE), and the chaos harness
                      relaunches the command with an N-device world —
                      the simulated slice grow/shrink; the relaunch
                      resumes through the elastic reshard path
                      (``resilience.cli.resume(elastic=...)``) instead
                      of cold restarting
    slice-loss@K->S   whole-slice failure after step K completes
                      (r20 multi-slice): drain like a preemption
                      (forced blocking save, exit
                      RELAUNCH_EXIT_CODE), and the chaos harness
                      relaunches onto the S SURVIVOR slices — world
                      shrinks to S * per_slice devices (per-slice
                      size read from the prior launch's forced
                      device count and ``KFAC_NUM_SLICES``), with
                      ``KFAC_NUM_SLICES=S`` exported so the CLI's
                      ``--num-slices`` default follows; the relaunch
                      resumes through the same elastic reshard path
                      as resize
    hang@K            after step K completes, stop making progress AND
                      stop heartbeating WITHOUT exiting (block forever
                      in the step hook) — the wedged-collective /
                      deadlocked-host failure the supervisor's lease
                      expiry (``--hang-timeout``) exists to catch; the
                      process only dies when something kills it
    slowrank@K        from step K onward, sleep SLOWRANK_DELAY_S per
                      step — the persistent-straggler fault: this rank
                      keeps beating and progressing, but the r10
                      barrier-probe skew (rank shards) shows every
                      other rank waiting on it, which is the signal
                      the supervisor's straggler classifier reads

Faults are one-shot by design: a relaunch (fresh process) re-reads the
env, so the chaos harness clears ``KFAC_CHAOS`` for relaunches unless
told otherwise (the resize fault's new world size persists across the
relaunch, of course — that is the point).
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

ENV_VAR = 'KFAC_CHAOS'
_KINDS = ('preempt', 'crash', 'nan-batch', 'crash-in-save',
          'corrupt-factor', 'corrupt-ckpt', 'diverge', 'resize',
          'slice-loss', 'hang', 'slowrank')
#: One line of grammar per fault kind — error messages cite the WHOLE
#: menu, not just the token that failed to parse, so a typo'd spec is
#: fixable from the traceback alone (r16 satellite: the old messages
#: only echoed the bad token plus a bare kind tuple).
_GRAMMAR = ('preempt@K, crash@K, nan-batch@K, crash-in-save@K, '
            'corrupt-factor@K, corrupt-ckpt@K, diverge@K, '
            'resize@K->N, slice-loss@K->S, hang@K, slowrank@K')
# How hard `diverge` kicks the parameters (see poison_params).
DIVERGE_SCALE = 8.0
# Per-step delay the `slowrank` fault injects (see slow_step). Chosen
# well above CPU-test step times so the injected skew dominates host
# jitter, but small enough that a smoke run still finishes.
SLOWRANK_DELAY_S = 0.25


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Global-step-indexed fault schedule (None = fault not armed)."""
    preempt_at: int | None = None
    crash_at: int | None = None
    nan_batch_at: int | None = None
    crash_in_save_at: int | None = None
    corrupt_factor_at: int | None = None
    corrupt_ckpt_at: int | None = None
    diverge_at: int | None = None
    resize_at: int | None = None
    resize_to: int | None = None  # new world size for resize_at
    slice_loss_at: int | None = None
    slice_loss_to: int | None = None  # SURVIVOR slice count
    hang_at: int | None = None
    slowrank_at: int | None = None

    def any(self) -> bool:
        return any(v is not None for v in dataclasses.astuple(self))


def parse_spec(spec: str | None) -> FaultPlan | None:
    """Parse a ``kind@step[,kind@step...]`` spec; None/'' -> None.

    Fails CLOSED at parse time: an unknown kind, a malformed step, or a
    duplicated kind raises here — before any training step runs — so a
    chaos run can never silently train fault-free because its spec
    never matched at fire time. The ``resize`` kind takes
    ``resize@<step>-><new_world_size>`` (e.g. ``resize@2->4``: drain
    after step 2, relaunch with 4 devices).
    """
    if not spec:
        return None
    fields = {}
    for part in spec.split(','):
        part = part.strip()
        if not part:
            continue
        kind, sep, at = part.partition('@')
        if sep and kind == 'resize':
            step_s, arrow, to_s = at.partition('->')
            if not (arrow and step_s.lstrip('-').isdigit()
                    and to_s.isdigit() and int(to_s) > 0):
                raise ValueError(
                    f'bad {ENV_VAR} fault spec {part!r}: expected '
                    "'resize@<step>-><new_world_size>' (e.g. "
                    f"'resize@2->4'); valid fault kinds: {_GRAMMAR}")
            _set_once(fields, 'resize_at', int(step_s), part, spec)
            fields['resize_to'] = int(to_s)
            continue
        if sep and kind == 'slice-loss':
            step_s, arrow, to_s = at.partition('->')
            if not (arrow and step_s.lstrip('-').isdigit()
                    and to_s.isdigit() and int(to_s) > 0):
                raise ValueError(
                    f'bad {ENV_VAR} fault spec {part!r}: expected '
                    "'slice-loss@<step>-><survivor_slices>' (e.g. "
                    f"'slice-loss@2->1'); valid fault kinds: "
                    f'{_GRAMMAR}')
            _set_once(fields, 'slice_loss_at', int(step_s), part, spec)
            fields['slice_loss_to'] = int(to_s)
            continue
        if not sep or kind not in _KINDS:
            raise ValueError(
                f'bad {ENV_VAR} fault spec {part!r}: unknown fault '
                f'kind {kind!r} — valid fault kinds: {_GRAMMAR}')
        if not at.lstrip('-').isdigit():
            raise ValueError(
                f'bad {ENV_VAR} fault spec {part!r}: {at!r} is not an '
                f'integer step; valid fault kinds: {_GRAMMAR}')
        _set_once(fields, kind.replace('-', '_') + '_at', int(at),
                  part, spec)
    drains = [k for k in ('preempt_at', 'resize_at', 'slice_loss_at')
              if k in fields]
    if len(drains) > 1:
        # All drain via the SAME relaunch exit code, so a supervisor
        # (resilience.chaos) could not tell which one caused a given
        # exit — and would change the world size on the wrong drain.
        # One drain fault per launch; chain launches for sequences.
        raise ValueError(
            f'bad {ENV_VAR} spec {spec!r}: preempt/resize/slice-loss '
            'cannot be combined in one launch (all exit with the '
            'relaunch code, so the supervisor cannot attribute the '
            'drain); inject them on separate launches instead')
    return FaultPlan(**fields) if fields else None


def _set_once(fields: dict, key: str, value: int, part: str,
              spec: str) -> None:
    """A duplicated kind is a spec bug, not a schedule: the dataclass
    holds ONE step per kind, so the old parser silently kept the last
    occurrence — the dropped injection then never fired and the chaos
    run 'passed' without testing anything. Fail closed instead."""
    if key in fields:
        raise ValueError(
            f'bad {ENV_VAR} spec {spec!r}: fault kind in {part!r} '
            'appears more than once (each kind fires at ONE step; '
            'chain separate launches for repeated faults)')
    fields[key] = value


def plan_from_env() -> FaultPlan | None:
    """The process's fault plan per ``$KFAC_CHAOS`` (None = no chaos)."""
    return parse_spec(os.environ.get(ENV_VAR))


def hard_crash(code: int = 137) -> None:
    """Die NOW: no save, no atexit, no orbax finalize — the moral
    equivalent of SIGKILL (137 = 128+9), from inside the process."""
    os._exit(code)


def hang() -> None:
    """Wedge NOW: stop progressing and stop heartbeating without
    exiting — the deadlocked-collective failure mode. Blocks in an
    interruptible sleep loop forever; a first SIGTERM only sets the
    (never again polled) preemption flag, exactly like a real hang
    past the drain poll point, so the supervisor's escalation to
    SIGKILL is what actually ends the process."""
    import sys
    import time as _time

    print('chaos: hang fault — blocking without exit (no further '
          'heartbeats); kill me', file=sys.stderr, flush=True)
    while True:
        _time.sleep(60)


def slow_step(plan: 'FaultPlan | None', global_step: int) -> None:
    """Inject the persistent-straggler delay: once ``global_step``
    reaches ``plan.slowrank_at``, every step on THIS process sleeps
    :data:`SLOWRANK_DELAY_S` (sustained skew, not a one-off spike —
    the supervisor's classifier requires persistence)."""
    if plan is not None and plan.slowrank_at is not None \
            and global_step >= plan.slowrank_at:
        import time as _time

        _time.sleep(SLOWRANK_DELAY_S)


def xla_flags_with_device_count(xla_flags: str, n: int) -> str:
    """``XLA_FLAGS`` with the host-platform device count forced to
    ``n`` (any prior count flag replaced) — the CPU-backend world-size
    knob both the chaos harness (``resize@K->N`` relaunches) and the
    supervisor (survivor-mesh failover / grow-back) use to model
    re-provisioning on a test box. On real TPU fleets the resource
    manager owns the device count; this helper only models its
    relaunch step."""
    kept = [f for f in xla_flags.split()
            if not f.startswith('--xla_force_host_platform_device_count')]
    kept.append(f'--xla_force_host_platform_device_count={int(n)}')
    return ' '.join(kept)


def forced_device_count(xla_flags: str) -> int | None:
    """The ``--xla_force_host_platform_device_count`` value in an
    ``XLA_FLAGS`` string, or None when unset — how the chaos harness's
    ``slice-loss`` relaunch leg recovers the prior world size to
    compute the per-slice device count (it fails closed when the flag
    is absent rather than guessing a world)."""
    val = None
    for f in xla_flags.split():
        name, sep, v = f.partition('=')
        if sep and name == '--xla_force_host_platform_device_count':
            val = int(v)
    return val


# ---------------------------------------------------------------------------
# NaN-batch injection (iterator level, before device transfer)
# ---------------------------------------------------------------------------

def poison_batch(batch):
    """Copy of ``batch`` with one NaN planted in its first float leaf
    (the model input) — the minimal poison that propagates to every
    gradient and factor capture."""
    out = list(batch)
    for i, leaf in enumerate(out):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            arr = arr.copy()
            arr.reshape(-1)[0] = np.nan
            out[i] = arr
            return tuple(out)
    raise ValueError('nan-batch fault: batch has no float leaf to poison')


def poison_at(batches, plan: FaultPlan | None, *, first_step: int = 0):
    """Wrap a batch iterator, poisoning the batch consumed at global
    step ``plan.nan_batch_at`` (``first_step`` = the global step the
    first yielded batch will be consumed at). Passthrough when the plan
    has no nan-batch fault."""
    if plan is None or plan.nan_batch_at is None:
        yield from batches
        return
    for i, batch in enumerate(batches):
        if first_step + i == plan.nan_batch_at:
            batch = poison_batch(batch)
        yield batch


# ---------------------------------------------------------------------------
# Live-state corruption (corrupt-factor / diverge — r16 ladder proofs)
# ---------------------------------------------------------------------------

def poison_factors(kfac_state: dict) -> dict:
    """Plant an ``inf`` in one live Kronecker factor (host-side).

    Deterministic target: the lexicographically-first registered layer's
    first factor leaf, element 0. Edited OUTSIDE the jitted step — the
    on-device EWMA guard never sees a candidate, so the poison lands
    exactly like a silent in-memory corruption would. Works on both the
    single-chip (``KFAC.init_state``) and SPMD
    (``DistributedKFAC.init_state``) state layouts (``'factors'`` is a
    per-layer dict in both).
    """
    import jax.numpy as jnp

    factors = dict(kfac_state['factors'])
    name = sorted(factors)[0]
    entry = dict(factors[name])
    key = sorted(entry)[0]
    leaf = entry[key]
    flat = jnp.ravel(leaf).at[0].set(jnp.inf)
    entry[key] = flat.reshape(leaf.shape).astype(leaf.dtype)
    factors[name] = entry
    return {**kfac_state, 'factors': factors}


def poison_params(params, scale: float = DIVERGE_SCALE):
    """Scale every float parameter by ``scale`` (host-side): a pure
    loss-spike injection — values stay finite (so the run survives
    ``KFAC_SANITIZE=nan``), but the loss/grad-norm jump is the
    divergence-window signature the self-healing damping-escalation
    rung keys on."""
    import jax
    import jax.numpy as jnp

    def bump(p):
        if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating):
            return (jnp.asarray(p) * scale).astype(p.dtype)
        return p

    return jax.tree.map(bump, params)


def corrupt_bundle_file(directory: str, step: int) -> str:
    """Flip one byte in the middle of the LARGEST file of a finalized
    step-checkpoint directory (the array-payload file, with
    overwhelming probability) — the bit-rot fault. The bundle stays
    present and listed; only the r16 integrity verification (content
    checksum recorded in the bundle's scalars) or a failing restore can
    tell it is bad. Returns the corrupted path."""
    root = os.path.join(directory, str(step))
    if not os.path.isdir(root):
        raise FileNotFoundError(
            f'corrupt-ckpt fault: no finalized step dir {root}')
    victim, size = None, -1
    for base, _dirs, files in os.walk(root):
        for f in files:
            p = os.path.join(base, f)
            s = os.path.getsize(p)
            if s > size:
                victim, size = p, s
    if victim is None or size == 0:
        raise FileNotFoundError(
            f'corrupt-ckpt fault: no non-empty file under {root}')
    with open(victim, 'r+b') as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    return victim


# ---------------------------------------------------------------------------
# Torn-checkpoint emulation (what a killed writer leaves on disk)
# ---------------------------------------------------------------------------

def torn_step_dir(directory: str, step: int) -> str:
    """Create the on-disk state a writer killed between snapshot and
    finalize leaves behind: an *uncommitted* orbax temp directory
    (``<step>.orbax-checkpoint-tmp-<ts>``). Finalize is an atomic
    rename to the bare ``<step>`` name, so this is exactly the torn
    state — ``CheckpointManager.latest_epoch()`` must never surface it
    (tests/test_resilience.py pins that)."""
    path = os.path.join(directory, f'{step}.orbax-checkpoint-tmp-0')
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, '_partial_write'), 'w') as f:
        f.write('torn')
    return path
