"""Shared CLI wiring for the resilience flags (mirrors
``observability.cli``).

All three example entry points expose the same resilience surface;
this module is its single implementation:

    add_resilience_args(parser)     # --checkpoint-steps /
                                    # --checkpoint-secs /
                                    # --preemption-grace / --resume-step
    handler = install_preemption(args)          # SIGTERM/SIGINT + env
    step_mgr = make_step_manager(args)
    ckpt = make_step_checkpointer(args, step_mgr, bundle_fn,
                                  preemption=handler, sink=sink,
                                  start_step=0)
    resumed = resume(args, epoch_mgr, step_mgr, like, sink=sink)

``resume`` unifies the two checkpoint trees: epoch-indexed checkpoints
(the pre-r8 format, still written at ``--checkpoint-freq``) and
global-step-indexed ones under ``<checkpoint-dir>/steps/``. Both bundle
kinds carry the resume point in their scalars (``epoch`` = the epoch to
(re)enter, offset by ``step_in_epoch`` batches — see
``resilience.dataiter``); the newest point wins, so a stale step
checkpoint left behind by an old preemption can never resume training
backwards past a newer epoch checkpoint.
"""

from __future__ import annotations

import os
import traceback

from distributed_kfac_pytorch_tpu.resilience import faults as faults_lib
from distributed_kfac_pytorch_tpu.resilience import (
    policy as policy_lib,
    preemption as preemption_lib,
)
from distributed_kfac_pytorch_tpu.training import checkpoint as ckpt_lib

STEP_SUBDIR = 'steps'


def add_resilience_args(p) -> None:
    """Resilience flags (r8; see README "Fault tolerance")."""
    p.add_argument('--checkpoint-steps', type=int, default=0,
                   metavar='N',
                   help='save a global-step-indexed checkpoint every N '
                        'optimizer steps into <checkpoint-dir>/steps '
                        '(0 = epoch checkpoints only) — bounds '
                        'preemption loss for long epochs')
    p.add_argument('--checkpoint-secs', type=float, default=0.0,
                   metavar='S',
                   help='also step-checkpoint when S wall-clock seconds '
                        'have passed since the last one (0 = off; on a '
                        "pod, rank 0's clock decides and the verdict "
                        'is broadcast so the collective save stays in '
                        'lockstep)')
    p.add_argument('--preemption-grace', type=float, default=30.0,
                   metavar='S',
                   help='grace budget after SIGTERM/SIGINT (or a '
                        'KFAC_PREEMPT_FILE sentinel): finish the '
                        'in-flight step, force a blocking step '
                        'checkpoint, exit with code '
                        f'{preemption_lib.RELAUNCH_EXIT_CODE} so a '
                        'relaunch loop restarts the run (a second '
                        'signal kills immediately)')
    p.add_argument('--resume-step', type=int, default=None, metavar='G',
                   help='resume from this exact global-step checkpoint '
                        'in <checkpoint-dir>/steps (default: the '
                        'newest of step/epoch checkpoints)')


def install_preemption(args) -> preemption_lib.PreemptionHandler:
    """Install the signal handler (plus the ``KFAC_PREEMPT_FILE``
    sentinel source when set). Call EARLY in main() — a preemption
    notice arriving before installation kills the process with the
    default disposition."""
    handler = preemption_lib.PreemptionHandler(
        grace_secs=args.preemption_grace).install()
    sentinel = os.environ.get('KFAC_PREEMPT_FILE')
    if sentinel:
        handler.add_source(preemption_lib.file_source(sentinel))
    return handler


def make_step_manager(args) -> ckpt_lib.CheckpointManager:
    """The global-step-indexed manager under ``<checkpoint-dir>/steps``
    (orbax ignores the non-integer subdirectory when scanning the
    parent epoch tree)."""
    return ckpt_lib.CheckpointManager(
        os.path.join(args.checkpoint_dir, STEP_SUBDIR), max_to_keep=2)


def make_step_checkpointer(args, step_mgr, bundle_fn, *,
                           preemption=None, sink=None,
                           start_step: int = 0
                           ) -> policy_lib.StepCheckpointer:
    """Assemble the per-step hook: interval policy + preemption forcing
    + any ``KFAC_CHAOS`` fault plan. Always constructed (even with both
    intervals at 0) because preemption must be able to force a save."""
    pol = policy_lib.CheckpointPolicy(
        every_steps=args.checkpoint_steps,
        every_secs=args.checkpoint_secs, start_step=start_step)
    return policy_lib.StepCheckpointer(
        step_mgr, pol, bundle_fn, preemption=preemption, sink=sink,
        plan=faults_lib.plan_from_env())


def resume(args, epoch_mgr, step_mgr, like, *, sink=None,
           verbose: bool = False):
    """Restore the newest checkpoint (step or epoch tree), if any.

    Returns ``(restored_tree, start_epoch, start_offset, source)`` or
    None when there is nothing to resume (or ``--no-resume``).
    ``like`` must be a live-state bundle template: restore always goes
    through ``like=`` so sharded SPMD state comes back with its
    committed shardings (restore without ``like`` yields host arrays —
    see ``CheckpointManager.restore``).
    """
    if getattr(args, 'no_resume', False):
        return None
    # Known tradeoff: picking the winner needs the step bundle's
    # scalars, and orbax StandardRestore is whole-tree, so a stale step
    # checkpoint costs one discarded full restore before the epoch one
    # loads. That only happens on the first relaunch after an old
    # preemption was overtaken by epoch checkpoints — accepted over
    # maintaining a second scalars-only manifest.
    candidates = []  # ((epoch, offset), tree, source, label)
    step_label = (args.resume_step if args.resume_step is not None
                  else step_mgr.latest_epoch())
    if args.resume_step is not None or step_label is not None:
        tree = _restore(step_mgr, step_label, like, args,
                        what=f'step checkpoint {step_label}')
        sc = tree['scalars']
        candidates.append(((int(sc['epoch']), int(sc['step_in_epoch'])),
                           tree, 'step', step_label))
    if args.resume_step is None:
        e = epoch_mgr.latest_epoch()
        if e is not None:
            # Epoch bundles record their resume point too ((e+1, 0) —
            # the epoch completed); restore only if it could win.
            if not candidates or (e + 1, 0) > candidates[0][0]:
                tree = _restore(epoch_mgr, e, like, args,
                                what=f'epoch checkpoint {e}')
                sc = tree['scalars']
                candidates.append(
                    ((int(sc['epoch']), int(sc['step_in_epoch'])),
                     tree, 'epoch', e))
    if not candidates:
        return None
    (start_epoch, offset), tree, source, label = max(
        candidates, key=lambda c: c[0])
    # The bundle's data_seed is part of the data-stream position
    # (resilience.dataiter): adopt it, or a supervisor that relaunches
    # without --seed would skip `offset` batches of a DIFFERENT
    # permutation — silently double-training some samples and never
    # seeing others.
    saved_seed = tree['scalars'].get('data_seed')
    if saved_seed is not None and hasattr(args, 'seed'):
        saved_seed = int(saved_seed)
        if saved_seed != args.seed:
            if verbose:
                print(f'resume: adopting checkpoint data_seed '
                      f'{saved_seed} (relaunch passed --seed '
                      f'{args.seed}) to keep the batch replay exact')
            args.seed = saved_seed
    if sink is not None:
        sink.event_record('restore', source=source, label=int(label),
                          global_step=int(tree['scalars']['step']),
                          epoch=start_epoch, step_in_epoch=offset)
    if verbose:
        at = f', mid-epoch offset {offset}' if offset else ''
        print(f'resumed from {source} checkpoint {label} '
              f'(epoch {start_epoch}{at})')
    return tree, start_epoch, offset, source


def _restore(mgr, label, like, args, *, what: str):
    try:
        return mgr.restore(label, like=like)
    except Exception as e:
        traceback.print_exc()  # keep the real cause diagnosable
        raise SystemExit(
            f'cannot resume from {what} under {args.checkpoint_dir}: '
            f'{e}\nThe checkpoint was likely written with a different '
            'model/K-FAC configuration, or by a version predating the '
            'resilience checkpoint-format extension (see MIGRATION.md '
            '"Checkpoint format") — pass --no-resume or a fresh '
            '--checkpoint-dir.')
