"""Shared CLI wiring for the resilience flags (mirrors
``observability.cli``).

All three example entry points expose the same resilience surface;
this module is its single implementation:

    add_resilience_args(parser)     # --checkpoint-steps /
                                    # --checkpoint-secs /
                                    # --preemption-grace / --resume-step
    handler = install_preemption(args)          # SIGTERM/SIGINT + env
    step_mgr = make_step_manager(args)
    ckpt = make_step_checkpointer(args, step_mgr, bundle_fn,
                                  preemption=handler, sink=sink,
                                  start_step=0)
    resumed = resume(args, epoch_mgr, step_mgr, like, sink=sink,
                     elastic=ElasticResume(mesh, dkfac, params))

``resume`` unifies the two checkpoint trees: epoch-indexed checkpoints
(the pre-r8 format, still written at ``--checkpoint-freq``) and
global-step-indexed ones under ``<checkpoint-dir>/steps/``. Both bundle
kinds carry the resume point in their scalars (``epoch`` = the epoch to
(re)enter, offset by ``step_in_epoch`` batches — see
``resilience.dataiter``); the newest point wins, so a stale step
checkpoint left behind by an old preemption can never resume training
backwards past a newer epoch checkpoint.
"""

from __future__ import annotations

import os
import traceback
import warnings

from distributed_kfac_pytorch_tpu.resilience import faults as faults_lib
from distributed_kfac_pytorch_tpu.resilience import (
    integrity as integrity_lib,
    policy as policy_lib,
    preemption as preemption_lib,
)
from distributed_kfac_pytorch_tpu.training import checkpoint as ckpt_lib

STEP_SUBDIR = 'steps'


def add_resilience_args(p) -> None:
    """Resilience flags (r8; see README "Fault tolerance")."""
    p.add_argument('--checkpoint-steps', type=int, default=0,
                   metavar='N',
                   help='save a global-step-indexed checkpoint every N '
                        'optimizer steps into <checkpoint-dir>/steps '
                        '(0 = epoch checkpoints only) — bounds '
                        'preemption loss for long epochs')
    p.add_argument('--checkpoint-secs', type=float, default=0.0,
                   metavar='S',
                   help='also step-checkpoint when S wall-clock seconds '
                        'have passed since the last one (0 = off; on a '
                        "pod, rank 0's clock decides and the verdict "
                        'is broadcast so the collective save stays in '
                        'lockstep)')
    p.add_argument('--preemption-grace', type=float, default=30.0,
                   metavar='S',
                   help='grace budget after SIGTERM/SIGINT (or a '
                        'KFAC_PREEMPT_FILE sentinel): finish the '
                        'in-flight step, force a blocking step '
                        'checkpoint, exit with code '
                        f'{preemption_lib.RELAUNCH_EXIT_CODE} so a '
                        'relaunch loop restarts the run (a second '
                        'signal kills immediately)')
    p.add_argument('--resume-step', type=int, default=None, metavar='G',
                   help='resume from this exact global-step checkpoint '
                        'in <checkpoint-dir>/steps (default: the '
                        'newest of step/epoch checkpoints)')
    # r17 heartbeat leases (README "Supervision & failover"). Off by
    # default; the supervisor arms them via KFAC_HEARTBEAT_DIR so the
    # command line needs no rewriting.
    p.add_argument('--heartbeat-dir', default=None, metavar='DIR',
                   help='publish a per-rank liveness lease (atomic '
                        'JSON file rank<r>.lease with global step, '
                        'wall time, incarnation) into DIR from the '
                        'train loop — the failure supervisor\'s hang/'
                        'dead-worker signal (default: the '
                        'KFAC_HEARTBEAT_DIR env var, unset = no '
                        'heartbeats; pure host-side file I/O, '
                        'bit-identical off AND on)')
    p.add_argument('--heartbeat-every', type=int, default=1,
                   metavar='N',
                   help='publish the lease every N optimizer steps '
                        '(keyed to the global step, so a resumed run '
                        'keeps the cadence); budget --hang-timeout '
                        'above N steps + the eval/checkpoint gaps')
    # r16 self-healing ladder (README "Self-healing"). Off by default:
    # with the ladder unarmed the engine is byte-for-byte the pre-r16
    # program (per-step-loss bit-identity pinned).
    p.add_argument('--selfheal', action='store_true',
                   help='arm the fault-response escalation ladder: '
                        'skip-window (the nonfinite guard, forced on) '
                        '-> damping escalation -> per-bucket layer '
                        'quarantine (identity/SGD fallback while '
                        'factors re-accumulate) -> in-process rollback '
                        'to the newest VERIFIED step checkpoint. '
                        'Requires --kfac-metrics (the ladder reads the '
                        'on-device metrics stream); adds one host '
                        'sync per --selfheal-window steps')
    p.add_argument('--selfheal-window', type=int, default=0,
                   metavar='N',
                   help='ladder observation window in optimizer steps '
                        '(0 = half the K-FAC inverse-update frequency: '
                        'two observations per cadence window, so a '
                        'factor corruption can be quarantined BEFORE '
                        'the next inverse firing decomposes it; '
                        'smaller = faster containment, one more host '
                        'sync per window)')
    p.add_argument('--selfheal-damping-factor', type=float,
                   default=10.0, metavar='F',
                   help='damping multiplier applied per escalation on '
                        'repeated bad windows, decayed one notch per '
                        'clean window (rung 2)')
    p.add_argument('--selfheal-diverge-ratio', type=float,
                   default=10.0, metavar='R',
                   help='a window whose loss exceeds R x the running '
                        'boundary-loss average counts as a divergence '
                        'window (rung-2 trigger). Workload-dependent: '
                        'quadratic losses spike multiplicatively, '
                        'cross-entropy saturates near log(vocab) — '
                        'lower R (e.g. 1.5) for CE workloads')
    p.add_argument('--selfheal-no-quarantine', action='store_true',
                   help='skip the per-bucket quarantine rung (the '
                        'ladder then goes skip -> damping -> '
                        'rollback); also the fallback when a workload '
                        'cannot serve identity directions')
    p.add_argument('--selfheal-max-rollbacks', type=int, default=1,
                   metavar='N',
                   help='in-process rollback budget; past it the '
                        'ladder is exhausted and the process dies '
                        'into the r8 relaunch loop (the last rung)')


def make_heartbeat(args, info):
    """The per-rank :class:`resilience.heartbeat.HeartbeatEmitter` for
    a CLI run, or None when heartbeats are off.

    ``--heartbeat-dir`` wins; the ``KFAC_HEARTBEAT_DIR`` env var is
    the supervisor's hands-off wiring (it exports the var so the
    supervised command line runs unmodified — the same pattern as
    ``KFAC_CHAOS``/``KFAC_PREEMPT_FILE``). EVERY rank emits its own
    lease (the inverse of the rank-0-gated metrics sink): liveness is
    per-host by nature.
    """
    directory = (getattr(args, 'heartbeat_dir', None)
                 or os.environ.get('KFAC_HEARTBEAT_DIR'))
    if not directory:
        return None
    from distributed_kfac_pytorch_tpu.resilience import (
        heartbeat as heartbeat_lib,
    )
    return heartbeat_lib.HeartbeatEmitter(
        directory, info['process_index'],
        every=max(1, int(getattr(args, 'heartbeat_every', 1) or 1)))


def install_preemption(args) -> preemption_lib.PreemptionHandler:
    """Install the signal handler (plus the ``KFAC_PREEMPT_FILE``
    sentinel source when set). Call EARLY in main() — a preemption
    notice arriving before installation kills the process with the
    default disposition."""
    handler = preemption_lib.PreemptionHandler(
        grace_secs=args.preemption_grace).install()
    sentinel = os.environ.get('KFAC_PREEMPT_FILE')
    if sentinel:
        handler.add_source(preemption_lib.file_source(sentinel))
    return handler


def make_step_manager(args) -> ckpt_lib.CheckpointManager:
    """The global-step-indexed manager under ``<checkpoint-dir>/steps``
    (orbax ignores the non-integer subdirectory when scanning the
    parent epoch tree).

    With ``--selfheal`` the retention deepens (10 bundles instead of
    2): the rung-4 rollback must find a VERIFIED bundle saved BEFORE
    the fault onset, and onset detection trails the fault by up to
    ``rollback_after`` observation windows — two kept bundles are
    routinely both post-fault by then (README "Self-healing").
    """
    keep = 10 if getattr(args, 'selfheal', False) else 2
    return ckpt_lib.CheckpointManager(
        os.path.join(args.checkpoint_dir, STEP_SUBDIR),
        max_to_keep=keep)


def make_step_checkpointer(args, step_mgr, bundle_fn, *,
                           preemption=None, sink=None,
                           start_step: int = 0
                           ) -> policy_lib.StepCheckpointer:
    """Assemble the per-step hook: interval policy + preemption forcing
    + any ``KFAC_CHAOS`` fault plan. Always constructed (even with both
    intervals at 0) because preemption must be able to force a save."""
    pol = policy_lib.CheckpointPolicy(
        every_steps=args.checkpoint_steps,
        every_secs=args.checkpoint_secs, start_step=start_step)
    return policy_lib.StepCheckpointer(
        step_mgr, pol, bundle_fn, preemption=preemption, sink=sink,
        plan=faults_lib.plan_from_env())


def wants_selfheal_guard(args) -> bool:
    """True when the CLI must arm the on-device non-finite factor
    guard because the ladder is armed (rung 1 is the guard; without it
    a poisoned candidate silently enters the EWMA and the ladder's
    ``nonfinite_skips`` signal never fires)."""
    return bool(getattr(args, 'selfheal', False))


def make_selfheal(args, *, kfac, params, sink=None):
    """Build the :class:`resilience.selfheal.SelfHealController` for a
    CLI run (or None when ``--selfheal`` is off).

    Fail-closed wiring: the ladder needs the on-device metrics stream
    (``--kfac-metrics``) and a K-FAC step — arming it without either
    is a usage error, not a silent no-op.
    """
    if not getattr(args, 'selfheal', False):
        return None
    from distributed_kfac_pytorch_tpu.resilience import (
        selfheal as selfheal_lib,
    )
    if not getattr(args, 'kfac_metrics', None):
        raise SystemExit('--selfheal requires --kfac-metrics (the '
                         'ladder is driven by the on-device metrics '
                         'stream)')
    if kfac is None:
        raise SystemExit('--selfheal requires the K-FAC step '
                         '(--kfac-update-freq > 0)')
    window = int(getattr(args, 'selfheal_window', 0) or 0)
    if window <= 0:
        # Half the inverse cadence: the quarantine rung can only
        # CONTAIN a factor corruption if it is detected (and the EWMA
        # reset) before the next inverse firing decomposes the poison
        # into the preconditioner — two observations per firing window
        # give it that head start (README "Self-healing"; a fault the
        # gate cannot outrun escalates to rollback instead, which is
        # the correct rung once parameters are contaminated).
        window = max(1, int(getattr(args, 'kfac_update_freq', 10)) // 2)
    cfg = selfheal_lib.SelfHealConfig(
        check_every=window,
        damping_factor=args.selfheal_damping_factor,
        diverge_ratio=args.selfheal_diverge_ratio,
        quarantine=not args.selfheal_no_quarantine,
        max_rollbacks=args.selfheal_max_rollbacks)
    bucket_layers = (None if args.selfheal_no_quarantine
                     else selfheal_lib.bucket_layer_map(kfac, params))
    return selfheal_lib.SelfHealController(
        cfg, bucket_layers=bucket_layers, sink=sink)


def resume(args, epoch_mgr, step_mgr, like, *, sink=None,
           verbose: bool = False, elastic=None):
    """Restore the newest checkpoint (step or epoch tree), if any.

    Returns ``(restored_tree, start_epoch, start_offset, source)`` or
    None when there is nothing to resume (or ``--no-resume``).

    r16 integrity: every candidate bundle's content checksum
    (``resilience.integrity``, recorded by ``bundle_state``) is
    verified after restore; a bundle that fails restore OR
    verification is quarantined (``ckpt_quarantine`` event + warning)
    and the walk continues to the next-older bundle in that tree —
    resume lands on the newest VERIFIABLE state instead of crashing
    on a torn/bit-rotted one. If bundles exist but none verifies,
    resume raises ``SystemExit`` rather than silently cold-starting.
    Pre-r16 bundles (no checksum field) restore unverified with a
    warning.
    ``like`` must be a live-state bundle template: restore always goes
    through ``like=`` so sharded SPMD state comes back with its
    committed shardings (restore without ``like`` yields host arrays —
    see ``CheckpointManager.restore``).

    ``elastic``: an ``elastic.ElasticResume(mesh=, dkfac=, params=)``
    describing the LIVE world. With it, a bundle saved on a DIFFERENT
    topology (detected from its recorded ``topo_*`` scalars,
    ``elastic.topology``) is restored replicated onto the live mesh
    (``CheckpointManager.restore_replicated``) and its K-FAC slot
    stacks are repacked for the new KAISA grid
    (``elastic.reshard``) instead of the restore failing — the
    grow/shrink resume path (README "Elastic training"). A
    ``topology_change`` event is emitted into ``sink``. Bundles that
    predate the topology record restore same-topology-only (their
    inverse stacks are rebuilt from factors if the layout happens to
    differ — ``DistributedKFAC.load_state_dict``'s shape check).
    Without ``elastic``, behavior is unchanged (same-topology
    ``like=`` restores).
    """
    if getattr(args, 'no_resume', False):
        return None
    # Known tradeoff: picking the winner needs the step bundle's
    # scalars, and orbax StandardRestore is whole-tree, so a stale step
    # checkpoint costs one discarded full restore before the epoch one
    # loads. That only happens on the first relaunch after an old
    # preemption was overtaken by epoch checkpoints — accepted over
    # maintaining a second scalars-only manifest.
    candidates = []  # ((epoch, offset), tree, source, label, relaid, mgr)
    quarantined: list[str] = []
    found = _walk_restore(step_mgr, like, args, kind='step',
                          sink=sink, elastic=elastic,
                          explicit=args.resume_step,
                          quarantined=quarantined)
    if found is not None:
        label, tree, relaid = found
        sc = tree['scalars']
        candidates.append(((int(sc['epoch']), int(sc['step_in_epoch'])),
                           tree, 'step', label, relaid, step_mgr))
    if args.resume_step is None:
        # Epoch bundles record their resume point too ((e+1, 0) — the
        # epoch completed); walk only the labels that could win over
        # the step candidate (older epoch bundles resume strictly
        # earlier, so the filtered list stays newest-first-best).
        step_point = candidates[0][0] if candidates else None
        epoch_labels = [e for e in sorted(epoch_mgr.all_steps(),
                                          reverse=True)
                        if step_point is None or (e + 1, 0) > step_point]
        found = _walk_restore(epoch_mgr, like, args, kind='epoch',
                              sink=sink, elastic=elastic,
                              labels=epoch_labels,
                              quarantined=quarantined)
        if found is not None:
            label, tree, relaid = found
            sc = tree['scalars']
            candidates.append(
                ((int(sc['epoch']), int(sc['step_in_epoch'])),
                 tree, 'epoch', label, relaid, epoch_mgr))
    if not candidates:
        if quarantined:
            # Bundles exist but none verifies: training from scratch
            # here would silently discard the run's history — that is
            # a decision for the operator, not a default.
            raise SystemExit(
                f'cannot resume under {args.checkpoint_dir}: every '
                f'checkpoint bundle failed restore/verification '
                f'({"; ".join(quarantined)}). Pass --no-resume to '
                'train from scratch or point --checkpoint-dir at a '
                'healthy tree.')
        return None
    (start_epoch, offset), tree, source, label, relaid, won_mgr = max(
        candidates, key=lambda c: c[0])
    if elastic is not None:
        tree = _adopt_topology(tree, elastic, relaid, won_mgr, label,
                               like, sink=sink, verbose=verbose)
    # The bundle's data_seed is part of the data-stream position
    # (resilience.dataiter): adopt it, or a supervisor that relaunches
    # without --seed would skip `offset` batches of a DIFFERENT
    # permutation — silently double-training some samples and never
    # seeing others.
    saved_seed = tree['scalars'].get('data_seed')
    if saved_seed is not None and hasattr(args, 'seed'):
        saved_seed = int(saved_seed)
        if saved_seed != args.seed:
            if verbose:
                print(f'resume: adopting checkpoint data_seed '
                      f'{saved_seed} (relaunch passed --seed '
                      f'{args.seed}) to keep the batch replay exact')
            args.seed = saved_seed
    if sink is not None:
        sink.event_record('restore', source=source, label=int(label),
                          global_step=int(tree['scalars']['step']),
                          epoch=start_epoch, step_in_epoch=offset)
    if verbose:
        at = f', mid-epoch offset {offset}' if offset else ''
        print(f'resumed from {source} checkpoint {label} '
              f'(epoch {start_epoch}{at})')
    return tree, start_epoch, offset, source


def _template_for(mgr, label, like):
    """The restore template for one bundle: ``like`` as-is for r16
    bundles, ``like`` minus the checksum scalar for bundles that
    predate it (orbax StandardRestore structures must match exactly;
    detected from the bundle's own metadata, no array reads)."""
    try:
        md = mgr.metadata_tree(label)
        scalars = md.get('scalars', {}) if isinstance(md, dict) else {}
        if integrity_lib.CHECKSUM_KEY not in scalars:
            return integrity_lib.strip_checksum(like)
    except Exception:
        pass  # unreadable metadata: try the full template; the
        # restore itself is the arbiter (and the walk quarantines).
    return like


def _walk_restore(mgr, like, args, *, kind: str, sink=None, elastic=None,
                  explicit: int | None = None,
                  labels: list[int] | None = None,
                  quarantined: list[str] | None = None):
    """Restore the newest VERIFIABLE bundle of one checkpoint tree.

    Walks ``labels`` (default: everything on disk, newest first); a
    bundle that fails to restore (torn/incompatible) or fails its
    content-checksum verification (bit rot — ``resilience.integrity``)
    is QUARANTINED: a ``ckpt_quarantine`` event goes into ``sink``, a
    warning names the reason, and the walk continues to the next-older
    bundle instead of crashing resume (r16). Bundles without a
    recorded checksum (pre-r16 / multi-process saves) restore
    unverified with a warning.

    ``explicit`` (``--resume-step``) pins the walk to exactly one
    label and converts its failures into a hard ``SystemExit`` — an
    operator who names a bundle should not be silently handed a
    different one.

    Returns ``(label, tree, relaid)`` or None when nothing restored.
    """
    if labels is None:
        labels = ([explicit] if explicit is not None
                  else sorted(mgr.all_steps(), reverse=True))
    if explicit is not None:
        # An operator naming a QUARANTINED label deserves the real
        # story — which directory the bundle was moved to and why the
        # verified walk moved it — not the generic not-found that a
        # never-saved step gets (r17 satellite; the quarantine reason
        # is recorded by CheckpointManager.quarantine).
        qinfo = getattr(mgr, 'quarantine_info', lambda _l: None)(
            explicit)
        if qinfo is not None:
            qpath, qreason = qinfo
            raise SystemExit(
                f'cannot resume from {kind} checkpoint {explicit}: '
                f'that bundle was QUARANTINED by a previous verified '
                f'resume walk — moved to {qpath} because {qreason}. '
                'Quarantined bundles failed restore or integrity '
                'verification and are kept only for forensics; pick a '
                'different --resume-step or drop the flag to resume '
                'from the newest verifiable checkpoint.')
    for label in labels:
        what = f'{kind} checkpoint {label}'
        use_like = _template_for(mgr, label, like)
        try:
            if elastic is None:
                tree, relaid = mgr.restore(label, like=use_like), False
            else:
                tree, relaid = _elastic_restore(mgr, label, use_like,
                                                elastic)
        except FileNotFoundError as e:
            if explicit is not None:
                # Already self-explanatory (names the requested step
                # and the steps on disk) — no format advice on top.
                raise SystemExit(f'cannot resume from {what}: {e}')
            _quarantine(sink, kind, label, f'restore failed: {e}',
                        quarantined)
            continue
        except Exception as e:
            if explicit is not None:
                traceback.print_exc()  # keep the real cause diagnosable
                raise SystemExit(
                    f'cannot resume from {what} under '
                    f'{args.checkpoint_dir}: {e}\nThe checkpoint was '
                    'likely written with a different model/K-FAC '
                    'configuration, or by a version predating the '
                    'resilience checkpoint-format extension (see '
                    'MIGRATION.md "Checkpoint format") — pass '
                    '--no-resume or a fresh --checkpoint-dir.')
            # No on-disk move here: a generic restore failure is
            # AMBIGUOUS — it hits every bundle identically when the
            # operator relaunched with a changed model/K-FAC config,
            # and renaming the whole history would make the NEXT
            # (fixed) relaunch silently cold-start. Only a confirmed
            # checksum mismatch (below) is unambiguous bit rot worth
            # moving aside; a replay re-saving over a still-present
            # corrupt label is handled by the force-replace in
            # CheckpointManager.save.
            _quarantine(sink, kind, label, f'restore failed: {e}',
                        quarantined)
            continue
        ok, recorded, actual = integrity_lib.verify_tree(tree)
        if ok is False:
            reason = integrity_lib.describe_mismatch(recorded, actual)
            if explicit is not None:
                raise SystemExit(
                    f'cannot resume from {what}: {reason}. The bundle '
                    'is corrupt on disk; drop --resume-step to walk '
                    'back to the newest verifiable checkpoint.')
            _quarantine(sink, kind, label, reason, quarantined,
                        mgr=mgr)
            continue
        if ok is None:
            warnings.warn(
                f'resume: {what} restored UNVERIFIED '
                f'({integrity_lib.describe_mismatch(recorded, actual)} '
                '— see MIGRATION.md "Checkpoint integrity")',
                RuntimeWarning)
        return label, tree, relaid
    return None


def _quarantine(sink, kind: str, label, reason: str,
                quarantined: list[str] | None, mgr=None) -> None:
    """One rejected bundle: durable event + loud warning + walk on.

    With ``mgr``, the bundle's directory is also MOVED aside
    (``CheckpointManager.quarantine`` — kept as ``<label>.quarantined``
    for forensics). Pass ``mgr`` ONLY for confirmed-bad content
    (checksum mismatch, non-finite state) — a generic restore failure
    may be a config mismatch hitting every bundle, and moving the
    whole history would make the next relaunch silently cold-start.
    """
    note = f'{kind} checkpoint {label}: {reason}'
    if quarantined is not None:
        quarantined.append(note)
    warnings.warn(f'resume: quarantining {note} — walking back to the '
                  'next older bundle', RuntimeWarning)
    if mgr is not None:
        try:
            mgr.quarantine(int(label), reason=str(reason))
        except Exception as e:  # best effort: never break the walk
            warnings.warn(f'resume: could not move quarantined '
                          f'{kind} checkpoint {label} aside: {e}',
                          RuntimeWarning)
    if sink is not None:
        sink.event_record('ckpt_quarantine', source=kind,
                          label=int(label), reason=str(reason)[:300])


def _elastic_restore(mgr, label, like, elastic):
    """Same-topology fast path when the saved shapes match the live
    template; otherwise the replicated cross-topology restore."""
    from distributed_kfac_pytorch_tpu.elastic import (
        reshard as reshard_lib,
    )
    md = None
    try:
        md = mgr.metadata_tree(label)
    except Exception:
        md = None  # metadata unreadable: same-topology restore only
    if md is None or reshard_lib.like_matches_metadata(md, like):
        try:
            return mgr.restore(label, like=like), False
        except Exception:
            if md is None:
                raise
            # The positional shape match was a coincidence (structure
            # differed) — the replicated restore below is authoritative.
    return mgr.restore_replicated(label, mesh=elastic.mesh,
                                  like=like), True


def _adopt_topology(tree, elastic, relaid, mgr, label, like, *,
                    sink=None, verbose=False):
    """Post-restore elastic step: reshard the winner's K-FAC state for
    the live world when its recorded topology differs, and re-commit
    replicated-restored groups onto the live mesh."""
    from distributed_kfac_pytorch_tpu.elastic import (
        topology as topo_lib,
    )
    saved = topo_lib.TopologySpec.from_scalars(tree.get('scalars', {}))
    live = elastic.topology
    if saved is not None and saved.needs_reshard(live):
        if not relaid:
            # Same shapes, different slot layout (possible when two
            # KAISA grids coincide in slot counts): the like= restore
            # handed back row-sharded arrays, which cannot be gathered
            # host-side on a pod — re-restore replicated.
            tree = mgr.restore_replicated(label, mesh=elastic.mesh,
                                          like=like)
        tree = elastic.reshard_tree(tree, saved)
    elif relaid:
        # Same layout (or a pre-topology bundle) through the replicated
        # path: no reshard, but the groups still need committing onto
        # the live mesh.
        tree = elastic.reshard_tree(tree, None)
    if saved is not None and saved != live:
        if sink is not None:
            sink.event_record(
                'topology_change',
                global_step=int(tree['scalars']['step']),
                resharded=bool(saved.needs_reshard(live)),
                from_processes=saved.processes, to_processes=live.processes,
                from_devices=saved.devices, to_devices=live.devices,
                from_grid=f'{saved.rows}x{saved.cols}',
                to_grid=f'{live.rows}x{live.cols}')
        if verbose:
            print(f'elastic resume: topology changed — saved on '
                  f'{saved.describe()}, resuming on {live.describe()}'
                  + ('' if saved.needs_reshard(live)
                     else ' (layout-compatible, no reshard)'))
    return tree
